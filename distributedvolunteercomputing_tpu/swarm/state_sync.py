"""Peer-pull state synchronisation: join the swarm at the swarm's step.

The capability that makes churn recovery real (SURVEY.md §5
checkpoint/resume): a volunteer that (re)joins — fresh process, restored
preemption, long absence — pulls the freshest params straight from a live
peer instead of training from its cold init and poisoning the next averaging
round with stale weights (the hivemind ``load_state_from_peers`` role, done
the swarm's way: DHT announcement + one transport RPC).

Protocol:
- every provider periodically announces ``state/<namespace>`` in the DHT
  with its current step (subkey = peer_id, TTL'd like heartbeats);
- a puller reads the key, targets the highest announced step above its own,
  and issues ``state.fetch``; the payload is the flattened f32 param buffer
  (always f32 — a one-off fetch shouldn't inherit the bf16 wire's rounding);
- the puller validates the buffer length against ITS OWN param schema before
  adopting (a wrong-model payload can't be loaded), and walks down the
  candidate list on failure — a dead or lagging peer costs one timeout.

Optimizer moments are NOT transferred: a pulled state resumes with a cold
optimizer at the correct step (the standard trade — moments are 2x params of
extra WAN bytes for marginal benefit after averaging rounds resync anyway).
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
from distributedvolunteercomputing_tpu.swarm.transport import Addr, RPCError, Transport
from distributedvolunteercomputing_tpu.utils.logging import get_logger
from distributedvolunteercomputing_tpu.utils.pytree import (
    flatten_to_buffer,
    tree_specs,
    unflatten_from_buffer,
)

log = get_logger(__name__)

# (step, params_tree) supplier — reads the live trainer state.
StateProvider = Callable[[], Tuple[int, Any]]


class StateSyncService:
    def __init__(
        self,
        transport: Transport,
        dht: DHTNode,
        peer_id: str,
        namespace: str,
        announce_ttl: float = 30.0,
        fetch_timeout: float = 60.0,
    ):
        self.transport = transport
        self.dht = dht
        self.peer_id = peer_id
        self.namespace = namespace
        self.announce_ttl = announce_ttl
        self.fetch_timeout = fetch_timeout
        self._provider: Optional[StateProvider] = None
        transport.register("state.fetch", self._rpc_fetch)

    @property
    def key(self) -> str:
        return f"state/{self.namespace}"

    def set_provider(self, provider: StateProvider) -> None:
        self._provider = provider

    # -- provider side -----------------------------------------------------

    async def announce(self) -> None:
        """Publish (addr, step) under the state key; call periodically."""
        if self._provider is None:
            return
        step, _ = self._provider()
        await self.dht.store(
            self.key,
            {"addr": list(self.transport.addr), "step": int(step)},
            subkey=self.peer_id,
            ttl=self.announce_ttl,
        )

    async def _rpc_fetch(self, args: dict, payload: bytes):
        if self._provider is None:
            raise RPCError("no state to serve yet")
        step, tree = self._provider()

        def _serialize() -> bytes:
            buf, _, _ = flatten_to_buffer(tree)
            return buf.tobytes()

        # Param-sized flatten+copy off the event loop: serving state must not
        # stall heartbeats/averaging RPCs for the duration of a big memcpy.
        return {"step": int(step)}, await asyncio.to_thread(_serialize)

    # -- puller side -------------------------------------------------------

    async def _candidates(self, min_step: int) -> List[Tuple[int, str, Addr]]:
        records = await self.dht.get(self.key)
        out = []
        for pid, rec in records.items():
            if pid == self.peer_id or not isinstance(rec, dict):
                continue
            try:
                step = int(rec["step"])
                host, port = rec["addr"]
                addr = (str(host), int(port))
            except (KeyError, TypeError, ValueError):
                continue
            if step > min_step:
                out.append((step, pid, addr))
        out.sort(reverse=True)  # freshest first
        return out

    async def pull(
        self, local_tree: Any, local_step: int, min_lead: int = 1
    ) -> Optional[Tuple[int, Any]]:
        """Fetch params from the freshest peer at least ``min_lead`` steps
        ahead; returns (step, tree) or None (nobody ahead / all fetches
        failed — both normal, the caller just trains on)."""
        # Schema only — no param-sized buffer materialized on the pull side.
        specs, treedef = tree_specs(local_tree)
        expect = int(sum(s.size for s in specs))
        for step, pid, addr in await self._candidates(local_step + min_lead - 1):
            try:
                ret, payload = await self.transport.call(
                    addr, "state.fetch", {"peer": self.peer_id},
                    timeout=self.fetch_timeout,
                )
                buf = np.frombuffer(payload, np.float32)
                if buf.size != expect:
                    log.warning(
                        "state pull from %s: buffer %d != local schema %d (skipping)",
                        pid, buf.size, expect,
                    )
                    continue
                got_step = int(ret.get("step", step))
                log.info("pulled state at step %d from %s", got_step, pid)
                # No defensive copy: unflatten's astype copies each chunk out
                # of the read-only frombuffer view.
                return got_step, unflatten_from_buffer(buf, specs, treedef)
            except (RPCError, OSError, asyncio.TimeoutError, ValueError) as e:
                log.info("state pull from %s failed (%s); trying next", pid, e)
        return None
