"""Closed-loop adaptive controller: the swarm retunes itself, live.

PRs 10-13 built the sensor suite Chameleon-style real-time policy
selection needs — per-round critical paths, per-level wall/failure
history, bandwidth EWMAs, codec-distortion telemetry, mixing-error
dispersion, the flight recorder — but every policy knob stayed hand-set.
This module closes the loop: one :class:`SwarmController` per volunteer
reads that telemetry and selects, per epoch, per hierarchy level:

- **regime** (the shared model): a per-level verdict in
  ``calm | churn | degraded`` from the level's round failure-rate EWMA,
  hysteresis-banded. Topology, hedge, and wire decisions all read THIS
  state instead of running three independent AIMD loops that fight each
  other (ROADMAP item 2's follow-on, folded in).
- **averaging topology**: a ladder ``sync-group -> butterfly -> gossip``
  over the rotating group schedule's geometry — one max-size gather
  group (best mixing per round, worst churn exposure), the configured
  Moshpit grid, or pairwise groups of two (maximum churn containment).
  Falling regime walks down the ladder; a recovered failure EWMA climbs
  back to the calm preference.
- **wire format**: dense f32 vs bf16 selected from measured
  convergence-per-byte — the PR-11 codec-distortion telemetry joined
  against the transport's bandwidth EWMAs and the current round budget.
  The compressed wires (q8 / topk / powersgd / sign) are RANKED in the
  same table and exported in the summary, but only the dense pair is
  switched live: they share tile geometry, so a flip re-keys the schema
  hash and nothing else (a disagreeing peer's push is rejected by
  schema, never mis-decoded — the documented mixed-wire degradation).
- **hierarchy cadence**: a learned ``k`` per zone pair replacing the
  static ``cross_zone_every_k`` — tightened (smaller k, more cross
  mixing) while the cross-round dispersion trend stalls above its
  floor (``mixing_stall`` risk), relaxed (larger k) once dispersion
  converges or the pair's bandwidth floor collapses (cross rounds that
  mostly fail spend committed-round rate for nothing). The schedule
  runs ONE k, so the applied value is the tightest (smallest) pair k —
  the neediest pair binds, and the per-pair state is what coord.status
  shows (the per-level cadence VECTOR is ROADMAP item 4e).
- **per-level round deadlines**: owned by the resilience policy's
  per-level AIMD split (``ResiliencePolicy.round_budget(level)``); the
  controller reports them and stamps its regime into the policy's hedge
  budget (``ResiliencePolicy.set_regime``).

Decision discipline (the whole point — no flapping, no mid-round mixes):

- every decision comes from a DETERMINISTIC policy table over
  hysteresis-guarded evidence gates (watchdog-style fire/clear bands
  with consecutive-breach counts) plus a per-knob dwell: a knob that
  just moved cannot move again for ``dwell_rounds`` rounds;
- every transition is **epoch-fenced like leadership**: staged when
  decided, applied only by :meth:`advance` — which the averager calls
  BEFORE forming the next round — so a mid-round regime shift can never
  mix two configurations into one round;
- every applied transition lands in the flight recorder as a
  ``policy_changed`` event carrying the knob, old/new value, reason, and
  the evidence snapshot it rode on, and annotates any in-window
  ``round_wall_inflation`` / ``commit_rate_collapse`` alert (an
  intentional retune must not page as an anomaly).

Everything follows the telemetry plane's contract: advisory and bounded.
The controller must never fail a round — observe/decide paths swallow
their own exceptions — and a disabled controller (``--no-adapt``) is
simply never constructed: no controller bytes ride the report beat.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

# Version stamp carried by every controller summary and the coord.status
# rollup (independent of the telemetry/health/watchdog versions; pinned
# by tests/test_controller.py).
CONTROLLER_SCHEMA_VERSION = 1

# The topology ladder, calm-most first. Falling regime moves RIGHT
# (smaller, churn-tolerant groups); recovery climbs back LEFT toward the
# preference. The names map onto the rotating schedule's geometry:
# sync-group = one max_group-sized gather group, butterfly = the
# configured Moshpit grid, gossip = pairwise groups of two.
TOPOLOGY_LADDER = ("sync-group", "butterfly", "gossip")

REGIMES = ("calm", "churn", "degraded")

# Static wire cost table (bytes per element shipped) for the
# convergence-per-byte ranking. topk/powersgd costs depend on frac/rank;
# the entries here are the stock-config estimates, labeled as such in
# the ranking output.
WIRE_BYTES_PER_ELEM: Dict[str, float] = {
    "f32": 4.0,
    "bf16": 2.0,
    "q8": 1.0,
    "topk": 0.08,      # ~frac 0.01 of (index+value) pairs
    "powersgd": 0.25,  # rank-4 over typical layer shapes
    "sign": 0.125,
}


class EvidenceGate:
    """Watchdog-style fire/clear hysteresis over one scalar evidence
    series, applied to DECISIONS: ``observe`` returns the gate's firing
    state after folding the value in. Fires after ``min_breaches``
    consecutive observations past ``fire``; clears after
    ``clear_breaches`` consecutive observations inside ``clear``. A
    value between the bands changes nothing — the no-flap property the
    ISSUE-15 hysteresis test pins."""

    __slots__ = (
        "fire", "clear", "low", "min_breaches", "clear_breaches",
        "_breach", "_inband", "firing",
    )

    def __init__(
        self,
        fire: float,
        clear: float,
        *,
        low: bool = False,
        min_breaches: int = 2,
        clear_breaches: int = 2,
    ):
        # "low" gates fire when the value drops BELOW fire (bandwidth
        # collapse); default gates fire above it (failure rate).
        if low:
            assert clear > fire, "low gate needs clear above fire"
        else:
            assert clear < fire, "high gate needs clear below fire"
        self.fire = float(fire)
        self.clear = float(clear)
        self.low = bool(low)
        self.min_breaches = int(min_breaches)
        self.clear_breaches = int(clear_breaches)
        self._breach = 0
        self._inband = 0
        self.firing = False

    def observe(self, value: float) -> bool:
        v = float(value)
        bad = v < self.fire if self.low else v > self.fire
        ok = v > self.clear if self.low else v < self.clear
        if not self.firing:
            if bad:
                self._breach += 1
                if self._breach >= self.min_breaches:
                    self.firing = True
                    self._inband = 0
            else:
                self._breach = 0
        else:
            if ok:
                self._inband += 1
                if self._inband >= self.clear_breaches:
                    self.firing = False
                    self._breach = 0
            else:
                self._inband = 0
        return self.firing


class SwarmController:
    """One closed-loop controller per volunteer (see module doc).

    Wiring: the volunteer constructs it next to the resilience policy
    and passes it into the averager, which feeds evidence
    (:meth:`observe_round`, :meth:`observe_dispersion`,
    :meth:`observe_cross_pair`) after each round and calls
    :meth:`advance` before forming the next one. Decisions are purely
    local and advisory: a knob that changes schedule geometry or wire
    degrades exactly like every other view divergence in this codebase —
    an underfilled rendezvous or a schema-rejected push, never mixed
    tensors."""

    # Failure-rate EWMA bands per regime step (fraction of rounds that
    # failed or degraded). calm->churn at 0.35/0.15, churn->degraded at
    # 0.7/0.45 — wide enough apart that EWMA noise inside a band moves
    # nothing.
    CHURN_FIRE, CHURN_CLEAR = 0.35, 0.15
    DEGRADED_FIRE, DEGRADED_CLEAR = 0.7, 0.45
    FAIL_ALPHA = 0.3
    # Wire gate: estimated push transfer time as a fraction of the round
    # budget. Above WIRE_FIRE_FRAC the link is budget-bound (halve the
    # bytes); below WIRE_CLEAR_FRAC at f32 cost it is comfortably idle
    # (full precision is free again).
    WIRE_FIRE_FRAC, WIRE_CLEAR_FRAC = 0.5, 0.15
    # bf16 is only eligible while its measured relative distortion stays
    # under this bound (sampled by the health layer's codec gauge).
    WIRE_DISTORTION_BOUND = 2e-2
    # Cadence: per-pair k bounds as multiples of the configured base k,
    # and the dispersion-trend window (cross rounds) the trend verdict
    # needs. Convergence floor matches the watchdog's StallDetector.
    CADENCE_MAX_STRETCH = 8
    DISPERSION_WINDOW = 4
    DISPERSION_FLOOR = 0.05
    DISPERSION_IMPROVE_TOL = 0.1
    # Per-pair bandwidth floor (bytes/s) under which cross rounds to the
    # pair are judged not worth their cadence (thin/partitioned WAN).
    PAIR_BW_FLOOR = 64 * 1024
    PAIR_BW_CLEAR = 256 * 1024
    # A knob that just moved cannot move again for this many rounds.
    DWELL_ROUNDS = 4
    # Transition history window for transitions/hour + alert annotation.
    MAX_TRANSITIONS = 64
    ANNOTATE_WINDOW_S = 60.0

    def __init__(
        self,
        *,
        policy=None,
        telemetry=None,
        topology_preference: str = "butterfly",
        clock: Callable[[], float] = time.time,
    ):
        if topology_preference not in TOPOLOGY_LADDER:
            raise ValueError(
                f"unknown topology preference {topology_preference!r}; "
                f"known: {TOPOLOGY_LADDER}"
            )
        self.policy = policy
        self.telemetry = telemetry
        self.clock = clock
        self.enabled = True
        self.topology_preference = topology_preference
        self._pref_idx = TOPOLOGY_LADDER.index(topology_preference)
        # Round sequence (one per average() call on the owning averager):
        # the epoch fence every staged decision is keyed to.
        self._seq = 0
        # Per-level regime state: failure EWMA + the two hysteresis gates.
        self._levels: Dict[str, dict] = {}
        # Wire state (None until attach() learns the configured wire).
        self.wire: Optional[str] = None
        self._wire_configured: Optional[str] = None
        self._wire_gate = EvidenceGate(self.WIRE_FIRE_FRAC, self.WIRE_CLEAR_FRAC)
        # Cadence state: base k + per-zone-pair learned k and evidence.
        self._base_k = 0
        self.applied_k = 0
        self._pairs: Dict[str, dict] = {}
        # Cross-round dispersion trend (relative contribution dispersion
        # observed by round leaders; the local form of the health
        # rollup's across-zone sketch dispersion).
        self._disp: "deque[float]" = deque(maxlen=2 * self.DISPERSION_WINDOW)
        # Topology state.
        self.topology = topology_preference
        # Staged (decided, not yet applied) transitions: the epoch fence.
        self._pending: List[dict] = []
        # Applied-transition history (bounded) + totals.
        self._transitions: "deque[dict]" = deque(maxlen=self.MAX_TRANSITIONS)
        self.transitions_total = 0
        self._knob_last_move: Dict[Tuple[str, str], int] = {}
        self._t0 = clock()
        self._watchdog_wired = False

    # -- attachment ---------------------------------------------------------

    def attach(
        self,
        *,
        wire: str,
        schedule=None,
        max_group: int = 16,
    ) -> None:
        """Adopt the averager's static configuration: the configured wire
        (the calm point the wire knob clears back to), and the schedule's
        geometry the topology/cadence knobs operate on. Called once by
        the averager's constructor; idempotent."""
        if self._wire_configured is None:
            self._wire_configured = wire
            self.wire = wire
        if schedule is not None and not hasattr(self, "_sched_target"):
            self._base_k = int(schedule.cross_zone_every_k)
            self.applied_k = self._base_k
            self._sched_target = int(schedule.target_size)
            self._max_group = int(max_group)
        if self.telemetry is not None and not self._watchdog_wired:
            self._watchdog_wired = True
            wd = getattr(self.telemetry, "watchdog", None)
            if wd is not None and getattr(wd, "enabled", False):
                wd.add_probe(self._annotate_probe)

    # -- evidence -----------------------------------------------------------

    def _level(self, level: Optional[str]) -> dict:
        lv = level or "flat"
        rec = self._levels.get(lv)
        if rec is None:
            rec = self._levels[lv] = {
                "fail_ewma": 0.0,
                "rounds": 0,
                "churn": EvidenceGate(self.CHURN_FIRE, self.CHURN_CLEAR),
                "degraded": EvidenceGate(self.DEGRADED_FIRE, self.DEGRADED_CLEAR),
                "regime": "calm",
            }
        return rec

    def regime(self, level: Optional[str] = None) -> str:
        return self._level(level)["regime"]

    def observe_round(
        self,
        *,
        level: Optional[str] = None,
        ok: bool,
        degraded: bool = False,
        duration_s: float = 0.0,
        push_bytes: Optional[int] = None,
        bw_floor: Optional[float] = None,
        budget_s: Optional[float] = None,
    ) -> None:
        """One finished round's evidence from the owning averager: the
        outcome feeds the level's regime model, and (when known) the push
        size + slowest group link feed the wire gate. Runs the decision
        table — transitions STAGE here and apply at the next advance()."""
        if not self.enabled:
            return
        try:
            rec = self._level(level)
            rec["rounds"] += 1
            bad = 1.0 if (not ok or degraded) else 0.0
            rec["fail_ewma"] += self.FAIL_ALPHA * (bad - rec["fail_ewma"])
            churn = rec["churn"].observe(rec["fail_ewma"])
            degr = rec["degraded"].observe(rec["fail_ewma"])
            new_regime = "degraded" if degr else ("churn" if churn else "calm")
            if new_regime != rec["regime"]:
                self._stage(
                    "regime", level or "flat", rec["regime"], new_regime,
                    reason=(
                        "failure-rate EWMA %.2f crossed the %s band"
                        % (rec["fail_ewma"],
                           "fire" if new_regime != "calm" else "clear")
                    ),
                    evidence={
                        "fail_ewma": round(rec["fail_ewma"], 4),
                        "rounds": rec["rounds"],
                    },
                )
            if push_bytes and bw_floor and budget_s:
                self._decide_wire(push_bytes, bw_floor, budget_s)
            self._decide_topology()
        except Exception as e:  # noqa: BLE001 — the controller must never fail a round
            log.debug("controller observe_round failed: %s", errstr(e))

    def observe_shard_health(
        self, level: Optional[str] = None, *, ok: bool,
    ) -> None:
        """Shard-domain health (zone-sharded training, swarm/sharding.py)
        as a regime input: a shard manager reporting degraded/recovering
        feeds the SAME failure EWMA + evidence gates a failed round does
        — for the level the loss actually sits on ("intra": the zone's
        gather/scatter plane) — so a degraded shard zone widens that
        level's deadlines and floors its hedge budget through the
        existing regime→policy folding, with no new knob. A healthy beat
        feeds 0 and walks the gates back toward calm, exactly like a
        committed round."""
        if not self.enabled:
            return
        try:
            rec = self._level(level)
            bad = 0.0 if ok else 1.0
            rec["fail_ewma"] += self.FAIL_ALPHA * (bad - rec["fail_ewma"])
            churn = rec["churn"].observe(rec["fail_ewma"])
            degr = rec["degraded"].observe(rec["fail_ewma"])
            new_regime = "degraded" if degr else ("churn" if churn else "calm")
            if new_regime != rec["regime"]:
                self._stage(
                    "regime", level or "flat", rec["regime"], new_regime,
                    reason=(
                        "shard-domain health fed failure EWMA %.2f across "
                        "the %s band"
                        % (rec["fail_ewma"],
                           "fire" if new_regime != "calm" else "clear")
                    ),
                    evidence={
                        "fail_ewma": round(rec["fail_ewma"], 4),
                        "source": "shard_health",
                    },
                )
        except Exception as e:  # noqa: BLE001 — the controller must never fail a beat
            log.debug("controller observe_shard_health failed: %s", errstr(e))

    def observe_dispersion(self, level: Optional[str], rel: float) -> None:
        """One cross-round relative contribution dispersion (the leader's
        per-peer distance evidence, sqrt(mean d2)/|agg|): the local
        mixing-error trend the cadence knob tightens/relaxes on. Only
        cross-level observations feed the trend."""
        if not self.enabled or (level or "flat") != "cross":
            return
        try:
            self._disp.append(float(rel))
            self._decide_cadence()
        except Exception as e:  # noqa: BLE001
            log.debug("controller observe_dispersion failed: %s", errstr(e))

    def observe_cross_pair(
        self, pair: str, *, bw_floor: Optional[float] = None,
        ok: bool = True, degraded: bool = False,
    ) -> None:
        """Per-zone-pair evidence from a cross round this node saw: the
        pair's slowest observed link and the round outcome. ``pair`` is
        the sorted "zoneA|zoneB" key."""
        if not self.enabled:
            return
        try:
            rec = self._pairs.get(pair)
            if rec is None:
                if len(self._pairs) >= 32:
                    return
                base = max(self._base_k, 1)
                rec = self._pairs[pair] = {
                    "k": base,
                    "rounds": 0,
                    "fail_ewma": 0.0,
                    "bw_floor": None,
                    "thin": EvidenceGate(
                        self.PAIR_BW_FLOOR, self.PAIR_BW_CLEAR, low=True
                    ),
                }
            rec["rounds"] += 1
            bad = 1.0 if (not ok or degraded) else 0.0
            rec["fail_ewma"] += self.FAIL_ALPHA * (bad - rec["fail_ewma"])
            if bw_floor is not None:
                rec["bw_floor"] = float(bw_floor)
                rec["thin"].observe(float(bw_floor))
            self._decide_cadence()
        except Exception as e:  # noqa: BLE001
            log.debug("controller observe_cross_pair failed: %s", errstr(e))

    # -- the policy table ---------------------------------------------------

    def _dwell_ok(self, knob: str, key: str) -> bool:
        last = self._knob_last_move.get((knob, key))
        return last is None or self._seq - last >= self.DWELL_ROUNDS

    def _staged_value(self, knob: str, key: str):
        for p in reversed(self._pending):
            if p["knob"] == knob and p["key"] == key:
                return p["to"]
        return None

    def _stage(
        self, knob: str, key: str, frm, to, *, reason: str, evidence: dict,
    ) -> None:
        """Stage one transition behind the epoch fence (applies from the
        NEXT round — advance() promotes it). Dwell- and dedup-guarded:
        a knob mid-dwell, or one already staged to this value, stays
        put."""
        if to == frm or self._staged_value(knob, key) == to:
            return
        if not self._dwell_ok(knob, key):
            return
        self._pending.append({
            "knob": knob, "key": key, "from": frm, "to": to,
            "reason": reason, "evidence": evidence,
            "staged_t": round(self.clock(), 3),
            "fence": self._seq + 1,
        })
        # Dwell counts from the STAGE: a gate that keeps firing while the
        # fence is pending must not pile up duplicate transitions.
        self._knob_last_move[(knob, key)] = self._seq

    def _decide_topology(self) -> None:
        """Ladder walk from the worst live regime across levels: calm ->
        the preference, churn -> one step down, degraded -> gossip."""
        if not hasattr(self, "_sched_target"):
            return  # no schedule attached: geometry is not ours to move
        worst = max(
            (REGIMES.index(rec["regime"]) for rec in self._levels.values()),
            default=0,
        )
        idx = min(max(self._pref_idx + worst, worst), len(TOPOLOGY_LADDER) - 1)
        target = TOPOLOGY_LADDER[idx]
        self._stage(
            "topology", "", self.topology, target,
            reason=f"worst level regime is {REGIMES[worst]}",
            evidence={
                lv: round(rec["fail_ewma"], 4)
                for lv, rec in self._levels.items()
            },
        )

    def _decide_wire(
        self, push_bytes: int, bw_floor: float, budget_s: float
    ) -> None:
        """Dense-pair wire selection on the transfer-time share of the
        round budget, distortion-bounded (see module doc)."""
        if self._wire_configured not in ("f32", "bf16"):
            return  # compressed wires are recommendation-only
        # Evaluate the gate at f32 cost, so firing means "f32 does not
        # fit" and clearing means "f32 fits comfortably" — one series,
        # no discontinuity at the flip itself.
        f32_bytes = push_bytes * (2 if self.wire == "bf16" else 1)
        share = (f32_bytes / max(bw_floor, 1.0)) / max(budget_s, 1e-6)
        fired = self._wire_gate.observe(share)
        distortion = self._wire_distortion("bf16")
        evidence = {
            "f32_transfer_share": round(share, 4),
            "bw_floor_bps": round(bw_floor, 1),
            "push_bytes": int(push_bytes),
            "budget_s": round(budget_s, 3),
            "bf16_rel_err": distortion,
        }
        if (
            fired
            and self.wire == "f32"
            and distortion is not None
            and distortion < self.WIRE_DISTORTION_BOUND
        ):
            self._stage(
                "wire", "", "f32", "bf16",
                reason="push transfer share over budget; bf16 distortion "
                       "within bound (convergence-per-byte favors bf16)",
                evidence=evidence,
            )
        elif not fired and self.wire == "bf16" and self._wire_configured == "f32":
            self._stage(
                "wire", "", "bf16", "f32",
                reason="bandwidth recovered; full precision fits the budget",
                evidence=evidence,
            )

    def _wire_distortion(self, wire: str) -> Optional[float]:
        """Measured relative codec error for ``wire`` from the health
        layer's codec gauge (EWMA), or None before any sample."""
        h = getattr(self.telemetry, "health", None)
        if h is None or not getattr(h, "enabled", False):
            return None
        rec = getattr(h, "_codec", {}).get(wire)
        return round(float(rec["ewma"]), 8) if rec else None

    def _decide_cadence(self) -> None:
        """Per-pair k from the dispersion trend + the pair's bandwidth
        gate; the applied (schedule) k is the tightest pair's."""
        if self._base_k <= 0 or not self._pairs:
            return
        trend = self._dispersion_trend()
        for pair, rec in self._pairs.items():
            k = rec["k"]
            if rec["thin"].firing:
                # Thin/partitioned WAN: cross rounds to this pair mostly
                # burn budget — relax toward the stretch cap.
                target = min(k * 2, self._base_k * self.CADENCE_MAX_STRETCH)
                reason = "pair bandwidth floor collapsed; relaxing cross cadence"
            elif trend == "stalled":
                target = max(k // 2, 1)
                reason = "cross dispersion stalled above floor; tightening"
            elif trend == "converged":
                target = min(k * 2, self._base_k * self.CADENCE_MAX_STRETCH)
                reason = "cross dispersion converged; relaxing"
            else:
                continue
            self._stage(
                "cadence", pair, k, target,
                reason=reason,
                evidence={
                    "dispersion_trend": trend,
                    "bw_floor_bps": rec["bw_floor"],
                    "pair_fail_ewma": round(rec["fail_ewma"], 4),
                    "dispersion_recent": [round(d, 6) for d in list(self._disp)[-4:]],
                },
            )

    def _dispersion_trend(self) -> Optional[str]:
        """"stalled" | "converged" | None (not enough evidence) over the
        cross-round dispersion window — the StallDetector's
        new-low-vs-previous-window verdict, plus a convergence floor."""
        if len(self._disp) < 2 * self.DISPERSION_WINDOW:
            return None
        vals = list(self._disp)
        prev_min = min(vals[: self.DISPERSION_WINDOW])
        new_min = min(vals[self.DISPERSION_WINDOW:])
        if new_min < self.DISPERSION_FLOOR:
            return "converged"
        if new_min >= (1.0 - self.DISPERSION_IMPROVE_TOL) * prev_min:
            return "stalled"
        return None

    # -- the epoch fence ----------------------------------------------------

    def advance(self) -> List[dict]:
        """Promote staged transitions whose fence has passed and return
        them. The owning averager calls this ONCE per round, BEFORE
        rendezvous/formation — the fencing contract: a decision staged
        during round N applies from round N+1, never to a round already
        in flight."""
        self._seq += 1
        if not self._pending:
            return []
        due = [p for p in self._pending if p["fence"] <= self._seq]
        if not due:
            return []
        self._pending = [p for p in self._pending if p["fence"] > self._seq]
        for p in due:
            self._apply(p)
        return due

    def _apply(self, p: dict) -> None:
        knob, key, to = p["knob"], p["key"], p["to"]
        if knob == "regime":
            self._level(key)["regime"] = to
            if self.policy is not None and hasattr(self.policy, "set_regime"):
                # Fold the hedge budget into the shared regime model.
                self.policy.set_regime(key, to)
        elif knob == "topology":
            self.topology = to
        elif knob == "wire":
            self.wire = to
        elif knob == "cadence":
            rec = self._pairs.get(key)
            if rec is not None:
                rec["k"] = int(to)
            self.applied_k = min(
                (r["k"] for r in self._pairs.values()),
                default=self._base_k,
            )
        p["applied_t"] = round(self.clock(), 3)
        p["seq"] = self._seq
        self._transitions.append(p)
        self.transitions_total += 1
        log.info(
            "controller: %s[%s] %s -> %s (%s)",
            knob, key or "-", p["from"], to, p["reason"],
        )
        if self.telemetry is not None:
            try:
                self.telemetry.event(
                    "policy_changed",
                    knob=knob,
                    key=key,
                    **{"from": p["from"]},
                    to=to,
                    reason=p["reason"],
                    evidence=p["evidence"],
                )
            except Exception:  # noqa: BLE001 — recording is advisory
                pass
        self._annotate_alerts(p)

    # -- knob readouts (what the averager applies) --------------------------

    def target_group_size(self) -> Optional[int]:
        """Schedule target size for the CURRENT topology, or None when no
        schedule geometry was attached."""
        if not hasattr(self, "_sched_target"):
            return None
        if self.topology == "sync-group":
            return self._max_group
        if self.topology == "gossip":
            return 2
        return self._sched_target

    def cross_zone_k(self) -> Optional[int]:
        """The applied cross-zone cadence (the tightest pair k), or None
        when the hierarchy is off."""
        return (self.applied_k or None) if self._base_k else None

    # -- watchdog annotation ------------------------------------------------

    def last_transition(self) -> Optional[dict]:
        return dict(self._transitions[-1]) if self._transitions else None

    def _annotate_alerts(self, p: dict) -> None:
        """Stamp the transition onto any currently-firing wall/commit
        alert (an intentional retune is context, not an anomaly — the
        PR-13 hedge-annotation pattern)."""
        wd = getattr(self.telemetry, "watchdog", None)
        if wd is None or not getattr(wd, "enabled", False):
            return
        note = {
            "policy_changed": f"{p['knob']}[{p['key'] or '-'}] "
                              f"{p['from']}->{p['to']}",
            "policy_reason": p["reason"],
            "policy_t": p.get("applied_t"),
        }
        for kind in ("round_wall_inflation", "commit_rate_collapse"):
            for alert in wd.alerts():
                if alert["kind"] == kind:
                    wd.annotate(kind, alert["key"], **note)

    def _annotate_probe(self, now: float, dt: Optional[float]) -> None:
        """Watchdog tick probe: an alert RAISED shortly after a transition
        (the other ordering _annotate_alerts can't see) still gets the
        in-window policy_changed stamp."""
        last = self.last_transition()
        if last is None:
            return
        t = last.get("applied_t") or 0.0
        if now - t > self.ANNOTATE_WINDOW_S:
            return
        self._annotate_alerts(last)

    # -- status -------------------------------------------------------------

    def transitions_per_hour(self) -> float:
        now = self.clock()
        window = [
            p for p in self._transitions
            if now - (p.get("applied_t") or 0.0) <= 3600.0
        ]
        span = min(3600.0, max(now - self._t0, 60.0))
        return round(len(window) * 3600.0 / span, 2)

    def wire_ranking(self) -> List[dict]:
        """Candidate wires ranked by estimated convergence-per-byte:
        measured relative distortion (health codec gauge; None = never
        sampled) joined against the static bytes/element table. Score =
        (1 - penalized distortion) / bytes_per_elem — the live half of
        ROADMAP item 1's "r5 codec-horizon" ranking; unsampled wires
        rank after every measured one and are labeled unmeasured."""
        out = []
        for wire, bpe in WIRE_BYTES_PER_ELEM.items():
            rel = self._wire_distortion(wire)
            penalty = min((rel or 0.0) * 10.0, 0.95)
            out.append({
                "wire": wire,
                "bytes_per_elem": bpe,
                "rel_err_ewma": rel,
                "measured": rel is not None,
                "score": round((1.0 - penalty) / bpe, 4),
            })
        # Measured wires first: a wire nobody has distortion evidence for
        # must not out-rank one the swarm is actually running.
        out.sort(key=lambda r: (not r["measured"], -r["score"]))
        return out

    def summary(self) -> dict:
        """Compact controller view for the volunteer report (rides the
        batched cp.exchange beat; rolled into coord.status["controller"])."""
        last = self.last_transition()
        if last is not None:
            last = {
                k: last[k]
                for k in ("knob", "key", "from", "to", "reason", "applied_t")
                if k in last
            }
        return {
            "schema_version": CONTROLLER_SCHEMA_VERSION,
            "regime": {
                lv: rec["regime"] for lv, rec in self._levels.items()
            } or {"flat": "calm"},
            "topology": self.topology,
            "wire": self.wire or "",
            "cadence": {
                "base_k": self._base_k,
                "applied_k": self.applied_k,
                "per_pair": {
                    pair: {
                        "k": rec["k"],
                        "bw_floor_bps": rec["bw_floor"],
                        "fail_ewma": round(rec["fail_ewma"], 4),
                    }
                    for pair, rec in self._pairs.items()
                },
            },
            "deadlines": (
                self.policy.deadlines() if self.policy is not None else {}
            ),
            "transitions_total": self.transitions_total,
            "transitions_per_hour": self.transitions_per_hour(),
            "pending": len(self._pending),
            "last_transition": last,
        }

    def scrape(self) -> dict:
        """Debug/collection view: the summary plus the bounded transition
        history and the live wire ranking."""
        out = self.summary()
        out["transitions"] = [dict(p) for p in self._transitions]
        out["wire_ranking"] = self.wire_ranking()
        return out


# -- coord.status["controller"] rollup ----------------------------------------

# The documented coord.status["controller"] schema — walked by
# tests/test_controller.py like the telemetry/health/watchdog ones, so
# drift breaks CI instead of dashboards. `age_s` is the usual serve-time
# staleness stamp.
STATUS_CONTROLLER_SCHEMA: Dict[str, type] = {
    "schema_version": int,
    "age_s": float,          # staleness stamp (serve-time, freshest report)
    "reporting": int,        # volunteers whose fresh report carried controller
    "regime": dict,          # level -> worst reporter regime
    "topology": dict,        # topology -> reporter count
    "wire": dict,            # wire -> reporter count
    "cadence": dict,         # {applied_k_min, per_pair: pair -> k/bw evidence}
    "deadlines": dict,       # level -> max learned deadline across reporters
    "transitions_total": int,
    "transitions_per_hour": float,
    "per_peer": dict,        # peer -> its summary (verbatim)
}


def rollup_status(fresh_reports: List[dict]) -> Optional[dict]:
    """Merge per-volunteer controller summaries (from fresh reports) into
    the versioned ``coord.status["controller"]`` rollup. None until some
    volunteer reports a controller — the telemetry rollup's contract
    (a --no-adapt fleet serves no controller section at all)."""
    per_peer: Dict[str, dict] = {}
    for m in fresh_reports:
        c = m.get("controller")
        if isinstance(c, dict) and c.get("schema_version") == CONTROLLER_SCHEMA_VERSION:
            per_peer[str(m.get("peer", "?"))] = c
    if not per_peer:
        return None
    regime: Dict[str, str] = {}
    topology: Dict[str, int] = {}
    wire: Dict[str, int] = {}
    deadlines: Dict[str, float] = {}
    pair_k: Dict[str, dict] = {}
    applied_ks: List[int] = []
    transitions = 0
    tph = 0.0
    last = None
    for c in per_peer.values():
        for lv, r in (c.get("regime") or {}).items():
            # Unknown regime strings (version skew, a buggy reporter)
            # rank as "calm" instead of raising — one bad report must
            # not fail every coord.status serve (the set_regime rule).
            cur = regime.get(str(lv), "calm")
            rank = REGIMES.index(str(r)) if str(r) in REGIMES else 0
            if rank > REGIMES.index(cur):
                regime[str(lv)] = str(r)
            else:
                regime.setdefault(str(lv), cur)
        t = str(c.get("topology") or "")
        if t:
            topology[t] = topology.get(t, 0) + 1
        w = str(c.get("wire") or "")
        if w:
            wire[w] = wire.get(w, 0) + 1
        for lv, d in (c.get("deadlines") or {}).items():
            if isinstance(d, (int, float)):
                deadlines[str(lv)] = max(deadlines.get(str(lv), 0.0), float(d))
        cad = c.get("cadence") or {}
        if cad.get("applied_k"):
            applied_ks.append(int(cad["applied_k"]))
        for pair, rec in (cad.get("per_pair") or {}).items():
            cur = pair_k.setdefault(
                str(pair), {"k": None, "bw_floor_bps": None, "reporters": 0}
            )
            cur["reporters"] += 1
            if isinstance(rec, dict) and rec.get("k") is not None:
                k = int(rec["k"])
                cur["k"] = k if cur["k"] is None else min(cur["k"], k)
                bw = rec.get("bw_floor_bps")
                if isinstance(bw, (int, float)) and (
                    cur["bw_floor_bps"] is None or bw < cur["bw_floor_bps"]
                ):
                    cur["bw_floor_bps"] = float(bw)
        transitions += int(c.get("transitions_total") or 0)
        tph += float(c.get("transitions_per_hour") or 0.0)
        lt = c.get("last_transition")
        if isinstance(lt, dict) and (
            last is None
            or (lt.get("applied_t") or 0) > (last.get("applied_t") or 0)
        ):
            last = lt
    return {
        "schema_version": CONTROLLER_SCHEMA_VERSION,
        "reporting": len(per_peer),
        "regime": regime,
        "topology": topology,
        "wire": wire,
        "cadence": {
            "applied_k_min": min(applied_ks) if applied_ks else None,
            "per_pair": pair_k,
        },
        "deadlines": deadlines,
        "transitions_total": transitions,
        "transitions_per_hour": round(tph, 2),
        "last_transition": last,
        "per_peer": per_peer,
    }
