"""Phi-accrual failure detection (Hayashibara et al., "The φ Accrual
Failure Detector") over swarm heartbeat observations.

The membership layer's TTL is BINARY liveness: a peer is alive until its
DHT record expires, then it is dead — there is no notion of "probably
stalled", which is exactly the state a straggler occupies for the seconds
that matter to an averaging round. The phi-accrual detector replaces that
cliff with a continuous suspicion score:

    phi(peer) = -log10( P(next heartbeat arrives later than it already has) )

computed from the observed distribution of that peer's heartbeat
inter-arrival times. phi ~ 1 means "this gap would happen ~10% of the
time"; phi ~ 8 means one-in-10^8 — for all practical purposes the peer is
stalled or partitioned. Because phi accrues CONTINUOUSLY as the silence
grows, consumers pick their own thresholds: the matchmaker pre-excludes
likely stragglers from group formation (swarm/matchmaking.py) well before
the membership TTL would expire the record, and the resilience policy
(swarm/resilience.py) folds phi into its per-peer outcome tracking.

Feeding: SwarmMembership observes peer records (each carries the sender's
announce timestamp ``t``); every time a peer's ``t`` CHANGES, that is one
heartbeat arrival at the local monotonic clock (swarm/membership.py
``_observe``). Observation cadence quantizes the samples, which is fine —
the detector only needs the gap distribution to be stationary, not exact.

All state is process-local and cheap (a bounded deque of floats per peer);
no I/O, no tasks — safe to call from RPC handlers and the trainer thread
(reads are over immutable snapshots of per-peer tuples).
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

from distributedvolunteercomputing_tpu.utils.logging import get_logger

log = get_logger(__name__)

# phi above this = suspected by default. 8 is the classic Cassandra/Akka
# default: P(false positive) ~ 1e-8 under the fitted model.
DEFAULT_PHI_THRESHOLD = 8.0


class PhiAccrualDetector:
    """Per-peer suspicion scores from heartbeat inter-arrival times.

    ``window``      — inter-arrival samples kept per peer (sliding).
    ``threshold``   — phi at/above which ``suspect()`` is True.
    ``min_std_s``   — floor on the fitted std-dev: localhost heartbeats can
                      be near-periodic, and a ~0 std would make the first
                      slightly-late beat spike phi to infinity.
    ``bootstrap_s`` — assumed mean gap before enough samples exist, so a
                      peer heard from ONCE still accrues suspicion if it
                      goes silent (rather than being unsuspectable until
                      its distribution is learned).
    ``clock``       — monotonic-time source (injectable for tests).

    Secondary signal: the transport's per-peer RPC latency EWMA
    (``observe_latency``, fed by SwarmMembership from the pooled
    transport's counters). Heartbeats ride the DHT with multi-second
    cadence, so a peer whose RPC latency explodes — congested link, paging
    host, half-partitioned pipe — can look heartbeat-healthy for several
    beats while already being a round-killing straggler. A peer whose
    current latency EWMA exceeds ``lat_factor`` x its own slow-moving
    baseline AND the absolute ``lat_floor_s`` is suspected even at phi 0.
    Both gates are deliberately conservative: localhost/CI jitter is
    routinely 5-10x on a ms-scale baseline, which the absolute floor
    ignores.
    """

    MIN_SAMPLES = 3  # below this, fall back to the bootstrap gap model
    # Latency-EWMA suspicion gates (see class docstring).
    LAT_FACTOR = 8.0
    LAT_FLOOR_S = 1.0
    # How long a directly-reported connection failure keeps a peer
    # suspected regardless of phi (see report_failure).
    FAILURE_HOLD_S = 30.0

    def __init__(
        self,
        *,
        window: int = 64,
        threshold: float = DEFAULT_PHI_THRESHOLD,
        min_std_s: float = 0.25,
        bootstrap_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        if threshold <= 0:
            raise ValueError(f"threshold must be > 0, got {threshold}")
        self.window = int(window)
        self.threshold = float(threshold)
        self.min_std_s = float(min_std_s)
        self.bootstrap_s = float(bootstrap_s)
        self.clock = clock
        self._last: Dict[str, float] = {}
        self._gaps: Dict[str, deque] = {}
        # peer -> (current latency EWMA, slow baseline) — see observe_latency.
        self._lat: Dict[str, Tuple[float, float]] = {}
        # peer -> suspicion-hold expiry from a reported connection failure.
        self._failed: Dict[str, float] = {}

    # -- feeding -----------------------------------------------------------

    def heartbeat(self, peer: str, t: Optional[float] = None) -> None:
        """Record one heartbeat ARRIVAL for ``peer`` (local monotonic time)."""
        now = self.clock() if t is None else float(t)
        # A fresh heartbeat is positive liveness evidence: it clears any
        # reported-failure hold (the peer restarted/healed) so a recovered
        # node isn't shut out of formation for the rest of the hold window.
        self._failed.pop(peer, None)
        last = self._last.get(peer)
        self._last[peer] = now
        if last is None:
            return
        gap = now - last
        if gap <= 0:  # duplicate observation in the same poll — not a beat
            return
        self._gaps.setdefault(peer, deque(maxlen=self.window)).append(gap)

    def observe_latency(self, peer: str, latency_s: float) -> None:
        """Record the transport's current RPC latency EWMA for ``peer``.

        The fast value is stored as-is (the transport already smooths it);
        this detector maintains the SLOW baseline (alpha 0.02, ~50-sample
        memory) the suspicion ratio compares against, so a gradual genuine
        latency regime change re-baselines instead of suspecting forever."""
        if not (isinstance(latency_s, (int, float)) and latency_s >= 0):
            return
        prev = self._lat.get(peer)
        if prev is None:
            self._lat[peer] = (float(latency_s), float(latency_s))
        else:
            _, slow = prev
            self._lat[peer] = (float(latency_s), slow + 0.02 * (latency_s - slow))

    def latency_suspect(self, peer: str) -> bool:
        """Is the peer's current RPC latency far outside its own baseline?
        (The secondary suspicion signal; see class docstring.)"""
        entry = self._lat.get(peer)
        if entry is None:
            return False
        fast, slow = entry
        return fast > max(self.LAT_FACTOR * slow, self.LAT_FLOOR_S)

    def report_failure(self, peer: str, hold_s: Optional[float] = None) -> None:
        """Direct connection-level failure evidence (refused dial, reset
        socket mid-RPC) — the tertiary suspicion signal. Heartbeats ride
        the DHT at multi-second cadence, so phi takes seconds to accrue on
        a peer that just dropped dead; a member that watched the peer's
        TCP connection die KNOWS, now. Holds the peer suspected for
        ``hold_s`` (default FAILURE_HOLD_S) regardless of phi, so successor
        election and formation pre-exclusion see the failure immediately.
        Cleared early by the next observed heartbeat (the peer healed)."""
        hold = self.FAILURE_HOLD_S if hold_s is None else float(hold_s)
        self._failed[peer] = self.clock() + hold

    def failure_reported(self, peer: str, now: Optional[float] = None) -> bool:
        """Is ``peer`` inside a reported-failure suspicion hold?"""
        expiry = self._failed.get(peer)
        if expiry is None:
            return False
        now = self.clock() if now is None else float(now)
        if now >= expiry:
            del self._failed[peer]
            return False
        return True

    def forget(self, peer: str) -> None:
        """Drop a peer's history (graceful leave / tombstone): a rejoiner
        starts with a clean distribution instead of inheriting the silence
        of its own absence as one giant inter-arrival sample."""
        self._last.pop(peer, None)
        self._gaps.pop(peer, None)
        self._lat.pop(peer, None)
        self._failed.pop(peer, None)

    # -- scoring -----------------------------------------------------------

    def phi(self, peer: str, now: Optional[float] = None) -> float:
        """Current suspicion for ``peer``; 0.0 for never-heard-from peers
        (no evidence either way — exclusion of unknowns is the caller's
        policy decision, not the detector's)."""
        last = self._last.get(peer)
        if last is None:
            return 0.0
        now = self.clock() if now is None else float(now)
        elapsed = now - last
        if elapsed <= 0:
            return 0.0
        gaps = self._gaps.get(peer)
        if gaps is None or len(gaps) < self.MIN_SAMPLES:
            mean, std = self.bootstrap_s, max(self.bootstrap_s / 2.0, self.min_std_s)
        else:
            n = len(gaps)
            mean = sum(gaps) / n
            var = sum((g - mean) ** 2 for g in gaps) / n
            std = max(math.sqrt(var), self.min_std_s)
        # Normal-model tail probability of a gap at least this long;
        # phi = -log10(P_later). erfc keeps precision in the far tail where
        # 1 - cdf would round to 0 (and phi to inf) around ~8 sigma.
        z = (elapsed - mean) / (std * math.sqrt(2.0))
        p_later = 0.5 * math.erfc(z)
        if p_later <= 0.0:
            return float("inf")
        return -math.log10(p_later)

    def suspect(self, peer: str, now: Optional[float] = None) -> bool:
        return (
            self.phi(peer, now) >= self.threshold
            or self.latency_suspect(peer)
            or self.failure_reported(peer, now)
        )

    def suspected(self, now: Optional[float] = None) -> Dict[str, float]:
        """{peer: phi} for every peer at/above the threshold right now."""
        now = self.clock() if now is None else float(now)
        out = {}
        for peer in list(self._last):
            p = self.phi(peer, now)
            if p >= self.threshold:
                out[peer] = p
        return out

    def snapshot(self) -> Dict[str, dict]:
        """Debug/metrics view: per-peer {phi, n_samples, mean_gap_s}."""
        now = self.clock()
        out = {}
        for peer in list(self._last):
            gaps = self._gaps.get(peer) or ()
            mean = sum(gaps) / len(gaps) if gaps else None
            lat = self._lat.get(peer)
            out[peer] = {
                "phi": round(self.phi(peer, now), 3),
                "n_samples": len(gaps),
                "mean_gap_s": round(mean, 4) if mean is not None else None,
                "lat_ewma_ms": round(lat[0] * 1e3, 3) if lat else None,
                "lat_suspect": self.latency_suspect(peer),
                "failure_reported": self.failure_reported(peer, now),
            }
        return out
