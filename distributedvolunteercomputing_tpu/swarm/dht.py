"""Kademlia-style DHT: decentralized peer discovery and rendezvous.

Reference parity: "coordinator/DHT peer-discovery on the host"
(BASELINE.json:5). The genre (SURVEY.md §0.2) uses the DHT for three things,
all supported here:

1. peer discovery — volunteers announce themselves under a shared key;
2. liveness — heartbeat records with TTL (absence == death);
3. matchmaking rendezvous — averaging groups form under round-scoped keys.

Design notes:
- 160-bit node ids, XOR metric, k-bucket routing table, iterative
  alpha-parallel lookups — standard Kademlia, sized down (k=8, alpha=3) for
  swarm scales the reference targets (4-ish volunteer slices, BASELINE.json:2).
- **Dict-valued keys**: every key holds a {subkey: (value, expiry)} map and
  STORE merges subkeys. Plain Kademlia can't enumerate "all peers"; the
  dict-value pattern makes membership listing one GET. (Same trick the
  hivemind lineage uses for its DHT records.)
- Values are small JSON blobs (addresses, step counts) — tensors NEVER go
  through the DHT; they ride Transport payloads peer-to-peer.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import random
import time
from typing import Dict, List, Optional, Set, Tuple

from distributedvolunteercomputing_tpu.swarm.transport import Addr, RPCError, Transport
from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

ID_BITS = 160
K = 8       # bucket size / replication factor
ALPHA = 3   # lookup parallelism


class StaleWriteFenced(RPCError):
    """A fenced store was rejected because a storage node holds a HIGHER
    generation watermark for the (key, subkey): the writer has been deposed
    (the control plane handed its key range to a newer replica). Carries the
    highest watermark seen so the writer can re-resolve ownership."""

    def __init__(self, key: str, subkey: str, gen: int):
        super().__init__(f"fenced: {key}/{subkey} watermark gen {gen}")
        self.key, self.subkey, self.gen = key, subkey, gen


def _sha1_int(data: bytes) -> int:
    return int.from_bytes(hashlib.sha1(data).digest(), "big")


def key_id(key: str) -> int:
    return _sha1_int(key.encode())


def keyspace_position(peer_id: str, salt: int = 0) -> int:
    """Deterministic 160-bit keyspace position for ``peer_id`` under
    ``salt``. The group schedule (swarm/matchmaking.py) partitions the
    swarm by cutting this keyspace into equal arcs and re-salting per
    rotation, so every volunteer computes the same grid from nothing but
    the peer id — no negotiation, no coordinator round-trip."""
    return _sha1_int(f"grid|{salt}|{peer_id}".encode())


def node_id_for(addr: Addr) -> int:
    return _sha1_int(f"{addr[0]}:{addr[1]}".encode())


class RoutingTable:
    """k-buckets by XOR-distance prefix; most-recently-seen wins."""

    def __init__(self, own_id: int):
        self.own_id = own_id
        self.buckets: List[List[Tuple[int, Addr]]] = [[] for _ in range(ID_BITS)]

    def _bucket_of(self, nid: int) -> int:
        d = nid ^ self.own_id
        return d.bit_length() - 1 if d else 0

    def add(self, nid: int, addr: Addr) -> Optional[Tuple[int, Addr]]:
        """Insert or touch (move to most-recently-seen).

        Returns ``None`` when the contact was inserted/refreshed, or the
        least-recently-seen (nid, addr) of the FULL bucket as an eviction
        CANDIDATE — the new contact is NOT inserted; the caller decides via
        ping-before-evict (DHTNode._add_contact). Blind LRS-drop would let
        churny newcomers evict stable long-lived nodes, the exact opposite
        of Kademlia's stability heuristic."""
        if nid == self.own_id:
            return None
        bucket = self.buckets[self._bucket_of(nid)]
        for i, (bid, _) in enumerate(bucket):
            if bid == nid:
                bucket.pop(i)
                bucket.append((nid, addr))
                return None
        if len(bucket) < K:
            bucket.append((nid, addr))
            return None
        return bucket[0]

    def replace(self, old_nid: int, nid: int, addr: Addr) -> None:
        """Evict ``old_nid`` and insert the pending contact in its place."""
        self.remove(old_nid)
        self.add(nid, addr)

    def remove(self, nid: int) -> None:
        bucket = self.buckets[self._bucket_of(nid)]
        self.buckets[self._bucket_of(nid)] = [(b, a) for b, a in bucket if b != nid]

    def closest(self, target: int, n: int = K) -> List[Tuple[int, Addr]]:
        allnodes = [na for bucket in self.buckets for na in bucket]
        allnodes.sort(key=lambda na: na[0] ^ target)
        return allnodes[:n]

    def __len__(self) -> int:
        return sum(len(b) for b in self.buckets)


class DHTNode:
    """One DHT participant bound to a Transport."""

    def __init__(self, transport: Transport, maintenance_interval: float = 15.0):
        self.transport = transport
        self.node_id: int = 0  # assigned at start() once the port is known
        self.table: Optional[RoutingTable] = None
        # key -> {subkey -> (json_value, expiry_monotonic)}
        self.storage: Dict[str, Dict[str, Tuple[str, float]]] = {}
        # Records THIS node stored via store(): republished to the (possibly
        # changed) k-closest set until their TTL runs out, so a record
        # survives its original replicas churning away. Value is
        # (json, expiry, fence_gen_or_None, fence_owner).
        self._owned: Dict[Tuple[str, str], Tuple[str, float, Optional[int], str]] = {}
        # Fencing watermarks for control-plane writes: (key, subkey) ->
        # (highest generation seen, its writer id, expiry). A store
        # carrying a LOWER generation is refused — the stale-replica-write
        # rejection the replicated control plane's shard handoff depends
        # on (same epoch+generation idea round leadership uses). An EQUAL
        # generation from a DIFFERENT writer is arbitrated by smallest
        # writer id (the election idiom): two replicas whose split views
        # both claimed gen g must converge on one writer, not flip-flop
        # silently forever. Kept well past the record's own TTL so a
        # deposed writer stays fenced across a gap.
        self._fence_gens: Dict[Tuple[str, str], Tuple[int, str, float]] = {}
        # Replica-set cache for stores: target -> (stamp, k-closest). A
        # periodic re-store of the SAME key (membership heartbeats every
        # ttl/3) was paying a full iterative lookup each time for an
        # answer that changes only on churn; within the TTL the cached set
        # is as fresh as the republish window already tolerated. Evicted
        # on any store failure to a cached replica (the churn signal).
        self._store_routes: Dict[int, Tuple[float, List[Tuple[int, Addr]]]] = {}
        self._last_sweep = time.monotonic()
        self.maintenance_interval = maintenance_interval
        self._maint_task: Optional[asyncio.Task] = None
        self._tasks: Set[asyncio.Task] = set()
        self._pinging: Set[int] = set()  # LRS nodes with a probe in flight
        transport.register("dht.ping", self._rpc_ping)
        transport.register("dht.store", self._rpc_store)
        transport.register("dht.find", self._rpc_find)

    def _sweep_storage(self, interval: float = 30.0) -> None:
        """Drop expired subkeys/keys (amortized on writes): long-lived nodes
        otherwise accumulate one dead record per averaging round forever."""
        now = time.monotonic()
        if now - self._last_sweep < interval:
            return
        self._last_sweep = now
        for key in list(self.storage):
            rec = {sk: ve for sk, ve in self.storage[key].items() if ve[1] > now}
            if rec:
                self.storage[key] = rec
            else:
                del self.storage[key]
        for ks in [ks for ks, (_, _, exp) in self._fence_gens.items() if exp <= now]:
            del self._fence_gens[ks]

    async def start(self, bootstrap: Optional[List[Addr]] = None) -> None:
        addr = self.transport.addr
        if self.transport._server is None:
            addr = await self.transport.start()
        self.node_id = node_id_for(addr)
        self.table = RoutingTable(self.node_id)
        for peer in bootstrap or []:
            try:
                ret, _ = await self.transport.call(
                    tuple(peer), "dht.ping", {"sender": self._self_info()}, timeout=5.0
                )
                self._add_contact(int(ret["id"]), tuple(ret["addr"]))
            except (RPCError, OSError, asyncio.TimeoutError) as e:
                log.warning("bootstrap peer %s unreachable: %s", peer, errstr(e))
        if bootstrap:
            # Standard Kademlia join: lookup own id to populate the table.
            await self._lookup(self.node_id)
        if self.maintenance_interval > 0:
            self._maint_task = asyncio.create_task(self._maintenance_loop())

    async def stop(self) -> None:
        """Cancel background maintenance (pings, refresh, republish)."""
        for task in [self._maint_task, *self._tasks]:
            if task is not None and not task.done():
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
        self._maint_task = None
        self._tasks.clear()

    def _self_info(self) -> dict:
        return {"id": str(self.node_id), "addr": list(self.transport.addr)}

    def _add_contact(self, nid: int, addr: Addr) -> None:
        """Routing-table insert with PING-BEFORE-EVICT: when the bucket is
        full, probe its least-recently-seen node; only a dead one is
        replaced (a live stable node beats an unknown newcomer)."""
        if self.table is None:
            return
        cand = self.table.add(nid, addr)
        if cand is None:
            return
        lrs_nid, lrs_addr = cand
        if lrs_nid in self._pinging:
            return  # probe already in flight; drop the newcomer for now
        self._pinging.add(lrs_nid)

        async def probe():
            try:
                try:
                    # Split budget: a dead LRS node usually fails at the dial
                    # (2s), leaving the RPC budget for peers that do accept.
                    await self.transport.call(
                        lrs_addr, "dht.ping", {"sender": self._self_info()},
                        timeout=3.0, connect_timeout=2.0,
                    )
                    self.table.add(lrs_nid, lrs_addr)  # alive: refresh to MRU
                except (RPCError, OSError, asyncio.TimeoutError):
                    self.table.replace(lrs_nid, nid, addr)
            finally:
                self._pinging.discard(lrs_nid)

        task = asyncio.create_task(probe())
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _note_sender(self, args: dict) -> None:
        sender = args.get("sender")
        if sender and self.table is not None:
            self._add_contact(int(sender["id"]), tuple(sender["addr"]))

    # -- RPC handlers ------------------------------------------------------

    async def _rpc_ping(self, args: dict, payload: bytes) -> Tuple[dict, bytes]:
        self._note_sender(args)
        return {"id": str(self.node_id), "addr": list(self.transport.addr)}, b""

    FENCE_TTL = 600.0

    def _store_local(
        self, key: str, subkey: str, value_json: str, ttl: float,
        fence: Optional[int] = None, fence_owner: str = "",
    ) -> Optional[int]:
        """Apply one subkey store to local storage, honoring the fence
        watermark. Returns None on success, or the blocking watermark
        generation when the write is stale-fenced (NOT applied). A write
        at the CURRENT generation from a different owner is accepted only
        from a SMALLER owner id (deterministic tiebreak for two replicas
        whose split views claimed the same generation — the larger id is
        fenced and escalates, instead of both flip-flopping the record
        silently)."""
        now = time.monotonic()
        if fence is not None:
            cur = self._fence_gens.get((key, subkey))
            if cur is not None and cur[2] > now:
                cur_gen, cur_owner, _ = cur
                if cur_gen > fence or (
                    cur_gen == fence
                    and fence_owner
                    and cur_owner
                    and fence_owner > cur_owner
                ):
                    return cur_gen
            self._fence_gens[(key, subkey)] = (
                int(fence), fence_owner, now + max(self.FENCE_TTL, ttl)
            )
        rec = self.storage.setdefault(key, {})
        rec[subkey] = (value_json, now + ttl)
        return None

    async def _rpc_store(self, args: dict, payload: bytes) -> Tuple[dict, bytes]:
        """Single-subkey store, or a BATCHED one: ``values`` maps subkey ->
        [json, ttl] so one RPC can carry a whole membership shard's records
        (the control plane's heartbeat coalescing — N peers' beats cross as
        one frame per storage replica instead of N)."""
        self._note_sender(args)
        self._sweep_storage()
        key = args["key"]
        fence = args.get("fence")
        fence = int(fence) if fence is not None else None
        fence_owner = str(args.get("fence_owner") or "")
        values = args.get("values")
        if values is None:
            values = {args.get("subkey", ""): [args["value"], float(args.get("ttl", 60.0))]}
        blocked = None
        for sk, (value_json, ttl) in values.items():
            w = self._store_local(
                key, sk, value_json, float(ttl),
                fence=fence, fence_owner=fence_owner,
            )
            if w is not None:
                blocked = max(blocked or 0, w)
        if blocked is not None:
            return {"ok": False, "fenced": True, "gen": blocked}, b""
        return {"ok": True}, b""

    async def _rpc_find(self, args: dict, payload: bytes) -> Tuple[dict, bytes]:
        """FIND_VALUE + FIND_NODE in one: returns value (if any) and closer nodes."""
        self._note_sender(args)
        target = int(args["target"])
        out: dict = {"nodes": [[str(nid), list(a)] for nid, a in self.table.closest(target)]}
        key = args.get("key")
        if key is not None and key in self.storage:
            now = time.monotonic()
            live = {
                sk: (v, exp - now)
                for sk, (v, exp) in self.storage[key].items()
                if exp > now
            }
            if live:
                out["value"] = {sk: [v, ttl] for sk, (v, ttl) in live.items()}
        return out, b""

    # -- iterative lookup --------------------------------------------------

    async def _lookup(
        self, target: int, key: Optional[str] = None
    ) -> Tuple[List[Tuple[int, Addr]], Dict[str, Tuple[str, float]]]:
        """Iterative Kademlia lookup. Returns (k closest nodes, merged values)."""
        assert self.table is not None
        shortlist: Dict[int, Addr] = dict(self.table.closest(target, K))
        queried: set = set()
        found_values: Dict[str, Tuple[str, float]] = {}

        while True:
            candidates = sorted(
                (nid for nid in shortlist if nid not in queried), key=lambda n: n ^ target
            )[:ALPHA]
            if not candidates:
                break

            async def query(nid: int):
                try:
                    ret, _ = await self.transport.call(
                        shortlist[nid],
                        "dht.find",
                        {"target": str(target), "key": key, "sender": self._self_info()},
                        timeout=5.0,
                    )
                    return nid, ret
                except (RPCError, OSError, asyncio.TimeoutError):
                    return nid, None

            results = await asyncio.gather(*(query(nid) for nid in candidates))
            for nid, ret in results:
                queried.add(nid)
                if ret is None:
                    self.table.remove(nid)
                    shortlist.pop(nid, None)
                    continue
                self._add_contact(nid, shortlist[nid])
                for nid_s, addr in ret.get("nodes", []):
                    n = int(nid_s)
                    if n != self.node_id and n not in queried:
                        shortlist.setdefault(n, tuple(addr))
                for sk, (v, ttl) in ret.get("value", {}).items():
                    # freshest record per subkey wins
                    if sk not in found_values or found_values[sk][1] < ttl:
                        found_values[sk] = (v, ttl)

        closest = sorted(shortlist.items(), key=lambda na: na[0] ^ target)[:K]
        return closest, found_values

    # -- maintenance (refresh / republish) ---------------------------------

    async def _maintenance_loop(self) -> None:
        """Periodic table refresh + owned-record republish.

        Refresh: look up a random id in a random non-empty bucket's range
        (plus the node's own id), so stale buckets relearn the topology and
        dead contacts get pruned even when the application is idle.
        Republish: push every still-live owned record to the CURRENT
        k-closest set — nodes that joined closer to the key since the
        original store get a replica; without this, a rolling restart of the
        original replica set silently loses live records."""
        while True:
            await asyncio.sleep(self.maintenance_interval)
            try:
                await self._republish_owned()
                await self._refresh_bucket()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — maintenance must not die
                log.debug("dht maintenance iteration failed: %s", errstr(e))

    async def _republish_owned(self) -> None:
        now = time.monotonic()
        for (key, subkey) in list(self._owned):
            value_json, expiry, fence, fence_owner = self._owned[(key, subkey)]
            if expiry <= now:
                del self._owned[(key, subkey)]
                continue
            # Remaining ttl, not the original: republish must never extend a
            # record's life beyond what its owner asked for.
            try:
                await self._store_raw(
                    key, subkey, value_json, expiry - now,
                    fence=fence, fence_owner=fence_owner,
                )
            except StaleWriteFenced:
                # Deposed mid-life: a newer generation owns this record now;
                # republishing it would be exactly the stale write the fence
                # exists to reject. Drop ownership.
                del self._owned[(key, subkey)]

    async def _refresh_bucket(self) -> None:
        nonempty = [i for i, b in enumerate(self.table.buckets) if b]
        if not nonempty:
            return
        i = random.choice(nonempty)
        # A random id at XOR-distance with highest bit i from ourselves.
        rand = random.getrandbits(i) | (1 << i) if i else 1
        await self._lookup(self.node_id ^ rand)

    # -- public API --------------------------------------------------------

    STORE_ROUTE_TTL = 15.0
    MAX_STORE_ROUTES = 64

    async def _store_raw(
        self,
        key: str,
        subkey: str,
        value_json: str,
        ttl: float,
        fence: Optional[int] = None,
        fence_owner: str = "",
        batch: Optional[Dict[str, Tuple[str, float]]] = None,
    ) -> int:
        """Fan one store (or a ``batch`` of subkeys in ONE RPC per storage
        replica) to the k-closest set. Raises StaleWriteFenced when any
        replica (or the local store) holds a higher fence watermark."""
        target = key_id(key)
        now = time.monotonic()
        cached = self._store_routes.get(target)
        if cached is not None and now - cached[0] <= self.STORE_ROUTE_TTL:
            closest = cached[1]
        else:
            closest, _ = await self._lookup(target)
            if len(self._store_routes) >= self.MAX_STORE_ROUTES:
                self._store_routes.pop(next(iter(self._store_routes)))
            self._store_routes[target] = (now, closest)
        entries = batch if batch is not None else {subkey: (value_json, ttl)}

        def _legacy_args(sk: str, vj: str, t: float) -> dict:
            # The pre-batching wire shape every storage-node version
            # understands.
            args = {
                "key": key, "subkey": sk, "value": vj, "ttl": t,
                "sender": self._self_info(),
            }
            if fence is not None:
                args["fence"] = int(fence)
                if fence_owner:
                    args["fence_owner"] = fence_owner
            return args

        if batch is not None:
            payload_args: dict = {
                "key": key,
                "values": {sk: [vj, t] for sk, (vj, t) in entries.items()},
                "sender": self._self_info(),
            }
            if fence is not None:
                payload_args["fence"] = int(fence)
                if fence_owner:
                    payload_args["fence_owner"] = fence_owner
        else:
            # Single-subkey stores keep the legacy wire shape outright: a
            # storage node one version behind (no ``values`` support) must
            # keep accepting ordinary membership/rendezvous stores from
            # upgraded peers.
            payload_args = _legacy_args(subkey, value_json, ttl)
        # Always keep a local replica too: tiny swarms (N < K) stay robust.
        fenced_gen: Optional[int] = None
        local_blocked: Optional[int] = None
        for sk, (vj, t) in entries.items():
            w = self._store_local(key, sk, vj, t, fence=fence, fence_owner=fence_owner)
            if w is not None:
                local_blocked = max(local_blocked or 0, w)
        if local_blocked is not None:
            # Our own storage already holds a higher watermark: the write
            # is KNOWN stale — fanning it out would waste K RPCs and seed
            # laggard replicas with bytes whose rejection is foregone.
            raise StaleWriteFenced(key, subkey, local_blocked)
        ok = 1
        for nid, addr in closest:
            try:
                try:
                    ret, _ = await self.transport.call(
                        addr, "dht.store", payload_args, timeout=5.0
                    )
                except RPCError:
                    if batch is None:
                        raise
                    # A storage node one version behind chokes on the
                    # batched ``values`` shape (its handler KeyErrors on
                    # args["value"]): it is alive, just old — replay the
                    # batch as individual legacy frames instead of
                    # misreading the version skew as death and evicting a
                    # healthy node from the table every flush.
                    ret = {"ok": True}
                    for sk, (vj, t) in entries.items():
                        r1, _ = await self.transport.call(
                            addr, "dht.store", _legacy_args(sk, vj, t),
                            timeout=5.0,
                        )
                        if r1.get("fenced"):
                            ret = r1
                if ret.get("fenced"):
                    fenced_gen = max(fenced_gen or 0, int(ret.get("gen", 0)))
                else:
                    ok += 1
            except (RPCError, OSError, asyncio.TimeoutError):
                self.table.remove(nid)
                # A cached replica died: next store re-walks the keyspace.
                self._store_routes.pop(target, None)
        if fenced_gen is not None:
            # Any higher watermark means a newer generation owns this key
            # range — the caller must stop writing and re-resolve, even if
            # some laggard replicas accepted the stale bytes (status
            # merges break the tie by generation, see control_plane).
            raise StaleWriteFenced(key, subkey, fenced_gen)
        return ok

    async def store(
        self,
        key: str,
        value: object,
        subkey: str = "",
        ttl: float = 60.0,
        fence: Optional[int] = None,
        fence_owner: str = "",
    ) -> int:
        """Store (replicated to the K closest nodes incl. possibly self).
        Owned records are republished to the current closest set until their
        TTL expires (see _maintenance_loop). ``fence`` attaches a generation
        watermark: storage nodes refuse stores whose generation is below the
        highest they have seen for the (key, subkey) — StaleWriteFenced —
        the control plane's stale-replica-write rejection. ``fence_owner``
        (the writer's id) arbitrates EQUAL generations: smallest id wins,
        so two writers whose split views claimed the same generation
        resolve deterministically instead of flip-flopping the record."""
        self._sweep_storage()
        value_json = json.dumps(value)
        self._owned[(key, subkey)] = (
            value_json, time.monotonic() + ttl, fence, fence_owner
        )
        try:
            return await self._store_raw(
                key, subkey, value_json, ttl, fence=fence, fence_owner=fence_owner
            )
        except StaleWriteFenced:
            self._owned.pop((key, subkey), None)
            raise

    async def store_many(
        self,
        key: str,
        values: Dict[str, object],
        ttl: float = 60.0,
        ttls: Optional[Dict[str, float]] = None,
        fence: Optional[int] = None,
        fence_owner: str = "",
    ) -> int:
        """Batched store: ALL subkeys of ``values`` cross in ONE dht.store
        RPC per storage replica (the dict-valued-key merge makes this
        natural). The control plane's heartbeat coalescing: a replica
        flushes a whole membership shard per interval as one frame instead
        of one RPC per peer. NOT registered as owned — callers re-send at
        their own cadence."""
        if not values:
            return 0
        self._sweep_storage()
        batch = {
            sk: (json.dumps(v), float((ttls or {}).get(sk, ttl)))
            for sk, v in values.items()
        }
        return await self._store_raw(
            key, "", "", 0.0, fence=fence, fence_owner=fence_owner, batch=batch
        )

    async def get(self, key: str) -> Dict[str, object]:
        """All live subkeys of ``key``, merged across replicas."""
        target = key_id(key)
        now = time.monotonic()
        local = {
            sk: (v, exp - now)
            for sk, (v, exp) in self.storage.get(key, {}).items()
            if exp > now
        }
        _, remote = await self._lookup(target, key=key)
        merged = dict(local)
        for sk, (v, ttl) in remote.items():
            if sk not in merged or merged[sk][1] < ttl:
                merged[sk] = (v, ttl)
        return {sk: json.loads(v) for sk, (v, _) in merged.items()}

    async def get_value(self, key: str, default: object = None) -> object:
        rec = await self.get(key)
        return rec.get("", default)
