"""Swarm membership: join/leave, heartbeat liveness, churn handling.

Reference parity (BASELINE.json:5): "a heartbeat and a join/leave handler"
adapted to TPU-VM volunteers — on TPU the dominant churn source is VM
PREEMPTION, so leave() is wired to SIGTERM (the preemption notice) as well as
normal shutdown (see swarm.volunteer).

Liveness is soft-state: each volunteer re-announces itself under the shared
``peers`` DHT key with a TTL; death == record expiry. Nobody has to observe a
crash — a kill -9'd volunteer vanishes from ``alive_peers()`` within one TTL
(SURVEY.md §3-E).

On top of the binary TTL, membership can feed a phi-accrual failure
detector (swarm/failure_detector.py): every time a peer's record timestamp
CHANGES between observations, that is one heartbeat arrival, and the
detector learns the peer's inter-arrival distribution. The TTL stays the
hard death line; phi is the earlier, continuous "probably stalled" signal
the matchmaker and resilience policy consult to pre-exclude stragglers
from rounds seconds before the record would expire.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional

from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

PEERS_KEY = "peers"


class SwarmMembership:
    def __init__(
        self,
        dht: DHTNode,
        peer_id: str,
        ttl: float = 15.0,
        extra_info: Optional[dict] = None,
        failure_detector=None,
        bandwidth_source=None,
        control_plane=None,
        report_source=None,
        telemetry=None,
    ):
        self.dht = dht
        self.peer_id = peer_id
        self.ttl = ttl
        self.extra_info = extra_info or {}
        self.failure_detector = failure_detector
        # Replicated-control-plane client (swarm/control_plane.py): when
        # attached AND a live replica set is discovered, each heartbeat
        # interval coalesces announce + metrics report + peers-snapshot
        # refresh into ONE cp.exchange RPC to this peer's shard-owner
        # replica (vs a K-replica DHT store fan-out plus an iterative
        # lookup). Pure accelerator: any failure falls back to the direct
        # DHT path the same beat, so the record never gaps.
        self.control_plane = control_plane
        # Callable returning this volunteer's metrics report (the old
        # coord.report payload) to piggyback on batched beats; None = the
        # beat carries membership only.
        self.report_source = report_source
        # Message accounting per beat (transport RPC deltas — the honest
        # counter): proves the batching claim in stats().
        self.beats = 0
        self.batched_beats = 0
        self.direct_beats = 0
        # Whether the MOST RECENT beat went through a replica: consumers
        # deciding "is my report already riding the exchange" must read
        # this, not the cumulative counter — a volunteer that can see
        # replica records but cannot dial the replicas falls back to
        # direct beats (which carry no report) for the rest of its life,
        # and its metrics must flow through the legacy path again.
        self.last_beat_batched = False
        self.msgs_last_beat = 0
        self._msgs_ewma: Optional[float] = None
        # Telemetry plane (swarm/telemetry.py): per-beat control traffic
        # lands in the unified registry — beats and messages as labeled
        # counters (msgs_total/beats_total = the live mean the batching
        # claim rides on; the registry's histograms keep duration-scaled
        # buckets, so a message COUNT belongs in a counter, not there).
        # The volunteer report's telemetry SUMMARY rides the batched
        # exchange itself via report_source; this is the beat-side half.
        self._beat_ctr = self._beat_msgs_ctr = None
        if telemetry is not None and getattr(telemetry, "enabled", False):
            self._beat_ctr = telemetry.registry.counter(
                "swarm.beats_total", "heartbeat intervals by path"
            )
            self._beat_msgs_ctr = telemetry.registry.counter(
                "swarm.beat_msgs_total", "control messages spent across beats"
            )
        # Callable returning this node's measured-bandwidth advertisement
        # fields (Transport.bandwidth_advertisement: {"bw_up": bps,
        # "bw_down": bps}, {} when nothing fresh) — re-evaluated on EVERY
        # announce, so the advertisement refreshes with each heartbeat and
        # a stale estimate ages out of the record rather than lingering.
        # Consumers (the hierarchical group schedule's bandwidth-weighted
        # leader election) treat absent fields as "no advertisement".
        self.bandwidth_source = bandwidth_source
        # Last announce-timestamp seen per peer: a new heartbeat is a CHANGED
        # record ``t``, so observation cadence (who calls alive_peers, how
        # often) can't fabricate arrivals out of re-reads of the same record.
        self._seen_beats: dict = {}
        self._heartbeat_task: Optional[asyncio.Task] = None
        self._left = False
        # Sticky addr -> zone attribution (zone_by_addr): once a peer has
        # advertised a zone from an address, the mapping OUTLIVES its
        # membership record. Consumers sum cumulative transport byte
        # counters against this map (Averager.zone_traffic, rolled into
        # the coordinator's cross_zone_bytes_per_commit as windowed
        # deltas), so the attribution must be as monotone as the counters:
        # a peer missing one heartbeat must not subtract its lifetime
        # bytes from the sum and re-add them as a phantom burst when the
        # record reappears. Bounded (addresses are one-per-process).
        self._zone_cache: Dict[tuple, str] = {}
        # Last live read of the peers key, for alive_peers(max_age=...):
        # consumers on a round's critical path (the group schedule's
        # per-round split) accept a view one heartbeat old instead of
        # paying an iterative DHT lookup per round. Set keep_snapshot_fresh
        # to make the heartbeat loop refresh it even without a failure
        # detector attached.
        self._snapshot: Optional[Dict[str, dict]] = None
        self._snapshot_t = 0.0
        self.keep_snapshot_fresh = False

    def _record(self) -> dict:
        rec = {
            "addr": list(self.dht.transport.addr),
            "t": time.time(),
            **self.extra_info,
        }
        if self.bandwidth_source is not None:
            try:
                rec.update(self.bandwidth_source() or {})
            except Exception as e:  # noqa: BLE001 — advertisement is advisory
                log.debug("bandwidth advertisement failed: %s", errstr(e))
        return rec

    async def join(self) -> None:
        """Announce and start heartbeating. The direct DHT store runs
        unconditionally (a join must be durable even if every control-plane
        replica is mid-churn); with a control plane attached, a best-effort
        join exchange additionally registers us with our shard owner and
        seeds the first peers snapshot in the same round trip."""
        self._left = False
        await self.dht.store(PEERS_KEY, self._record(), subkey=self.peer_id, ttl=self.ttl)
        cp = self.control_plane
        if cp is not None:
            try:
                await cp.refresh()
                if cp.has_replicas:
                    ret = await cp.exchange(
                        self._record(), ttl=self.ttl, join=True,
                        report=self._build_report(),
                    )
                    if ret is not None:
                        self._adopt_records(self._reply_peers(cp, ret))
            except Exception as e:  # noqa: BLE001 — join exchange is best-effort
                log.debug("join exchange failed: %s", errstr(e))
        if self._heartbeat_task is None:
            self._heartbeat_task = asyncio.create_task(self._heartbeat_loop())
        log.info("peer %s joined swarm", self.peer_id)

    async def leave(self) -> None:
        """Graceful leave: tombstone the record (preemption path calls this).
        With a control plane, the tombstone also rides one exchange so the
        shard owner's served snapshots drop us immediately instead of after
        our last batched record expires."""
        self._left = True
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            self._heartbeat_task = None
        await self.dht.store(PEERS_KEY, None, subkey=self.peer_id, ttl=self.ttl)
        cp = self.control_plane
        if cp is not None and cp.has_replicas:
            try:
                await cp.exchange(None, ttl=self.ttl)
            except Exception:
                pass
        log.info("peer %s left swarm", self.peer_id)

    @staticmethod
    def _reply_peers(cp, ret: dict) -> dict:
        """The peers snapshot out of an exchange reply: resolved through
        the client's delta cache when it has one (replies may carry
        changes-since-version instead of the full map), with the legacy
        full-map shape as the fallback for older clients in tests."""
        merge = getattr(cp, "merge_peers_reply", None)
        if merge is not None:
            return merge(ret)
        return dict(ret.get("peers") or {})

    def _build_report(self) -> Optional[dict]:
        if self.report_source is None:
            return None
        try:
            return self.report_source()
        except Exception as e:  # noqa: BLE001 — a gauge bug must not kill beats
            log.debug("report source failed: %s", errstr(e))
            return None

    async def _beat_once(self) -> None:
        """One heartbeat interval's control traffic. Batched path first
        (one coalesced cp.exchange carrying announce + report, returning
        the peers snapshot + replica set); ANY failure — no replicas
        known, all reachable replicas dead, an RPC error — falls back to
        the direct DHT announce the same beat, so a control-plane outage
        can neither expire our record nor stall this loop (the client's
        calls are fast-fail with bounded AIMD backoff per replica)."""
        transport = self.dht.transport
        rpcs0 = transport.rpcs_sent
        batched = False
        cp = self.control_plane
        if cp is not None and not self._left:
            try:
                if not cp.has_replicas:
                    # Discovery (TTL'd): one DHT read, only while we know
                    # of no live replica — steady-state batched beats learn
                    # the set from exchange replies for free.
                    await cp.refresh()
                if cp.has_replicas:
                    ret = await cp.exchange(
                        self._record(), ttl=self.ttl,
                        report=self._build_report(),
                    )
                    if ret is None:
                        # Every replica this client knew refused/died. Its
                        # view can be corpse-heavy under replica churn
                        # (reply-confirmed sets lag fresh spawns by one
                        # serving-replica tick): re-discover from the DHT
                        # — the authoritative live set, fresh replicas
                        # announce there first — and retry ONCE within the
                        # same beat, so a kill-plus-replace costs zero
                        # batched beats instead of one.
                        await cp.refresh(force=True)
                        if cp.has_replicas:
                            ret = await cp.exchange(
                                self._record(), ttl=self.ttl,
                                report=self._build_report(),
                            )
                    if ret is not None:
                        self._adopt_records(self._reply_peers(cp, ret))
                        batched = True
            except Exception as e:  # noqa: BLE001 — exchange is an accelerator
                log.debug("batched beat failed: %s", errstr(e))
        if not batched:
            await self.dht.store(
                PEERS_KEY, self._record(), subkey=self.peer_id, ttl=self.ttl
            )
            if self.failure_detector is not None or self.keep_snapshot_fresh:
                # Piggyback one observation pass per own beat: the
                # detector keeps accruing even when nothing else on
                # this node happens to call alive_peers (an idle
                # trainer between wall-clock cadence boundaries),
                # and the snapshot stays one-beat fresh for
                # max_age readers.
                await self.alive_peers()
        self.beats += 1
        self.last_beat_batched = batched
        if batched:
            self.batched_beats += 1
            # Exact: the client's own attempt count for THIS exchange (1 +
            # failover tries). A transport-global counter delta would bill
            # whatever averaging-round RPCs happened to be in flight across
            # the exchange's await to the beat.
            self.msgs_last_beat = max(cp.last_call_attempts, 1)
        else:
            self.direct_beats += 1
            # Transport delta: the direct path's store fan-out + snapshot
            # lookup all issue from this coroutine, so the delta is the
            # beat's own traffic up to concurrent-round noise (an upper
            # bound; exactness matters for the batched number above, which
            # is the one the batching claim rides on).
            self.msgs_last_beat = transport.rpcs_sent - rpcs0
        a = 0.2
        self._msgs_ewma = (
            float(self.msgs_last_beat)
            if self._msgs_ewma is None
            else (1 - a) * self._msgs_ewma + a * self.msgs_last_beat
        )
        if self._beat_ctr is not None:
            path = "batched" if batched else "direct"
            self._beat_ctr.inc(path=path)
            self._beat_msgs_ctr.inc(float(self.msgs_last_beat), path=path)

    async def _heartbeat_loop(self) -> None:
        # Re-announce at TTL/3: two missed beats still leave the record live.
        try:
            while not self._left:
                await asyncio.sleep(self.ttl / 3.0)
                try:
                    await self._beat_once()
                except Exception as e:
                    log.warning("heartbeat store failed: %s", errstr(e))
        except asyncio.CancelledError:
            pass

    def stats(self) -> dict:
        """Control-traffic accounting: RPC messages this node spent per
        heartbeat interval (transport-counter deltas, so DHT store fan-out
        and lookups are all counted) — the number the batched control
        plane exists to shrink (one coalesced exchange vs ~K store RPCs +
        a lookup per beat)."""
        out = {
            "mode": "batched" if self.batched_beats > self.direct_beats else "direct",
            "beats": self.beats,
            "batched_beats": self.batched_beats,
            "direct_beats": self.direct_beats,
            "msgs_last_beat": self.msgs_last_beat,
            "msgs_per_interval_ewma": (
                round(self._msgs_ewma, 2) if self._msgs_ewma is not None else None
            ),
        }
        if self.control_plane is not None:
            out["client"] = self.control_plane.stats()
        return out

    def _observe_beats(self, records: Dict[str, dict]) -> None:
        """Feed the phi-accrual detector: a peer whose announce timestamp
        changed since the last observation produced one heartbeat arrival
        (stamped at the LOCAL monotonic clock — sender timestamps are only
        compared for change, never trusted as times)."""
        fd = self.failure_detector
        if fd is None:
            return
        transport = self.dht.transport
        for pid, rec in records.items():
            if pid == self.peer_id:
                continue
            t = rec.get("t")
            if isinstance(t, (int, float)) and self._seen_beats.get(pid) != t:
                self._seen_beats[pid] = t
                fd.heartbeat(pid)
            # Secondary signal: the pooled transport's per-peer RPC latency
            # EWMA, mapped from the record's advertised address to the peer
            # id here (the one place both are known). Heartbeats ride the
            # DHT at a multi-second cadence; the RPC latency notices a
            # congested/paging peer rounds earlier.
            addr = rec.get("addr")
            if isinstance(addr, (list, tuple)) and len(addr) == 2:
                lat = transport.peer_latency(addr)
                if lat is not None:
                    fd.observe_latency(pid, lat)

    async def alive_peers(
        self,
        include_self: bool = True,
        exclude_suspected: bool = False,
        max_age: float = 0.0,
    ) -> Dict[str, dict]:
        """Live peer_id -> record; tombstones (None) are filtered out.

        ``exclude_suspected`` additionally drops peers the phi-accrual
        detector currently suspects — the soft pre-exclusion consumers like
        gossip partner selection opt into (the hard TTL filter always
        applies).

        ``max_age`` > 0 accepts a cached view at most that old instead of
        walking the DHT — for per-round consumers (the group schedule's
        split) where one heartbeat interval of staleness only ever costs
        an underfilled formation, never correctness. Detector bookkeeping
        runs on live reads only (a cache re-read carries no new beats)."""
        if (
            max_age > 0
            and self._snapshot is not None
            and time.monotonic() - self._snapshot_t <= max_age
        ):
            out = dict(self._snapshot)
            if self.failure_detector is not None and exclude_suspected:
                out = {
                    pid: info
                    for pid, info in out.items()
                    if pid == self.peer_id
                    or not self.failure_detector.suspect(pid)
                }
            if not include_self:
                out.pop(self.peer_id, None)
            return out
        rec = await self.dht.get(PEERS_KEY)
        out = self._adopt_records(rec)
        if self.failure_detector is not None:
            if exclude_suspected:
                out = {
                    pid: info
                    for pid, info in out.items()
                    if pid == self.peer_id or not self.failure_detector.suspect(pid)
                }
        if not include_self:
            out.pop(self.peer_id, None)
        return out

    def _adopt_records(self, rec: Dict[str, Optional[dict]]) -> Dict[str, dict]:
        """Adopt one live view of the peers key (a DHT read, or a batched
        exchange reply's snapshot): filter tombstones, refresh the cached
        snapshot, feed the failure detector, and forget departed peers so
        they stop accruing suspicion. Returns the live records."""
        out = {pid: info for pid, info in rec.items() if info is not None}
        self._snapshot = dict(out)
        self._snapshot_t = time.monotonic()
        self._observe_beats(out)
        if self.failure_detector is not None:
            # A tombstoned/expired peer must not keep accruing silence as
            # suspicion — its next join starts with a clean history.
            for pid in [p for p in self._seen_beats if p not in out]:
                self._seen_beats.pop(pid, None)
                self.failure_detector.forget(pid)
        return out

    def peer_record(self, peer_id: str) -> Optional[dict]:
        """The cached membership record for ``peer_id`` (our own record
        included), from the last live read — NO DHT walk, so it is safe on
        a round's critical path. None before the first read or for an
        unknown peer. The hierarchical schedule and the bandwidth-weighted
        leader election read zones/bandwidth advertisements through this:
        every member consults the same soft state, so their choices agree
        up to one heartbeat of staleness (divergence costs an underfilled
        round via begin-wins, never mixed tensors)."""
        if peer_id == self.peer_id and (
            self._snapshot is None or peer_id not in self._snapshot
        ):
            return self._record()
        if self._snapshot is None:
            return None
        return self._snapshot.get(peer_id)

    MAX_ZONE_CACHE = 4096

    def zone_by_addr(self) -> Dict[tuple, str]:
        """Advertised zone per peer ADDRESS — the join key for charging
        the transport's per-peer byte counters to zones (the transport
        knows addresses, membership knows zones; this is where both are
        known). STICKY: entries learned from any snapshot persist after
        the record expires, so byte sums over cumulative counters stay
        monotone through heartbeat churn (a one-beat record gap must not
        read as the peer's lifetime traffic vanishing and reappearing)."""
        cache = self._zone_cache
        for rec in (self._snapshot or {}).values():
            addr = rec.get("addr")
            if isinstance(addr, (list, tuple)) and len(addr) == 2:
                key = (str(addr[0]), int(addr[1]))
                zone = str(rec.get("zone") or "")
                if not zone and cache.get(key):
                    # Never downgrade a zoned attribution to "": a
                    # restarted (or zone-stripped) peer on a known address
                    # would flip that address's historical bytes from
                    # cross to intra (or back) and dip the cumulative sum.
                    # A real zone CHANGE (zone -> other zone) still lands.
                    continue
                if key not in cache and len(cache) >= self.MAX_ZONE_CACHE:
                    cache.clear()  # churn far beyond any real swarm; reset
                cache[key] = zone
        return dict(cache)

    def invalidate_snapshot(self) -> None:
        """Force the next ``alive_peers(max_age=...)`` to walk the DHT.
        Called by consumers whose operation FAILED in a way stale
        membership explains (a scheduled group that never formed): the
        cheap view was wrong, buy a fresh one."""
        self._snapshot = None

    def update_info(self, **kv: object) -> None:
        """Update fields (e.g. current step) carried in the next heartbeat."""
        self.extra_info.update(kv)
