"""DCN transport: the WAN tensor/RPC fabric between volunteer slices.

TPU-native replacement for the reference's gloo/NCCL WAN path
(BASELINE.json:5): intra-slice collectives ride ICI inside ``pjit`` and never
touch this layer; everything BETWEEN volunteer slices — DHT RPCs, gossip,
butterfly rounds, robust aggregation — crosses here.

Design:
- asyncio TCP, length-prefixed binary frames; JSON meta + raw tensor payload
  (a param pytree crosses as ONE contiguous buffer from utils.pytree).
- CRC32-guarded payloads: WAN volunteers are untrusted/lossy, and the
  Byzantine path (config 5) must distinguish corruption from malice.
- **Persistent multiplexed connections**: one long-lived connection per
  dialed peer, shared by every in-flight RPC to that peer and demultiplexed
  by the request's ``rid``. Every heartbeat, DHT ping, clock probe, and
  averaging contribution used to pay a fresh TCP handshake + slow-start
  (the WAN tier's dominant fixed cost per the Moshpit/OptiReduce genre);
  now only the FIRST call to a peer does. A broken or idle-closed pooled
  socket is redialed transparently — the failed call is retried exactly
  once on a fresh connection (fresh rid, fresh MAC), so a peer restart
  looks like one slightly slower call, never an error surfaced to the
  averager. The server half handles requests CONCURRENTLY per connection
  (bounded in-flight), so a parked handler (e.g. a member's fetch awaiting
  the round result) cannot head-of-line-block heartbeats sharing the pipe.
- **Chunked payload streaming**: payloads above ``chunk_bytes`` cross as a
  header frame (meta declares the chunk count) followed by bounded chunk
  frames, each with its own CRC32. A multi-MB contribution no longer forces
  one giant allocation or a single monolithic write; the receiver
  assembles into ONE preallocated buffer (no join copy), enforces size
  caps incrementally, and can hand verified chunks to a ``chunk_sink`` so
  decode starts on the FIRST chunk instead of after the last. Senders may
  pass a ``StreamPayload`` whose chunks are produced (encoded) lazily on a
  worker thread while earlier chunks are already on the wire — encode/send
  overlap for the averaging tier (see AveragerBase._wire_stream). A bad
  chunk CRC or out-of-order chunk index is rejected with an attributable
  error frame WITHOUT dropping the connection (the explicit per-chunk
  lengths keep the stream in sync); only unparseable framing (bad magic,
  absurd lengths) kills the connection.
- Per-peer counters (bytes in/out, RPC count, connect count, latency EWMA)
  feed ``stats()``/`coord.status`` and the phi-accrual failure detector's
  secondary latency signal (swarm/membership.py).
- Timeout split: ``connect_timeout`` bounds the dial, ``timeout`` bounds
  the RPC itself (request write -> response). One slow dial can no longer
  eat the whole per-call budget the way the old combined wait_for did.
- The native C++ core (native/) accelerates checksum + quantization of the
  payload bytes; the socket path stays asyncio.
- Optional shared-secret message authentication (``secret=``): every frame
  carries an HMAC-SHA256 over (frame type, canonical meta, payload) plus a
  timestamp bounded by ``auth_window``. One chokepoint covers the whole
  swarm tier — DHT records, membership, state sync, and averaging
  contributions all cross this transport, so identity spoofing (which the
  Byzantine first-write-wins rule implicitly trusts) requires the secret,
  not just an open port. Replay is closed at this layer too, on two axes:
  SAME-NODE replay — every request carries a fresh uuid ``rid`` inside the
  MAC'd meta, so legitimate request frames are never byte-identical, and
  the server rejects an already-accepted MAC within the auth window;
  CROSS-NODE replay — the MAC also binds ``dst`` (the address the caller
  dialed), so a frame captured on its way to node X is refused by node Y
  (a captured membership heartbeat or DHT announce can NOT be re-played
  anywhere to keep a departed peer alive). Authenticated swarms must
  therefore dial peers at their advertised addresses — which every code
  path does (addresses always come from DHT/membership records).
  Responses are bound to their request by the MAC'd echoed ``rid``; the
  demultiplexer resolves exactly the pending call with that rid and
  discards unknown rids, so a replayed or stale response frame can never
  complete a different call (rids are fresh uuids, never reused).
  CHUNKED frames authenticate in two stages: the header MAC covers the
  meta (including rid, chunk count, and destination) and is verified
  BEFORE any chunk is read — so an unauthenticated peer cannot make a
  server buffer megabytes — and the payload itself is covered by a
  trailing HMAC computed incrementally over the chunk bytes and bound to
  the same rid, verified after the last chunk.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import json
import statistics
import struct
import time
import uuid
import zlib
from collections import deque
from typing import (
    Awaitable,
    Callable,
    Dict,
    Iterator,
    Optional,
    Set,
    Tuple,
    Union,
)

from distributedvolunteercomputing_tpu.swarm import telemetry
from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

MAGIC = b"DV"
VERSION = 1
TYPE_REQ, TYPE_RESP, TYPE_ERR = 1, 2, 3
_HEADER = struct.Struct("!2sBBIQI")  # magic, version, type, meta_len, payload_len, payload_crc32
# Chunk frame header: index, length, crc32 of this chunk's bytes. Chunk
# frames immediately follow a chunked message's header frame on the same
# (write-locked) stream, so they need no rid of their own.
_CHUNK = struct.Struct("!III")
MAX_PAYLOAD = 2 << 30  # 2 GiB guard
MAX_META = 4 << 20  # 4 MiB: meta is a small JSON dict, never tensor data
# Default per-chunk payload bound AND the inline threshold: payloads at or
# under this ride in the header frame exactly as the v1 wire did (the small
# RPCs — heartbeats, DHT, matchmaking — are byte-identical to pre-pool
# frames); bigger payloads stream as chunk frames.
CHUNK_BYTES = 1 << 20
MAX_CHUNKS = 1 << 20  # framing sanity bound, far above MAX_PAYLOAD/CHUNK_BYTES
# Smallest payload that contributes a bandwidth sample to the per-peer
# up/down throughput EWMAs: below this, per-RPC overhead (syscalls, loop
# scheduling) dominates the measurement and the estimate would read as a
# slow link. 256 KiB ~ a handful of wire chunks.
MIN_BW_SAMPLE_BYTES = 256 << 10
# A bandwidth estimate older than this no longer appears in
# bandwidth_advertisement(): links change (congestion, migration), and an
# aged-out advertisement degrades consumers to the unweighted default
# instead of electing yesterday's fat uplink.
BW_ADVERT_MAX_AGE_S = 120.0
DEFAULT_CONNECT_TIMEOUT = 5.0
# Concurrent in-flight requests served per inbound connection; past this the
# read loop stops pulling frames (TCP backpressure) until a handler finishes.
MAX_INFLIGHT_PER_CONN = 64
# Trailer MAC domain separator (payload HMAC of chunked frames).
_PAYLOAD_MAC_TAG = b"DVCP"

Addr = Tuple[str, int]
Handler = Callable[[dict, bytes], Awaitable[Tuple[dict, bytes]]]


class RPCError(Exception):
    """Remote handler raised, or the wire was corrupt."""


class _PayloadError(RPCError):
    """Payload-level rejection of an otherwise well-framed message (bad
    chunk CRC, out-of-order chunk index, corrupt inline payload). The
    explicit lengths kept the stream in sync, so the CONNECTION survives:
    the server answers with an error frame bound to ``rid`` and keeps
    serving; the client fails exactly the one pending call."""

    def __init__(self, rid: str, msg: str):
        super().__init__(msg)
        self.rid = rid if isinstance(rid, str) else ""


class StreamPayload:
    """A large outbound payload produced chunk-by-chunk.

    ``factory`` returns a fresh iterator of byte chunks summing to exactly
    ``total`` bytes; the transport pulls it on a worker thread while the
    event loop writes already-produced chunks — encode/send overlap. A
    factory (not a bare iterator) so the transparent single retry after a
    stale pooled socket can restart the stream from scratch.
    """

    __slots__ = ("total", "factory")

    def __init__(self, total: int, factory: Callable[[], Iterator[bytes]]):
        self.total = int(total)
        self.factory = factory


WirePayload = Union[bytes, bytearray, memoryview, StreamPayload]


def _payload_len(payload: WirePayload) -> int:
    return payload.total if isinstance(payload, StreamPayload) else len(payload)


def read_secret(path: Optional[str]) -> Optional[bytes]:
    """Swarm secret from a file (whitespace-stripped); None = auth off.
    A file, not a flag value — secrets in argv leak via process listings."""
    if not path:
        return None
    with open(path, "rb") as fh:
        secret = fh.read().strip()
    if not secret:
        raise ValueError(f"swarm secret file {path!r} is empty")
    return secret


class _PeerStats:
    """Per-dialed-peer WAN accounting: the transport-level evidence behind
    the pooling/bandwidth claims, and the latency EWMA the phi-accrual
    detector consumes as its secondary (RPC-level) liveness signal."""

    __slots__ = (
        "bytes_sent", "bytes_received", "rpcs", "connects", "lat_ewma",
        "last_used", "bw_up_ewma", "bw_down_ewma", "bw_up_t", "bw_down_t",
    )

    def __init__(self):
        self.bytes_sent = 0
        self.bytes_received = 0
        self.rpcs = 0
        self.connects = 0
        self.lat_ewma: Optional[float] = None
        self.last_used = time.monotonic()
        # Observed payload throughput to/from this peer (bytes/s), sampled
        # only on bulk transfers (>= MIN_BW_SAMPLE_BYTES) so control-plane
        # RPC timing never pollutes the estimate. Both directions are
        # measured at a RECEIVER (reads wait for bytes to actually arrive;
        # a sender's drain() only measures the kernel socket buffer):
        # ``bw_down`` from our own reads of this peer's responses,
        # ``bw_up`` from the peer's echoed arrival rate of our request
        # payloads (the ``rx_bps`` response field). Floors of the real
        # link rate — the safe direction for the consumers: bandwidth-
        # weighted leader election (matchmaking) and the membership
        # advertisement (bandwidth_advertisement). Each direction carries
        # its OWN sample timestamp so a stale estimate ages out of the
        # advertisement independently — a node still fetching bulk results
        # (fresh bw_down) but no longer pushing bulk payloads must not
        # keep advertising yesterday's uplink.
        self.bw_up_ewma: Optional[float] = None
        self.bw_down_ewma: Optional[float] = None
        self.bw_up_t = 0.0
        self.bw_down_t = 0.0

    def observe_latency(self, dt: float) -> None:
        if self.lat_ewma is None:
            self.lat_ewma = dt
        else:
            self.lat_ewma += 0.2 * (dt - self.lat_ewma)

    def observe_bw_up(self, bps: float) -> None:
        self.bw_up_ewma = (
            bps if self.bw_up_ewma is None
            else self.bw_up_ewma + 0.3 * (bps - self.bw_up_ewma)
        )
        self.bw_up_t = time.monotonic()

    def observe_bw_down(self, bps: float) -> None:
        self.bw_down_ewma = (
            bps if self.bw_down_ewma is None
            else self.bw_down_ewma + 0.3 * (bps - self.bw_down_ewma)
        )
        self.bw_down_t = time.monotonic()

    def as_dict(self) -> dict:
        return {
            "bytes_sent": self.bytes_sent,
            "bytes_received": self.bytes_received,
            "rpcs": self.rpcs,
            "connects": self.connects,
            "latency_ewma_ms": (
                round(self.lat_ewma * 1e3, 3) if self.lat_ewma is not None else None
            ),
            "bw_up_bps": (
                round(self.bw_up_ewma) if self.bw_up_ewma is not None else None
            ),
            "bw_down_bps": (
                round(self.bw_down_ewma) if self.bw_down_ewma is not None else None
            ),
        }


class _Conn:
    """One pooled client connection: write-locked frame writes, rid-demuxed
    response reads. The demux loop is the only reader; writers (concurrent
    calls) serialize whole messages under ``wlock`` so chunk sequences never
    interleave."""

    __slots__ = (
        "transport", "addr", "reader", "writer", "wlock", "pending", "sinks",
        "broken", "reused", "task",
    )

    def __init__(self, transport: "Transport", addr: Addr, reader, writer):
        self.transport = transport
        self.addr = addr
        self.reader = reader
        self.writer = writer
        self.wlock = asyncio.Lock()
        self.pending: Dict[str, asyncio.Future] = {}
        self.sinks: Dict[str, Callable[[int, int, bytes], None]] = {}
        self.broken = False
        # True once a call completed on this conn: only a REUSED (possibly
        # idle-closed / stale) socket earns the one transparent retry.
        self.reused = False
        self.task = asyncio.create_task(self._demux_loop())

    async def _demux_loop(self) -> None:
        t = self.transport
        try:
            while True:
                try:
                    ftype, meta, payload = await t._read_frame(
                        self.reader, sink_lookup=self.sinks.get, peer=self.addr
                    )
                except _PayloadError as e:
                    fut = self.pending.pop(e.rid, None)
                    if fut is not None and not fut.done():
                        fut.set_exception(RPCError(str(e)))
                    continue
                rid = meta.get("rid") if isinstance(meta, dict) else None
                if ftype == TYPE_ERR and not rid:
                    # Connection-level rejection from the server (framing /
                    # auth): the stream is done — surface the reason to
                    # every in-flight call rather than a bare disconnect.
                    raise RPCError(meta.get("error", "connection-level remote error"))
                fut = self.pending.pop(rid, None) if isinstance(rid, str) else None
                if fut is not None and not fut.done():
                    fut.set_result((ftype, meta, payload))
                # Unknown rid: the response to a call that already timed out
                # locally (its future was withdrawn) — discard. rids are
                # fresh uuids, so it can never complete a different call.
        except (
            asyncio.IncompleteReadError, ConnectionResetError,
            BrokenPipeError, OSError,
        ) as e:
            # Connection-level death: retryable by the caller (stale pooled
            # socket / peer restart).
            self._fail_pending(
                ConnectionResetError(f"connection to {self.addr} lost: {errstr(e)}")
            )
        except RPCError as e:
            # Protocol-level failure (unparseable/unauthenticated response):
            # NOT retryable — redialing an auth-failing peer is a retry storm.
            self._fail_pending(e)
        except asyncio.CancelledError:
            self._fail_pending(ConnectionResetError("transport closed"))
            raise
        finally:
            self.broken = True
            self.transport._drop_conn(self.addr, self)
            self.writer.close()

    def _fail_pending(self, exc: BaseException) -> None:
        for fut in list(self.pending.values()):
            if not fut.done():
                fut.set_exception(exc)
        self.pending.clear()

    def close(self) -> None:
        self.broken = True
        if not self.task.done():
            self.task.cancel()
        else:
            self.writer.close()


class Transport:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        advertise_host: Optional[str] = None,
        secret: Optional[bytes] = None,
        auth_window: float = 300.0,
        pooled: bool = True,
        connect_timeout: float = DEFAULT_CONNECT_TIMEOUT,
        chunk_bytes: int = CHUNK_BYTES,
    ):
        self._secret = secret
        self._auth_window = auth_window
        # Accepted-request MAC cache (replay rejection; see module doc).
        # FIFO deque gives cheap age+cap eviction: entries arrive in ~ts
        # order, so pruning from the left is enough.
        self._seen_macs: Dict[str, float] = {}
        self._seen_order: "deque[Tuple[float, str]]" = deque()
        self._host = host
        self._port = port
        # Bind address != reachable address when binding 0.0.0.0 (or behind
        # NAT): peers must be told an address they can dial, or every DHT
        # record we publish points back at the reader's own machine.
        self._advertise_host = advertise_host
        if advertise_host is None and host in ("0.0.0.0", "::", ""):
            log.warning(
                "binding %s without advertise_host: remote peers cannot dial "
                "the advertised address; pass --advertise-host for multi-host swarms",
                host or "ANY",
            )
        self._server: Optional[asyncio.base_events.Server] = None
        self._handlers: Dict[str, Handler] = {}
        # method -> factory(args, payload_len) returning a per-request sink
        # (or None to buffer normally): the server-side twin of call()'s
        # chunk_sink. Verified REQUEST chunks stream to the sink as they
        # arrive instead of assembling in a bytearray — the leader-side
        # aggregation pipeline consumes contribution chunks this way
        # (swarm/agg_stream.py). The matching handler then runs with an
        # empty payload. See register_request_sink.
        self._stream_factories: Dict[
            str, Callable[[dict, int], Optional[Callable[[int, int, bytes], None]]]
        ] = {}
        # ``pooled=False`` restores one-connection-per-call (the v1 wire
        # behavior): the escape hatch, and the baseline arm of
        # experiments/transport_bench.py.
        self.pooled = pooled
        self.connect_timeout = float(connect_timeout)
        self.chunk_bytes = int(chunk_bytes)
        # addr -> _Conn (ready) or asyncio.Task resolving to one (dialing);
        # concurrent calls to the same peer share the dial.
        self._conns: Dict[Addr, object] = {}
        self._server_writers: Set[asyncio.StreamWriter] = set()
        self._server_tasks: Set[asyncio.Task] = set()
        # WAN accounting (frame headers + meta + payload, both directions):
        # the evidence behind wire-codec claims — experiments read these off
        # the volunteer summary instead of estimating. Per-peer detail in
        # _peer_stats (dialed peers only: a server can't know which
        # LISTENING addr an inbound ephemeral port belongs to).
        self.bytes_sent = 0
        self.bytes_received = 0
        self.rpcs_sent = 0
        self.connects = 0
        self._peer_stats: Dict[Addr, _PeerStats] = {}

    @property
    def addr(self) -> Addr:
        """The ADVERTISED (dialable) address, used in every published record."""
        return (self._advertise_host or self._host, self._port)

    def register(self, method: str, handler: Handler) -> None:
        self._handlers[method] = handler

    def register_request_sink(
        self,
        method: str,
        factory: Callable[[dict, int], Optional[Callable[[int, int, bytes], None]]],
    ) -> None:
        """Stream ``method``'s chunked REQUEST payloads to a per-request sink.

        Only active when the transport has NO shared secret: chunks reach
        the sink after per-chunk CRC32 only, which is unkeyed, and sinks
        may consume irreversibly — with auth on the transport buffers the
        whole payload and verifies the HMAC trailer before the handler
        sees a byte, so tampered payloads are discarded whole.

        ``factory(args, payload_len)`` runs after the header frame is
        parsed. Returning None falls back to normal buffering — streaming
        is an optimization the factory may decline per request. The sink is
        called ``sink(offset, total, data)`` per verified in-order chunk,
        then ``sink.close(ok)`` exactly once: ok=True after the whole
        payload verified, ok=False on any abort — bad chunk CRC, framing
        error, connection death — possibly after some chunks were already
        delivered. Inline (sub-chunk) payloads never stream. The handler
        registered for ``method`` runs with an empty payload when the sink
        consumed it."""
        self._stream_factories[method] = factory

    def _request_sink(self, meta: dict, payload_len: int):
        fac = self._stream_factories.get(meta.get("method", ""))
        if fac is None:
            return None
        try:
            return fac(meta.get("args") or {}, payload_len)
        except Exception as e:  # noqa: BLE001 — a factory bug must buffer, not kill the conn
            log.debug("request sink factory failed (%s); buffering", errstr(e))
            return None

    async def start(self) -> Addr:
        self._server = await asyncio.start_server(self._serve_conn, self._host, self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        return self.addr

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        # Tear down the client pool: cancel demux loops (they close their
        # writers) and any dial still in flight.
        tasks = []
        for entry in list(self._conns.values()):
            if isinstance(entry, _Conn):
                entry.close()
                tasks.append(entry.task)
            elif isinstance(entry, asyncio.Task):
                entry.cancel()
                tasks.append(entry)
        self._conns.clear()
        # Force-close inbound connections and cancel parked handler tasks so
        # a closing node never keeps a test loop (or a real process) alive.
        for w in list(self._server_writers):
            w.close()
        for t in list(self._server_tasks):
            t.cancel()
        tasks.extend(self._server_tasks)
        self._server_tasks.clear()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    # -- counters ----------------------------------------------------------

    # Distinct dialed peers whose counters are retained. Long-lived nodes in
    # a churning swarm dial an unbounded sequence of peer addresses; without
    # a cap the stats dict — serialized into every stats()/summary/
    # coord.status — would grow for the process lifetime.
    MAX_PEER_STATS = 512

    def _peer(self, addr: Addr) -> _PeerStats:
        st = self._peer_stats.get(addr)
        if st is None:
            if len(self._peer_stats) >= self.MAX_PEER_STATS:
                # Evict least-recently-used entries WITHOUT a live pooled
                # connection (an active peer's counters must survive).
                evictable = sorted(
                    (a for a in self._peer_stats if a not in self._conns),
                    key=lambda a: self._peer_stats[a].last_used,
                )
                for a in evictable[: max(1, len(evictable) // 4)]:
                    del self._peer_stats[a]
            st = self._peer_stats[addr] = _PeerStats()
        st.last_used = time.monotonic()
        return st

    def peer_latency(self, addr) -> Optional[float]:
        """RPC round-trip latency EWMA (seconds) to a dialed peer, or None
        before the first completed call. Fed to the phi-accrual failure
        detector as its secondary signal (swarm/membership.py)."""
        try:
            st = self._peer_stats.get((str(addr[0]), int(addr[1])))
        except (TypeError, ValueError, IndexError):
            return None
        return st.lat_ewma if st is not None else None

    def peer_bw_down(self, addr) -> Optional[float]:
        """Measured downlink throughput (bytes/s EWMA) FROM a dialed peer —
        our own read-timed samples of its bulk transfers, or None before
        the first >=MIN_BW_SAMPLE_BYTES payload. The hedge loop's transfer
        estimator reads this to predict whether a straggler's missing
        tiles can still arrive inside the round deadline."""
        try:
            st = self._peer_stats.get((str(addr[0]), int(addr[1])))
        except (TypeError, ValueError, IndexError):
            return None
        return st.bw_down_ewma if st is not None else None

    def bandwidth_advertisement(
        self, max_age_s: float = BW_ADVERT_MAX_AGE_S
    ) -> dict:
        """This node's measured up/down bandwidth, as the membership
        advertisement fields (``bw_up``/``bw_down``, bytes/s). ``bw_down``
        is the MAX of the fresh per-peer EWMAs — measured locally (our
        own reads), so every sample is a trustworthy floor and the best
        observed peer is the tightest floor on our link. ``bw_up``
        samples are peer-REPORTED (the rx_bps response echo), so one
        lying peer must not control the advertisement: with >= 3 fresh
        reporters the MEDIAN is taken (a minority of byzantine peers
        can't push it past honest reports), max otherwise (too few
        reporters to out-vote — the residual trust a 2-peer swarm always
        has). Each direction ages out independently; with nothing fresh
        within ``max_age_s`` the field is simply omitted and consumers
        degrade to unweighted behavior — a stale advertisement ages out
        rather than lingering."""
        cutoff = time.monotonic() - max_age_s
        up = [
            st.bw_up_ewma for st in self._peer_stats.values()
            if st.bw_up_ewma is not None and st.bw_up_t >= cutoff
        ]
        down = [
            st.bw_down_ewma for st in self._peer_stats.values()
            if st.bw_down_ewma is not None and st.bw_down_t >= cutoff
        ]
        out: dict = {}
        if up:
            out["bw_up"] = round(
                statistics.median(up) if len(up) >= 3 else max(up)
            )
        if down:
            out["bw_down"] = round(max(down))
        return out

    def stats(self) -> dict:
        """Transport-level counters: totals plus per-dialed-peer detail."""
        return {
            "bytes_sent": self.bytes_sent,
            "bytes_received": self.bytes_received,
            "rpcs": self.rpcs_sent,
            "connects": self.connects,
            "pooled_conns": sum(
                1 for c in self._conns.values()
                if isinstance(c, _Conn) and not c.broken
            ),
            "peers": {
                f"{h}:{p}": st.as_dict() for (h, p), st in self._peer_stats.items()
            },
        }

    # -- wire helpers ------------------------------------------------------

    def _mac(self, ftype: int, meta: dict, payload: bytes) -> str:
        """HMAC over (frame type, canonical meta minus auth, payload)."""
        canon = json.dumps(
            {k: v for k, v in meta.items() if k != "auth"},
            sort_keys=True,
            separators=(",", ":"),
        ).encode()
        return hmac.new(
            self._secret, bytes([ftype]) + canon + payload, hashlib.sha256
        ).hexdigest()

    def _payload_mac_ctx(self, ftype: int, rid: str):
        """Incremental HMAC over a chunked message's payload bytes, bound to
        the frame type and rid (the rid itself rides inside the MAC'd meta,
        closing the splice-a-different-payload-under-this-header hole)."""
        ctx = hmac.new(self._secret, _PAYLOAD_MAC_TAG, hashlib.sha256)
        ctx.update(bytes([ftype]))
        ctx.update(rid.encode())
        return ctx

    def _verify_auth(self, ftype: int, meta: dict, payload: bytes) -> None:
        got = meta.get("auth", "")
        if not isinstance(got, str) or not hmac.compare_digest(
            got, self._mac(ftype, meta, payload)
        ):
            raise RPCError("auth failure (missing/invalid frame HMAC)")
        ts = meta.get("ts")
        if not isinstance(ts, (int, float)) or abs(time.time() - ts) > self._auth_window:
            raise RPCError("auth failure (frame timestamp outside window)")
        if ftype == TYPE_REQ:
            if not self._dst_is_me(meta.get("dst")):
                # The MAC binds the address the caller DIALED: a frame
                # captured en route to another node must not be replayable
                # here (per-node seen-MAC caches can't see each other).
                raise RPCError("auth failure (frame addressed to a different node)")
            if not self._mac_fresh(got, float(ts)):
                # A fresh rid is in every legitimate request's MAC'd meta,
                # so an identical MAC within the window is a replay.
                raise RPCError("auth failure (replayed request frame)")

    def _chaos_corrupt_offset(self, ftype: int, total: int) -> Optional[int]:
        """Fault-injection hook (overridden by chaos.ChaosTransport): byte
        offset within the payload to flip AFTER checksums are computed, or
        None. Production transports never corrupt."""
        return None

    async def _iter_wire_chunks(self, payload: WirePayload):
        """Yield exactly-``chunk_bytes``-sized pieces (last may be short).

        bytes-likes are sliced zero-copy; a StreamPayload's factory iterator
        is pulled on a worker thread (the chunks are typically produced by a
        CPU-bound codec) and re-sliced to the wire chunk size, so encode of
        chunk k+1 overlaps the socket write of chunk k."""
        cb = self.chunk_bytes
        if not isinstance(payload, StreamPayload):
            view = memoryview(payload)
            for off in range(0, len(view), cb):
                yield view[off : off + cb]
            return
        it = payload.factory()
        pending = bytearray()
        _END = object()
        while True:
            piece = await asyncio.to_thread(next, it, _END)
            if piece is _END:
                break
            if not pending and len(piece) == cb:
                yield piece  # aligned producer: no re-buffer copy
                continue
            pending.extend(piece)
            while len(pending) >= cb:
                yield bytes(pending[:cb])
                del pending[:cb]
        if pending:
            yield bytes(pending)

    async def _write_message(
        self,
        writer: asyncio.StreamWriter,
        wlock: asyncio.Lock,
        ftype: int,
        meta: dict,
        payload: WirePayload,
        peer: Optional[Addr] = None,
        started: Optional[list] = None,
    ) -> None:
        """Serialize one message (inline or chunked) onto ``writer`` under
        ``wlock``. Any exception after the first byte leaves the stream
        mid-message — the CALLER must treat the connection as poisoned.
        ``started`` (when given) is appended to right before the first byte
        goes out, so a caller cancelled while still QUEUED on the write
        lock can tell it never touched the stream (the connection — and
        every other in-flight RPC multiplexed on it — survives)."""
        total = _payload_len(payload)
        if total > MAX_PAYLOAD:
            raise RPCError(f"payload {total} exceeds {MAX_PAYLOAD}")
        chunked = isinstance(payload, StreamPayload) or total > self.chunk_bytes
        rid = meta.get("rid", "")
        rid = rid if isinstance(rid, str) else ""
        corrupt_at = self._chaos_corrupt_offset(ftype, total)
        if chunked:
            n_chunks = -(-total // self.chunk_bytes)
            meta = dict(meta, chunks=n_chunks)
            if self._secret is not None:
                meta["ptrail"] = True  # payload MAC trailer follows the chunks
                meta["ts"] = round(time.time(), 3)
                meta["auth"] = self._mac(ftype, meta, b"")
        elif self._secret is not None:
            pl = payload if isinstance(payload, bytes) else bytes(payload)
            meta = dict(meta, ts=round(time.time(), 3))
            meta["auth"] = self._mac(ftype, meta, pl)
            payload = pl
        meta_b = json.dumps(meta).encode()
        sent = 0
        async with wlock:
            if started is not None:
                started.append(True)
            if not chunked:
                data = payload if isinstance(payload, bytes) else bytes(payload)
                crc = zlib.crc32(data) & 0xFFFFFFFF  # checksum of the TRUE payload
                if corrupt_at is not None:
                    bad = bytearray(data)
                    bad[corrupt_at] ^= 0xFF
                    data = bytes(bad)
                # One write: header + meta + payload coalesced. Separate
                # writes each poke the transport (a send syscall when the
                # kernel buffer has room) — at small-RPC rates the extra
                # syscalls were ~10% of swarm CPU.
                frame = _HEADER.pack(MAGIC, VERSION, ftype, len(meta_b), total, crc)
                writer.write(frame + meta_b + (data if total else b""))
                sent = _HEADER.size + len(meta_b) + total
                await writer.drain()
            else:
                writer.write(_HEADER.pack(MAGIC, VERSION, ftype, len(meta_b), total, 0))
                writer.write(meta_b)
                sent = _HEADER.size + len(meta_b)
                mac = (
                    self._payload_mac_ctx(ftype, rid)
                    if self._secret is not None
                    else None
                )
                idx = 0
                off = 0
                async for piece in self._iter_wire_chunks(payload):
                    data = piece  # bytes-like; crc/hmac/write all take views
                    crc = zlib.crc32(data) & 0xFFFFFFFF
                    if mac is not None:
                        mac.update(data)
                    if corrupt_at is not None and off <= corrupt_at < off + len(data):
                        bad = bytearray(data)
                        bad[corrupt_at - off] ^= 0xFF
                        data = bytes(bad)
                    writer.write(_CHUNK.pack(idx, len(data), crc))
                    writer.write(data)
                    sent += _CHUNK.size + len(data)
                    # Drain per chunk: the loop stays responsive and the
                    # socket applies backpressure chunk-by-chunk instead of
                    # buffering the whole payload in userspace.
                    await writer.drain()
                    idx += 1
                    off += len(data)
                if off != total or idx != -(-total // self.chunk_bytes):
                    raise RPCError(
                        f"stream payload produced {off}B/{idx} chunks, "
                        f"declared {total}B"
                    )
                if mac is not None:
                    digest = mac.digest()
                    writer.write(
                        _CHUNK.pack(idx, len(digest), zlib.crc32(digest) & 0xFFFFFFFF)
                    )
                    writer.write(digest)
                    sent += _CHUNK.size + len(digest)
                await writer.drain()
        self.bytes_sent += sent
        if peer is not None:
            self._peer(peer).bytes_sent += sent

    async def _read_frame(
        self,
        reader: asyncio.StreamReader,
        sink_lookup: Optional[Callable[[str], Optional[Callable]]] = None,
        peer: Optional[Addr] = None,
        req_sinks: bool = False,
    ) -> Tuple[int, dict, bytes]:
        """Read one complete message (header frame + any chunk frames).

        Raises IncompleteReadError/ConnectionResetError when the stream
        dies, _PayloadError for an attributable payload rejection (the
        connection survives), and plain RPCError for unparseable or
        unauthenticated framing (the caller must drop the connection)."""
        header = await reader.readexactly(_HEADER.size)
        magic, version, ftype, meta_len, payload_len, crc = _HEADER.unpack(header)
        if magic != MAGIC or version != VERSION:
            raise RPCError(f"bad frame header: magic={magic!r} version={version}")
        if payload_len > MAX_PAYLOAD:
            raise RPCError(f"payload {payload_len} exceeds {MAX_PAYLOAD}")
        if meta_len > MAX_META:
            raise RPCError(f"meta {meta_len} exceeds {MAX_META}")
        meta_b = await reader.readexactly(meta_len) if meta_len else b"{}"
        received = _HEADER.size + meta_len
        try:
            meta = json.loads(meta_b)
        except (ValueError, RecursionError) as e:
            # Attacker-controlled bytes: a JSONDecodeError is a ValueError,
            # not an RPCError — without this wrap it would escape the serve
            # loop's bad-frame containment and kill the connection task with
            # an unhandled exception instead of a clean error frame.
            # RecursionError too: deeply-nested JSON (200 KB of '[' fits
            # comfortably under MAX_META) blows the parser's stack.
            self.bytes_received += received
            raise RPCError(f"malformed frame meta (not JSON: {e})") from e
        if not isinstance(meta, dict):
            # json.loads happily returns lists/scalars; meta.get() downstream
            # would AttributeError outside the containment net.
            self.bytes_received += received
            raise RPCError(f"malformed frame meta (not an object: {type(meta).__name__})")
        rid = meta.get("rid", "")
        rid = rid if isinstance(rid, str) else ""
        # Local measurement stash only (set below, echoed by the server
        # half): a remote peer must not be able to pre-seed it.
        meta.pop("_rx_bps", None)
        n_chunks = meta.get("chunks")
        if n_chunks is None:
            # Inline message: the v1 wire, byte-identical.
            t_payload = time.monotonic()
            payload = await reader.readexactly(payload_len) if payload_len else b""
            received += payload_len
            self.bytes_received += received
            dt = time.monotonic() - t_payload
            if peer is not None:
                st = self._peer(peer)
                st.bytes_received += received
                if payload_len >= MIN_BW_SAMPLE_BYTES and dt > 0:
                    st.observe_bw_down(payload_len / dt)
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                # The declared lengths were honored, so the stream is still
                # in sync: reject THIS message, keep the connection.
                raise _PayloadError(rid, "payload CRC mismatch (corrupt frame)")
            if self._secret is not None:
                self._verify_auth(ftype, meta, payload)
            if payload_len >= MIN_BW_SAMPLE_BYTES and dt > 0:
                # Read-side throughput is genuine: readexactly waits for
                # bytes to actually ARRIVE (stream buffer caps at 64 KiB),
                # so the rate is bounded by the sender's uplink + path.
                # Stashed in the meta so the server half can echo it back
                # to the sender as its measured uplink (see
                # _handle_request); the sender CANNOT measure this itself —
                # its drain() returns once the kernel socket buffer accepts
                # the bytes, a ceiling on the link rate, not a floor. Set
                # AFTER auth: the MAC covers the meta as the sender sent it.
                meta["_rx_bps"] = payload_len / dt
            return ftype, meta, payload
        # Chunked message.
        if (
            not isinstance(n_chunks, int)
            or isinstance(n_chunks, bool)
            or n_chunks < 1
            or n_chunks > MAX_CHUNKS
            or payload_len <= 0
            or n_chunks > payload_len
        ):
            self.bytes_received += received
            raise RPCError(f"malformed chunked frame (chunks={n_chunks!r})")
        if self._secret is not None:
            # Header MAC verified BEFORE any chunk is read: an
            # unauthenticated peer cannot make this node buffer megabytes,
            # and the replay/dst checks run on bounded work.
            self._verify_auth(ftype, meta, b"")
        sink = sink_lookup(rid) if sink_lookup is not None else None
        if sink is None and req_sinks and ftype == TYPE_REQ and self._secret is None:
            # Server-side request streaming (register_request_sink). Only
            # without auth: a streamed chunk reaches the sink after its
            # CRC32 — an unkeyed check — but BEFORE the payload HMAC
            # trailer, and request sinks may consume irreversibly (the
            # leader axpy-folds mean-mode chunks into the aggregate). With
            # a secret set we buffer instead, so a MAC-failing payload is
            # discarded whole and never touches the consumer — the same
            # integrity guarantee the pre-streaming path gave. (The CLIENT
            # fetch sink stays streamed under auth: it fills a staging
            # buffer the caller drops when the call errors.)
            sink = self._request_sink(meta, payload_len)
        sink_closed = False

        def _close_sink(ok: bool) -> None:
            # Exactly-once completion signal for sinks that track a
            # lifecycle (request sinks do; the client fetch sink doesn't).
            nonlocal sink_closed
            if sink is None or sink_closed:
                return
            sink_closed = True
            close = getattr(sink, "close", None)
            if close is not None:
                try:
                    close(ok)
                except Exception as e:  # noqa: BLE001 — a sink bug must not kill the conn
                    log.debug("chunk sink close(%s) failed: %s", ok, errstr(e))

        mac = (
            self._payload_mac_ctx(ftype, rid) if self._secret is not None else None
        )
        buf: Optional[bytearray] = None if sink is not None else bytearray(payload_len)
        got = 0
        bad: Optional[str] = None
        t_chunks = time.monotonic()
        try:
            for i in range(n_chunks):
                ch = await reader.readexactly(_CHUNK.size)
                idx, length, ccrc = _CHUNK.unpack(ch)
                if length == 0 or got + length > payload_len:
                    # Framing no longer adds up — the incremental size cap. The
                    # stream position past this point is untrustworthy.
                    self.bytes_received += received
                    raise RPCError(
                        f"chunk framing exceeds declared payload "
                        f"({got}+{length} > {payload_len})"
                    )
                data = await reader.readexactly(length)
                received += _CHUNK.size + length
                if mac is not None:
                    mac.update(data)
                if bad is None and idx != i:
                    bad = f"chunk index {idx} != expected {i} (duplicated/reordered)"
                elif bad is None and (zlib.crc32(data) & 0xFFFFFFFF) != ccrc:
                    bad = f"chunk {i} CRC mismatch (corrupt frame)"
                if bad is None:
                    if sink is not None:
                        try:
                            # Verified chunk straight to the consumer: decode
                            # (and leader-side aggregation) starts on the
                            # FIRST chunk.
                            sink(got, payload_len, data)
                        except Exception as e:  # noqa: BLE001 — a sink bug fails the call, not the conn
                            bad = f"chunk sink rejected payload: {errstr(e)}"
                    else:
                        buf[got : got + length] = data
                got += length
            if bad is None and got != payload_len:
                bad = f"chunked payload short of declared total ({got} < {payload_len})"
            if meta.get("ptrail"):
                th = await reader.readexactly(_CHUNK.size)
                t_idx, t_len, t_crc = _CHUNK.unpack(th)
                if t_idx != n_chunks or t_len != hashlib.sha256().digest_size:
                    self.bytes_received += received
                    raise RPCError("malformed payload MAC trailer")
                digest = await reader.readexactly(t_len)
                received += _CHUNK.size + t_len
                if mac is not None and bad is None and not hmac.compare_digest(
                    digest, mac.digest()
                ):
                    self.bytes_received += received
                    raise RPCError("auth failure (chunked payload MAC mismatch)")
            elif mac is not None:
                self.bytes_received += received
                raise RPCError("auth failure (chunked payload without MAC trailer)")
        except BaseException:
            # Framing/auth failure or connection death mid-payload: the sink
            # may have consumed verified chunks already — tell it the stream
            # died so it can withdraw or quarantine them.
            _close_sink(False)
            raise
        self.bytes_received += received
        chunk_dt = time.monotonic() - t_chunks
        if bad is None and payload_len >= MIN_BW_SAMPLE_BYTES and chunk_dt > 0:
            # First chunk to last: a throughput floor (the sender's encode
            # pacing only makes the true link faster). Same echo contract
            # as the inline path above.
            meta["_rx_bps"] = payload_len / chunk_dt
        if peer is not None:
            st = self._peer(peer)
            st.bytes_received += received
            if bad is None and payload_len >= MIN_BW_SAMPLE_BYTES and chunk_dt > 0:
                st.observe_bw_down(payload_len / chunk_dt)
        if bad is not None:
            _close_sink(False)
            raise _PayloadError(rid, bad)
        _close_sink(True)
        # The assembled bytearray is returned as-is (bytes-like): converting
        # would copy the whole payload — at contribution scale, a real cost.
        return ftype, meta, buf if buf is not None else b""

    def _dst_is_me(self, dst) -> bool:
        """Is the MAC'd destination this node? Port must match the bound
        port; the host may be any name this node is legitimately dialed by
        (advertised, bound, or loopback). Alias sets of distinct nodes
        cannot collide: same machine implies distinct ports, distinct
        machines implies distinct hosts."""
        if not (isinstance(dst, (list, tuple)) and len(dst) == 2):
            return False
        host, port = dst
        if port != self._port:
            return False
        aliases = {self._advertise_host, self._host, "127.0.0.1", "localhost"}
        return host in aliases

    # Hard cap on remembered request MACs: ~5 MB worst case, and at any
    # realistic RPC rate the age-based pruning keeps it far smaller.
    MAX_SEEN_MACS = 65536

    def _mac_fresh(self, mac: str, ts: float) -> bool:
        """Record ``mac``; False if it was already accepted in the window.

        Entries are retained until max(accept_time, frame ts) + auth_window:
        a frame from an ahead-of-clock peer stays timestamp-valid until
        ts + window, so evicting by accept time alone would reopen a replay
        window of exactly the sender's clock skew."""
        now = time.time()
        cutoff = now - self._auth_window
        order, seen = self._seen_order, self._seen_macs
        while order and (order[0][0] < cutoff or len(order) > self.MAX_SEEN_MACS):
            _, old = order.popleft()
            seen.pop(old, None)
        if mac in seen:
            return False
        seen[mac] = now
        order.append((max(now, ts), mac))
        return True

    # -- server ------------------------------------------------------------

    async def _serve_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._server_writers.add(writer)
        wlock = asyncio.Lock()
        sem = asyncio.Semaphore(MAX_INFLIGHT_PER_CONN)
        try:
            while True:
                try:
                    ftype, meta, payload = await self._read_frame(
                        reader, req_sinks=True
                    )
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                except _PayloadError as e:
                    # Attributable payload rejection (bad CRC, chunk index,
                    # sink refusal): error frame bound to the rid; the
                    # connection — and every other in-flight RPC on it —
                    # keeps going.
                    try:
                        await self._write_message(
                            writer, wlock, TYPE_ERR,
                            {"rid": e.rid, "error": f"bad frame: {e}"}, b"",
                        )
                    except Exception:
                        return
                    continue
                except RPCError as e:
                    # Unparseable framing / auth failure: the stream position
                    # is untrustworthy past this point, so report the reason
                    # and drop the connection — the caller can then
                    # distinguish corruption from a disconnect (the
                    # Byzantine path needs that signal).
                    try:
                        await self._write_message(
                            writer, wlock, TYPE_ERR,
                            {"rid": "", "error": f"bad frame: {e}"}, b"",
                        )
                    except Exception:
                        pass
                    return
                if ftype != TYPE_REQ:
                    return
                # Concurrent handling per connection: a parked handler (e.g.
                # sync.fetch awaiting the round result) must not
                # head-of-line-block the heartbeats and DHT RPCs sharing
                # this multiplexed pipe. The semaphore bounds in-flight
                # handlers; past it the read loop itself applies TCP
                # backpressure.
                await sem.acquire()
                task = asyncio.create_task(
                    self._handle_request(writer, wlock, sem, meta, payload)
                )
                self._server_tasks.add(task)
                task.add_done_callback(self._server_tasks.discard)
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            self._server_writers.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    async def _handle_request(
        self,
        writer: asyncio.StreamWriter,
        wlock: asyncio.Lock,
        sem: asyncio.Semaphore,
        meta: dict,
        payload: bytes,
    ) -> None:
        """One request end-to-end: dispatch, run the handler, write the
        response. Handler errors go back on the wire; write failures mean
        the client vanished (its call timed out / conn dropped) — the
        handler's state effects stand, the response is simply lost, exactly
        as with the old per-call connections."""
        # Round-trace propagation (swarm/telemetry.py): the caller's
        # ambient trace id rides the MAC'd frame meta (``tr``); restoring
        # it around this handler task is what lets a leader's handler-side
        # spans and flight events stitch into the member's round trace
        # without any new RPC.
        tr = meta.get("tr")
        tr_token = (
            telemetry.set_current_trace(tr) if isinstance(tr, str) and tr else None
        )
        try:
            method = meta.get("method", "")
            rid = meta.get("rid", "")
            handler = self._handlers.get(method)
            if handler is None:
                out_type: int = TYPE_ERR
                out_meta: dict = {"rid": rid, "error": f"no such method {method!r}"}
                out_payload: WirePayload = b""
            else:
                try:
                    resp_meta, out_payload = await handler(meta.get("args", {}), payload)
                    out_type, out_meta = TYPE_RESP, {"rid": rid, "ret": resp_meta}
                    rx_bps = meta.get("_rx_bps")
                    if rx_bps:
                        # Echo the measured arrival rate of the request's
                        # bulk payload back to its sender — the only place
                        # the sender's UPLINK is genuinely observable (its
                        # own drain() only measures the kernel buffer).
                        # MAC-covered under auth like the rest of the
                        # response meta. Trust note: a LYING responder
                        # inflates the honest REQUESTER's uplink estimate
                        # (possibly electing a thin-linked leader), which
                        # is why bandwidth_advertisement aggregates these
                        # by MEDIAN across reporters — a minority of
                        # byzantine peers can't move the advertisement —
                        # and why samples age out in BW_ADVERT_MAX_AGE_S.
                        out_meta["rx_bps"] = round(rx_bps)
                except Exception as e:  # handler errors go back on the wire
                    log.debug("handler %s raised: %s", method, errstr(e))
                    out_type = TYPE_ERR
                    out_meta = {"rid": rid, "error": f"{type(e).__name__}: {e}"}
                    out_payload = b""
            try:
                await self._write_message(writer, wlock, out_type, out_meta, out_payload)
            except (ConnectionResetError, BrokenPipeError, OSError, RPCError) as e:
                log.debug("response write failed (client gone?): %s", errstr(e))
                writer.close()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — a request task must never die loudly
            log.debug("request task failed: %s", errstr(e))
        finally:
            if tr_token is not None:
                telemetry.reset_current_trace(tr_token)
            sem.release()

    # -- client ------------------------------------------------------------

    def _drop_conn(self, addr: Addr, conn: "_Conn") -> None:
        if self._conns.get(addr) is conn:
            del self._conns[addr]

    def drop_peer(self, addr) -> None:
        """Proactively retire the pooled connection (or in-flight dial) to
        ``addr``. Failover support: a deposed round leader's socket must
        stop being a transparent-retry target the instant the deposition is
        decided — every RPC still multiplexed on it fails NOW with a
        connection error instead of discovering the corpse one timeout at a
        time. A later call to the same address dials fresh."""
        try:
            addr = (str(addr[0]), int(addr[1]))
        except (TypeError, ValueError, IndexError):
            return
        entry = self._conns.get(addr)
        if isinstance(entry, _Conn):
            entry.close()
        elif isinstance(entry, asyncio.Task):
            entry.cancel()
            self._conns.pop(addr, None)

    async def _dial(self, addr: Addr, connect_timeout: float) -> "_Conn":
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(*addr), timeout=connect_timeout
            )
        except asyncio.TimeoutError:
            # Surface dial timeouts as OSError (unreachable peer), keeping
            # TimeoutError for "the RPC itself blew its budget" — callers
            # catch both, but the distinction matters for retry/backoff
            # policies and logs.
            raise OSError(
                f"connect to {addr[0]}:{addr[1]} timed out after {connect_timeout:.1f}s"
            ) from None
        self.connects += 1
        self._peer(addr).connects += 1
        return _Conn(self, addr, reader, writer)

    def _finish_dial(self, addr: Addr, task: asyncio.Task) -> None:
        current = self._conns.get(addr)
        if current is not task:
            return
        if task.cancelled() or task.exception() is not None:
            del self._conns[addr]
        else:
            self._conns[addr] = task.result()

    async def _checkout_conn(
        self, addr: Addr, connect_timeout: float
    ) -> Tuple["_Conn", bool]:
        """(conn, fresh): the pooled connection to ``addr``, dialing if
        absent/broken. Concurrent callers share one dial. ``fresh`` is True
        when this caller's conn came from a dial it (co-)initiated — only
        REUSED conns earn the transparent retry."""
        entry = self._conns.get(addr)
        if isinstance(entry, _Conn):
            if not entry.broken:
                return entry, not entry.reused
            self._drop_conn(addr, entry)
            entry = None
        if entry is None:
            task = asyncio.create_task(self._dial(addr, connect_timeout))
            self._conns[addr] = task
            task.add_done_callback(lambda t, a=addr: self._finish_dial(a, t))
            entry = task
        # shield: a caller timing out must not cancel the dial other
        # concurrent callers are waiting on.
        conn = await asyncio.shield(entry)
        return conn, True

    async def _roundtrip(
        self,
        conn: "_Conn",
        addr: Addr,
        method: str,
        args: Optional[dict],
        payload: WirePayload,
        chunk_sink: Optional[Callable[[int, int, bytes], None]],
        record_latency: bool,
    ) -> Tuple[dict, bytes]:
        rid = uuid.uuid4().hex[:16]
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        conn.pending[rid] = fut
        if chunk_sink is not None:
            conn.sinks[rid] = chunk_sink
        t0 = time.monotonic()
        started: list = []
        req_meta = {
            "rid": rid, "method": method, "args": args or {},
            "dst": [addr[0], addr[1]],
        }
        # Ambient round-trace id (swarm/telemetry.py) rides the frame meta:
        # the server half restores it around the handler, stitching the
        # remote spans into this round's trace with zero extra RPCs.
        tr = telemetry.current_trace()
        if tr:
            req_meta["tr"] = tr
        try:
            try:
                # dst (the dialed address) rides inside the MAC'd meta so an
                # authenticated frame is only acceptable at the node it was
                # sent to (see module doc: cross-node replay).
                await self._write_message(
                    conn.writer, conn.wlock, TYPE_REQ,
                    req_meta, payload, peer=addr, started=started,
                )
            except BaseException:
                # A failure (or cancellation) mid-write leaves the
                # multiplexed stream half-way through a message: poison the
                # connection so no other call inherits a desynced wire.
                # Cancelled while still QUEUED on the write lock (no byte
                # out yet) the stream is untouched — the connection, and
                # every other RPC in flight on it, survives.
                if started:
                    conn.close()
                raise
            ftype, meta, resp_payload = await fut
        finally:
            conn.pending.pop(rid, None)
            conn.sinks.pop(rid, None)
            if fut.done() and not fut.cancelled():
                # Consume a result/exception the demux set concurrently with
                # our own cancellation — silences 'exception was never
                # retrieved' for races between a timeout and a conn death.
                fut.exception()
        st = self._peer(addr)
        st.rpcs += 1
        if record_latency:
            st.observe_latency(time.monotonic() - t0)
        rx_bps = meta.get("rx_bps") if isinstance(meta, dict) else None
        if (
            isinstance(rx_bps, (int, float))
            and not isinstance(rx_bps, bool)
            and 0 < rx_bps < 1e12
        ):
            # The receiver's measured arrival rate of our bulk request
            # payload (see _handle_request): the honest uplink sample —
            # our own drain() timing only measures the kernel buffer.
            st.observe_bw_up(float(rx_bps))
        self.rpcs_sent += 1
        conn.reused = True
        if ftype == TYPE_ERR:
            raise RPCError(meta.get("error", "unknown remote error"))
        if meta.get("rid") != rid:
            raise RPCError("response rid mismatch")
        return meta.get("ret", {}), resp_payload

    async def call(
        self,
        addr,
        method: str,
        args: Optional[dict] = None,
        payload: WirePayload = b"",
        timeout: float = 30.0,
        connect_timeout: Optional[float] = None,
        chunk_sink: Optional[Callable[[int, int, bytes], None]] = None,
        record_latency: bool = True,
    ) -> Tuple[dict, bytes]:
        """One RPC to ``addr``; raises RPCError/OSError/TimeoutError on failure.

        ``connect_timeout`` bounds the dial (when no pooled connection
        exists); ``timeout`` bounds the RPC itself, starting AFTER the
        connection is up — a slow dial can no longer eat the whole budget.
        On a pooled connection that turns out stale (idle-closed socket,
        restarted peer) the call transparently redials and retries EXACTLY
        once with a fresh rid (a ``chunk_sink`` with a ``reset`` attribute
        is reset first, discarding any chunks the dead stream delivered);
        fresh-connection failures, RPC errors, and timeouts are never
        retried. ``payload`` may be bytes or a StreamPayload (chunks
        encoded while earlier ones are in flight); ``chunk_sink(offset,
        total, data)``, when given, receives the response payload's
        verified chunks as they arrive (the returned payload is then
        empty). ``record_latency=False`` keeps this call out of the
        per-peer latency EWMA — REQUIRED for calls that park on the remote
        handler by design (a member's result fetch) or move bulk payloads,
        since that EWMA feeds the failure detector's straggler suspicion
        and must sample only quick control-plane RPCs."""
        addr = (str(addr[0]), int(addr[1]))
        if connect_timeout is None:
            connect_timeout = min(self.connect_timeout, timeout)
        # ONE deadline across both attempts: the transparent retry must not
        # double the budget the caller planned around (averaging rounds pass
        # their remaining deadline-wait here).
        deadline = time.monotonic() + timeout
        attempt = 0
        while True:
            attempt += 1
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise asyncio.TimeoutError()
            if self.pooled:
                conn, fresh = await self._checkout_conn(
                    addr, min(connect_timeout, remaining)
                )
            else:
                conn, fresh = await self._dial(addr, min(connect_timeout, remaining)), True
            try:
                return await asyncio.wait_for(
                    self._roundtrip(
                        conn, addr, method, args, payload, chunk_sink,
                        record_latency,
                    ),
                    timeout=max(deadline - time.monotonic(), 0.001),
                )
            except (asyncio.TimeoutError, TimeoutError):
                # Never retried, and explicit: on Python >= 3.11
                # asyncio.TimeoutError IS builtins.TimeoutError, an OSError
                # subclass — without this clause the conn-error handler
                # below would close the pooled connection and silently
                # re-send the timed-out RPC with a fresh budget.
                raise
            except (ConnectionResetError, BrokenPipeError, asyncio.IncompleteReadError, OSError) as e:
                conn.close()
                if fresh or attempt > 1:
                    if isinstance(e, asyncio.IncompleteReadError):
                        raise ConnectionResetError(
                            f"connection to {addr[0]}:{addr[1]} lost mid-call"
                        ) from e
                    raise
                # Stale pooled socket (the peer idle-closed it, or restarted
                # since we dialed): one transparent retry on a fresh
                # connection — a peer restart is a retried call, not an
                # error surfaced to the averager.
                if chunk_sink is not None:
                    # The dead stream may have delivered some response
                    # chunks already; the retry re-delivers from offset 0,
                    # so the sink must forget them or its accounting
                    # double-counts and fails the very call the retry saves.
                    reset = getattr(chunk_sink, "reset", None)
                    if reset is not None:
                        reset()
                log.debug(
                    "pooled connection to %s:%d stale (%s); redialing once",
                    addr[0], addr[1], errstr(e),
                )
            finally:
                if not self.pooled:
                    conn.close()
