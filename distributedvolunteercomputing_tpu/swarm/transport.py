"""DCN transport: the WAN tensor/RPC fabric between volunteer slices.

TPU-native replacement for the reference's gloo/NCCL WAN path
(BASELINE.json:5): intra-slice collectives ride ICI inside ``pjit`` and never
touch this layer; everything BETWEEN volunteer slices — DHT RPCs, gossip,
butterfly rounds, robust aggregation — crosses here.

Design:
- asyncio TCP, length-prefixed binary frames; JSON meta + raw tensor payload
  (a param pytree crosses as ONE contiguous buffer from utils.pytree).
- CRC32-guarded payloads: WAN volunteers are untrusted/lossy, and the
  Byzantine path (config 5) must distinguish corruption from malice.
- One connection per call: volunteer churn means peers vanish mid-round;
  per-call connections make failure units obvious and retries trivial.
  The native C++ core (native/) accelerates checksum + quantization of the
  payload bytes; the socket path stays asyncio.
- Optional shared-secret message authentication (``secret=``): every frame
  carries an HMAC-SHA256 over (frame type, canonical meta, payload) plus a
  timestamp bounded by ``auth_window``. One chokepoint covers the whole
  swarm tier — DHT records, membership, state sync, and averaging
  contributions all cross this transport, so identity spoofing (which the
  Byzantine first-write-wins rule implicitly trusts) requires the secret,
  not just an open port. Replay is closed at this layer too, on two axes:
  SAME-NODE replay — every request carries a fresh uuid ``rid`` inside the
  MAC'd meta, so legitimate request frames are never byte-identical, and
  the server rejects an already-accepted MAC within the auth window;
  CROSS-NODE replay — the MAC also binds ``dst`` (the address the caller
  dialed), so a frame captured on its way to node X is refused by node Y
  (a captured membership heartbeat or DHT announce can NOT be re-played
  anywhere to keep a departed peer alive). Authenticated swarms must
  therefore dial peers at their advertised addresses — which every code
  path does (addresses always come from DHT/membership records).
  Responses need no cache: per-call connections mean a client reads
  exactly one response on its own stream, and the MAC binds the echoed
  ``rid`` to this request.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import json
import struct
import time
import uuid
import zlib
from collections import deque
from typing import Awaitable, Callable, Dict, Optional, Tuple

from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

MAGIC = b"DV"
VERSION = 1
TYPE_REQ, TYPE_RESP, TYPE_ERR = 1, 2, 3
_HEADER = struct.Struct("!2sBBIQI")  # magic, version, type, meta_len, payload_len, payload_crc32
MAX_PAYLOAD = 2 << 30  # 2 GiB guard
MAX_META = 4 << 20  # 4 MiB: meta is a small JSON dict, never tensor data

Addr = Tuple[str, int]
Handler = Callable[[dict, bytes], Awaitable[Tuple[dict, bytes]]]


class RPCError(Exception):
    """Remote handler raised, or the wire was corrupt."""


def read_secret(path: Optional[str]) -> Optional[bytes]:
    """Swarm secret from a file (whitespace-stripped); None = auth off.
    A file, not a flag value — secrets in argv leak via process listings."""
    if not path:
        return None
    with open(path, "rb") as fh:
        secret = fh.read().strip()
    if not secret:
        raise ValueError(f"swarm secret file {path!r} is empty")
    return secret


class Transport:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        advertise_host: Optional[str] = None,
        secret: Optional[bytes] = None,
        auth_window: float = 300.0,
    ):
        self._secret = secret
        self._auth_window = auth_window
        # Accepted-request MAC cache (replay rejection; see module doc).
        # FIFO deque gives cheap age+cap eviction: entries arrive in ~ts
        # order, so pruning from the left is enough.
        self._seen_macs: Dict[str, float] = {}
        self._seen_order: "deque[Tuple[float, str]]" = deque()
        self._host = host
        self._port = port
        # Bind address != reachable address when binding 0.0.0.0 (or behind
        # NAT): peers must be told an address they can dial, or every DHT
        # record we publish points back at the reader's own machine.
        self._advertise_host = advertise_host
        if advertise_host is None and host in ("0.0.0.0", "::", ""):
            log.warning(
                "binding %s without advertise_host: remote peers cannot dial "
                "the advertised address; pass --advertise-host for multi-host swarms",
                host or "ANY",
            )
        self._server: Optional[asyncio.base_events.Server] = None
        self._handlers: Dict[str, Handler] = {}
        # WAN accounting (frame headers + meta + payload, both directions):
        # the evidence behind wire-codec claims — experiments read these off
        # the volunteer summary instead of estimating.
        self.bytes_sent = 0
        self.bytes_received = 0

    @property
    def addr(self) -> Addr:
        """The ADVERTISED (dialable) address, used in every published record."""
        return (self._advertise_host or self._host, self._port)

    def register(self, method: str, handler: Handler) -> None:
        self._handlers[method] = handler

    async def start(self) -> Addr:
        self._server = await asyncio.start_server(self._serve_conn, self._host, self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        return self.addr

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- wire helpers ------------------------------------------------------

    def _mac(self, ftype: int, meta: dict, payload: bytes) -> str:
        """HMAC over (frame type, canonical meta minus auth, payload)."""
        canon = json.dumps(
            {k: v for k, v in meta.items() if k != "auth"},
            sort_keys=True,
            separators=(",", ":"),
        ).encode()
        return hmac.new(
            self._secret, bytes([ftype]) + canon + payload, hashlib.sha256
        ).hexdigest()

    async def _write_frame(
        self, writer: asyncio.StreamWriter, ftype: int, meta: dict, payload: bytes
    ) -> None:
        if self._secret is not None:
            meta = dict(meta, ts=round(time.time(), 3))
            meta["auth"] = self._mac(ftype, meta, payload)
        meta_b = json.dumps(meta).encode()
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        writer.write(_HEADER.pack(MAGIC, VERSION, ftype, len(meta_b), len(payload), crc))
        writer.write(meta_b)
        writer.write(payload)
        self.bytes_sent += _HEADER.size + len(meta_b) + len(payload)
        await writer.drain()

    async def _read_frame(self, reader: asyncio.StreamReader) -> Tuple[int, dict, bytes]:
        header = await reader.readexactly(_HEADER.size)
        magic, version, ftype, meta_len, payload_len, crc = _HEADER.unpack(header)
        if magic != MAGIC or version != VERSION:
            raise RPCError(f"bad frame header: magic={magic!r} version={version}")
        if payload_len > MAX_PAYLOAD:
            raise RPCError(f"payload {payload_len} exceeds {MAX_PAYLOAD}")
        if meta_len > MAX_META:
            raise RPCError(f"meta {meta_len} exceeds {MAX_META}")
        meta_b = await reader.readexactly(meta_len) if meta_len else b"{}"
        try:
            meta = json.loads(meta_b)
        except (ValueError, RecursionError) as e:
            # Attacker-controlled bytes: a JSONDecodeError is a ValueError,
            # not an RPCError — without this wrap it would escape the serve
            # loop's bad-frame containment and kill the connection task with
            # an unhandled exception instead of a clean error frame.
            # RecursionError too: deeply-nested JSON (200 KB of '[' fits
            # comfortably under MAX_META) blows the parser's stack.
            raise RPCError(f"malformed frame meta (not JSON: {e})") from e
        if not isinstance(meta, dict):
            # json.loads happily returns lists/scalars; meta.get() downstream
            # would AttributeError outside the containment net.
            raise RPCError(f"malformed frame meta (not an object: {type(meta).__name__})")
        payload = await reader.readexactly(payload_len) if payload_len else b""
        self.bytes_received += _HEADER.size + meta_len + payload_len
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            raise RPCError("payload CRC mismatch (corrupt frame)")
        if self._secret is not None:
            got = meta.get("auth", "")
            if not isinstance(got, str) or not hmac.compare_digest(
                got, self._mac(ftype, meta, payload)
            ):
                raise RPCError("auth failure (missing/invalid frame HMAC)")
            ts = meta.get("ts")
            if not isinstance(ts, (int, float)) or abs(time.time() - ts) > self._auth_window:
                raise RPCError("auth failure (frame timestamp outside window)")
            if ftype == TYPE_REQ:
                if not self._dst_is_me(meta.get("dst")):
                    # The MAC binds the address the caller DIALED: a frame
                    # captured en route to another node must not be
                    # replayable here (per-node seen-MAC caches can't see
                    # each other).
                    raise RPCError("auth failure (frame addressed to a different node)")
                if not self._mac_fresh(got, float(ts)):
                    # A fresh rid is in every legitimate request's MAC'd
                    # meta, so an identical MAC within the window is a
                    # replay.
                    raise RPCError("auth failure (replayed request frame)")
        return ftype, meta, payload

    def _dst_is_me(self, dst) -> bool:
        """Is the MAC'd destination this node? Port must match the bound
        port; the host may be any name this node is legitimately dialed by
        (advertised, bound, or loopback). Alias sets of distinct nodes
        cannot collide: same machine implies distinct ports, distinct
        machines implies distinct hosts."""
        if not (isinstance(dst, (list, tuple)) and len(dst) == 2):
            return False
        host, port = dst
        if port != self._port:
            return False
        aliases = {self._advertise_host, self._host, "127.0.0.1", "localhost"}
        return host in aliases

    # Hard cap on remembered request MACs: ~5 MB worst case, and at any
    # realistic RPC rate the age-based pruning keeps it far smaller.
    MAX_SEEN_MACS = 65536

    def _mac_fresh(self, mac: str, ts: float) -> bool:
        """Record ``mac``; False if it was already accepted in the window.

        Entries are retained until max(accept_time, frame ts) + auth_window:
        a frame from an ahead-of-clock peer stays timestamp-valid until
        ts + window, so evicting by accept time alone would reopen a replay
        window of exactly the sender's clock skew."""
        now = time.time()
        cutoff = now - self._auth_window
        order, seen = self._seen_order, self._seen_macs
        while order and (order[0][0] < cutoff or len(order) > self.MAX_SEEN_MACS):
            _, old = order.popleft()
            seen.pop(old, None)
        if mac in seen:
            return False
        seen[mac] = now
        order.append((max(now, ts), mac))
        return True

    # -- server ------------------------------------------------------------

    async def _serve_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    ftype, meta, payload = await self._read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                except RPCError as e:
                    # Corrupt frame (bad magic / CRC mismatch / oversize):
                    # the stream position is untrustworthy past this point,
                    # so report the reason and drop the connection — the
                    # caller can then distinguish corruption from a
                    # disconnect (the Byzantine path needs that signal).
                    try:
                        await self._write_frame(
                            writer, TYPE_ERR, {"rid": "", "error": f"bad frame: {e}"}, b""
                        )
                    except Exception:
                        pass
                    return
                if ftype != TYPE_REQ:
                    return
                method = meta.get("method", "")
                handler = self._handlers.get(method)
                rid = meta.get("rid", "")
                if handler is None:
                    await self._write_frame(
                        writer, TYPE_ERR, {"rid": rid, "error": f"no such method {method!r}"}, b""
                    )
                    continue
                try:
                    resp_meta, resp_payload = await handler(meta.get("args", {}), payload)
                except Exception as e:  # handler errors go back on the wire
                    log.debug("handler %s raised: %s", method, errstr(e))
                    await self._write_frame(
                        writer, TYPE_ERR, {"rid": rid, "error": f"{type(e).__name__}: {e}"}, b""
                    )
                    continue
                await self._write_frame(
                    writer, TYPE_RESP, {"rid": rid, "ret": resp_meta}, resp_payload
                )
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    # -- client ------------------------------------------------------------

    async def call(
        self,
        addr: Addr,
        method: str,
        args: Optional[dict] = None,
        payload: bytes = b"",
        timeout: float = 30.0,
    ) -> Tuple[dict, bytes]:
        """One RPC to ``addr``; raises RPCError/OSError/TimeoutError on failure."""

        async def _do() -> Tuple[dict, bytes]:
            reader, writer = await asyncio.open_connection(*addr)
            try:
                rid = uuid.uuid4().hex[:16]
                # dst (the dialed address) rides inside the MAC'd meta so an
                # authenticated frame is only acceptable at the node it was
                # sent to (see module doc: cross-node replay).
                await self._write_frame(
                    writer, TYPE_REQ,
                    {"rid": rid, "method": method, "args": args or {},
                     "dst": [addr[0], addr[1]]},
                    payload,
                )
                ftype, meta, resp_payload = await self._read_frame(reader)
                # Errors first: a frame-level rejection (corrupt request) has
                # no rid to echo; per-call connections mean nothing else can
                # be in flight, so this cannot mask a stale response.
                if ftype == TYPE_ERR:
                    raise RPCError(meta.get("error", "unknown remote error"))
                if meta.get("rid") != rid:
                    raise RPCError("response rid mismatch")
                return meta.get("ret", {}), resp_payload
            finally:
                writer.close()
                try:
                    await writer.wait_closed()
                except Exception:
                    pass

        return await asyncio.wait_for(_do(), timeout=timeout)
