"""Sharded, replicated control plane: membership/matchmaking state in the
swarm itself, served by elected coordinator replicas.

PRs 1-8 made the data plane survive leader death, stragglers, partitions,
and zone-level churn — but the coordinator stayed one stateful host holding
membership rollups and ``coord.status``; kill it and the swarm went blind.
At scale the CONTROL plane, not the data plane, is what breaks first (the
100k-GPU HSDP observation in PAPERS.md), and Moshpit-style matchmaking shows
the state belongs in the DHT. This module makes coordinator death a
non-event:

- **State lives in the DHT.** Membership records were already DHT soft
  state (``peers``); the per-peer metrics rollups that used to live only in
  the coordinator's process memory now ride TTL'd DHT records
  (``cp/rollup``), so ANY replica can serve ``coord.status`` by merging
  them.
- **Elected replicas, key-range sharded.** Every candidate (a standalone
  coordinator process, or any volunteer run with ``--host-replica``)
  announces under ``cp/replicas``; the ACTIVE set is the first
  ``MAX_REPLICAS`` live candidates in replica-id order — the same
  deterministic smallest-id election every other leader slot in this repo
  uses, computed by everyone from the same soft state. The 160-bit peer-id
  keyspace is cut into ``N_SHARDS`` fixed arcs; active replica *i* of *R*
  owns the contiguous shard range ``[i*S/R, (i+1)*S/R)`` and ingests
  reports / flushes heartbeats / writes rollups for the peers whose ids
  hash into it.
- **Epoch-fenced handoff.** Shard ownership moves on replica churn exactly
  the way round leadership moved in PR 4: the acquiring replica bumps the
  shard's GENERATION and every control-plane write carries it
  (``DHTNode.store(fence=gen)``); storage nodes refuse writes below their
  watermark, so a deposed/partitioned ex-replica's late rollup can never
  shadow the new owner's on any node that saw the claim. Status merges
  additionally prefer the highest generation among the rollup records they
  read, and the owner re-writes every tick — so a laggard storage node
  that accepted stale bytes is corrected within one interval (the
  record-level merge inside one ``dht.get`` is freshness-based, not
  generation-based; the exposure is tick-bounded, not eliminated).
- **Batched heartbeats.** A volunteer's per-interval control traffic —
  membership announce + metrics report + peers-snapshot refresh — coalesces
  into ONE ``cp.exchange`` RPC to its shard owner (PR 2 made the connection
  cheap; this cuts the message count). The replica flushes a whole shard's
  records to the DHT as one batched ``dht.store`` frame per storage
  replica (``store_many``), so N peers' beats cost O(K) RPCs per interval
  instead of O(N*K). Volunteers fall back to the direct DHT path the
  moment no replica answers — the control plane accelerates the swarm, it
  never gates it.

Trust model matches the rest of the swarm: replicas are honest-but-mortal
(transport HMAC keeps outsiders out; a Byzantine replica is out of scope —
it could already lie in ``coord.status``, which steers no tensor).
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from typing import Dict, List, Optional, Tuple

from distributedvolunteercomputing_tpu.swarm.dht import (
    ID_BITS,
    DHTNode,
    StaleWriteFenced,
    key_id,
)
from distributedvolunteercomputing_tpu.swarm.membership import PEERS_KEY
from distributedvolunteercomputing_tpu.swarm import controller as controller_mod
from distributedvolunteercomputing_tpu.swarm import health as health_mod
from distributedvolunteercomputing_tpu.swarm import telemetry as telemetry_mod
from distributedvolunteercomputing_tpu.swarm import watchdog as watchdog_mod
from distributedvolunteercomputing_tpu.swarm.transport import Addr, Transport
from distributedvolunteercomputing_tpu.utils.logging import errstr, get_logger

log = get_logger(__name__)

REPLICAS_KEY = "cp/replicas"
ROLLUP_KEY = "cp/rollup"
# Fixed shard count, independent of how many replicas are live: ownership
# generations are PER SHARD, so the shard grid must not re-index itself
# when a replica joins or dies (only the owner mapping moves).
N_SHARDS = 16
# Active-set cap: candidates beyond this stay hot standbys (announced,
# serving status reads, owning nothing) until churn promotes them.
MAX_REPLICAS = 5


def shard_of(peer_id: str) -> int:
    """Fixed key-range shard a peer id hashes into (equal arcs of the
    160-bit keyspace — the same arc idiom the group schedule uses)."""
    return (key_id(peer_id) * N_SHARDS) >> ID_BITS


def owner_index(shard: int, n_replicas: int) -> int:
    """Which active replica (by index in rid-sorted order) owns ``shard``:
    contiguous ranges, so each replica serves one key range."""
    return shard * n_replicas // N_SHARDS


def active_replicas(records: Dict[str, dict]) -> List[Tuple[str, Addr]]:
    """The elected ACTIVE replica set from ``cp/replicas`` soft state:
    live, non-retiring candidates in rid order, first MAX_REPLICAS.
    Deterministic and local — every volunteer computes the same set from
    the same records, no negotiation (divergence from staleness costs one
    misrouted-then-failed-over RPC, never lost state)."""
    out: List[Tuple[str, Addr]] = []
    for rid in sorted(records):
        rec = records.get(rid)
        if not isinstance(rec, dict) or rec.get("retiring"):
            continue
        addr = rec.get("addr")
        if isinstance(addr, (list, tuple)) and len(addr) == 2:
            out.append((rid, (str(addr[0]), int(addr[1]))))
    return out[:MAX_REPLICAS]


class ControlPlaneReplica:
    """One control-plane replica: stateless-front coordinator logic any
    host can run. All durable state is DHT soft state; everything held in
    process memory is a cache or at most one reporting window deep, so a
    SIGKILL loses nothing a surviving replica can't re-serve within one
    heartbeat interval."""

    REPLICA_TTL = 15.0
    ROLLUP_TTL = 75.0
    # Reports older than this fall out of status rollups (same freshness
    # line the single coordinator drew).
    FRESH_S = 60.0
    COMMIT_WINDOW_S = 60.0
    # Volunteer ids are fresh uuids per process, so churn would grow the
    # per-peer maps without bound on a long-running replica; a peer silent
    # this long is dropped (a late reappearance re-seeds its commit
    # baseline at delta 0, identical to first sight).
    STALE_PEER_TTL_S = 600.0
    # Rendezvous read micro-cache: every member of a forming group polls
    # the same round key at ~100 ms cadence; one iterative DHT lookup per
    # cache window serves them all.
    RENDEZVOUS_CACHE_S = 0.25
    MAX_RENDEZVOUS_CACHE = 128
    RETIRE_TTL = 5.0

    def __init__(
        self,
        transport: Transport,
        dht: DHTNode,
        rid: Optional[str] = None,
        interval: Optional[float] = None,
        metrics_path: Optional[str] = None,
        telemetry=None,
    ):
        self.transport = transport
        self.dht = dht
        # Replica id: ELECTION RANK (smallest-id-first, like every leader
        # slot here). Stable per host:port so a restarted replica re-takes
        # its slot instead of reshuffling every shard. Resolved at start()
        # when no explicit id was given (the bound port isn't known yet).
        self._rid_given = rid
        self.rid = rid or f"cpr-{key_id(f'{transport.addr}') % 10**10:010d}"
        self.interval = float(interval) if interval else self.REPLICA_TTL / 3.0
        self.metrics_path = metrics_path
        self._t0 = time.time()
        # peer -> latest report (+recv_t): the live ingestion cache; the
        # durable form is the per-shard DHT rollup written every tick.
        self.latest_metrics: Dict[str, dict] = {}
        # Commit-rate / cross-zone-byte windows, PER SHARD so they ride the
        # shard's rollup record and survive this replica's death (the new
        # owner adopts the freshest rollup's window and re-seeds deltas).
        self._commit_seen: Dict[str, int] = {}
        self._commit_window: Dict[int, list] = {}
        self._xz_seen: Dict[str, int] = {}
        self._xz_window: Dict[int, list] = {}
        # Membership records heartbeated THROUGH this replica (batched
        # cp.exchange): pid -> (record_or_tombstone, expiry_mono, ttl).
        self._mem_records: Dict[str, Tuple[Optional[dict], float, float]] = {}
        self._mem_dirty: set = set()
        # Cached DHT views (refreshed once per tick, serving every client
        # between ticks): the whole point — N clients cost O(1) lookups.
        self._peers_view: Dict[str, object] = {}
        self._replica_view: Dict[str, dict] = {}
        self._rollup_view: Dict[str, dict] = {}
        self._views_t = 0.0
        # Peers-snapshot delta state (batched exchange replies ship
        # changes-since-version instead of the full map every beat — at
        # fleet scale the full map is the dominant control-plane byte
        # stream). _pv is this replica's monotone snapshot version;
        # _psig holds per-peer SIGNIFICANCE signatures (the beat
        # timestamp and jittery measured floats excluded — else every
        # record "changes" every beat and deltas degenerate to fulls);
        # _plog is the (version, pid) change log a delta is computed
        # from, trimmed whole-version-batches at a time so _plog_floor
        # (the oldest version a delta can be served FROM) is exact.
        # Versions are PER-REPLICA: clients echo (rid, version) and a
        # failover lands on a replica whose rid mismatch forces one
        # full-replace — stale-version fallback, by construction.
        self._pv = 0
        self._psig: Dict[str, str] = {}
        self._plog: List[Tuple[int, str]] = []
        self._plog_floor = 0
        self._psig_t = 0.0
        self._rendezvous_cache: Dict[str, Tuple[float, dict]] = {}
        # shard -> generation this replica owns it at (fence for writes).
        self._shard_gens: Dict[int, int] = {}
        # Highest fence watermark ever reported back for a shard
        # (StaleWriteFenced.gen): re-acquisition must claim ABOVE it, not
        # above the rollup record's gen — the record TTLs out in ~75s
        # while the watermark holds for FENCE_TTL (600s), and deriving the
        # claim from the record alone would livelock the shard against
        # the watermark for the difference (claim gen 1, fenced by gen 5,
        # drop, repeat) after any ownership gap longer than ROLLUP_TTL.
        self._gen_floor: Dict[int, int] = {}
        self.retiring = False
        # Peer replicas that failed a liveness probe (rid -> expiry_mono):
        # pruned from the active set and from every served replica view,
        # so a SIGKILLed replica disappears from the control plane within
        # ONE TICK — clients and ownership handoff do not wait out the
        # replica record's TTL. Negative-cached briefly so a corpse is
        # not re-probed every tick forever; a revived replica re-enters
        # once the cache entry lapses (ping-before-evict, control-plane
        # edition).
        self._dead_replicas: Dict[str, float] = {}
        # Consecutive soft probe failures per peer replica (see
        # _probe_replicas): one timeout under load must not depose a live
        # replica.
        self._probe_strikes: Dict[str, int] = {}
        self._tick_task: Optional[asyncio.Task] = None
        # Load/observability counters (the control-plane bench reads these).
        self.counters: Dict[str, int] = {
            "exchanges": 0, "joins": 0, "reports": 0, "status_served": 0,
            "rendezvous_served": 0, "rendezvous_lookups": 0,
            "rollup_writes": 0, "rollups_fenced": 0, "shards_acquired": 0,
            "shards_released": 0, "mem_flushed": 0,
            "peers_delta_replies": 0, "peers_full_replies": 0,
        }
        transport.register("coord.report", self._rpc_report)
        transport.register("coord.status", self._rpc_status)
        transport.register("cp.exchange", self._rpc_exchange)
        transport.register("cp.rendezvous", self._rpc_rendezvous)
        transport.register("cp.ping", self._rpc_ping)
        # Replica-side telemetry: the load counters re-register into a
        # scrapeable registry, and the telemetry.* debug RPCs answer on
        # the replica's transport too (a coordinator is also a fleet
        # member). A volunteer hosting a replica passes its OWN bundle —
        # the shared transport already serves that bundle's RPCs, so the
        # replica source lands in the registry every scrape reaches (and
        # honors the host's --no-telemetry); a standalone coordinator
        # gets a private bundle plus the RPC registration.
        if telemetry is not None:
            self.telemetry = telemetry
        else:
            self.telemetry = telemetry_mod.Telemetry(peer_id=self.rid)
            self.telemetry.register_rpcs(transport)
        self.telemetry.registry.source("control_plane.replica", self.stats)
        # Swarm watchdog (swarm/watchdog.py): SLO burn rates over the
        # merged rollup plus the swarm-level detectors no volunteer can
        # see (cross-zone mixing stall), evaluated once per tick and
        # served as coord.status["slo"] / ["alerts"]. Replica-side only —
        # pure rollup math, no per-round cost — so it stays on even when
        # a hosting volunteer disabled its own telemetry.
        self.watchdog = watchdog_mod.SwarmWatchdog(
            recorder=self.telemetry.recorder, peer_id=self.rid,
        )

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self._rid_given is None:
            self.rid = f"cpr-{key_id(f'{self.transport.addr}') % 10**10:010d}"
        await self._announce()
        await self._refresh_views()
        await self._recompute_ownership()
        # Claim writes for acquired shards go out IMMEDIATELY: the fenced
        # store is what raises the watermark that deposes the previous
        # owner — waiting a tick would leave a handoff window where its
        # stale writes still land.
        await self._write_rollups()
        self._tick_task = asyncio.create_task(self._tick_loop())
        log.info(
            "control-plane replica %s up on %s:%d (owns %d/%d shards)",
            self.rid, *self.transport.addr, len(self._shard_gens), N_SHARDS,
        )

    async def stop(self) -> None:
        if self._tick_task is not None:
            self._tick_task.cancel()
            try:
                await self._tick_task
            except (asyncio.CancelledError, Exception):
                pass
            self._tick_task = None

    async def retire(self, grace: float = 0.5) -> None:
        """Graceful shutdown (SIGTERM): publish a RETIRING tombstone under
        our replica record so volunteers and peer replicas re-resolve the
        active set immediately — within one exchange round-trip — instead
        of waiting for the record's TTL to expire. Keeps serving for
        ``grace`` so in-flight exchanges drain and the tombstone
        propagates; one final membership flush so records heartbeated
        through us don't gap while their owners re-route."""
        self.retiring = True
        try:
            await self.dht.store(
                REPLICAS_KEY, self._self_record(), subkey=self.rid,
                ttl=self.RETIRE_TTL,
            )
        except Exception as e:  # noqa: BLE001 — retiring must not hang shutdown
            log.warning("retire tombstone store failed: %s", errstr(e))
        try:
            await self._flush_mem_records(force=True)
        except Exception:
            pass
        if grace > 0:
            await asyncio.sleep(grace)
        await self.stop()
        log.info("control-plane replica %s retired", self.rid)

    def _self_record(self) -> dict:
        rec = {"addr": list(self.transport.addr), "t": time.time()}
        if self.retiring:
            rec["retiring"] = True
        return rec

    async def _announce(self) -> None:
        await self.dht.store(
            REPLICAS_KEY, self._self_record(), subkey=self.rid,
            ttl=self.RETIRE_TTL if self.retiring else self.REPLICA_TTL,
        )

    # -- periodic tick -----------------------------------------------------

    async def _tick_loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                await self._announce()
                await self._refresh_views()
                await self._probe_replicas()
                await self._recompute_ownership()
                await self._flush_mem_records()
                await self._write_rollups()
                self._sweep()
                self._eval_watchdog()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — the tick must not die
                log.warning("control-plane tick failed: %s", errstr(e))

    async def _rpc_ping(self, args: dict, payload: bytes):
        return {"rid": self.rid, "retiring": self.retiring}, b""

    async def _probe_replicas(self) -> None:
        """Liveness-probe the peer replicas in view (a handful, concurrent,
        fast-fail) and prune non-responders: a SIGKILLed replica must fall
        out of the ACTIVE set — and therefore out of every served replica
        view and the shard ownership map — within one tick, not one record
        TTL. A probe false-positive (a briefly-stalled replica) costs one
        spurious handoff generation, which the fencing arbitrates; the
        negative cache keeps a real corpse from being re-dialed every
        tick."""
        now = time.monotonic()
        self._dead_replicas = {
            r: e for r, e in self._dead_replicas.items() if e > now
        }
        targets = [
            (rid, rec.get("addr"))
            for rid, rec in self._replica_view.items()
            if rid != self.rid
            and rid not in self._dead_replicas
            and isinstance(rec.get("addr"), (list, tuple))
        ]
        if not targets:
            return

        async def probe(rid, addr):
            try:
                await self.transport.call(
                    tuple(addr), "cp.ping", {},
                    timeout=1.5, connect_timeout=1.0,
                )
                return rid, "ok"
            except ConnectionRefusedError:
                # Nothing listens on the advertised port: a genuine corpse
                # (SIGKILL path) — prune immediately.
                return rid, "dead"
            except Exception:  # noqa: BLE001 — timeout/transient
                # SOFT failure: a probe can time out because OUR loop or
                # the peer's is briefly saturated — one strike must not
                # depose a live replica (the false positive would ripple
                # into every served client view).
                return rid, "soft"

        for rid, verdict in await asyncio.gather(
            *(probe(rid, addr) for rid, addr in targets)
        ):
            if verdict == "ok":
                self._probe_strikes.pop(rid, None)
                continue
            strikes = self._probe_strikes.get(rid, 0) + 1
            self._probe_strikes[rid] = strikes
            if verdict == "dead" or strikes >= 2:
                self._dead_replicas[rid] = now + 4 * self.interval
                self._probe_strikes.pop(rid, None)
                log.info(
                    "replica %s: peer replica %s failed liveness probe "
                    "(%s), pruning from active set", self.rid, rid, verdict,
                )

    async def _refresh_views(self) -> None:
        # Stamped BEFORE the walks: concurrent exchanges must not stampede
        # duplicate lookups while one refresh is in flight.
        self._views_t = time.monotonic()
        self._replica_view = {
            rid: rec
            for rid, rec in (await self.dht.get(REPLICAS_KEY)).items()
            if isinstance(rec, dict)
        }
        # Tombstones (None) kept: a snapshot served to clients must carry
        # them so a leave propagates through batched beats too.
        self._peers_view = dict(await self.dht.get(PEERS_KEY))
        self._rollup_view = {
            sk: rec
            for sk, rec in (await self.dht.get(ROLLUP_KEY)).items()
            if isinstance(rec, dict)
        }

    def _live_replica_view(self) -> Dict[str, dict]:
        now = time.monotonic()
        return {
            rid: rec
            for rid, rec in self._replica_view.items()
            if not (
                rid in self._dead_replicas and self._dead_replicas[rid] > now
            )
        }

    def active_set(self) -> List[Tuple[str, Addr]]:
        view = self._live_replica_view()
        if not self.retiring:
            view[self.rid] = self._self_record()
        return active_replicas(view)

    async def _recompute_ownership(self) -> None:
        """Key-range handoff: recompute which shards this replica owns
        under the current active set; ACQUIRED shards claim generation =
        (highest seen in the shard's rollup record) + 1 — the PR-4 fencing
        move — so the deposed owner's next fenced write is refused."""
        active = self.active_set()
        rids = [rid for rid, _ in active]
        if self.retiring or self.rid not in rids:
            owned: set = set()
        else:
            i = rids.index(self.rid)
            owned = {
                s for s in range(N_SHARDS) if owner_index(s, len(rids)) == i
            }
        released = [s for s in self._shard_gens if s not in owned]
        for s in released:
            del self._shard_gens[s]
            # The windows go with the shard: keeping them would double-
            # count those deltas if this replica re-acquires later (it
            # re-adopts the then-current rollup's window below).
            self._commit_window.pop(s, None)
            self._xz_window.pop(s, None)
            self.counters["shards_released"] += 1
        fresh = [s for s in owned if s not in self._shard_gens]
        if released or fresh:
            # The per-peer delta BASELINES go with the shard too: a stale
            # baseline surviving a release/acquire cycle would compute a
            # delta spanning the other owner's tenure — commits already in
            # the adopted rollup window — and double-count them. Dropping
            # the baseline re-seeds the peer at first sight (delta 0), the
            # same contract a fresh replica has.
            moved = set(released) | set(fresh)
            for seen in (self._commit_seen, self._xz_seen):
                for pid in [p for p in seen if shard_of(p) in moved]:
                    del seen[pid]
        for s in fresh:
            prev = self._rollup_view.get(f"s{s}") or {}
            self._shard_gens[s] = (
                max(int(prev.get("gen") or 0), self._gen_floor.get(s, 0)) + 1
            )
            self.counters["shards_acquired"] += 1
            # ADOPT (replace, never merge) the previous owner's reporting
            # window so the commit-rate gauge survives the handoff: the
            # rollup is the authoritative view, and merging could repeat
            # deltas this replica saw in an earlier ownership stint.
            # Per-peer deltas re-seed at first sight, losing at most one
            # report per peer.
            self._commit_window[s] = [
                (float(t), int(d)) for t, d in (prev.get("commit_window") or [])
            ]
            self._xz_window[s] = [
                (float(t), int(d)) for t, d in (prev.get("xz_window") or [])
            ]
        if fresh:
            log.info(
                "replica %s acquired shards %s (gens %s)", self.rid,
                sorted(fresh), {s: self._shard_gens[s] for s in fresh},
            )

    async def _flush_mem_records(self, force: bool = False) -> None:
        """Write the membership records heartbeated through this replica to
        the shared ``peers`` DHT key — ONE batched store frame per storage
        replica for the whole cohort (vs one fan-out per peer on the
        direct path). Unfenced: membership subkeys are per-peer records
        only their own peer writes, so there is no cross-writer race for a
        generation to arbitrate."""
        now = time.monotonic()
        live = {
            pid: (rec, exp, ttl)
            for pid, (rec, exp, ttl) in self._mem_records.items()
            if exp > now
        }
        self._mem_records = live
        dirty = set(live) if force else (self._mem_dirty & set(live))
        self._mem_dirty = set()
        if not dirty:
            return
        await self.dht.store_many(
            PEERS_KEY,
            {pid: live[pid][0] for pid in dirty},
            ttls={pid: live[pid][2] for pid in dirty},
        )
        self.counters["mem_flushed"] += len(dirty)

    async def _write_rollups(self) -> None:
        """Fenced per-shard rollup writes: the durable (DHT) form of this
        replica's ingested reports. A StaleWriteFenced reply means a newer
        generation owns the shard — stop writing it and re-resolve."""
        now = time.time()
        fresh_cutoff = now - self.FRESH_S
        by_shard: Dict[int, Dict[str, dict]] = {}
        for pid, m in self.latest_metrics.items():
            if m.get("recv_t", 0) >= fresh_cutoff:
                by_shard.setdefault(shard_of(pid), {})[pid] = m
        for s in list(self._shard_gens):
            gen = self._shard_gens[s]
            cw = [
                (t, d) for t, d in self._commit_window.get(s, [])
                if t >= now - self.COMMIT_WINDOW_S
            ]
            xw = [
                (t, d) for t, d in self._xz_window.get(s, [])
                if t >= now - self.COMMIT_WINDOW_S
            ]
            self._commit_window[s] = cw
            self._xz_window[s] = xw
            rec = {
                "gen": gen,
                "rid": self.rid,
                "t": now,
                "peers": by_shard.get(s, {}),
                "commit_window": cw,
                "xz_window": xw,
            }
            try:
                # fence_owner arbitrates equal-generation claims from two
                # replicas with split views: smallest rid wins, the other
                # gets StaleWriteFenced and escalates — never a silent
                # dual-writer.
                await self.dht.store(
                    ROLLUP_KEY, rec, subkey=f"s{s}", ttl=self.ROLLUP_TTL,
                    fence=gen, fence_owner=self.rid,
                )
                self.counters["rollup_writes"] += 1
            except StaleWriteFenced as e:
                # Deposed: a newer owner claimed this key range while our
                # view was stale. Drop it now — the next tick's ownership
                # recompute decides whether we re-acquire, and the recorded
                # watermark floor guarantees any re-claim lands ABOVE the
                # generation that fenced us (the rollup record it would
                # otherwise derive from may long have expired).
                log.info(
                    "replica %s fenced off shard %d (watermark gen %d > "
                    "ours %d)", self.rid, s, e.gen, gen,
                )
                self._gen_floor[s] = max(self._gen_floor.get(s, 0), e.gen)
                self._shard_gens.pop(s, None)
                self.counters["rollups_fenced"] += 1
                self.counters["shards_released"] += 1

    def _eval_watchdog(self) -> None:
        """One SLO/detector evaluation over the merged view (tick-paced;
        the status path re-evaluates lazily under the same spacing guard).
        Advisory: a watchdog bug must never take the tick down."""
        try:
            fresh_map, commit_w, xz_w = self._merged_metrics()
            fresh = list(fresh_map.values())
            self.watchdog.evaluate(
                fresh,
                multigroup=self._multigroup_rollup(fresh, commit_w, xz_w),
                health=health_mod.rollup_status(fresh),
            )
        except Exception as e:  # noqa: BLE001
            log.debug("watchdog evaluation failed: %s", errstr(e))

    @staticmethod
    def _stamp_age(rollup: Optional[dict], fresh: list, now: float) -> Optional[dict]:
        """Staleness stamp for a status rollup section: seconds since the
        freshest contributing report landed — a frozen replica serves a
        growing age_s, a healthy quiet swarm a small one."""
        if rollup is None:
            return None
        recvs = [
            m.get("recv_t") for m in fresh
            if isinstance(m.get("recv_t"), (int, float))
        ]
        rollup["age_s"] = round(max(0.0, now - max(recvs)) if recvs else -1.0, 3)
        return rollup

    def _sweep(self) -> None:
        now = time.time()
        for p in [
            p for p, m in self.latest_metrics.items()
            if now - m.get("recv_t", 0) > self.STALE_PEER_TTL_S
        ]:
            self.latest_metrics.pop(p, None)
            self._commit_seen.pop(p, None)
            self._xz_seen.pop(p, None)
        # Windows for UNOWNED shards (strays ingested while a cohort
        # failed over through us) are trimmed here — the rollup writer
        # only trims the owned ones — so they cannot grow for the process
        # lifetime.
        cutoff = now - self.COMMIT_WINDOW_S
        for wmap in (self._commit_window, self._xz_window):
            for s in list(wmap):
                wmap[s] = [(t, d) for t, d in wmap[s] if t >= cutoff]
                if not wmap[s] and s not in self._shard_gens:
                    del wmap[s]
        if len(self._rendezvous_cache) > self.MAX_RENDEZVOUS_CACHE:
            self._rendezvous_cache.clear()

    # -- ingestion ---------------------------------------------------------

    def _ingest_report(self, report: dict) -> None:
        import json as _json

        peer = str(report.get("peer", "?"))
        now = time.time()
        self.latest_metrics[peer] = {**report, "recv_t": now}
        s = shard_of(peer)
        groups = report.get("groups")
        if isinstance(groups, dict):
            total = groups.get("rounds_ok")
            if isinstance(total, int):
                prev = self._commit_seen.get(peer)
                self._commit_seen[peer] = total
                if prev is None:
                    # First sight (fresh replica joining a long-running
                    # swarm, a new volunteer, or a shard handoff): seed the
                    # baseline only — injecting the lifetime total would
                    # report a bogus commit burst for the next window.
                    delta = 0
                elif total >= prev:
                    delta = total - prev
                else:
                    # Counter went backwards = the volunteer restarted;
                    # count from zero, don't subtract history.
                    delta = total
                if delta > 0:
                    self._commit_window.setdefault(s, []).append((now, delta))
            xz = groups.get("cross_zone_bytes_sent")
            if isinstance(xz, int):
                prev = self._xz_seen.get(peer)
                self._xz_seen[peer] = xz
                # Unlike the commit counter, a DECREASE here re-baselines
                # at delta 0: the byte sum is cumulative-but-not-strictly-
                # monotone (peer-stats LRU eviction / zone re-attribution),
                # and "count from zero" would re-inject a lifetime's bytes
                # as one phantom burst.
                xdelta = xz - prev if prev is not None and xz >= prev else 0
                if xdelta > 0:
                    self._xz_window.setdefault(s, []).append((now, xdelta))
        if self.metrics_path:
            with open(self.metrics_path, "a") as fh:
                fh.write(_json.dumps(self.latest_metrics[peer]) + "\n")

    # -- RPC handlers ------------------------------------------------------

    async def _rpc_report(self, args: dict, payload: bytes):
        """Legacy per-message metrics push (kept verbatim for mixed-version
        volunteers and tests); batched peers use cp.exchange instead."""
        self.counters["reports"] += 1
        self._ingest_report(args)
        return {"ok": True}, b""

    async def _rpc_exchange(self, args: dict, payload: bytes):
        """The coalesced per-interval control RPC: one frame carries the
        peer's membership announce AND its metrics report; the reply
        carries the peers snapshot AND the replica set — everything the
        volunteer's heartbeat interval needs, in one round trip."""
        self.counters["exchanges"] += 1
        if time.monotonic() - self._views_t > self.interval:
            # The serving views refresh once per interval regardless of
            # who pays (normally the tick; lazily here if the tick lagged
            # or the interval was stretched) — amortized over every client
            # served from the cache in between.
            await self._refresh_views()
        pid = str(args["peer"])
        ttl = float(args.get("ttl", 15.0))
        rec = args.get("record")
        if args.get("join"):
            self.counters["joins"] += 1
        self._mem_records[pid] = (rec, time.monotonic() + ttl, ttl)
        self._mem_dirty.add(pid)
        # The serving view must reflect this beat immediately: the NEXT
        # exchange this interval (any peer) already sees pid live.
        self._peers_view[pid] = rec
        report = args.get("report")
        if isinstance(report, dict):
            self._ingest_report(report)
        replicas = self._live_replica_view()
        # Our own record rides every reply (carries retiring=True during
        # the drain, which is how clients re-resolve "immediately").
        replicas[self.rid] = self._self_record()
        merged = self._merged_peers()
        self._version_peers(merged)
        reply: Dict[str, object] = {
            "ok": True,
            "rid": self.rid,
            "replicas": replicas,
            "peers_ver": self._pv,
        }
        cv = args.get("peers_ver")
        if (
            isinstance(cv, int)
            and not isinstance(cv, bool)
            and args.get("peers_rid") == self.rid
            and self._plog_floor <= cv <= self._pv
        ):
            # Delta reply: the records whose significance changed since
            # the client's version (None = departed/tombstoned), plus the
            # compact liveness sidecar — every live peer's beat timestamp
            # — so the client's failure detector keeps observing beats it
            # no longer receives full records for.
            changed = {p for v, p in self._plog if v > cv}
            reply["peers_delta"] = {p: merged.get(p) for p in changed}
            reply["beats"] = {
                p: r["t"] for p, r in merged.items()
                if isinstance(r, dict) and isinstance(r.get("t"), (int, float))
            }
            self.counters["peers_delta_replies"] += 1
        else:
            # Full replace: first contact, a failover from another
            # replica's version stream, or a client staler than the
            # change log covers.
            reply["peers"] = merged
            self.counters["peers_full_replies"] += 1
        return reply, b""

    def _merged_peers(self) -> Dict[str, object]:
        """Peers snapshot served to batched clients: the cached DHT view
        overlaid with records heartbeated through THIS replica (which are
        at most one flush behind in the DHT)."""
        now = time.monotonic()
        out = dict(self._peers_view)
        for pid, (rec, exp, _) in self._mem_records.items():
            if exp > now:
                out[pid] = rec
        return out

    # -- peers-snapshot deltas ---------------------------------------------

    # Change-log length bound; at ~one changed record per churn event this
    # covers minutes of heavy churn before a client falls back to a full.
    MAX_PLOG = 4096

    @staticmethod
    def _peers_sig(rec: object) -> str:
        """Significance signature of one membership record: what a delta
        considers "changed". The per-beat timestamp is EXCLUDED (it moves
        every beat by design — liveness rides the compact ``beats``
        sidecar instead) and floats are quantized to 2 significant digits
        (measured bandwidth EWMAs jitter every beat; a 1% wiggle is not a
        membership change)."""
        if not isinstance(rec, dict):
            return "~"
        parts = []
        for k in sorted(rec):
            if k == "t":
                continue
            v = rec[k]
            if isinstance(v, float):
                v = float(f"{v:.2g}")
            parts.append(f"{k}={v!r}")
        return hashlib.blake2b(
            "|".join(parts).encode(), digest_size=8
        ).hexdigest()

    def _version_peers(self, merged: Dict[str, object]) -> None:
        """Advance the snapshot version from a significance diff of the
        serving view. Record MUTATIONS are amortized once per interval
        like the view refresh itself (they already lag a delta by up to
        one interval through the view cache; same staleness class as
        every other serving-view read) — but a changed KEY SET (join,
        departure, expiry) bypasses the throttle: the live record store
        grows mid-interval as clients exchange, and a delta reply
        claiming the current version while the significance table
        predates a join would silently starve those clients of the new
        peer until the next interval tick. The diff runs over the MERGED
        view, so expiries and tombstones version exactly like fresh
        records do."""
        now = time.monotonic()
        if (
            self._psig
            and now - self._psig_t < self.interval
            and self._psig.keys() == merged.keys()
        ):
            return
        self._psig_t = now
        changed = set()
        for pid, rec in merged.items():
            s = self._peers_sig(rec)
            if self._psig.get(pid) != s:
                self._psig[pid] = s
                changed.add(pid)
        for pid in [p for p in self._psig if p not in merged]:
            del self._psig[pid]
            changed.add(pid)
        if not changed:
            return
        self._pv += 1
        self._plog.extend((self._pv, pid) for pid in sorted(changed))
        if len(self._plog) > self.MAX_PLOG:
            # Trim whole version batches: a partially-dropped version
            # would serve an INCOMPLETE delta as if it were complete.
            vcut = self._plog[len(self._plog) - self.MAX_PLOG][0]
            self._plog = [(v, p) for v, p in self._plog if v > vcut]
        self._plog_floor = (self._plog[0][0] - 1) if self._plog else self._pv

    async def _rpc_rendezvous(self, args: dict, payload: bytes):
        """Matchmaking rendezvous read through the replicated control
        plane: members polling a forming round's key hit the micro-cache
        instead of each paying an iterative DHT lookup per poll."""
        self.counters["rendezvous_served"] += 1
        key = str(args["key"])
        now = time.monotonic()
        hit = self._rendezvous_cache.get(key)
        if hit is not None and now - hit[0] <= self.RENDEZVOUS_CACHE_S:
            return {"ok": True, "rec": hit[1]}, b""
        rec = await self.dht.get(key)
        self.counters["rendezvous_lookups"] += 1
        if len(self._rendezvous_cache) >= self.MAX_RENDEZVOUS_CACHE:
            self._rendezvous_cache.clear()
        self._rendezvous_cache[key] = (now, rec)
        return {"ok": True, "rec": rec}, b""

    # -- status ------------------------------------------------------------

    def _merged_metrics(self) -> Tuple[Dict[str, dict], list, list]:
        """Swarm-wide fresh metrics + reporting windows, merged from the
        live local cache and every shard's DHT rollup. Per shard the
        highest GENERATION wins (fencing's reader half); per peer the
        freshest recv_t wins."""
        now = time.time()
        merged: Dict[str, dict] = {}
        best_gen: Dict[int, int] = {s: g for s, g in self._shard_gens.items()}
        commit_w: Dict[int, list] = {
            s: list(w) for s, w in self._commit_window.items()
            if s in self._shard_gens
        }
        xz_w: Dict[int, list] = {
            s: list(w) for s, w in self._xz_window.items()
            if s in self._shard_gens
        }
        for sk, rec in self._rollup_view.items():
            if not sk.startswith("s"):
                continue
            try:
                s = int(sk[1:])
            except ValueError:
                continue
            gen = int(rec.get("gen") or 0)
            if s in best_gen and gen <= best_gen[s]:
                continue  # our live ownership (or a newer rollup) wins
            best_gen[s] = gen
            commit_w[s] = [(float(t), int(d)) for t, d in rec.get("commit_window") or []]
            xz_w[s] = [(float(t), int(d)) for t, d in rec.get("xz_window") or []]
            for pid, m in (rec.get("peers") or {}).items():
                if not isinstance(m, dict):
                    continue
                cur = merged.get(pid)
                if cur is None or m.get("recv_t", 0) > cur.get("recv_t", 0):
                    merged[pid] = m
        # Local live cache LAST: whatever this replica ingested directly is
        # at least as fresh as what it wrote to the DHT.
        for pid, m in self.latest_metrics.items():
            cur = merged.get(pid)
            if cur is None or m.get("recv_t", 0) > cur.get("recv_t", 0):
                merged[pid] = m
        fresh = {
            pid: m for pid, m in merged.items()
            if now - m.get("recv_t", 0) < self.FRESH_S
        }
        cutoff = now - self.COMMIT_WINDOW_S
        commits = [
            (t, d) for w in commit_w.values() for t, d in w if t >= cutoff
        ]
        xz = [(t, d) for w in xz_w.values() for t, d in w if t >= cutoff]
        return fresh, commits, xz

    def _multigroup_rollup(
        self, fresh: list, commit_window: list, xz_window: list
    ) -> Optional[dict]:
        """Swarm-level view of the rotating group schedule, from the fresh
        reports that carry ``groups`` gauges. Namespaced PER GROUP — the
        flat per-peer maps elsewhere in status would silently average
        across groups — plus the rollups a dashboard needs: groups active
        this rotation, committed-round rate, and the slowest group's lag
        behind its last commit."""
        gstats = {
            m.get("peer", "?"): m["groups"]
            for m in fresh
            if isinstance(m.get("groups"), dict) and m["groups"].get("enabled")
        }
        if not gstats:
            return None
        now = time.time()
        rot = max(
            (gs.get("rot") for gs in gstats.values() if gs.get("rot") is not None),
            default=None,
        )
        active = {
            gs["group_id"] for gs in gstats.values() if gs.get("group_id")
        }
        # Per-group breakdown, merged across reporters. Counters are
        # volunteer-rounds (a committed group round counts once per member
        # that saw it commit) — a participation measure, not a round count.
        per_group: Dict[str, dict] = {}
        for peer, gs in gstats.items():
            for gid, rec in (gs.get("recent") or {}).items():
                g = per_group.setdefault(
                    gid,
                    {"volunteers": 0, "rounds_ok": 0, "rounds_skipped": 0,
                     "rounds_degraded": 0, "last_commit_t": None},
                )
                g["volunteers"] += 1
                for k in ("rounds_ok", "rounds_skipped", "rounds_degraded"):
                    g[k] += int(rec.get(k) or 0)
                t = rec.get("last_commit_t")
                if t is not None and (
                    g["last_commit_t"] is None or t > g["last_commit_t"]
                ):
                    g["last_commit_t"] = t
        # Slowest ACTIVE group's lag behind its last commit (volunteer
        # clocks, so skew-accurate only to ClockSync quality): the
        # "is any group silently stuck" gauge.
        lags = [
            now - per_group[gid]["last_commit_t"]
            for gid in active
            if gid in per_group and per_group[gid]["last_commit_t"] is not None
        ]
        # Per-zone breakdown (hierarchical schedule): volunteers, commit
        # totals, and each zone's cross-zone byte footprint — so an
        # operator sees WHICH zone is burning WAN bytes or lagging, not
        # one flat number averaging a DC slice against a home DSL line.
        per_zone: Dict[str, dict] = {}
        per_level: Dict[str, dict] = {}
        for gs in gstats.values():
            z = per_zone.setdefault(
                str(gs.get("zone") or ""),
                {"volunteers": 0, "rounds_ok": 0,
                 "cross_zone_bytes_sent": 0, "cross_zone_bytes_received": 0},
            )
            z["volunteers"] += 1
            z["rounds_ok"] += int(gs.get("rounds_ok") or 0)
            for k in ("cross_zone_bytes_sent", "cross_zone_bytes_received"):
                z[k] += int(gs.get(k) or 0)
            for lv, rec in (gs.get("levels") or {}).items():
                agg = per_level.setdefault(
                    str(lv),
                    {"rounds_ok": 0, "rounds_skipped": 0, "rounds_degraded": 0},
                )
                for k in agg:
                    agg[k] += int(rec.get(k) or 0)
        cutoff = now - self.COMMIT_WINDOW_S
        commits = sum(d for t, d in commit_window if t >= cutoff)
        xz_bytes = sum(d for t, d in xz_window if t >= cutoff)
        return {
            "volunteers": len(gstats),
            "rot": rot,
            "groups_active": len(active),
            "rounds_ok_total": sum(
                int(gs.get("rounds_ok") or 0) for gs in gstats.values()
            ),
            "commits_per_min": round(
                commits * 60.0 / self.COMMIT_WINDOW_S, 2
            ),
            "slowest_group_lag_s": round(max(lags), 3) if lags else None,
            "per_group": per_group,
            "per_zone": per_zone,
            "per_level": per_level or None,
            # The hierarchical schedule's headline metric, live: WAN bytes
            # that crossed a zone boundary (sent-side counters, each wire
            # byte counted once) per committed volunteer-round, over the
            # sliding window (None until a commit lands in it).
            "cross_zone_bytes_per_commit": (
                round(xz_bytes / commits, 1) if commits else None
            ),
        }

    async def _rpc_status(self, args: dict, payload: bytes):
        """Swarm-level view, servable from ANY replica: alive peers from
        the shared membership key, metrics merged across every shard's
        replicated rollup plus this replica's live ingestion cache."""
        self.counters["status_served"] += 1
        # Status is operator-cadence, not the hot path: pay the DHT walk so
        # the view is live (the batched exchange path is where the cached
        # views earn their keep).
        await self._refresh_views()
        peers = self._merged_peers()
        alive = {pid: rec for pid, rec in peers.items() if rec is not None}
        fresh_map, commit_w, xz_w = self._merged_metrics()
        fresh = list(fresh_map.values())
        agg_sps = sum(float(m.get("samples_per_sec", 0.0)) for m in fresh)
        multigroup = self._multigroup_rollup(fresh, commit_w, xz_w)
        now = time.time()
        health_roll = health_mod.rollup_status(fresh)
        # A status serve is also an evaluation opportunity (spacing-
        # guarded inside, so a status storm cannot inflate burn windows):
        # an operator probing a freshly-failed-over replica sees live
        # objectives, not a blank watchdog.
        self.watchdog.evaluate(fresh, multigroup=multigroup, health=health_roll)
        return {
            # Rotating group-schedule rollup (None until some volunteer
            # reports multi-group gauges).
            "multigroup": multigroup,
            # Telemetry-plane rollup (versioned; None until some volunteer
            # reports a telemetry summary): per-span count/sum merged
            # swarm-wide plus every reporter's verbatim summary — the
            # schema tests/test_telemetry.py pins per version. Every
            # rollup section carries an age_s staleness stamp (satellite:
            # a frozen replica is distinguishable from a quiet swarm).
            "telemetry": self._stamp_age(
                telemetry_mod.rollup_status(fresh), fresh, now
            ),
            # Training-health rollup (versioned; None until some volunteer
            # reports a health summary): cross-peer sketch dispersion —
            # the LIVE mixing error, global / per zone / across zone
            # means — plus mass-accounting stats, merged per-peer quality
            # scores, the flagged-peer union, and per-wire codec
            # distortion. Pinned by health.STATUS_HEALTH_SCHEMA.
            "health": self._stamp_age(health_roll, fresh, now),
            # Closed-loop controller rollup (versioned; None until some
            # volunteer reports a controller summary — a --no-adapt
            # fleet serves no section at all): worst regime per level,
            # topology/wire census, the tightest per-zone-pair cadence,
            # max learned deadline per level, transition totals + the
            # freshest transition with its reason. Pinned by
            # controller.STATUS_CONTROLLER_SCHEMA.
            "controller": self._stamp_age(
                controller_mod.rollup_status(fresh), fresh, now
            ),
            # Watchdog plane (versioned, ALWAYS dicts — the plane exists
            # the moment a replica does): declarative objectives with
            # fast/slow burn rates, and the swarm-wide firing-alert rollup
            # (volunteer-reported firing sets + replica-local swarm-level
            # alerts). Pinned by watchdog.STATUS_WATCHDOG_SCHEMA.
            "slo": self.watchdog.slo_status(now),
            "alerts": self.watchdog.alerts_status(fresh, now),
            "alive": alive,
            "n_alive": len(alive),
            "swarm_samples_per_sec": agg_sps,
            "uptime_s": time.time() - self._t0,
            # Which replica served this, and the active set it believes in
            # — the operator's first failover question.
            "control_plane": self.stats(),
            # Transport-level counters: THIS replica's WAN vantage.
            "transport": self.transport.stats(),
            # Per-volunteer leader-aggregation pipeline gauges from the
            # freshest reports — empty until some volunteer has led a
            # streaming round.
            "aggregation": {
                m.get("peer", "?"): m["aggregation"]
                for m in fresh
                if m.get("aggregation")
            },
            # Per-volunteer leader-failover gauges — empty until a
            # volunteer has lived through a leader death.
            "failover": {
                m.get("peer", "?"): m["failover"]
                for m in fresh
                if m.get("failover")
            },
        }, b""

    def stats(self) -> dict:
        active = self.active_set()
        return {
            "rid": self.rid,
            "retiring": self.retiring,
            "active_replicas": [rid for rid, _ in active],
            "n_replicas": len(active),
            "shards_owned": sorted(self._shard_gens),
            "shard_gens": {str(s): g for s, g in self._shard_gens.items()},
            **self.counters,
        }


class ControlPlaneClient:
    """Volunteer-side failover client for the replicated control plane.

    Discovers the live replica set from ``cp/replicas`` soft state (and
    from every exchange reply), routes each peer's control traffic to the
    replica OWNING its key-range shard, and on conn failure fails over to
    the next replica in ring order — the PR-4 deposal move applied to the
    control plane. Failed replicas go on bounded AIMD backoff (delay
    doubles per consecutive failure up to a cap, shrinks additively on
    recovery), and every attempt is FAST-FAIL (short connect budget), so a
    dead coordinator costs the heartbeat loop ~a second, never the generic
    call timeout."""

    # Fast-fail budgets: a control RPC to a corpse must cost the dial
    # budget, not the generic call timeout (satellite: heartbeat cadence
    # must hold through a coordinator outage).
    CALL_TIMEOUT = 2.5
    CONNECT_TIMEOUT = 1.0
    # Bounded AIMD backoff per replica.
    BACKOFF_START = 0.5
    BACKOFF_CAP = 8.0
    BACKOFF_DECREASE = 0.5
    # At most this many replicas tried per operation: bounds the worst
    # case (every replica dead) to ~2 dial budgets before the caller falls
    # back to the direct DHT path.
    MAX_TRIES = 2
    REFRESH_S = 5.0
    # Discovery backoff ceiling for swarms with NO replicas at all: a
    # refresh that finds nothing doubles the next refresh interval up to
    # this, so volunteers in a control-plane-less swarm don't pay an
    # iterative cp/replicas lookup on every heartbeat forever.
    EMPTY_REFRESH_CAP_S = 60.0

    # A replica record adopted this long ago without reconfirmation (an
    # exchange reply or a DHT refresh) no longer counts as live — matches
    # the replica announce TTL.
    RECORD_TTL = ControlPlaneReplica.REPLICA_TTL

    def __init__(self, transport: Transport, dht: DHTNode, peer_id: str):
        self.transport = transport
        self.dht = dht
        self.peer_id = peer_id
        # rid -> (record, adopted_mono)
        self._replicas: Dict[str, Tuple[dict, float]] = {}
        # Replicas a serving replica's reply did NOT list: likely dead
        # (replicas liveness-probe each other), but a reply can also
        # simply predate a young replica's announce — so absent rids are
        # DEMOTED to last-resort fallbacks rather than dropped (dropping
        # on a stale reply would erase a live replica and strand the
        # client when its shard owner dies). Re-listed or re-read from
        # the DHT -> re-confirmed.
        self._unconfirmed: set = set()
        self._refreshed = 0.0
        self._refresh_interval = self.REFRESH_S
        # rid -> (blocked_until_mono, current_delay)
        self._backoff: Dict[str, Tuple[float, float]] = {}
        self.counters: Dict[str, int] = {
            "calls_ok": 0, "calls_failed": 0, "failovers": 0,
            "refreshes": 0, "fallbacks": 0,
            "peers_full_replies": 0, "peers_delta_replies": 0,
        }
        # Peers-snapshot delta state: the cached full map delta replies
        # patch, and the (rid, version) echo that entitles this client to
        # deltas from that replica's change log. A failover to a replica
        # with a different rid mismatches the echo server-side and forces
        # one full-replace — the stale-version fallback needs no client
        # logic at all.
        self._peers_cache: Dict[str, object] = {}
        self._peers_ver: Optional[int] = None
        self._peers_rid: Optional[str] = None
        # RPC attempts the most recent _call made (1 on the happy path,
        # +1 per failover try): the per-beat message accounting reads this
        # instead of a transport-global counter delta, which would bill
        # concurrent round traffic to the beat.
        self.last_call_attempts = 0

    # -- replica-set discovery --------------------------------------------

    def update_replicas(self, records: Dict[str, dict]) -> None:
        """Adopt a replica-set view (from an exchange reply or a DHT
        read). Retiring records REPLACE live ones — that is the whole
        point of the retiring tombstone."""
        now = time.monotonic()
        for rid, rec in (records or {}).items():
            if isinstance(rec, dict):
                self._replicas[rid] = (rec, now)
                self._unconfirmed.discard(rid)
        self._refreshed = now
        if records:
            self._refresh_interval = self.REFRESH_S

    async def refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._refreshed <= self._refresh_interval:
            return
        self.counters["refreshes"] += 1
        try:
            recs = await self.dht.get(REPLICAS_KEY)
        except Exception as e:  # noqa: BLE001 — discovery is best-effort
            log.debug("replica-set refresh failed: %s", errstr(e))
            return
        # Full replace: records absent from the DHT have expired (the DHT
        # is authoritative up to the announce TTL).
        self._replicas = {
            rid: (rec, now)
            for rid, rec in recs.items()
            if isinstance(rec, dict)
        }
        self._unconfirmed.clear()
        self._refreshed = now
        if self._replicas:
            self._refresh_interval = self.REFRESH_S
        else:
            # No control plane anywhere: decay the discovery cadence so a
            # swarm not using the feature doesn't pay a DHT walk per beat.
            self._refresh_interval = min(
                self._refresh_interval * 2.0, self.EMPTY_REFRESH_CAP_S
            )

    def active(self) -> List[Tuple[str, Addr]]:
        cutoff = time.monotonic() - self.RECORD_TTL
        return active_replicas(
            {rid: rec for rid, (rec, at) in self._replicas.items() if at >= cutoff}
        )

    @property
    def has_replicas(self) -> bool:
        return bool(self.active())

    # -- routing + backoff -------------------------------------------------

    def _routes(self, shard: int) -> List[Tuple[str, Addr]]:
        """Replica attempt order for a shard: its owner first, then the
        ring in order — the same order every client computes, so failover
        traffic converges on the replica that will own the shard once the
        set re-forms, and backoff'd corpses are skipped outright."""
        active = self.active()
        if not active:
            return []
        start = owner_index(shard, len(active))
        ring = active[start:] + active[:start]
        now = time.monotonic()
        routes = [
            (rid, addr) for rid, addr in ring
            if self._backoff.get(rid, (0.0, 0.0))[0] <= now
        ]
        # CONFIRMED replicas first (ring order preserved within each
        # class): a replica absent from the last serving reply is probably
        # a corpse — dial it only after the confirmed ones fail.
        routes.sort(key=lambda r: r[0] in self._unconfirmed)
        if routes:
            return routes
        # Every replica in backoff: try the one whose backoff expires
        # SOONEST (the most-nearly-recovered) rather than going dark —
        # or rather than re-dialing the ring head, which is often exactly
        # the long-backed-off corpse.
        return [min(ring, key=lambda r: self._backoff.get(r[0], (0.0, 0.0))[0])]

    def _note_ok(self, rid: str) -> None:
        until, delay = self._backoff.get(rid, (0.0, 0.0))
        self._backoff[rid] = (0.0, max(delay - self.BACKOFF_DECREASE, 0.0))
        self.counters["calls_ok"] += 1

    def _note_fail(self, rid: str) -> None:
        _, delay = self._backoff.get(rid, (0.0, 0.0))
        delay = min(max(delay * 2.0, self.BACKOFF_START), self.BACKOFF_CAP)
        self._backoff[rid] = (time.monotonic() + delay, delay)
        self.counters["calls_failed"] += 1

    async def _call(
        self, shard: int, method: str, args: dict
    ) -> Optional[dict]:
        """Fast-fail, failover call: first reachable replica in route
        order wins. None when no replica answered (caller falls back to
        the direct DHT path)."""
        routes = self._routes(shard)
        last_err: Optional[Exception] = None
        self.last_call_attempts = min(len(routes), self.MAX_TRIES)
        for i, (rid, addr) in enumerate(routes[: self.MAX_TRIES]):
            try:
                ret, _ = await self.transport.call(
                    addr, method, args,
                    timeout=self.CALL_TIMEOUT,
                    connect_timeout=self.CONNECT_TIMEOUT,
                )
                self._note_ok(rid)
                self.last_call_attempts = i + 1
                if i > 0:
                    self.counters["failovers"] += 1
                return ret
            except Exception as e:  # noqa: BLE001 — replica down: fail over
                self._note_fail(rid)
                last_err = e
        if routes:
            log.debug(
                "control-plane call %s failed on %d replica(s): %s",
                method, min(len(routes), self.MAX_TRIES), errstr(last_err),
            )
            self.counters["fallbacks"] += 1
        return None

    # -- operations --------------------------------------------------------

    async def exchange(
        self,
        record: Optional[dict],
        ttl: float,
        report: Optional[dict] = None,
        join: bool = False,
    ) -> Optional[dict]:
        """The batched per-interval control RPC (see ControlPlaneReplica).
        Returns the reply (peers snapshot + replica set, already adopted
        into this client's view) or None when no replica answered."""
        args: Dict[str, object] = {
            "peer": self.peer_id,
            "record": record,
            "ttl": float(ttl),
            "report": report,
            "join": bool(join),
        }
        if self._peers_ver is not None and self._peers_rid is not None:
            # Entitles us to a changes-since-version reply instead of the
            # full peers map (see merge_peers_reply).
            args["peers_ver"] = self._peers_ver
            args["peers_rid"] = self._peers_rid
        ret = await self._call(shard_of(self.peer_id), "cp.exchange", args)
        if ret is not None:
            recs = {
                rid: rec
                for rid, rec in (ret.get("replicas") or {}).items()
                if isinstance(rec, dict)
            }
            if recs:
                # The reply is the serving replica's liveness-probed view:
                # listed rids are CONFIRMED live; known rids it does NOT
                # list are DEMOTED to last-resort fallbacks (they are
                # probably corpses — but the reply may also just predate a
                # young replica's announce, so they are not dropped; the
                # RECORD_TTL ages real corpses out).
                self.update_replicas(recs)
                for rid in self._replicas:
                    if rid not in recs:
                        self._unconfirmed.add(rid)
        return ret

    def merge_peers_reply(self, ret: Optional[dict]) -> Dict[str, object]:
        """Resolve an exchange reply into the FULL peers snapshot the
        membership layer adopts, whichever shape the reply took:

        - a full reply (``peers``) replaces the local cache outright —
          also the legacy shape, so mixed-version replicas keep working;
        - a delta reply (``peers_delta``) patches the cache (None values
          evict) and folds the ``beats`` sidecar's timestamps into the
          cached records, so the caller's failure detector keeps seeing
          every peer's beat even though only changed records shipped.

        Tombstones are delivered to the caller exactly once (they ride
        the returned map this call, then leave the cache), matching the
        one-shot departure semantics of the full map. The version echo
        for the NEXT exchange is adopted here too."""
        if not isinstance(ret, dict):
            return {}
        delta = ret.get("peers_delta")
        if not isinstance(delta, dict):
            snap = dict(ret.get("peers") or {})
            self.counters["peers_full_replies"] += 1
            self._peers_cache = {
                p: r for p, r in snap.items() if r is not None
            }
        else:
            self.counters["peers_delta_replies"] += 1
            for pid, rec in delta.items():
                if rec is None:
                    self._peers_cache.pop(pid, None)
                else:
                    self._peers_cache[pid] = rec
            beats = ret.get("beats")
            if isinstance(beats, dict):
                for pid, t in beats.items():
                    rec = self._peers_cache.get(pid)
                    if (
                        isinstance(rec, dict)
                        and isinstance(t, (int, float))
                        and rec.get("t") != t
                    ):
                        # Copy-on-write: the cached record may still be
                        # referenced by a snapshot handed out earlier.
                        rec = dict(rec)
                        rec["t"] = t
                        self._peers_cache[pid] = rec
            snap = dict(self._peers_cache)
            for pid, rec in delta.items():
                if rec is None:
                    snap[pid] = None
        ver = ret.get("peers_ver")
        if isinstance(ver, int) and not isinstance(ver, bool):
            self._peers_ver = ver
            self._peers_rid = str(ret.get("rid") or "") or None
        else:
            # Legacy replica: no version stream to subscribe to.
            self._peers_ver = None
            self._peers_rid = None
        return snap

    async def status(self, fresh: bool = False) -> Optional[dict]:
        await self.refresh()
        return await self._call(
            shard_of(self.peer_id), "coord.status", {"fresh": bool(fresh)}
        )

    async def rendezvous_get(self, key: str) -> Optional[Dict[str, object]]:
        """Matchmaking rendezvous read via a replica's micro-cache; None
        on failure (the matchmaker then walks the DHT itself). Routed by
        the KEY's shard so all members polling one forming round hit the
        same replica's cache."""
        if not self.has_replicas:
            return None
        ret = await self._call(shard_of(key), "cp.rendezvous", {"key": key})
        if ret is None or not ret.get("ok"):
            return None
        return dict(ret.get("rec") or {})

    def stats(self) -> dict:
        now = time.monotonic()
        return {
            "replicas_known": len(self._replicas),
            "active": [rid for rid, _ in self.active()],
            "unconfirmed": sorted(self._unconfirmed),
            "backed_off": sorted(
                rid for rid, (until, _) in self._backoff.items() if until > now
            ),
            **self.counters,
        }
