"""ctypes binding for the C++ host core (dvc_native.cpp), with lazy build.

The library is compiled ON FIRST USE with the system g++ (no pybind11 in the
environment — plain C ABI + ctypes, per SURVEY.md §2's native-code
checklist) and cached next to the source; a stale .so (older than the .cpp)
is rebuilt. Every caller goes through ``get_lib()`` and falls back to numpy
when the toolchain is missing or ``DVC_NATIVE=0`` — the native core is a
throughput upgrade for the WAN path, never a hard dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from distributedvolunteercomputing_tpu.utils.logging import get_logger

log = get_logger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "dvc_native.cpp")
_SO = os.path.join(_DIR, "libdvc_native.so")
_ABI = 3

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_done = False  # build+load attempt finished (success or permanent failure)
_builder: Optional[threading.Thread] = None


def _build() -> bool:
    """Compile to a temp file, then atomically rename into place: concurrent
    volunteer processes racing the build can never dlopen a half-written
    ELF, and a killed compile never leaves a corrupt .so behind."""
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        _SRC, "-o", tmp,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            log.warning("native build failed; using numpy fallbacks:\n%s", proc.stderr[-2000:])
            return False
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.TimeoutExpired) as e:
        log.info("native build unavailable (%s); using numpy fallbacks", e)
        return False
    finally:
        try:
            if os.path.exists(tmp):
                os.unlink(tmp)
        except OSError:
            pass


def _load() -> Optional[ctypes.CDLL]:
    lib = ctypes.CDLL(_SO)
    lib.dvc_abi_version.restype = ctypes.c_int
    if lib.dvc_abi_version() != _ABI:
        log.warning("native ABI mismatch; rebuilding")
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    f32p = ctypes.POINTER(ctypes.c_float)
    u16p = ctypes.POINTER(ctypes.c_uint16)
    u64 = ctypes.c_uint64
    lib.dvc_crc32.argtypes = [u8p, u64, ctypes.c_uint32]
    lib.dvc_crc32.restype = ctypes.c_uint32
    lib.dvc_f32_to_bf16.argtypes = [f32p, u16p, u64]
    lib.dvc_bf16_to_f32.argtypes = [u16p, f32p, u64]
    lib.dvc_weighted_sum.argtypes = [f32p, f32p, ctypes.c_float, u64]
    lib.dvc_coord_median.argtypes = [f32p, u64, u64, f32p]
    lib.dvc_trimmed_mean.argtypes = [f32p, u64, u64, u64, f32p]
    i8p = ctypes.POINTER(ctypes.c_int8)
    lib.dvc_f32_to_q8.argtypes = [f32p, u64, u64, f32p, i8p]
    lib.dvc_q8_to_f32.argtypes = [i8p, f32p, u64, u64, f32p]
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.dvc_topk_indices.argtypes = [f32p, u64, u64, u32p]
    return lib


def _build_and_load() -> None:
    """The one-shot build+load state machine (runs in the builder thread).

    Load failures (truncated .so from a crashed writer, ABI drift) get ONE
    rebuild before giving up — a stale-but-newer corrupt artifact must not
    disable the native path forever."""
    global _lib, _done
    try:
        stale = (not os.path.exists(_SO)) or (
            os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        )
        lib = None
        if not stale:
            try:
                lib = _load()
            except OSError:
                lib = None
        if lib is None and _build():
            try:
                lib = _load()
            except OSError as e:
                log.warning("native load failed after fresh build (%s)", e)
        _lib = lib
    except OSError as e:
        log.info("native core unavailable (%s); using numpy fallbacks", e)
        _lib = None
    finally:
        _done = True


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library; never blocks the caller on a compile.

    On first call with no usable .so, the build is kicked off on a
    background thread and None is returned (callers fall back to numpy)
    until it lands — a volunteer's asyncio loop must not stall for a g++
    run mid-round. Use ensure_built() at process start to wait for it.
    """
    global _builder
    if _done or os.environ.get("DVC_NATIVE", "1") == "0":
        return _lib
    with _lock:
        if _done:
            return _lib
        if _builder is None:
            _builder = threading.Thread(
                target=_build_and_load, name="dvc-native-build", daemon=True
            )
            _builder.start()
    return _lib


def ensure_built(timeout: float = 150.0) -> bool:
    """Block until the native core is built+loaded (or failed); returns
    availability. Call from process entrypoints BEFORE the event loop."""
    get_lib()
    b = _builder
    if b is not None:
        b.join(timeout)
    return _lib is not None


def available() -> bool:
    return get_lib() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


# ---------------------------------------------------------------------------
# public ops (native with numpy fallback)
# ---------------------------------------------------------------------------


def crc32(data: bytes, seed: int = 0) -> int:
    """Frame checksum. zlib's crc32 measured ~2x faster than the C++
    slice-by-8 path on this host (hardware CRC in zlib), so it is the
    primary; dvc_crc32 stays in the ABI as a cross-check implementation
    (tests validate the two agree — a real integrity test of the codec)."""
    import zlib

    return zlib.crc32(data, seed) & 0xFFFFFFFF


def crc32_native(data: bytes, seed: int = 0) -> int:
    lib = get_lib()
    if lib is None:
        return crc32(data, seed)
    buf = np.frombuffer(data, np.uint8)
    return int(lib.dvc_crc32(_ptr(buf, ctypes.c_uint8), len(data), seed))


def f32_to_bf16(arr: np.ndarray) -> np.ndarray:
    """float32 [n] -> uint16 [n] bf16 bit patterns (round-to-nearest-even)."""
    arr = np.ascontiguousarray(arr, np.float32)
    lib = get_lib()
    out = np.empty(arr.size, np.uint16)
    if lib is not None:
        lib.dvc_f32_to_bf16(_ptr(arr, ctypes.c_float), _ptr(out, ctypes.c_uint16), arr.size)
        return out
    import ml_dtypes

    return arr.astype(ml_dtypes.bfloat16).view(np.uint16)


def bf16_to_f32(bits: np.ndarray, out: Optional[np.ndarray] = None) -> np.ndarray:
    """uint16 bf16 bit patterns -> float32. ``out``, when given, receives
    the decode in place (must be a contiguous f32 buffer of matching size)
    — the streaming aggregation tier decodes wire chunks straight into
    pooled tile buffers instead of allocating per chunk."""
    bits = np.ascontiguousarray(bits, np.uint16)
    if out is None:
        out = np.empty(bits.size, np.float32)
    elif (
        out.dtype != np.float32 or out.size != bits.size
        or not out.flags.c_contiguous
    ):
        raise ValueError(
            f"bf16_to_f32 out= needs a contiguous float32[{bits.size}], got "
            f"{out.dtype}[{out.size}]"
        )
    lib = get_lib()
    if lib is not None:
        lib.dvc_bf16_to_f32(_ptr(bits, ctypes.c_uint16), _ptr(out, ctypes.c_float), bits.size)
        return out
    import ml_dtypes

    out[:] = bits.view(ml_dtypes.bfloat16).astype(np.float32)
    return out


def weighted_sum_inplace(acc: np.ndarray, x: np.ndarray, w: float) -> None:
    """acc += w * x over float32 buffers — the sync leader's streaming
    weighted-mean accumulation (swarm/averager.py _lead_round)."""
    # ValueError, not assert: this guards the native kernel's dtype/size
    # contract (out-of-bounds read if violated) and must survive `python -O`.
    if acc.dtype != np.float32 or x.dtype != np.float32 or acc.size != x.size:
        raise ValueError(
            f"weighted_sum_inplace needs matching float32 buffers, got "
            f"{acc.dtype}[{acc.size}] += w * {x.dtype}[{x.size}]"
        )
    lib = get_lib()
    if lib is not None and acc.flags.c_contiguous and x.flags.c_contiguous:
        lib.dvc_weighted_sum(_ptr(acc, ctypes.c_float), _ptr(x, ctypes.c_float), w, acc.size)
        return
    acc += np.float32(w) * x


Q8_CHUNK = 1024  # floats per quantization chunk (one f32 scale each)


def q8_encode(arr: np.ndarray, chunk: int = Q8_CHUNK) -> bytes:
    """f32 -> q8 wire bytes: [u64 n][f32 scale/chunk][int8 data]. ~4x fewer
    bytes than f32; symmetric per-chunk scales; exact on round-tripped
    values (pairwise protocols rely on idempotency)."""
    arr = np.ascontiguousarray(arr, np.float32).ravel()
    n = arr.size
    n_chunks = -(-n // chunk) if n else 0
    scales = np.empty(n_chunks, np.float32)
    out = np.empty(n, np.int8)
    lib = get_lib()
    if lib is not None and n:
        lib.dvc_f32_to_q8(
            _ptr(arr, ctypes.c_float), n, chunk, _ptr(scales, ctypes.c_float),
            _ptr(out, ctypes.c_int8),
        )
    elif n:
        # Mirrors the native path: non-finite -> 0 before scaling (UB-free,
        # scale stays finite), quantize via x * (1/scale) in f32 with
        # round-half-away-from-zero. Exact agreement with the C++ isn't
        # guaranteed at rounding boundaries (FMA contraction differs by
        # compiler), but both stay within one quantization step.
        arr = np.where(np.isfinite(arr), arr, np.float32(0))
        pad = n_chunks * chunk - n
        padded = np.pad(arr, (0, pad)).reshape(n_chunks, chunk)
        amax = np.max(np.abs(padded), axis=1)
        scales[:] = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
        q = padded * (np.float32(1.0) / scales)[:, None]
        q = np.clip(q, -127.0, 127.0)
        q = np.where(q >= 0, np.floor(q + 0.5), np.ceil(q - 0.5)).astype(np.int8)
        out[:] = q.reshape(-1)[:n]
    return (
        np.uint64(n).tobytes() + scales.tobytes() + out.tobytes()
    )


def q8_coded_size(n: int, chunk: int = Q8_CHUNK) -> int:
    """Exact q8 wire size for n f32 elements — the ONE home of the layout
    (header u64 + f32 scale per chunk + int8 data); peers validate transfer
    sizes against this instead of re-deriving the format."""
    n_chunks = -(-n // chunk) if n else 0
    return 8 + 4 * n_chunks + n


def q8_decode(payload: bytes, chunk: int = Q8_CHUNK) -> np.ndarray:
    """Inverse of q8_encode; raises ValueError on malformed payloads."""
    if len(payload) < 8:
        raise ValueError("q8 payload too short for header")
    n = int(np.frombuffer(payload[:8], np.uint64)[0])
    n_chunks = -(-n // chunk) if n else 0
    expect = q8_coded_size(n, chunk)
    if len(payload) != expect:
        raise ValueError(f"q8 payload {len(payload)}B != expected {expect}B for n={n}")
    scales = np.frombuffer(payload[8 : 8 + 4 * n_chunks], np.float32)
    data = np.frombuffer(payload[8 + 4 * n_chunks :], np.int8)
    out = np.empty(n, np.float32)
    lib = get_lib()
    if lib is not None and n:
        data = np.ascontiguousarray(data)
        scales = np.ascontiguousarray(scales)
        lib.dvc_q8_to_f32(
            _ptr(data, ctypes.c_int8), _ptr(scales, ctypes.c_float), n, chunk,
            _ptr(out, ctypes.c_float),
        )
    elif n:
        pad = n_chunks * chunk - n
        padded = np.pad(data.astype(np.float32), (0, pad)).reshape(n_chunks, chunk)
        out[:] = (padded * scales[:, None]).reshape(-1)[:n]
    return out


# Decode-allocation ceiling when the caller has no schema to bound by:
# 2^29 f32 = the 2 GiB transport MAX_PAYLOAD expressed in floats. A sparse
# frame's uint64 n is attacker-controlled (a ~100-byte frame can claim any
# n), so the dense reconstruction must never exceed what a dense payload of
# the transport's own cap could have shipped.
TOPK_MAX_DECODE_FLOATS = 1 << 29


# 1-bit sign wire codec (EF-signSGD, Karimireddy et al.'s error-fixed
# signSGD lineage): ship sign(x) packed 1 bit/coord plus a per-chunk f32
# scale = mean(|x|) over the chunk, so the reconstruction ±scale carries the
# chunk's average magnitude (plain ±1 signs would need a global lr rescale;
# mean-|x| scaling is what makes EF residuals drain). ~32x fewer bytes than
# f32 — the extreme rung of the codec family (f32 -> bf16 2x -> q8 4x ->
# powersgd ~7x -> topk ~14-50x -> sign 32x on the contribution leg).
# Self-describing magic so the averager can tell a sign contribution from
# its q8-coded round RESULT on the same wire (see averager._buf_from_payload).
SIGN_MAGIC = b"SG1"
_SIGN_HDR = 3 + 8  # magic, n u64


def sign_coded_size(n: int, chunk: int = Q8_CHUNK) -> int:
    n_chunks = -(-n // chunk) if n else 0
    return _SIGN_HDR + 4 * n_chunks + (n + 7) // 8


def sign_encode(arr: np.ndarray, chunk: int = Q8_CHUNK) -> bytes:
    """f32 -> sign wire bytes: [SG1][u64 n][f32 mean-|x| per chunk][packed
    sign bits, 1 = negative]. Non-finite values encode as +scale with the
    non-finites excluded from the chunk mean (matching q8's zero-poison
    policy: one NaN must not wipe a 1024-float chunk's information)."""
    arr = np.ascontiguousarray(arr, np.float32).ravel()
    n = arr.size
    n_chunks = -(-n // chunk) if n else 0
    finite = np.isfinite(arr)
    clean = np.where(finite, arr, np.float32(0))
    pad = n_chunks * chunk - n
    padded = np.pad(clean, (0, pad)).reshape(n_chunks, chunk) if n else clean.reshape(0, 1)
    counts = np.pad(finite.astype(np.float64), (0, pad)).reshape(n_chunks, chunk).sum(axis=1) if n else np.zeros(0)
    sums = np.abs(padded).sum(axis=1, dtype=np.float64)  # f64: ulp-stable chunk means
    scales = np.where(counts > 0, sums / np.maximum(counts, 1), 0.0).astype(np.float32)
    bits = np.packbits((clean < 0).astype(np.uint8))
    return SIGN_MAGIC + np.uint64(n).tobytes() + scales.tobytes() + bits.tobytes()


def sign_decode(
    payload: bytes, chunk: int = Q8_CHUNK,
    max_floats: int = TOPK_MAX_DECODE_FLOATS,
) -> np.ndarray:
    """Inverse of sign_encode: dense f32 of ±chunk-scale. ``max_floats``
    bounds the allocation (the u64 n is sender-controlled — same
    resource-exhaustion guard as topk/powersgd decodes)."""
    if len(payload) < _SIGN_HDR or payload[:3] != SIGN_MAGIC:
        raise ValueError("sign payload: bad header")
    n = int(np.frombuffer(payload[3:11], np.uint64)[0])
    if n > max_floats:
        raise ValueError(f"sign payload: n={n} exceeds decode cap {max_floats}")
    if len(payload) != sign_coded_size(n, chunk):
        raise ValueError(
            f"sign payload {len(payload)}B != expected {sign_coded_size(n, chunk)}B for n={n}"
        )
    n_chunks = -(-n // chunk) if n else 0
    scales = np.frombuffer(payload[_SIGN_HDR : _SIGN_HDR + 4 * n_chunks], np.float32)
    bits = np.unpackbits(
        np.frombuffer(payload[_SIGN_HDR + 4 * n_chunks :], np.uint8), count=n
    )
    signs = np.where(bits == 1, np.float32(-1.0), np.float32(1.0))
    pad = n_chunks * chunk - n
    out = (
        np.pad(signs, (0, pad)).reshape(n_chunks, chunk) * scales[:, None]
    ).reshape(-1)[:n].astype(np.float32)
    return np.ascontiguousarray(out)


# Top-k sparse wire codec (Deep-Gradient-Compression style): ship only the
# largest-magnitude entries. Self-describing header so the decoder needs no
# out-of-band state; falls back to dense when sparsity wouldn't pay.
_TOPK_MAGIC = b"TK1"
_TOPK_HDR = 3 + 1 + 8  # magic, mode u8, n u64
_TOPK_SPARSE, _TOPK_DENSE = 0, 1


def topk_encode(arr: np.ndarray, frac: float | None = None) -> bytes:
    """f32 -> top-k wire bytes.

    ``frac`` = fraction of entries to keep (by |value|). ``None`` = auto:
    keep every nonzero, or go dense when sparse coding (8 B/entry) would
    exceed dense f32 — the right mode for aggregation RESULTS, whose support
    is the union of sparse contributions. Non-finite values are zeroed (they
    would otherwise win the magnitude sort and poison the average)."""
    arr = np.ascontiguousarray(arr, np.float32).ravel()
    arr = np.where(np.isfinite(arr), arr, np.float32(0))
    n = arr.size
    if n >= 1 << 32:
        raise ValueError(f"topk codec supports < 2^32 elements, got {n}")
    header = _TOPK_MAGIC + bytes([_TOPK_SPARSE]) + np.uint64(n).tobytes()

    def dense() -> bytes:  # built on demand: it copies the whole buffer
        return _TOPK_MAGIC + bytes([_TOPK_DENSE]) + np.uint64(n).tobytes() + arr.tobytes()

    if frac is None:
        idx = np.flatnonzero(arr).astype(np.uint32)
        if 8 * idx.size >= 4 * n:  # sparse (8 B/entry) wouldn't pay
            return dense()
    else:
        k = max(1, int(n * frac)) if n else 0
        if 8 * k >= 4 * n or k >= n:
            # Dense mode is knowable from k alone — decide BEFORE paying
            # for any selection work.
            return dense()
        # numpy's SIMD introselect beats the C++ nth_element ~2x on this
        # hardware (measured at 31M f32: 0.30s vs 0.64s), so numpy is the
        # default; the native path (parity-tested) is an opt-in for
        # platforms where numpy's partition underperforms. Env checked
        # first: get_lib() would otherwise kick off the background g++
        # build for a value the condition then ignores.
        if (
            os.environ.get("DVC_TOPK_NATIVE") == "1"
            and n >= (1 << 15)
            and (lib := get_lib()) is not None
        ):
            idx = np.empty(k, np.uint32)
            lib.dvc_topk_indices(_ptr(arr, ctypes.c_float), n, k, _ptr(idx, ctypes.c_uint32))
        else:
            idx = np.sort(
                np.argpartition(np.abs(arr), n - k)[n - k:]
            ).astype(np.uint32)
    return header + idx.tobytes() + arr[idx].tobytes()




def topk_decode(
    payload: bytes, max_floats: int = TOPK_MAX_DECODE_FLOATS
) -> np.ndarray:
    """Inverse of topk_encode: dense f32 with zeros off-support."""
    if len(payload) < _TOPK_HDR or payload[:3] != _TOPK_MAGIC:
        raise ValueError("topk payload: bad header")
    mode = payload[3]
    n = int(np.frombuffer(payload[4:12], np.uint64)[0])
    if n > max_floats:
        raise ValueError(f"topk payload: n={n} exceeds decode cap {max_floats}")
    body = payload[_TOPK_HDR:]
    if mode == _TOPK_DENSE:
        if len(body) != 4 * n:
            raise ValueError(f"topk dense body {len(body)}B != {4 * n}B for n={n}")
        return np.frombuffer(body, np.float32).copy()
    if mode != _TOPK_SPARSE or len(body) % 8 != 0:
        raise ValueError("topk payload: bad mode or body size")
    k = len(body) // 8
    idx = np.frombuffer(body[: 4 * k], np.uint32)
    vals = np.frombuffer(body[4 * k:], np.float32)
    if k and (idx[-1] >= n or np.any(np.diff(idx.astype(np.int64)) <= 0)):
        raise ValueError("topk payload: indices out of range or unsorted")
    out = np.zeros(n, np.float32)
    out[idx] = vals
    return out


def coordinate_median(stack: np.ndarray) -> np.ndarray:
    """np.median(stack, axis=0) for float32 [n_peers, D], threaded."""
    lib = get_lib()
    if lib is None or stack.dtype != np.float32 or not stack.flags.c_contiguous:
        return np.median(stack, axis=0).astype(stack.dtype)
    out = np.empty(stack.shape[1], np.float32)
    lib.dvc_coord_median(
        _ptr(stack, ctypes.c_float), stack.shape[0], stack.shape[1], _ptr(out, ctypes.c_float)
    )
    return out


def trimmed_mean(stack: np.ndarray, trim: int) -> np.ndarray:
    """Coordinate-wise trimmed mean for float32 [n_peers, D], threaded."""
    n = stack.shape[0]
    if 2 * trim >= n:
        raise ValueError(f"trim={trim} too large for n={n}")
    lib = get_lib()
    if lib is None or stack.dtype != np.float32 or not stack.flags.c_contiguous:
        srt = np.sort(stack, axis=0)
        return srt[trim : n - trim].mean(axis=0)
    out = np.empty(stack.shape[1], np.float32)
    lib.dvc_trimmed_mean(
        _ptr(stack, ctypes.c_float), n, stack.shape[1], trim, _ptr(out, ctypes.c_float)
    )
    return out
