// dvc_native: host-side C++ core for the WAN (DCN) averaging path.
//
// TPU-native stand-in for the native tier of the reference stack: the
// reference's collectives ride NCCL/gloo (C++); here the intra-slice tier is
// XLA-emitted ICI collectives (no code to write), and THIS library covers the
// host/WAN tier those libraries covered — payload checksums, wire codecs and
// robust reduction over peer contributions (SURVEY.md §2 native-code
// checklist). Exposed as a plain C ABI and bound from Python with ctypes
// (no pybind11 in this environment).
//
// Everything here is trivially parallel over the buffer, so each entry point
// slices the work across a small std::thread pool — these run on the
// volunteer HOST next to param-sized buffers (10^7..10^9 bytes) while the
// TPU step runs, so wall-clock here is overlap budget for the WAN round.

#include <algorithm>
#include <cmath>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <memory>
#include <thread>
#include <vector>

namespace {

unsigned hw_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n == 0 ? 4 : n;
}

// Run fn(begin, end) over [0, n) in roughly equal chunks on up to max_thr
// threads; serial when the buffer is too small to amortise thread spawn.
template <typename F>
void parallel_for(uint64_t n, uint64_t serial_cutoff, F fn) {
  unsigned max_thr = hw_threads();
  if (n < serial_cutoff || max_thr <= 1) {
    fn(0, n);
    return;
  }
  unsigned thr = static_cast<unsigned>(
      std::min<uint64_t>(max_thr, (n + serial_cutoff - 1) / serial_cutoff));
  std::vector<std::thread> pool;
  pool.reserve(thr);
  uint64_t chunk = (n + thr - 1) / thr;
  for (unsigned t = 0; t < thr; ++t) {
    uint64_t b = t * chunk, e = std::min(n, b + chunk);
    if (b >= e) break;
    pool.emplace_back([=] { fn(b, e); });
  }
  for (auto& th : pool) th.join();
}

// ---------------------------------------------------------------------------
// CRC32 (zlib polynomial 0xEDB88320), slice-by-8.
// ---------------------------------------------------------------------------

struct Crc32Tables {
  uint32_t t[8][256];
  Crc32Tables() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[0][i] = c;
    }
    for (int s = 1; s < 8; ++s)
      for (uint32_t i = 0; i < 256; ++i)
        t[s][i] = t[s - 1][i] >> 8 ^ t[0][t[s - 1][i] & 0xFF];
  }
};
const Crc32Tables kCrc;

uint32_t crc32_serial(const uint8_t* p, uint64_t len, uint32_t crc) {
  crc = ~crc;
  while (len >= 8) {
    uint32_t lo, hi;
    std::memcpy(&lo, p, 4);
    std::memcpy(&hi, p + 4, 4);
    lo ^= crc;
    crc = kCrc.t[7][lo & 0xFF] ^ kCrc.t[6][(lo >> 8) & 0xFF] ^
          kCrc.t[5][(lo >> 16) & 0xFF] ^ kCrc.t[4][lo >> 24] ^
          kCrc.t[3][hi & 0xFF] ^ kCrc.t[2][(hi >> 8) & 0xFF] ^
          kCrc.t[1][(hi >> 16) & 0xFF] ^ kCrc.t[0][hi >> 24];
    p += 8;
    len -= 8;
  }
  while (len--) crc = kCrc.t[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// GF(2) trick to combine per-chunk CRCs: crc(A||B) from crc(A), crc(B), |B|.
uint32_t gf2_times(uint32_t a, const uint32_t* mat) {
  uint32_t s = 0;
  for (int i = 0; a; ++i, a >>= 1)
    if (a & 1) s ^= mat[i];
  return s;
}

void gf2_square(uint32_t* sq, const uint32_t* mat) {
  for (int i = 0; i < 32; ++i) sq[i] = gf2_times(mat[i], mat);
}

uint32_t crc32_combine(uint32_t crc1, uint32_t crc2, uint64_t len2) {
  if (len2 == 0) return crc1;
  uint32_t even[32], odd[32];
  odd[0] = 0xEDB88320u;
  for (int i = 1; i < 32; ++i) odd[i] = 1u << (i - 1);
  gf2_square(even, odd);
  gf2_square(odd, even);
  do {
    gf2_square(even, odd);
    if (len2 & 1) crc1 = gf2_times(crc1, even);
    len2 >>= 1;
    if (!len2) break;
    gf2_square(odd, even);
    if (len2 & 1) crc1 = gf2_times(crc1, odd);
    len2 >>= 1;
  } while (len2);
  return crc1 ^ crc2;
}

// ---------------------------------------------------------------------------
// f32 <-> bf16 (round-to-nearest-even), the wire codec.
// ---------------------------------------------------------------------------

inline uint16_t f32_to_bf16_1(float f) {
  uint32_t x;
  std::memcpy(&x, &f, 4);
  if ((x & 0x7FFFFFFFu) > 0x7F800000u) return static_cast<uint16_t>(x >> 16) | 0x40;  // quiet NaN
  uint32_t rounding = 0x7FFFu + ((x >> 16) & 1);
  return static_cast<uint16_t>((x + rounding) >> 16);
}

}  // namespace

extern "C" {

int dvc_abi_version() { return 3; }

uint32_t dvc_crc32(const uint8_t* data, uint64_t len, uint32_t seed) {
  const uint64_t kCut = 1 << 20;
  unsigned thr = hw_threads();
  if (len < 2 * kCut || thr <= 1) return crc32_serial(data, len, seed);
  thr = static_cast<unsigned>(std::min<uint64_t>(thr, len / kCut));
  uint64_t chunk = (len + thr - 1) / thr;
  std::vector<uint32_t> crcs(thr, 0);
  std::vector<uint64_t> lens(thr, 0);
  std::vector<std::thread> pool;
  for (unsigned t = 0; t < thr; ++t) {
    uint64_t b = t * chunk, e = std::min(len, b + chunk);
    if (b >= e) break;
    lens[t] = e - b;
    pool.emplace_back([&, t, b, e] { crcs[t] = crc32_serial(data + b, e - b, 0); });
  }
  for (auto& th : pool) th.join();
  uint32_t crc = seed;
  for (unsigned t = 0; t < pool.size(); ++t) crc = crc32_combine(crc, crcs[t], lens[t]);
  return crc;
}

void dvc_f32_to_bf16(const float* src, uint16_t* dst, uint64_t n) {
  parallel_for(n, 1 << 18, [&](uint64_t b, uint64_t e) {
    for (uint64_t i = b; i < e; ++i) dst[i] = f32_to_bf16_1(src[i]);
  });
}

void dvc_bf16_to_f32(const uint16_t* src, float* dst, uint64_t n) {
  parallel_for(n, 1 << 18, [&](uint64_t b, uint64_t e) {
    for (uint64_t i = b; i < e; ++i) {
      uint32_t x = static_cast<uint32_t>(src[i]) << 16;
      std::memcpy(&dst[i], &x, 4);
    }
  });
}

// acc += w * x, the leader-gather accumulation.
void dvc_weighted_sum(float* acc, const float* x, float w, uint64_t n) {
  parallel_for(n, 1 << 18, [&](uint64_t b, uint64_t e) {
    for (uint64_t i = b; i < e; ++i) acc[i] += w * x[i];
  });
}

// Robust reduction over stack[n_peers, dim] (row-major), coordinate-wise,
// threaded over dim. n_peers is swarm-scale (<= 64), so a stack buffer +
// insertion-grade std::sort per coordinate beats numpy's full-matrix sort
// (which allocates and sorts the whole [n, D] copy single-threaded).
void dvc_coord_median(const float* stack, uint64_t n_peers, uint64_t dim, float* out) {
  parallel_for(dim, 1 << 14, [&](uint64_t b, uint64_t e) {
    std::vector<float> col(n_peers);
    for (uint64_t j = b; j < e; ++j) {
      for (uint64_t i = 0; i < n_peers; ++i) col[i] = stack[i * dim + j];
      std::sort(col.begin(), col.end());
      out[j] = (n_peers & 1)
                   ? col[n_peers / 2]
                   : 0.5f * (col[n_peers / 2 - 1] + col[n_peers / 2]);
    }
  });
}

void dvc_trimmed_mean(const float* stack, uint64_t n_peers, uint64_t dim,
                      uint64_t trim, float* out) {
  if (2 * trim >= n_peers) return;  // caller validates; keep ABI total
  parallel_for(dim, 1 << 14, [&](uint64_t b, uint64_t e) {
    std::vector<float> col(n_peers);
    for (uint64_t j = b; j < e; ++j) {
      for (uint64_t i = 0; i < n_peers; ++i) col[i] = stack[i * dim + j];
      std::sort(col.begin(), col.end());
      double s = 0;
      for (uint64_t i = trim; i < n_peers - trim; ++i) s += col[i];
      out[j] = static_cast<float>(s / static_cast<double>(n_peers - 2 * trim));
    }
  });
}

// Chunked symmetric int8 quantization for the WAN wire ("q8" codec):
// per-chunk scale = absmax/127, values round to int8. ~4x fewer DCN bytes
// than f32 (2x vs bf16) at <=0.4% per-element relative error within a
// chunk; round-tripping already-quantized values is exact (the scale
// reconstructs bit-identically), which pairwise protocols rely on.
// Non-finite inputs are mapped to 0 BEFORE scaling: a diverged peer's
// NaN/Inf must not poison the chunk scale or hit the UB of casting a
// non-finite float to int8 (robust aggregation / the state-sync sanity
// guard are the layers that deal with divergent peers; the codec's job is
// merely to never corrupt silently or invoke UB).
void dvc_f32_to_q8(const float* in, uint64_t n, uint64_t chunk, float* scales,
                   int8_t* out) {
  if (chunk == 0) return;
  uint64_t n_chunks = (n + chunk - 1) / chunk;
  parallel_for(n_chunks, 8, [&](uint64_t b, uint64_t e) {
    for (uint64_t c = b; c < e; ++c) {
      uint64_t lo = c * chunk, hi = std::min(n, lo + chunk);
      float amax = 0.0f;
      for (uint64_t i = lo; i < hi; ++i) {
        float v = in[i];
        if (!std::isfinite(v)) continue;
        float a = v < 0 ? -v : v;
        if (a > amax) amax = a;
      }
      float scale = amax > 0 ? amax / 127.0f : 1.0f;
      scales[c] = scale;
      float inv = 1.0f / scale;
      for (uint64_t i = lo; i < hi; ++i) {
        float v = std::isfinite(in[i]) ? in[i] : 0.0f;
        float q = v * inv;
        q = q < -127.0f ? -127.0f : (q > 127.0f ? 127.0f : q);
        out[i] = static_cast<int8_t>(q >= 0 ? q + 0.5f : q - 0.5f);
      }
    }
  });
}

void dvc_q8_to_f32(const int8_t* in, const float* scales, uint64_t n,
                   uint64_t chunk, float* out) {
  if (chunk == 0) return;
  uint64_t n_chunks = (n + chunk - 1) / chunk;
  parallel_for(n_chunks, 8, [&](uint64_t b, uint64_t e) {
    for (uint64_t c = b; c < e; ++c) {
      uint64_t lo = c * chunk, hi = std::min(n, lo + chunk);
      float scale = scales[c];
      for (uint64_t i = lo; i < hi; ++i)
        out[i] = static_cast<float>(in[i]) * scale;
    }
  });
}

// Indices of the k largest-|value| entries, ascending index order (the
// top-k sparse wire codec's selection phase). Caller guarantees finite
// input (the Python side zeroes NaN/Inf first) and 0 < k <= n. Threshold
// via nth_element on a magnitude copy, then one in-order scan emitting
// strictly-above-threshold entries plus as many threshold-equal ones as k
// still needs — output is sorted by construction, as the wire format
// requires.
void dvc_topk_indices(const float* in, uint64_t n, uint64_t k,
                      uint32_t* idx_out) {
  if (k == 0 || k > n) return;
  // One UNINITIALIZED scratch magnitude array (vector would zero-fill n
  // floats serially before the parallel fill overwrites them), consumed
  // destructively by nth_element; the counting/emit scans read |in[i]|
  // directly (fabs is cheaper than a second n-float allocation + copy).
  std::unique_ptr<float[]> mag(new float[n]);
  parallel_for(n, 1u << 16, [&](uint64_t b, uint64_t e) {
    for (uint64_t i = b; i < e; ++i) mag[i] = in[i] < 0 ? -in[i] : in[i];
  });
  std::nth_element(mag.get(), mag.get() + (n - k), mag.get() + n);
  float thr = mag[n - k];
  std::atomic<uint64_t> greater_at{0};
  parallel_for(n, 1u << 16, [&](uint64_t b, uint64_t e) {
    uint64_t g = 0;
    for (uint64_t i = b; i < e; ++i) {
      float a = in[i] < 0 ? -in[i] : in[i];
      if (a > thr) ++g;
    }
    greater_at.fetch_add(g, std::memory_order_relaxed);
  });
  uint64_t need_eq = k - greater_at.load();
  uint64_t w = 0;
  for (uint64_t i = 0; i < n && w < k; ++i) {
    float a = in[i] < 0 ? -in[i] : in[i];
    if (a > thr) {
      idx_out[w++] = static_cast<uint32_t>(i);
    } else if (a == thr && need_eq > 0) {
      idx_out[w++] = static_cast<uint32_t>(i);
      --need_eq;
    }
  }
}

}  // extern "C"
