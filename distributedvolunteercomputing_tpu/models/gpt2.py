"""GPT-2 small — reference config 4 and the north-star workload
(BASELINE.json:10, north_star: GPT-2-small on 4x v4-8 volunteer slices).

Pre-LN transformer decoder with learned positional embeddings and tied
input/output embeddings. Flagship model for bench.py and __graft_entry__.

TPU-first layout decisions:
- Blocks are ONE stacked pytree scanned with ``lax.scan`` (common.scan_blocks)
  — each block's HLO appears once in the XLA program instead of n_layers
  times, which cuts compile time and program size on-chip.
- The loss never materializes the [B, T, 50257] f32 logits tensor
  (1.6 GB at bench shapes); it streams vocab projection + cross-entropy over
  time chunks (common.lm_xent_chunked) with rematerialized backward.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from distributedvolunteercomputing_tpu.models import common
from distributedvolunteercomputing_tpu.ops.attention import multi_head_attention


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab: int = 50257
    max_len: int = 1024
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    # Rematerialize each block in backward: trades ~30% FLOPs for O(layers x
    # activations) HBM — required to train at bs>=8, seq 1024 on one 16GB chip.
    remat: bool = True
    # Time-chunk size for the streamed vocab projection + xent.
    xent_chunk: int = 128

    @classmethod
    def medium(cls) -> "GPT2Config":
        """GPT-2 medium (~355M params): the next standard rung above the
        flagship; with ``--fsdp`` over dp it fits comfortably per chip."""
        return cls(d_model=1024, n_heads=16, n_layers=24, d_ff=4096)

    @classmethod
    def large(cls) -> "GPT2Config":
        """GPT-2 large (~774M params): Adam state pushes past one 16 GB
        chip in f32 — the regime ZeRO-1/FSDP exist for."""
        return cls(d_model=1280, n_heads=20, n_layers=36, d_ff=5120)


def _layer_init(rng: jax.Array, cfg: GPT2Config) -> common.Params:
    k = jax.random.split(rng, 4)
    # GPT-2 uses fused qkv; residual projections scaled by 1/sqrt(2*n_layers)
    res_scale = 1.0 / ((2 * cfg.n_layers) ** 0.5 * cfg.d_model ** 0.5)
    return {
        "ln1": common.layernorm_init(cfg.d_model),
        "qkv": common.dense_init(k[0], cfg.d_model, 3 * cfg.d_model, scale=0.02),
        "attn_out": common.dense_init(k[1], cfg.d_model, cfg.d_model, scale=res_scale),
        "ln2": common.layernorm_init(cfg.d_model),
        "mlp_in": common.dense_init(k[2], cfg.d_model, cfg.d_ff, scale=0.02),
        "mlp_out": common.dense_init(k[3], cfg.d_ff, cfg.d_model, scale=res_scale),
    }


def init(rng: jax.Array, cfg: GPT2Config) -> common.Params:
    keys = jax.random.split(rng, 3)
    return {
        "wte": common.embed_init(keys[0], cfg.vocab, cfg.d_model),
        "wpe": common.embed_init(keys[1], cfg.max_len, cfg.d_model, scale=0.01),
        "blocks": common.stacked_init(
            lambda k: _layer_init(k, cfg), keys[2], cfg.n_layers
        ),
        "ln_f": common.layernorm_init(cfg.d_model),
    }


def _block(p: common.Params, x: jax.Array, cfg: GPT2Config) -> jax.Array:
    h = common.layernorm(p["ln1"], x)
    qkv = common.dense(p["qkv"], h)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    attn = multi_head_attention(q, k, v, cfg.n_heads, causal=True)
    x = x + common.dense(p["attn_out"], attn)
    h = common.layernorm(p["ln2"], x)
    h = common.dense(p["mlp_out"], jax.nn.gelu(common.dense(p["mlp_in"], h)))
    return x + h


def embed(params: common.Params, tokens: jax.Array, cfg: GPT2Config) -> jax.Array:
    """Token + position embeddings [B, T, d] in compute dtype — the trunk's
    input. Public so parallel/pipeline.py can wrap just the block trunk."""
    dtype = common.compute_dtype()
    t = tokens.shape[1]
    return (params["wte"][tokens] + params["wpe"][:t][None]).astype(dtype)


def block_fn(p: common.Params, x: jax.Array, cfg: GPT2Config) -> jax.Array:
    """One block's pure function (public for the pipeline trunk)."""
    return _block(p, x, cfg)


def lm_loss_from_hidden(
    params: common.Params, x: jax.Array, batch: Dict[str, jax.Array], cfg: GPT2Config
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Final LN + streamed tied-vocab xent, from post-trunk hidden states."""
    x = common.layernorm(params["ln_f"], x)
    loss = common.lm_xent_chunked(
        x, params["wte"], batch["targets"], chunk=cfg.xent_chunk, head_layout="vd"
    )
    return loss, {"loss": loss}


def _trunk(params: common.Params, tokens: jax.Array, cfg: GPT2Config) -> jax.Array:
    """embed -> scanned blocks (pre-ln_f). Shared by loss_fn and hidden so
    the training and inference trunks can never drift apart."""
    x = embed(params, tokens, cfg)
    return common.scan_blocks(
        lambda p, h: _block(p, h, cfg), params["blocks"], x, remat=cfg.remat
    )


def hidden(params: common.Params, tokens: jax.Array, cfg: GPT2Config) -> jax.Array:
    """Final-layer hidden states [B, T, d] (after ln_f, pre vocab projection)."""
    return common.layernorm(params["ln_f"], _trunk(params, tokens, cfg))


def forward(params: common.Params, tokens: jax.Array, cfg: GPT2Config) -> jax.Array:
    """Full logits [B, T, V] — for tests/inference; the train loss uses the
    chunked path in loss_fn and never builds this tensor."""
    x = hidden(params, tokens, cfg)
    # tied output embedding
    return jnp.einsum(
        "btd,vd->btv", x, params["wte"].astype(x.dtype)
    ).astype(jnp.float32)


def loss_fn(
    params: common.Params, batch: Dict[str, jax.Array], rng: jax.Array, cfg: GPT2Config
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    return lm_loss_from_hidden(params, _trunk(params, batch["tokens"], cfg), batch, cfg)
