"""Vision Transformer (ViT) image classifier — beyond-parity zoo member.

The reference's vision workloads are CNNs (configs 1-2, BASELINE.json:7-8);
ViT is the TPU-preferred vision architecture: patchification turns an image
into one [B, N, p²·c] @ [p²·c, d] projection plus the SAME pre-LN attention
trunk the language models use — pure large matmuls on the MXU, no
small-window conv shapes, and the whole stack reuses `ops/attention.py`
(flash-kernel routing, sequence-parallel contexts) and
`common.scan_blocks` (one block's HLO, remat knob) unchanged.

Architecture: Dosovitskiy et al., "An Image is Worth 16x16 Words" — CLS
token, learned positions, pre-LN encoder blocks, classification head on the
CLS hidden state. Defaults are a CIFAR-scale ViT-Tiny.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from distributedvolunteercomputing_tpu.models import common
from distributedvolunteercomputing_tpu.ops.attention import multi_head_attention


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    channels: int = 3
    n_classes: int = 10
    d_model: int = 192
    n_heads: int = 3
    n_layers: int = 12
    d_ff: int = 768
    remat: bool = True  # see GPT2Config.remat

    @property
    def n_patches(self) -> int:
        side = self.image_size // self.patch_size
        return side * side

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels


def _layer_init(rng: jax.Array, cfg: ViTConfig) -> common.Params:
    k = jax.random.split(rng, 4)
    return {
        "ln1": common.layernorm_init(cfg.d_model),
        "qkv": common.dense_init(k[0], cfg.d_model, 3 * cfg.d_model, scale=0.02),
        "attn_out": common.dense_init(k[1], cfg.d_model, cfg.d_model, scale=0.02),
        "ln2": common.layernorm_init(cfg.d_model),
        "mlp_in": common.dense_init(k[2], cfg.d_model, cfg.d_ff, scale=0.02),
        "mlp_out": common.dense_init(k[3], cfg.d_ff, cfg.d_model, scale=0.02),
    }


def init(rng: jax.Array, cfg: ViTConfig) -> common.Params:
    if cfg.image_size % cfg.patch_size != 0:
        raise ValueError(
            f"patch_size {cfg.patch_size} must divide image_size {cfg.image_size}"
        )
    k = jax.random.split(rng, 5)
    return {
        "patch_proj": common.dense_init(k[0], cfg.patch_dim, cfg.d_model, scale=0.02),
        "cls": common.embed_init(k[1], 1, cfg.d_model)[None],  # [1, 1, d]
        # +1 position for the CLS token.
        "pos": common.embed_init(k[2], cfg.n_patches + 1, cfg.d_model),
        "blocks": common.stacked_init(lambda kk: _layer_init(kk, cfg), k[3], cfg.n_layers),
        "ln_out": common.layernorm_init(cfg.d_model),
        "head": common.dense_init(k[4], cfg.d_model, cfg.n_classes, scale=0.02),
    }


def _patchify(x: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[B, H, W, C] -> [B, N, p*p*C]: a reshape/transpose, no gather — XLA
    lowers it to a layout change feeding one big MXU matmul."""
    b = x.shape[0]
    s, p = cfg.image_size // cfg.patch_size, cfg.patch_size
    x = x.reshape(b, s, p, s, p, cfg.channels)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [B, s, s, p, p, C]
    return x.reshape(b, s * s, cfg.patch_dim)


def _block(p: common.Params, x: jax.Array, cfg: ViTConfig) -> jax.Array:
    # Pre-LN (ViT standard): residuals stay un-normalized.
    h = common.layernorm(p["ln1"], x)
    qkv = common.dense(p["qkv"], h)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    x = x + common.dense(p["attn_out"], multi_head_attention(q, k, v, cfg.n_heads))
    h = common.layernorm(p["ln2"], x)
    return x + common.dense(p["mlp_out"], jax.nn.gelu(common.dense(p["mlp_in"], h)))


def forward(params: common.Params, x: jax.Array, cfg: ViTConfig) -> jax.Array:
    """Class logits [B, n_classes]."""
    dtype = common.compute_dtype()
    patches = _patchify(x.astype(jnp.float32), cfg)
    h = common.dense(params["patch_proj"], patches.astype(dtype))  # [B, N, d]
    cls = jnp.broadcast_to(
        params["cls"].astype(dtype), (h.shape[0], 1, cfg.d_model)
    )
    h = jnp.concatenate([cls, h], axis=1) + params["pos"].astype(dtype)[None]
    h = common.scan_blocks(
        lambda p, hh: _block(p, hh, cfg), params["blocks"], h, remat=cfg.remat
    )
    h = common.layernorm(params["ln_out"], h[:, 0])  # CLS hidden state
    return common.dense(params["head"], h, dtype=jnp.float32)


def loss_fn(
    params: common.Params, batch: Dict[str, jax.Array], rng: jax.Array, cfg: ViTConfig
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    logits = forward(params, batch["x"], cfg)
    loss = common.softmax_xent(logits, batch["y"])
    return loss, {"loss": loss, "accuracy": common.accuracy(logits, batch["y"])}
