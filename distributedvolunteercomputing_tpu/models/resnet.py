"""ResNet-18 for CIFAR-10 — reference config 2 (BASELINE.json:8).

TPU-first deviations from the torchvision-style reference genre:

- **GroupNorm instead of BatchNorm.** BN running statistics are mutable
  cross-batch state; in a volunteer swarm they would ALSO need averaging and
  churn-safe bookkeeping. GN is stateless (pure function of params + batch),
  equally accurate at CIFAR scale, and keeps the whole zoo uniform as
  "params pytree -> loss".
- NHWC layout (TPU-native conv layout for XLA).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from distributedvolunteercomputing_tpu.models import common


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    n_classes: int = 10
    stage_sizes: Tuple[int, ...] = (2, 2, 2, 2)  # ResNet-18
    widths: Tuple[int, ...] = (64, 128, 256, 512)
    stem_width: int = 64
    groups: int = 8  # GroupNorm groups


def _conv_init(rng: jax.Array, kh: int, kw: int, c_in: int, c_out: int) -> jax.Array:
    fan_in = kh * kw * c_in
    return jax.random.normal(rng, (kh, kw, c_in, c_out), jnp.float32) * (2.0 / fan_in) ** 0.5


def _conv(w: jax.Array, x: jax.Array, stride: int = 1) -> jax.Array:
    dtype = common.compute_dtype()
    return jax.lax.conv_general_dilated(
        x.astype(dtype),
        w.astype(dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _groupnorm_init(c: int) -> common.Params:
    return {"g": jnp.ones((c,), jnp.float32), "b": jnp.zeros((c,), jnp.float32)}


def _groupnorm(p: common.Params, x: jax.Array, groups: int, eps: float = 1e-5) -> jax.Array:
    b, h, w, c = x.shape
    xf = x.astype(jnp.float32).reshape(b, h, w, groups, c // groups)
    mu = jnp.mean(xf, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(xf, axis=(1, 2, 4), keepdims=True)
    xf = (xf - mu) * jax.lax.rsqrt(var + eps)
    xf = xf.reshape(b, h, w, c)
    return (xf * p["g"] + p["b"]).astype(x.dtype)


def _block_init(rng: jax.Array, c_in: int, c_out: int) -> common.Params:
    k1, k2, k3 = jax.random.split(rng, 3)
    p = {
        "conv1": _conv_init(k1, 3, 3, c_in, c_out),
        "gn1": _groupnorm_init(c_out),
        "conv2": _conv_init(k2, 3, 3, c_out, c_out),
        "gn2": _groupnorm_init(c_out),
    }
    if c_in != c_out:
        p["proj"] = _conv_init(k3, 1, 1, c_in, c_out)
        p["gn_proj"] = _groupnorm_init(c_out)
    return p


def _block(p: common.Params, x: jax.Array, stride: int, groups: int) -> jax.Array:
    h = _conv(p["conv1"], x, stride)
    h = jax.nn.relu(_groupnorm(p["gn1"], h, groups))
    h = _conv(p["conv2"], h)
    h = _groupnorm(p["gn2"], h, groups)
    if "proj" in p:
        x = _groupnorm(p["gn_proj"], _conv(p["proj"], x, stride), groups)
    elif stride != 1:
        x = x[:, ::stride, ::stride, :]
    return jax.nn.relu(h + x)


def init(rng: jax.Array, cfg: ResNetConfig) -> common.Params:
    keys = jax.random.split(rng, 2 + sum(cfg.stage_sizes))
    params: Dict = {
        "stem": _conv_init(keys[0], 3, 3, 3, cfg.stem_width),
        "gn_stem": _groupnorm_init(cfg.stem_width),
        "head": common.dense_init(keys[1], cfg.widths[-1], cfg.n_classes),
    }
    ki = 2
    c_in = cfg.stem_width
    for si, (n_blocks, width) in enumerate(zip(cfg.stage_sizes, cfg.widths)):
        for bi in range(n_blocks):
            params[f"s{si}b{bi}"] = _block_init(keys[ki], c_in, width)
            c_in = width
            ki += 1
    return params


def forward(params: common.Params, x: jax.Array, cfg: ResNetConfig) -> jax.Array:
    h = jax.nn.relu(_groupnorm(params["gn_stem"], _conv(params["stem"], x), cfg.groups))
    for si, n_blocks in enumerate(cfg.stage_sizes):
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            h = _block(params[f"s{si}b{bi}"], h, stride, cfg.groups)
    h = jnp.mean(h, axis=(1, 2))  # global average pool
    return common.dense(params["head"], h).astype(jnp.float32)


def loss_fn(
    params: common.Params, batch: Dict[str, jax.Array], rng: jax.Array, cfg: ResNetConfig
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    logits = forward(params, batch["x"], cfg)
    loss = common.softmax_xent(logits, batch["y"])
    return loss, {"loss": loss, "accuracy": common.accuracy(logits, batch["y"])}
