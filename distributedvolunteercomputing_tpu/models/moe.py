"""Mixture-of-Experts FFN (Switch top-1 / GShard top-2 routing) + GPT-2-MoE.

Build-side extension beyond reference parity (SURVEY.md §2 lists the
reference as dense volunteer-DP only), completing the parallelism set with
EXPERT parallelism: expert weights are stacked on a leading E axis and
sharded over the mesh's ``ep`` axis (parallel/sharding.py rules), so the
dispatch/combine einsums below compile to GSPMD all-to-alls over ICI — the
canonical GShard/Switch TPU formulation, where routing is expressed as
dense one-hot einsums the MXU eats, never as data-dependent gathers.

Routing (``router_top_k``; 1 = Switch Transformer, 2 = GShard top-2):
- router logits [S, E] -> softmax gates; each token goes to its top-k
  experts, output scaled by the gate(s) (renormalized over the chosen
  experts for k > 1; the raw argmax gate for k = 1, as in Switch);
- static capacity C = ceil(capacity_factor * router_top_k * S / E) per
  expert (capacity scales with k — 2S assignments need 2x the slots);
  tokens beyond an expert's capacity are DROPPED for the FFN (their
  residual stream passes through unchanged) — the standard fixed-shape
  trade that keeps the whole layer jit-compatible;
- load-balancing aux loss (Switch eq. 4): E * sum_e(frac_tokens_e *
  mean_gate_e), minimized at uniform routing; returned in metrics and
  added to the objective with ``aux_coef``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from distributedvolunteercomputing_tpu.models import common
from distributedvolunteercomputing_tpu.models.gpt2 import GPT2Config
from distributedvolunteercomputing_tpu.ops.attention import multi_head_attention


@dataclasses.dataclass(frozen=True)
class GPT2MoEConfig(GPT2Config):
    n_experts: int = 8
    capacity_factor: float = 1.25
    aux_coef: float = 0.01
    # Experts each token is routed to: 1 = Switch, 2 = GShard-style top-2
    # (gates renormalized over the chosen experts; the second choice queues
    # for capacity AFTER all first choices).
    router_top_k: int = 1
    # MoE replaces the dense FFN in EVERY block (Switch layout); d_ff is the
    # per-expert hidden width.

    def __post_init__(self):
        if not 1 <= self.router_top_k <= self.n_experts:
            raise ValueError(
                f"router_top_k={self.router_top_k} must be in [1, n_experts={self.n_experts}]"
            )


def moe_init(rng: jax.Array, cfg: GPT2MoEConfig) -> common.Params:
    kr, ki, ko = jax.random.split(rng, 3)
    e, d, f = cfg.n_experts, cfg.d_model, cfg.d_ff
    res_scale = 1.0 / ((2 * cfg.n_layers) ** 0.5 * d**0.5)
    return {
        "router": jax.random.normal(kr, (d, e), jnp.float32) * 0.02,
        # experts stacked on the leading E axis -> sharded over ep
        "moe_in": jax.random.normal(ki, (e, d, f), jnp.float32) * 0.02,
        "moe_out": jax.random.normal(ko, (e, f, d), jnp.float32) * res_scale,
    }


def moe_ffn(p: common.Params, x: jax.Array, cfg: GPT2MoEConfig) -> Tuple[jax.Array, jax.Array]:
    """x: [B, T, d] -> (y [B, T, d], aux_loss scalar)."""
    b, t, d = x.shape
    s = b * t
    e = cfg.n_experts
    # ceil, not truncation: capacity_factor=1.25 must mean >= 25% headroom
    # over the uniform share, never less. Capacity scales with router_top_k
    # (GShard): top-2 makes 2S total assignments, so per-expert slots must
    # double for the same factor or ~a third of assignments drop even under
    # perfectly uniform routing.
    cap = max(math.ceil(cfg.capacity_factor * cfg.router_top_k * s / e), 1)
    xs = x.reshape(s, d)

    # Router in f32 (softmax statistics), gates carry the gradient.
    logits = jnp.einsum("sd,de->se", xs.astype(jnp.float32), p["router"])
    gates = jax.nn.softmax(logits, axis=-1)  # [S, E]
    k_router = cfg.router_top_k
    top_gates, top_idx = jax.lax.top_k(gates, k_router)  # [S, K]
    if k_router > 1:
        # GShard: renormalize over the chosen experts so the combined output
        # is a convex mixture. (Deliberately NOT applied at K=1, matching
        # Switch — the raw gate carries the router gradient.)
        top_gates = top_gates / jnp.sum(top_gates, axis=-1, keepdims=True)

    # Per-choice dispatch: choice i's tokens queue for expert capacity AFTER
    # every earlier choice's assignments (count_prev), the standard GShard
    # ordering — a token's second choice never displaces a first choice.
    dispatch = jnp.zeros((s, e, cap), x.dtype)
    combine = jnp.zeros((s, e, cap), x.dtype)
    count_prev = jnp.zeros((e,), jnp.float32)
    onehot1 = None
    for i in range(k_router):
        oh = jax.nn.one_hot(top_idx[:, i], e, dtype=jnp.float32)  # [S, E]
        if i == 0:
            onehot1 = oh
        # Position within the expert queue; -1 where unrouted, >= cap drops.
        pos = (jnp.cumsum(oh, axis=0) + count_prev[None, :]) * oh - 1.0
        kept = (pos >= 0) & (pos < cap)
        pos_oh = jax.nn.one_hot(
            jnp.clip(pos, 0, cap - 1).astype(jnp.int32), cap, dtype=x.dtype
        )  # [S, E, C]
        disp = pos_oh * kept.astype(x.dtype)[..., None]
        dispatch = dispatch + disp
        combine = combine + disp * top_gates[:, i].astype(x.dtype)[:, None, None]
        count_prev = count_prev + jnp.sum(oh, axis=0)

    # dispatch/combine einsums: with moe_in/out sharded over ep, GSPMD emits
    # the all-to-alls here.
    ein = jnp.einsum("sec,sd->ecd", dispatch, xs)  # [E, C, d]
    dtype = x.dtype
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", ein, p["moe_in"].astype(dtype)))
    eout = jnp.einsum("ecf,efd->ecd", h, p["moe_out"].astype(dtype))  # [E, C, d]
    y = jnp.einsum("sec,ecd->sd", combine, eout)

    # Load-balance loss (Switch eq. 4 / GShard): E * sum_e(frac of tokens
    # whose FIRST choice is e * mean_gate_e).
    frac = jnp.mean(onehot1, axis=0)  # [E]
    mean_gate = jnp.mean(gates, axis=0)  # [E]
    aux = e * jnp.sum(frac * mean_gate)
    return y.reshape(b, t, d), aux.astype(jnp.float32)


def _layer_init(rng: jax.Array, cfg: GPT2MoEConfig) -> common.Params:
    k = jax.random.split(rng, 3)
    res_scale = 1.0 / ((2 * cfg.n_layers) ** 0.5 * cfg.d_model**0.5)
    return {
        "ln1": common.layernorm_init(cfg.d_model),
        "qkv": common.dense_init(k[0], cfg.d_model, 3 * cfg.d_model, scale=0.02),
        "attn_out": common.dense_init(k[1], cfg.d_model, cfg.d_model, scale=res_scale),
        "ln2": common.layernorm_init(cfg.d_model),
        "moe": moe_init(k[2], cfg),
    }


def init(rng: jax.Array, cfg: GPT2MoEConfig) -> common.Params:
    keys = jax.random.split(rng, 3)
    return {
        "wte": common.embed_init(keys[0], cfg.vocab, cfg.d_model),
        "wpe": common.embed_init(keys[1], cfg.max_len, cfg.d_model, scale=0.01),
        "blocks": common.stacked_init(
            lambda k: _layer_init(k, cfg), keys[2], cfg.n_layers
        ),
        "ln_f": common.layernorm_init(cfg.d_model),
    }


def _block(p: common.Params, x_aux, cfg: GPT2MoEConfig):
    x, aux = x_aux
    h = common.layernorm(p["ln1"], x)
    qkv = common.dense(p["qkv"], h)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    attn = multi_head_attention(q, k, v, cfg.n_heads, causal=True)
    x = x + common.dense(p["attn_out"], attn)
    h = common.layernorm(p["ln2"], x)
    y, layer_aux = moe_ffn(p["moe"], h, cfg)
    return x + y, aux + layer_aux


def loss_fn(
    params: common.Params, batch: Dict[str, jax.Array], rng: jax.Array, cfg: GPT2MoEConfig
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    from distributedvolunteercomputing_tpu.models import gpt2

    x = gpt2.embed(params, batch["tokens"], cfg)
    aux0 = jnp.zeros((), jnp.float32)
    (x, aux) = common.scan_blocks(
        lambda p, xa: _block(p, xa, cfg), params["blocks"], (x, aux0), remat=cfg.remat
    )
    x = common.layernorm(params["ln_f"], x)
    lm = common.lm_xent_chunked(
        x, params["wte"], batch["targets"], chunk=cfg.xent_chunk, head_layout="vd"
    )
    aux = aux / cfg.n_layers
    loss = lm + cfg.aux_coef * aux
    return loss, {"loss": loss, "lm_loss": lm, "aux_loss": aux}
