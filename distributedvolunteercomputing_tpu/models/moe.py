"""Mixture-of-Experts FFN (Switch-style top-1 routing) + GPT-2-MoE.

Build-side extension beyond reference parity (SURVEY.md §2 lists the
reference as dense volunteer-DP only), completing the parallelism set with
EXPERT parallelism: expert weights are stacked on a leading E axis and
sharded over the mesh's ``ep`` axis (parallel/sharding.py rules), so the
dispatch/combine einsums below compile to GSPMD all-to-alls over ICI — the
canonical GShard/Switch TPU formulation, where routing is expressed as
dense one-hot einsums the MXU eats, never as data-dependent gathers.

Routing (top-1, Switch Transformer):
- router logits [S, E] -> softmax gates; each token goes to its argmax
  expert, output scaled by that gate (the gate carries the gradient);
- static capacity C = ceil(capacity_factor * S / E) per expert; tokens
  beyond an expert's capacity are DROPPED for the FFN (their residual
  stream passes through unchanged) — the standard fixed-shape trade that
  keeps the whole layer jit-compatible;
- load-balancing aux loss (Switch eq. 4): E * sum_e(frac_tokens_e *
  mean_gate_e), minimized at uniform routing; returned in metrics and
  added to the objective with ``aux_coef``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from distributedvolunteercomputing_tpu.models import common
from distributedvolunteercomputing_tpu.models.gpt2 import GPT2Config
from distributedvolunteercomputing_tpu.ops.attention import multi_head_attention


@dataclasses.dataclass(frozen=True)
class GPT2MoEConfig(GPT2Config):
    n_experts: int = 8
    capacity_factor: float = 1.25
    aux_coef: float = 0.01
    # MoE replaces the dense FFN in EVERY block (Switch layout); d_ff is the
    # per-expert hidden width.


def moe_init(rng: jax.Array, cfg: GPT2MoEConfig) -> common.Params:
    kr, ki, ko = jax.random.split(rng, 3)
    e, d, f = cfg.n_experts, cfg.d_model, cfg.d_ff
    res_scale = 1.0 / ((2 * cfg.n_layers) ** 0.5 * d**0.5)
    return {
        "router": jax.random.normal(kr, (d, e), jnp.float32) * 0.02,
        # experts stacked on the leading E axis -> sharded over ep
        "moe_in": jax.random.normal(ki, (e, d, f), jnp.float32) * 0.02,
        "moe_out": jax.random.normal(ko, (e, f, d), jnp.float32) * res_scale,
    }


def moe_ffn(p: common.Params, x: jax.Array, cfg: GPT2MoEConfig) -> Tuple[jax.Array, jax.Array]:
    """x: [B, T, d] -> (y [B, T, d], aux_loss scalar)."""
    b, t, d = x.shape
    s = b * t
    e = cfg.n_experts
    # ceil, not truncation: capacity_factor=1.25 must mean >= 25% headroom
    # over the uniform share, never less.
    cap = max(math.ceil(cfg.capacity_factor * s / e), 1)
    xs = x.reshape(s, d)

    # Router in f32 (softmax statistics), gates carry the gradient.
    logits = jnp.einsum("sd,de->se", xs.astype(jnp.float32), p["router"])
    gates = jax.nn.softmax(logits, axis=-1)  # [S, E]
    expert = jnp.argmax(gates, axis=-1)  # [S]
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # [S, E]
    gate = jnp.sum(gates * onehot, axis=-1)  # [S] chosen gate

    # Position of each token within its expert; >= cap overflows (dropped).
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # [S, E], -1 where unrouted
    kept = (pos >= 0) & (pos < cap)
    pos_oh = jax.nn.one_hot(
        jnp.clip(pos, 0, cap - 1).astype(jnp.int32), cap, dtype=x.dtype
    )  # [S, E, C]
    dispatch = pos_oh * kept.astype(x.dtype)[..., None]  # [S, E, C]
    combine = dispatch * gate.astype(x.dtype)[:, None, None]

    # dispatch/combine einsums: with moe_in/out sharded over ep, GSPMD emits
    # the all-to-alls here.
    ein = jnp.einsum("sec,sd->ecd", dispatch, xs)  # [E, C, d]
    dtype = x.dtype
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", ein, p["moe_in"].astype(dtype)))
    eout = jnp.einsum("ecf,efd->ecd", h, p["moe_out"].astype(dtype))  # [E, C, d]
    y = jnp.einsum("sec,ecd->sd", combine, eout)

    # Switch load-balance loss: E * sum_e(frac_routed_e * mean_gate_e).
    frac = jnp.mean(onehot, axis=0)  # [E]
    mean_gate = jnp.mean(gates, axis=0)  # [E]
    aux = e * jnp.sum(frac * mean_gate)
    return y.reshape(b, t, d), aux.astype(jnp.float32)


def _layer_init(rng: jax.Array, cfg: GPT2MoEConfig) -> common.Params:
    k = jax.random.split(rng, 3)
    res_scale = 1.0 / ((2 * cfg.n_layers) ** 0.5 * cfg.d_model**0.5)
    return {
        "ln1": common.layernorm_init(cfg.d_model),
        "qkv": common.dense_init(k[0], cfg.d_model, 3 * cfg.d_model, scale=0.02),
        "attn_out": common.dense_init(k[1], cfg.d_model, cfg.d_model, scale=res_scale),
        "ln2": common.layernorm_init(cfg.d_model),
        "moe": moe_init(k[2], cfg),
    }


def init(rng: jax.Array, cfg: GPT2MoEConfig) -> common.Params:
    keys = jax.random.split(rng, 3)
    return {
        "wte": common.embed_init(keys[0], cfg.vocab, cfg.d_model),
        "wpe": common.embed_init(keys[1], cfg.max_len, cfg.d_model, scale=0.01),
        "blocks": common.stacked_init(
            lambda k: _layer_init(k, cfg), keys[2], cfg.n_layers
        ),
        "ln_f": common.layernorm_init(cfg.d_model),
    }


def _block(p: common.Params, x_aux, cfg: GPT2MoEConfig):
    x, aux = x_aux
    h = common.layernorm(p["ln1"], x)
    qkv = common.dense(p["qkv"], h)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    attn = multi_head_attention(q, k, v, cfg.n_heads, causal=True)
    x = x + common.dense(p["attn_out"], attn)
    h = common.layernorm(p["ln2"], x)
    y, layer_aux = moe_ffn(p["moe"], h, cfg)
    return x + y, aux + layer_aux


def loss_fn(
    params: common.Params, batch: Dict[str, jax.Array], rng: jax.Array, cfg: GPT2MoEConfig
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    from distributedvolunteercomputing_tpu.models import gpt2

    x = gpt2.embed(params, batch["tokens"], cfg)
    aux0 = jnp.zeros((), jnp.float32)
    (x, aux) = common.scan_blocks(
        lambda p, xa: _block(p, xa, cfg), params["blocks"], (x, aux0), remat=cfg.remat
    )
    x = common.layernorm(params["ln_f"], x)
    lm = common.lm_xent_chunked(
        x, params["wte"], batch["targets"], chunk=cfg.xent_chunk, head_layout="vd"
    )
    aux = aux / cfg.n_layers
    loss = lm + cfg.aux_coef * aux
    return loss, {"loss": loss, "lm_loss": lm, "aux_loss": aux}
