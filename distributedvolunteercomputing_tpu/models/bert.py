"""BERT-base masked-LM — reference config 3 (BASELINE.json:9).

Post-LN encoder (original BERT) with learned positions and a tied-embedding
MLM head. Only the MLM objective is implemented — that is the workload the
reference trains (4 volunteers, async gossip averaging).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from distributedvolunteercomputing_tpu.models import common
from distributedvolunteercomputing_tpu.ops.attention import multi_head_attention

MASK_ID = 103  # [MASK] in the BERT-base vocab


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab: int = 30522
    max_len: int = 512
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    remat: bool = True  # see GPT2Config.remat


def _layer_init(rng: jax.Array, cfg: BertConfig) -> common.Params:
    k = jax.random.split(rng, 4)
    return {
        "qkv": common.dense_init(k[0], cfg.d_model, 3 * cfg.d_model, scale=0.02),
        "attn_out": common.dense_init(k[1], cfg.d_model, cfg.d_model, scale=0.02),
        "ln1": common.layernorm_init(cfg.d_model),
        "mlp_in": common.dense_init(k[2], cfg.d_model, cfg.d_ff, scale=0.02),
        "mlp_out": common.dense_init(k[3], cfg.d_ff, cfg.d_model, scale=0.02),
        "ln2": common.layernorm_init(cfg.d_model),
    }


def init(rng: jax.Array, cfg: BertConfig) -> common.Params:
    keys = jax.random.split(rng, 4)
    return {
        "wte": common.embed_init(keys[0], cfg.vocab, cfg.d_model),
        "wpe": common.embed_init(keys[1], cfg.max_len, cfg.d_model, scale=0.01),
        "ln_emb": common.layernorm_init(cfg.d_model),
        "blocks": common.stacked_init(
            lambda k: _layer_init(k, cfg), keys[3], cfg.n_layers
        ),
        "mlm_dense": common.dense_init(keys[2], cfg.d_model, cfg.d_model, scale=0.02),
        "ln_mlm": common.layernorm_init(cfg.d_model),
    }


def _block(p: common.Params, x: jax.Array, cfg: BertConfig) -> jax.Array:
    qkv = common.dense(p["qkv"], x)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    attn = multi_head_attention(q, k, v, cfg.n_heads)
    x = common.layernorm(p["ln1"], x + common.dense(p["attn_out"], attn))
    h = common.dense(p["mlp_out"], jax.nn.gelu(common.dense(p["mlp_in"], x)))
    return common.layernorm(p["ln2"], x + h)


def hidden(params: common.Params, tokens: jax.Array, cfg: BertConfig) -> jax.Array:
    """MLM-head hidden states [B, T, d] (before the tied vocab projection)."""
    dtype = common.compute_dtype()
    t = tokens.shape[1]
    x = (params["wte"][tokens] + params["wpe"][:t][None]).astype(dtype)
    x = common.layernorm(params["ln_emb"], x)
    x = common.scan_blocks(
        lambda p, h: _block(p, h, cfg), params["blocks"], x, remat=cfg.remat
    )
    h = jax.nn.gelu(common.dense(params["mlm_dense"], x))
    return common.layernorm(params["ln_mlm"], h)


def forward(params: common.Params, tokens: jax.Array, cfg: BertConfig) -> jax.Array:
    h = hidden(params, tokens, cfg)
    return jnp.einsum(
        "btd,vd->btv", h, params["wte"].astype(h.dtype)
    ).astype(jnp.float32)


def loss_fn(
    params: common.Params, batch: Dict[str, jax.Array], rng: jax.Array, cfg: BertConfig
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    h = hidden(params, batch["tokens"], cfg)
    loss = common.lm_xent_chunked(
        h, params["wte"], batch["targets"], mask=batch["mask"], head_layout="vd"
    )
    return loss, {"loss": loss}
