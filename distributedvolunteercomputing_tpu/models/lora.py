"""LoRA adapters as separate pytree leaves.

Reference config 5 finetunes Llama-2-7B with LoRA under Byzantine-tolerant
averaging (BASELINE.json:11). Keeping adapters in their own subtree means the
swarm averages ONLY the adapter params — a ~1000x smaller WAN payload than
full params, which is what makes robust aggregation affordable per round
(SURVEY.md §7 hard part d).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp


def lora_init(rng: jax.Array, d_in: int, d_out: int, rank: int) -> Dict[str, jax.Array]:
    # A ~ N(0, 1/d_in), B = 0: adapters start as identity (zero delta).
    return {
        "a": jax.random.normal(rng, (d_in, rank), jnp.float32) * (1.0 / d_in**0.5),
        "b": jnp.zeros((rank, d_out), jnp.float32),
    }


def lora_delta(p: Dict[str, jax.Array], x: jax.Array, alpha: float, rank: int) -> jax.Array:
    scale = alpha / rank
    dtype = x.dtype
    return ((x @ p["a"].astype(dtype)) @ p["b"].astype(dtype)) * scale
