"""Llama-family decoder with LoRA finetuning — reference config 5
(BASELINE.json:11: Llama-2-7B LoRA under Byzantine-tolerant averaging).

RMSNorm + RoPE + SwiGLU, no biases (Llama-2 architecture). The default
config is a sandbox proxy (SURVEY.md §7 step 6 prescribes a scaled-down
proxy); ``LlamaConfig.llama2_7b()`` gives the real dims for multi-chip runs.

When ``lora_rank > 0`` the params split into ``{"base", "lora"}`` subtrees;
the base is frozen with ``stop_gradient`` (XLA prunes its whole backward
pass) and only the ``lora`` subtree carries gradients — so averagers ship
just the adapters.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from distributedvolunteercomputing_tpu.models import common
from distributedvolunteercomputing_tpu.models.lora import lora_delta, lora_init
from distributedvolunteercomputing_tpu.ops.attention import attention_core, merge_heads, rope, split_heads


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 2048
    max_len: int = 256
    d_model: int = 512
    n_heads: int = 8
    n_kv_heads: int = 8
    n_layers: int = 8
    d_ff: int = 1408
    lora_rank: int = 8
    lora_alpha: float = 16.0
    remat: bool = True  # see GPT2Config.remat

    @classmethod
    def llama2_7b(cls, lora_rank: int = 8) -> "LlamaConfig":
        return cls(
            vocab=32000, max_len=4096, d_model=4096, n_heads=32, n_kv_heads=32,
            n_layers=32, d_ff=11008, lora_rank=lora_rank,
        )


def _no_bias_dense_init(rng: jax.Array, d_in: int, d_out: int) -> jax.Array:
    return jax.random.normal(rng, (d_in, d_out), jnp.float32) * (1.0 / d_in**0.5)


def _layer_init(rng: jax.Array, cfg: LlamaConfig) -> common.Params:
    k = jax.random.split(rng, 7)
    d_head = cfg.d_model // cfg.n_heads
    d_kv = cfg.n_kv_heads * d_head
    return {
        "ln_attn": common.rmsnorm_init(cfg.d_model),
        "wq": _no_bias_dense_init(k[0], cfg.d_model, cfg.d_model),
        "wk": _no_bias_dense_init(k[1], cfg.d_model, d_kv),
        "wv": _no_bias_dense_init(k[2], cfg.d_model, d_kv),
        "wo": _no_bias_dense_init(k[3], cfg.d_model, cfg.d_model),
        "ln_mlp": common.rmsnorm_init(cfg.d_model),
        "w_gate": _no_bias_dense_init(k[4], cfg.d_model, cfg.d_ff),
        "w_up": _no_bias_dense_init(k[5], cfg.d_model, cfg.d_ff),
        "w_down": _no_bias_dense_init(k[6], cfg.d_ff, cfg.d_model),
    }


def _lora_layer_init(rng: jax.Array, cfg: LlamaConfig) -> common.Params:
    kq, kv = jax.random.split(rng)
    d_head = cfg.d_model // cfg.n_heads
    d_kv = cfg.n_kv_heads * d_head
    return {
        "q": lora_init(kq, cfg.d_model, cfg.d_model, cfg.lora_rank),
        "v": lora_init(kv, cfg.d_model, d_kv, cfg.lora_rank),
    }


def init(rng: jax.Array, cfg: LlamaConfig) -> common.Params:
    keys = jax.random.split(rng, 3)
    base = {
        "wte": common.embed_init(keys[0], cfg.vocab, cfg.d_model),
        "blocks": common.stacked_init(
            lambda k: _layer_init(k, cfg), keys[2], cfg.n_layers
        ),
        "ln_f": common.rmsnorm_init(cfg.d_model),
        "lm_head": _no_bias_dense_init(keys[1], cfg.d_model, cfg.vocab),
    }
    if cfg.lora_rank <= 0:
        return base
    return {
        "base": base,
        "lora": {
            "blocks": common.stacked_init(
                lambda k: _lora_layer_init(k, cfg),
                jax.random.fold_in(rng, 1),
                cfg.n_layers,
            )
        },
    }


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=1)


def _block(p: common.Params, lp: common.Params, x: jax.Array, cfg: LlamaConfig) -> jax.Array:
    dtype = x.dtype
    h = common.rmsnorm(p["ln_attn"], x)
    q = h @ p["wq"].astype(dtype)
    k = h @ p["wk"].astype(dtype)
    v = h @ p["wv"].astype(dtype)
    if lp is not None:
        q = q + lora_delta(lp["q"], h, cfg.lora_alpha, cfg.lora_rank)
        v = v + lora_delta(lp["v"], h, cfg.lora_alpha, cfg.lora_rank)
    qh = rope(split_heads(q, cfg.n_heads))
    kh = rope(split_heads(k, cfg.n_kv_heads))
    vh = split_heads(v, cfg.n_kv_heads)
    n_rep = cfg.n_heads // cfg.n_kv_heads
    attn = attention_core(qh, _repeat_kv(kh, n_rep), _repeat_kv(vh, n_rep), causal=True)
    x = x + merge_heads(attn) @ p["wo"].astype(dtype)
    h = common.rmsnorm(p["ln_mlp"], x)
    gate = jax.nn.silu(h @ p["w_gate"].astype(dtype))
    up = h @ p["w_up"].astype(dtype)
    return x + (gate * up) @ p["w_down"].astype(dtype)


def _trunk(params: common.Params, tokens: jax.Array, cfg: LlamaConfig):
    """Shared fwd trunk: returns (final hidden [B,T,d], frozen-or-not base)."""
    lora_enabled = cfg.lora_rank > 0
    base = params["base"] if lora_enabled else params
    if lora_enabled:
        # Freeze the base: its backward pass is pruned entirely by XLA.
        base = jax.tree_util.tree_map(jax.lax.stop_gradient, base)
    dtype = common.compute_dtype()
    x = base["wte"][tokens].astype(dtype)
    if lora_enabled:
        x = common.scan_blocks(
            lambda pl, h: _block(pl[0], pl[1], h, cfg),
            (base["blocks"], params["lora"]["blocks"]),
            x,
            remat=cfg.remat,
        )
    else:
        x = common.scan_blocks(
            lambda p, h: _block(p, None, h, cfg), base["blocks"], x, remat=cfg.remat
        )
    return common.rmsnorm(base["ln_f"], x), base


def forward(params: common.Params, tokens: jax.Array, cfg: LlamaConfig) -> jax.Array:
    x, base = _trunk(params, tokens, cfg)
    return (x @ base["lm_head"].astype(x.dtype)).astype(jnp.float32)


def loss_fn(
    params: common.Params, batch: Dict[str, jax.Array], rng: jax.Array, cfg: LlamaConfig
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    x, base = _trunk(params, batch["tokens"], cfg)
    loss = common.lm_xent_chunked(
        x, base["lm_head"], batch["targets"], head_layout="dv"
    )
    return loss, {"loss": loss}


def lora_subtree(params: common.Params) -> common.Params:
    """The averaging payload for config 5: adapters only."""
    return params["lora"]


def with_lora_subtree(params: common.Params, lora: common.Params) -> common.Params:
    return {"base": params["base"], "lora": lora}
