"""Model registry: one bundle per reference workload (BASELINE.json:7-11).

Bundles are built lazily so importing the registry never pays for the whole
zoo. Each bundle closes over its config and exposes:

    init(rng) -> params
    loss_fn(params, batch, rng) -> (loss, metrics)
    make_batch(rng, batch_size) -> synthetic batch with the right shapes
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Tuple

import jax

Batch = Dict[str, jax.Array]
Metrics = Dict[str, jax.Array]


def _identity_select(params: Any) -> Any:
    return params


def _identity_merge(params: Any, averaged: Any) -> Any:
    return averaged


@dataclasses.dataclass(frozen=True)
class ModelBundle:
    name: str
    config: Any
    init: Callable[[jax.Array], Any]
    loss_fn: Callable[[Any, Batch, jax.Array], Tuple[jax.Array, Metrics]]
    make_batch: Callable[[jax.Array, int], Batch]
    # What the swarm averages: select the payload subtree out of the params
    # (identity for full averaging; the LoRA bundle selects adapters only so
    # the WAN round ships ~1000x less) and merge the averaged result back.
    avg_select: Callable[[Any], Any] = _identity_select
    avg_merge: Callable[[Any, Any], Any] = _identity_merge


def _mlp(**overrides: Any) -> ModelBundle:
    from distributedvolunteercomputing_tpu.models import mlp
    from distributedvolunteercomputing_tpu.training import data

    cfg = dataclasses.replace(mlp.MLPConfig(), **overrides)
    return ModelBundle(
        name="mnist_mlp",
        config=cfg,
        init=lambda rng: mlp.init(rng, cfg),
        loss_fn=lambda p, b, rng: mlp.loss_fn(p, b, rng, cfg),
        make_batch=lambda rng, bs: data.synthetic_image_batch(
            rng, bs, shape=(28, 28, 1), n_classes=cfg.n_classes
        ),
    )


def _resnet18(**overrides: Any) -> ModelBundle:
    from distributedvolunteercomputing_tpu.models import resnet
    from distributedvolunteercomputing_tpu.training import data

    cfg = dataclasses.replace(resnet.ResNetConfig(), **overrides)
    return ModelBundle(
        name="cifar10_resnet18",
        config=cfg,
        init=lambda rng: resnet.init(rng, cfg),
        loss_fn=lambda p, b, rng: resnet.loss_fn(p, b, rng, cfg),
        make_batch=lambda rng, bs: data.synthetic_image_batch(
            rng, bs, shape=(32, 32, 3), n_classes=cfg.n_classes
        ),
    )


def _bert(**overrides: Any) -> ModelBundle:
    from distributedvolunteercomputing_tpu.models import bert
    from distributedvolunteercomputing_tpu.training import data

    cfg = dataclasses.replace(bert.BertConfig(), **overrides)
    return ModelBundle(
        name="bert_mlm",
        config=cfg,
        init=lambda rng: bert.init(rng, cfg),
        loss_fn=lambda p, b, rng: bert.loss_fn(p, b, rng, cfg),
        make_batch=lambda rng, bs: data.synthetic_mlm_batch(
            rng, bs, seq_len=cfg.max_len, vocab=cfg.vocab, mask_id=bert.MASK_ID
        ),
    )


def _gpt2(**overrides: Any) -> ModelBundle:
    from distributedvolunteercomputing_tpu.models import gpt2
    from distributedvolunteercomputing_tpu.training import data

    cfg = dataclasses.replace(gpt2.GPT2Config(), **overrides)
    return ModelBundle(
        name="gpt2_small",
        config=cfg,
        init=lambda rng: gpt2.init(rng, cfg),
        loss_fn=lambda p, b, rng: gpt2.loss_fn(p, b, rng, cfg),
        make_batch=lambda rng, bs: data.synthetic_lm_batch(
            rng, bs, seq_len=cfg.max_len, vocab=cfg.vocab
        ),
    )


def _gpt2_preset(preset: str, **overrides: Any) -> ModelBundle:
    """gpt2_medium / gpt2_large as first-class registry names: the scale
    rungs above the flagship (GPT2Config.medium/.large presets), nameable
    from the CLI (--model) and the bench (DVC_BENCH_MODEL) without a
    config-override incantation. Overrides still apply on top."""
    from distributedvolunteercomputing_tpu.models import gpt2
    from distributedvolunteercomputing_tpu.training import data

    base = getattr(gpt2.GPT2Config, preset)()
    cfg = dataclasses.replace(base, **overrides)
    return ModelBundle(
        name=f"gpt2_{preset}",
        config=cfg,
        init=lambda rng: gpt2.init(rng, cfg),
        loss_fn=lambda p, b, rng: gpt2.loss_fn(p, b, rng, cfg),
        make_batch=lambda rng, bs: data.synthetic_lm_batch(
            rng, bs, seq_len=cfg.max_len, vocab=cfg.vocab
        ),
    )


def _gpt2_moe(**overrides: Any) -> ModelBundle:
    from distributedvolunteercomputing_tpu.models import moe
    from distributedvolunteercomputing_tpu.training import data

    cfg = dataclasses.replace(moe.GPT2MoEConfig(), **overrides)
    return ModelBundle(
        name="gpt2_moe",
        config=cfg,
        init=lambda rng: moe.init(rng, cfg),
        loss_fn=lambda p, b, rng: moe.loss_fn(p, b, rng, cfg),
        make_batch=lambda rng, bs: data.synthetic_lm_batch(
            rng, bs, seq_len=cfg.max_len, vocab=cfg.vocab
        ),
    )


def _vit(**overrides: Any) -> ModelBundle:
    from distributedvolunteercomputing_tpu.models import vit
    from distributedvolunteercomputing_tpu.training import data

    cfg = dataclasses.replace(vit.ViTConfig(), **overrides)
    return ModelBundle(
        name="cifar10_vit",
        config=cfg,
        init=lambda rng: vit.init(rng, cfg),
        loss_fn=lambda p, b, rng: vit.loss_fn(p, b, rng, cfg),
        make_batch=lambda rng, bs: data.synthetic_image_batch(
            rng, bs,
            shape=(cfg.image_size, cfg.image_size, cfg.channels),
            n_classes=cfg.n_classes,
        ),
    )


def _llama_lora(**overrides: Any) -> ModelBundle:
    from distributedvolunteercomputing_tpu.models import llama
    from distributedvolunteercomputing_tpu.training import data

    cfg = dataclasses.replace(llama.LlamaConfig(), **overrides)
    lora_on = cfg.lora_rank > 0
    return ModelBundle(
        name="llama_lora",
        config=cfg,
        init=lambda rng: llama.init(rng, cfg),
        loss_fn=lambda p, b, rng: llama.loss_fn(p, b, rng, cfg),
        make_batch=lambda rng, bs: data.synthetic_lm_batch(
            rng, bs, seq_len=cfg.max_len, vocab=cfg.vocab
        ),
        avg_select=llama.lora_subtree if lora_on else _identity_select,
        avg_merge=llama.with_lora_subtree if lora_on else _identity_merge,
    )


_REGISTRY: Dict[str, Callable[..., ModelBundle]] = {
    "mnist_mlp": _mlp,
    "cifar10_resnet18": _resnet18,
    "cifar10_vit": _vit,
    "bert_mlm": _bert,
    "gpt2_small": _gpt2,
    "gpt2_medium": lambda **kw: _gpt2_preset("medium", **kw),
    "gpt2_large": lambda **kw: _gpt2_preset("large", **kw),
    "gpt2_moe": _gpt2_moe,
    "llama_lora": _llama_lora,
}


def get_model(name: str, **overrides: Any) -> ModelBundle:
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**overrides)


def list_models() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))
