from distributedvolunteercomputing_tpu.models.registry import ModelBundle, get_model, list_models

__all__ = ["ModelBundle", "get_model", "list_models"]
