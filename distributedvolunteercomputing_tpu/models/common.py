"""Shared building blocks for the plain-JAX functional model zoo.

Models are pure functions over explicit param pytrees (nested dicts of
jnp arrays). That keeps the whole zoo uniform for the three things this
framework does with params: shard them with ``pjit``, average them on host
across volunteers, and checkpoint them — no framework Module state to
special-case.

Params are stored float32; matmul-heavy compute casts to bfloat16 on TPU so
the MXU runs at full rate. Reference parity: the CUDA train_step genre uses
AMP the same way (SURVEY.md L1/L5).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

# bf16 on TPU keeps the MXU at full rate; f32 on CPU keeps tests exact enough
# to compare against numpy references.
def compute_dtype() -> jnp.dtype:
    if jax.default_backend() in ("tpu", "axon"):
        return jnp.bfloat16
    return jnp.float32


def dense_init(rng: jax.Array, d_in: int, d_out: int, scale: Optional[float] = None) -> Params:
    if scale is None:
        scale = 1.0 / (d_in ** 0.5)
    w_rng, _ = jax.random.split(rng)
    return {
        "w": (jax.random.normal(w_rng, (d_in, d_out), jnp.float32) * scale),
        "b": jnp.zeros((d_out,), jnp.float32),
    }


def dense(p: Params, x: jax.Array, dtype: Optional[jnp.dtype] = None) -> jax.Array:
    dtype = dtype or compute_dtype()
    y = jnp.dot(x.astype(dtype), p["w"].astype(dtype))
    return y + p["b"].astype(dtype)


def embed_init(rng: jax.Array, vocab: int, d: int, scale: float = 0.02) -> jax.Array:
    return jax.random.normal(rng, (vocab, d), jnp.float32) * scale


def layernorm_init(d: int) -> Params:
    return {"g": jnp.ones((d,), jnp.float32), "b": jnp.zeros((d,), jnp.float32)}


def layernorm(p: Params, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    # LN statistics in f32 for stability even when activations are bf16.
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["g"] + p["b"]).astype(x.dtype)


def rmsnorm_init(d: int) -> Params:
    return {"g": jnp.ones((d,), jnp.float32)}


def rmsnorm(p: Params, x: jax.Array, eps: float = 1e-6) -> jax.Array:
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (y * p["g"]).astype(x.dtype)


def softmax_xent(logits: jax.Array, labels: jax.Array, mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean cross-entropy; ``labels`` are int ids; optional 0/1 mask."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if mask is not None:
        mask = mask.astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))


def count_params(params: Params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


def split_keys(rng: jax.Array, n: int) -> Tuple[jax.Array, ...]:
    return tuple(jax.random.split(rng, n))
