"""Shared building blocks for the plain-JAX functional model zoo.

Models are pure functions over explicit param pytrees (nested dicts of
jnp arrays). That keeps the whole zoo uniform for the three things this
framework does with params: shard them with ``pjit``, average them on host
across volunteers, and checkpoint them — no framework Module state to
special-case.

Params are stored float32; matmul-heavy compute casts to bfloat16 on TPU so
the MXU runs at full rate. Reference parity: the CUDA train_step genre uses
AMP the same way (SURVEY.md L1/L5).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

# bf16 on TPU keeps the MXU at full rate; f32 on CPU keeps tests exact enough
# to compare against numpy references.
def compute_dtype() -> jnp.dtype:
    from distributedvolunteercomputing_tpu.utils.jaxenv import tpu_backend

    if tpu_backend():
        return jnp.bfloat16
    return jnp.float32


def dense_init(rng: jax.Array, d_in: int, d_out: int, scale: Optional[float] = None) -> Params:
    if scale is None:
        scale = 1.0 / (d_in ** 0.5)
    w_rng, _ = jax.random.split(rng)
    return {
        "w": (jax.random.normal(w_rng, (d_in, d_out), jnp.float32) * scale),
        "b": jnp.zeros((d_out,), jnp.float32),
    }


def dense(p: Params, x: jax.Array, dtype: Optional[jnp.dtype] = None) -> jax.Array:
    dtype = dtype or compute_dtype()
    y = jnp.dot(x.astype(dtype), p["w"].astype(dtype))
    return y + p["b"].astype(dtype)


def embed_init(rng: jax.Array, vocab: int, d: int, scale: float = 0.02) -> jax.Array:
    return jax.random.normal(rng, (vocab, d), jnp.float32) * scale


def layernorm_init(d: int) -> Params:
    return {"g": jnp.ones((d,), jnp.float32), "b": jnp.zeros((d,), jnp.float32)}


def layernorm(p: Params, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    # LN statistics in f32 for stability even when activations are bf16.
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["g"] + p["b"]).astype(x.dtype)


def rmsnorm_init(d: int) -> Params:
    return {"g": jnp.ones((d,), jnp.float32)}


def rmsnorm(p: Params, x: jax.Array, eps: float = 1e-6) -> jax.Array:
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (y * p["g"]).astype(x.dtype)


def softmax_xent(logits: jax.Array, labels: jax.Array, mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean cross-entropy; ``labels`` are int ids; optional 0/1 mask."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if mask is not None:
        mask = mask.astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def stacked_init(layer_init, rng: jax.Array, n_layers: int) -> Params:
    """Init ``n_layers`` identical layers as ONE stacked pytree (leading axis
    = layer). The zoo's transformers scan over this stack (``lax.scan``)
    instead of unrolling a Python loop, so the XLA program contains each
    block's HLO once — smaller programs, faster compiles, and the layout the
    TPU sharding rules (parallel/sharding.py) expect for block weights."""
    keys = jax.random.split(rng, n_layers)
    return jax.vmap(layer_init)(keys)


def scan_blocks(body, blocks: Params, x: jax.Array, remat: bool = True) -> jax.Array:
    """Run ``x`` through stacked ``blocks`` with ``lax.scan``; ``body`` is
    ``(layer_params, x) -> x``. With ``remat`` each layer's activations are
    rematerialized in backward (checkpoint-per-scan-step), the standard
    O(sqrt)-free layerwise remat that keeps HBM at one layer's activations."""
    fn = jax.checkpoint(body) if remat else body

    def step(h, p):
        return fn(p, h), None

    x, _ = jax.lax.scan(step, x, blocks)
    return x


def _project_vocab(x: jax.Array, head: jax.Array, head_layout: str) -> jax.Array:
    # f32 accumulation out of the MXU regardless of the bf16 inputs.
    eq = "...d,vd->...v" if head_layout == "vd" else "...d,dv->...v"
    return jnp.einsum(eq, x, head.astype(x.dtype), preferred_element_type=jnp.float32)


def lm_xent_chunked(
    x: jax.Array,
    head: jax.Array,
    labels: jax.Array,
    mask: Optional[jax.Array] = None,
    chunk: int = 128,
    head_layout: str = "vd",
) -> jax.Array:
    """Mean LM cross-entropy WITHOUT materializing the [B, T, V] f32 logits.

    For GPT-2-small shapes (B=8, T=1024, V=50257) the full logits tensor is
    1.6 GB f32 — and its backward residuals double that. This scans over T in
    ``chunk``-sized slices with a checkpointed body, so peak memory is one
    [B, chunk, V] buffer (~206 MB at chunk=128) and the backward pass
    recomputes each chunk's logits instead of saving them.

    ``head`` is the projection matrix: [V, d] (``head_layout="vd"``, tied
    embeddings — GPT-2/BERT) or [d, V] (``"dv"``, a separate lm_head — Llama).
    ``mask`` is an optional 0/1 token mask (MLM objective).
    """
    b, t, _ = x.shape
    if t % chunk != 0:
        chunk = t  # tiny test configs: single chunk, same math
    n = t // chunk
    if n <= 1:
        logits = _project_vocab(x, head, head_layout)
        return softmax_xent(logits, labels, mask)

    # [n, B, chunk, ...] so scan's leading axis is the chunk index.
    xs = jnp.moveaxis(x.reshape(b, n, chunk, x.shape[-1]), 1, 0)
    ls = jnp.moveaxis(labels.reshape(b, n, chunk), 1, 0)
    ms = (
        jnp.moveaxis(mask.astype(jnp.float32).reshape(b, n, chunk), 1, 0)
        if mask is not None
        else jnp.ones((n, 1, 1), jnp.float32) * 0  # placeholder, unused
    )
    use_mask = mask is not None

    def body(carry, xc_lc_mc):
        nll_sum, denom = carry
        xc, lc, mc = xc_lc_mc
        logits = _project_vocab(xc, head, head_layout)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, lc[..., None], axis=-1)[..., 0]
        nll = logz - gold
        if use_mask:
            return (nll_sum + jnp.sum(nll * mc), denom + jnp.sum(mc)), None
        return (nll_sum + jnp.sum(nll), denom + nll.size * 1.0), None

    zero = jnp.zeros((), jnp.float32)
    (nll_sum, denom), _ = jax.lax.scan(jax.checkpoint(body), (zero, zero), (xs, ls, ms))
    return nll_sum / jnp.maximum(denom, 1.0)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))


def count_params(params: Params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


def split_keys(rng: jax.Array, n: int) -> Tuple[jax.Array, ...]:
    return tuple(jax.random.split(rng, n))
