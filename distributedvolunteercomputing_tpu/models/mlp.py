"""2-layer MLP for MNIST — reference config 1 (BASELINE.json:7).

Single-volunteer local SGD, no averaging: the minimum end-to-end slice of the
framework (SURVEY.md §7 step 1).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from distributedvolunteercomputing_tpu.models import common


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    d_in: int = 784
    d_hidden: int = 256
    n_classes: int = 10


def init(rng: jax.Array, cfg: MLPConfig) -> common.Params:
    k1, k2 = jax.random.split(rng)
    return {
        "fc1": common.dense_init(k1, cfg.d_in, cfg.d_hidden),
        "fc2": common.dense_init(k2, cfg.d_hidden, cfg.n_classes),
    }


def forward(params: common.Params, x: jax.Array, cfg: MLPConfig) -> jax.Array:
    x = x.reshape((x.shape[0], -1))
    h = jax.nn.relu(common.dense(params["fc1"], x))
    return common.dense(params["fc2"], h).astype(jnp.float32)


def loss_fn(
    params: common.Params, batch: Dict[str, jax.Array], rng: jax.Array, cfg: MLPConfig
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    logits = forward(params, batch["x"], cfg)
    loss = common.softmax_xent(logits, batch["y"])
    return loss, {"loss": loss, "accuracy": common.accuracy(logits, batch["y"])}
