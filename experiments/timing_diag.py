"""Diagnose the attn_sweep timing artifact on the axon remote platform.

attn_sweep measured 0.02ms fwd+bwd at shapes where chip_probe measured 8ms —
block_until_ready(grads) is apparently not waiting for real completion here.
Times the same jitted grad three ways at one shape to see which sync method
reflects real execution: (a) block_until_ready per iter, (b) one block after
N iters, (c) chained data dependency + scalar device_get.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from distributedvolunteercomputing_tpu.ops import attention

B, H, T, D = 8, 12, 1024, 64
ks = jax.random.split(jax.random.PRNGKey(0), 3)
q = jax.random.normal(ks[0], (B, H, T, D), jnp.float32)
k = jax.random.normal(ks[1], (B, H, T, D), jnp.float32)
v = jax.random.normal(ks[2], (B, H, T, D), jnp.float32)

attention.set_attention_impl("xla")


def loss(q, k, v):
    o = attention.attention_core_local(q, k, v, causal=True)
    return o.astype(jnp.float32).sum()


f = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
g = f(q, k, v)
jax.block_until_ready(g)
print("compiled", flush=True)

# (a) block each iteration
t0 = time.perf_counter()
for _ in range(10):
    g = f(q, k, v)
    jax.block_until_ready(g)
print(f"a per-iter block: {(time.perf_counter()-t0)/10*1e3:.3f} ms", flush=True)

# (b) one block at the end
t0 = time.perf_counter()
for _ in range(10):
    g = f(q, k, v)
jax.block_until_ready(g)
print(f"b end block:      {(time.perf_counter()-t0)/10*1e3:.3f} ms", flush=True)

# (c) chained dependency: feed grad back in, then fetch a scalar
t0 = time.perf_counter()
qq = q
for _ in range(10):
    g = f(qq, k, v)
    qq = g[0]
s = float(jax.device_get(jnp.sum(qq)))
print(f"c chained+get:    {(time.perf_counter()-t0)/10*1e3:.3f} ms (s={s:.3g})", flush=True)

# (d) the probe's exact pattern: value_and_grad with aux out, block on both
def loss2(q, k, v):
    o = attention.attention_core_local(q, k, v, causal=True)
    return o.astype(jnp.float32).sum(), o


f2 = jax.jit(jax.value_and_grad(loss2, argnums=(0, 1, 2), has_aux=True))
(_, out), g2 = f2(q, k, v)
jax.block_until_ready((out, g2))
t0 = time.perf_counter()
for _ in range(10):
    (_, out), g2 = f2(q, k, v)
jax.block_until_ready((out, g2))
print(f"d probe pattern:  {(time.perf_counter()-t0)/10*1e3:.3f} ms", flush=True)
