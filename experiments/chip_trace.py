"""Capture ONE jax.profiler trace of the flagship step on the real chip.

VERDICT r4 weak #3: est_mfu 0.23 has no committed evidence of WHERE the
remaining time goes — no profiler trace from the chip exists. This script
runs a handful of gpt2_small steps (bench shapes, remat off — the fastest
schedule, i.e. the one the headline number uses) inside a
``jax.profiler.trace`` window and saves the trace to
``experiments/results/trace/``; a summary JSON with the trace dir listing is
written to ``results/chip_trace.json`` so the watcher can done-marker it.

The profiler may not work over the tunneled axon runtime (device-side TPU
profiling needs the libtpu profiler plugin on the far side); this script is
deliberately cheap and runs LATE in the window agenda so a hang here costs
nothing that matters. Even a host-only trace still attributes dispatch gaps.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

R = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")
TRACE_DIR = os.path.join(R, "trace")


def main() -> int:
    t0 = time.time()
    import jax

    devs = jax.devices()
    print(f"[{time.time() - t0:5.1f}s] backend up: {devs[0].device_kind}", flush=True)

    from distributedvolunteercomputing_tpu.models import get_model
    from distributedvolunteercomputing_tpu.training.optim import make_optimizer
    from distributedvolunteercomputing_tpu.training.steps import TrainState, make_train_step

    # Knobs exist so the script's trace plumbing is verifiable on CPU (where
    # a gpt2_small f32 step takes tens of seconds); the TPU default is the
    # flagship bench config.
    model_name = os.environ.get("DVC_TRACE_MODEL", "gpt2_small")
    n_steps = int(os.environ.get("DVC_TRACE_STEPS", "8"))
    kw = {"remat": False} if model_name.startswith("gpt2") else {}
    b = get_model(model_name, **kw)
    tx = make_optimizer("adamw", lr=1e-4)
    params = b.init(jax.random.PRNGKey(1))
    st = TrainState.create(params, tx, jax.random.PRNGKey(2))
    del params
    step = make_train_step(b.loss_fn, tx)
    batch = b.make_batch(jax.random.PRNGKey(0), 8)
    for _ in range(3):  # compile + settle outside the trace window
        st, m = step(st, batch)
    float(m["loss"])
    print(f"[{time.time() - t0:5.1f}s] warm; tracing {n_steps} steps", flush=True)

    os.makedirs(TRACE_DIR, exist_ok=True)
    with jax.profiler.trace(TRACE_DIR):
        for _ in range(n_steps):
            st, m = step(st, batch)
        float(m["loss"])  # materialize INSIDE the window: chained scalar
        # fetch is the only op observed to synchronize this runtime
        # (experiments/timing_diag.py).

    files = []
    for root, _dirs, names in os.walk(TRACE_DIR):
        for n in names:
            p = os.path.join(root, n)
            if os.path.getmtime(p) < t0:
                continue  # stale entry from a previous trace run, not ours
            files.append({"path": os.path.relpath(p, R), "bytes": os.path.getsize(p)})
    payload = {
        "device_kind": devs[0].device_kind,
        "model": model_name,
        "traced_steps": n_steps,
        "files": files,
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "wall_s": round(time.time() - t0, 1),
    }
    with open(os.path.join(R, "chip_trace.json"), "w") as fh:
        json.dump(payload, fh, indent=1)
    print(json.dumps(payload)[:400], flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
