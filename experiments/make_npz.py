#!/usr/bin/env python
"""Deterministic .npz dataset generator for the file-backed data path.

The sandbox has zero egress (training/data.py:3), so the real MNIST/CIFAR
files the reference's configs 1-2 train on (BASELINE.json:7-8) cannot be
downloaded. This writes datasets with the SAME shapes and the same
class-conditional-blob learnability recipe as the synthetic stream, but as a
fixed finite file — which is what actually exercises the ``--data`` path end
to end: np.load, key/schema validation, per-peer shuffle sharding, epoch
reshuffles, partial-batch dropping, and the separate held-out eval stream.

Deterministic by construction (fixed default seed, no clock): two calls with
the same arguments produce byte-identical files.

Usage:
  python experiments/make_npz.py --task mnist --out /tmp/mnist.npz
  python experiments/make_npz.py --task cifar10 --out /tmp/cifar.npz --n 2048
"""

from __future__ import annotations

import argparse

import numpy as np

SHAPES = {
    # task: (x shape per example, n_classes) — mnist flat [784] (the MLP
    # reshapes anyway), cifar10 NHWC [32, 32, 3] (the resnet stem wants it).
    "mnist": ((784,), 10),
    "cifar10": ((32, 32, 3), 10),
}


def make(task: str, n: int, seed: int, noise: float = 0.3) -> dict:
    shape, n_classes = SHAPES[task]
    rng = np.random.default_rng(seed)
    protos = rng.standard_normal((n_classes,) + shape, dtype=np.float32)
    y = rng.integers(0, n_classes, size=n)
    x = protos[y] + noise * rng.standard_normal((n,) + shape, dtype=np.float32)
    return {"x": x.astype(np.float32), "y": y.astype(np.int32)}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--task", choices=sorted(SHAPES), required=True)
    ap.add_argument("--out", required=True, help="output .npz path")
    ap.add_argument("--n", type=int, default=4096, help="number of examples")
    ap.add_argument("--seed", type=int, default=1234)
    args = ap.parse_args()
    data = make(args.task, args.n, args.seed)
    np.savez(args.out, **data)
    print(
        f"{args.out}: x{data['x'].shape} y{data['y'].shape} "
        f"(task={args.task} seed={args.seed})"
    )


if __name__ == "__main__":
    main()
