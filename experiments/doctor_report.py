#!/usr/bin/env python
"""Root-cause doctor: join firing alerts with flight-recorder events,
trace evidence, and health/quality telemetry into RANKED root-cause
hypotheses — the third layer of the swarm watchdog (ISSUE 13).

The alerting tier (swarm/watchdog.py) answers *that* something broke;
this module answers *what probably broke it*, by joining the three
evidence planes the telemetry substrate already shares a round-key/time
axis across:

- **alerts** — ``alert_raised`` transitions (volunteer detectors + the
  replica-side SLO/mixing plane), each naming a detector kind and a key
  (hierarchy level, peer, link).
- **flight events** — every volunteer's flight-recorder ring
  (depositions, fence rejections, mass-loss, quality flags, backoff),
  each carrying peer, severity, and the round trace it happened under.
- **health/quality** — per-peer quality scores, lost-mass attribution,
  bandwidth evidence.

Each RULE below scores one failure-class hypothesis from that joined
evidence and emits a causal chain (e.g. ``cross-zone bw collapse on
dc<->home -> level=cross deadline inflation -> mixing stall``). The
output is the ranked list — highest score first — with the evidence each
hypothesis rode on, so an operator (or the chaos verdict) can audit the
diagnosis instead of trusting it.

Usage:
    python experiments/doctor_report.py <chaos_artifact.json> [--scenario k]
        # diagnose a chaos_soak artifact (reads its alerts + flight dumps)
    python experiments/doctor_report.py --bundle <bundle.json>
        # diagnose a raw evidence bundle (the diagnose() input, verbatim)

Library use (what ``chaos_soak.py --watchdog`` asserts against):
    from doctor_report import diagnose
    ranked = diagnose(bundle)   # bundle: {"alerts": [...], "flight": {...}, ...}
    ranked[0]["cause"]          # the top hypothesis
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter
from typing import Any, Dict, List, Optional

# Evidence weights per rule: corroboration across planes beats volume
# within one plane, so each distinct evidence CLASS contributes once and
# the score saturates — 10 depositions are not 10x the evidence of 3.
_CAP = 1.0


def _alerts_of(bundle: dict, kind: str, key_prefix: str = "") -> List[dict]:
    out = []
    for a in bundle.get("alerts") or []:
        if a.get("kind") != kind:
            continue
        if key_prefix and not str(a.get("key", "")).startswith(key_prefix):
            continue
        out.append(a)
    return out


def _events_of(bundle: dict, kind: str) -> List[dict]:
    out = []
    for events in (bundle.get("flight") or {}).values():
        for e in events or []:
            if e.get("kind") == kind:
                out.append(e)
    return out


def _sat(n: int, k: int) -> float:
    """Saturating evidence weight: 0 at n=0, 1 at n>=k."""
    return min(float(n) / float(max(k, 1)), _CAP)


def _rule_leader_crash_storm(bundle: dict) -> Optional[dict]:
    """Repeated depositions of the same leader + wall/commit anomalies ->
    a crash-looping (or serially killed) leader."""
    deps = _events_of(bundle, "leader_deposed")
    if not deps:
        return None
    by_leader = Counter(str(e.get("leader", "?")) for e in deps)
    leader, n = by_leader.most_common(1)[0]
    wall = _alerts_of(bundle, "round_wall_inflation")
    rate = _alerts_of(bundle, "commit_rate_collapse")
    recov = _events_of(bundle, "round_recovered")
    score = (
        0.5 * _sat(n, 3)
        + 0.3 * _sat(len(wall) + len(rate), 1)
        + 0.2 * _sat(len(recov), 2)
    )
    chain = (
        f"leader {leader} deposed {n}x -> epoch-fenced recovery rounds "
        f"({len(recov)} recovered) -> round wall inflation"
    )
    return {
        "cause": "leader_crash_storm",
        "score": round(score, 4),
        "peers": [leader],
        "chain": chain,
        "evidence": {
            "leader_deposed_events": n,
            "depositions_by_leader": dict(by_leader),
            "round_wall_alerts": len(wall),
            "commit_rate_alerts": len(rate),
            "rounds_recovered_events": len(recov),
        },
    }


def _rule_straggler(bundle: dict) -> Optional[dict]:
    """Deadline-dropped gradient mass repeatedly attributed to one peer +
    a mass-fraction alert -> a straggler losing its mass at the deadline.
    DEMOTED when hedged recovery is committing that mass anyway
    (``mass_recovered_by_hedge`` events dominating the loss events, or the
    peer itself showing up in the recovered sets): a post-mortem must not
    page on a problem the hedger already fixed."""
    losses = _events_of(bundle, "mass_lost_at_deadline")
    recoveries = _events_of(bundle, "mass_recovered_by_hedge")
    if not losses:
        return None
    dropped = Counter()
    for e in losses:
        for p in (e.get("excluded") or []) + (e.get("aborted") or []):
            dropped[str(p)] += 1
    if not dropped:
        return None
    peer, n = dropped.most_common(1)[0]
    mass = _alerts_of(bundle, "mass_frac_drop")
    slo = _alerts_of(bundle, "slo_burn", key_prefix="mass_committed_frac")
    # A straggler inflates nothing per se — its mass is CUT at the
    # deadline — so wall evidence is not required; quality flags argue
    # AGAINST this rule (that is the byzantine rule's evidence).
    flags = [
        e for e in _events_of(bundle, "peer_quality_flagged")
        if str(e.get("peer_flagged", e.get("peer"))) == peer
    ]
    # Hedge mitigation evidence: rounds where the tail pipeline COMMITTED
    # recovered mass, and specifically this peer's.
    peer_recovered = sum(
        1 for e in recoveries if str(peer) in [str(p) for p in (e.get("recovered") or [])]
    )
    saved = bool(recoveries) and (
        peer_recovered >= n or len(recoveries) >= 2 * len(losses)
    )
    score = (
        0.5 * _sat(n, 3)
        + 0.4 * _sat(len(mass) + len(slo), 1)
        + (-0.3 if flags else 0.1)
        + (-0.4 * _sat(peer_recovered + len(recoveries), 2) if recoveries else 0.0)
    )
    chain = (
        f"peer {peer} dropped at the round deadline {n}x -> "
        f"mass_committed_frac drop ({len(mass)} alert(s))"
    )
    if recoveries:
        chain += (
            f" [hedge_saved_mass: {len(recoveries)} recovered-mass round(s), "
            f"{peer_recovered}x this peer — "
            + ("mitigated, demoted" if saved else "partial mitigation")
            + "]"
        )
    return {
        "cause": "straggler_deadline_drop",
        "score": round(max(score, 0.0), 4),
        "peers": [peer],
        "chain": chain,
        "evidence": {
            "mass_lost_events": len(losses),
            "dropped_by_peer": dict(dropped),
            "mass_frac_alerts": len(mass),
            "slo_burn_alerts": len(slo),
            "hedge_saved_mass": {
                "recovered_mass_events": len(recoveries),
                "peer_recovered_rounds": peer_recovered,
                "mitigated": saved,
            },
        },
    }


def _rule_thin_cross_zone_link(bundle: dict) -> Optional[dict]:
    """Cross-LEVEL wall inflation + mixing stall (+ bandwidth collapse on
    a zone pair) -> the cross-zone links are the bottleneck, not any one
    peer."""
    wall_cross = _alerts_of(bundle, "round_wall_inflation", key_prefix="cross")
    stall = _alerts_of(bundle, "mixing_stall")
    bw = _alerts_of(bundle, "peer_bw_collapse")
    if not wall_cross and not stall:
        return None
    links = sorted({str(a.get("key", "")) for a in bw if a.get("key")})
    score = (
        0.4 * _sat(len(wall_cross), 1)
        + 0.4 * _sat(len(stall), 1)
        + 0.2 * _sat(len(bw), 1)
    )
    chain = (
        (f"bw collapse on {', '.join(links)} -> " if links else "")
        + "level=cross deadline inflation -> cross-zone mixing stall"
    )
    return {
        "cause": "thin_cross_zone_link",
        "score": round(score, 4),
        "peers": links,
        "chain": chain,
        "evidence": {
            "cross_wall_alerts": len(wall_cross),
            "mixing_stall_alerts": len(stall),
            "bw_collapse_alerts": len(bw),
            "links": links,
        },
    }


def _rule_byzantine_contributor(bundle: dict) -> Optional[dict]:
    """Persistent quality flags on one peer (the robust estimators keep
    trimming it) -> a byzantine/garbage contributor."""
    flags = _events_of(bundle, "peer_quality_flagged")
    byz_alerts = _alerts_of(bundle, "byzantine_contributor")
    flagged = Counter(
        str(e.get("peer_flagged") or e.get("peer") or "?") for e in flags
    )
    for a in byz_alerts:
        if a.get("key"):
            flagged[str(a["key"])] += 1
    if not flagged:
        return None
    peer, n = flagged.most_common(1)[0]
    quality = bundle.get("quality") or {}
    qrec = quality.get(peer) or {}
    score = (
        0.5 * _sat(n, 2)
        + 0.3 * _sat(len(byz_alerts), 1)
        + (0.2 if qrec.get("flagged") or qrec.get("score", 1.0) < 0.5 else 0.0)
    )
    chain = (
        f"peer {peer} persistently trimmed by the robust fold -> "
        f"quality flag ({n} flag event(s)/alert(s))"
    )
    return {
        "cause": "byzantine_contributor",
        "score": round(score, 4),
        "peers": [peer],
        "chain": chain,
        "evidence": {
            "flag_events": len(flags),
            "byzantine_alerts": len(byz_alerts),
            "flagged_by_peer": dict(flagged),
            "quality_record": qrec or None,
        },
    }


def _rule_control_plane_outage(bundle: dict) -> Optional[dict]:
    """Beat failure streaks + status staleness -> the control plane, not
    the data plane, is what broke."""
    beats = _alerts_of(bundle, "cp_beat_failures")
    fresh = _alerts_of(bundle, "slo_burn", key_prefix="status_freshness")
    if not beats and not fresh:
        return None
    score = 0.6 * _sat(len(beats), 1) + 0.4 * _sat(len(fresh), 1)
    return {
        "cause": "control_plane_outage",
        "score": round(score, 4),
        "peers": [],
        "chain": "control-plane beat failures -> report staleness",
        "evidence": {
            "beat_failure_alerts": len(beats),
            "freshness_burn_alerts": len(fresh),
        },
    }


def _rule_policy_flap(bundle: dict) -> Optional[dict]:
    """A controller knob OSCILLATING — revisiting a value it just left —
    while wall/commit anomalies fire -> the controller itself is the
    root cause, and the wall/commit symptoms are downstream of it. Ranked
    ABOVE the symptom rules by construction: when a knob demonstrably
    flapped, chasing the straggler/thin-link it manufactured wastes the
    operator's time. A healthy controller (monotone transitions tracking
    a real regime change) scores ~0 here: transitions alone are not
    flapping — only value REVISITS within the window are."""
    changes = _events_of(bundle, "policy_changed")
    if len(changes) < 3:
        return None
    # Group by (peer, knob, key) and count A->B->A-style revisits. The
    # PEER is part of the group: every volunteer runs its own
    # controller, so three vantages each walking a knob MONOTONICALLY
    # through the same values (2->4->8 on three recorders) is a healthy
    # fleet converging, not a flap — only one controller revisiting a
    # value it already left is.
    by_knob: Dict[tuple, List[dict]] = {}
    for e in changes:
        by_knob.setdefault(
            (
                str(e.get("peer") or ""),
                str(e.get("knob")),
                str(e.get("key") or ""),
            ),
            [],
        ).append(e)
    flaps = 0
    worst_knob, worst_n = None, 0
    for knob, evs in by_knob.items():
        # A revisit = returning to a value this controller already LEFT:
        # event i's target appeared as some EARLIER event's old value.
        # The prefix matters — in a monotone walk 2->4->8 the "4" is
        # both a target and (later) an old value, and comparing against
        # the whole from-set would count it; against the prefix it is
        # a plain forward step.
        revisits = sum(
            1
            for i, e in enumerate(evs)
            if str(e.get("to")) in {str(p.get("from")) for p in evs[:i]}
        )
        if revisits > worst_n:
            worst_knob, worst_n = knob, revisits
        flaps += revisits
    if not flaps:
        return None
    wall = _alerts_of(bundle, "round_wall_inflation")
    rate = _alerts_of(bundle, "commit_rate_collapse")
    # Saturates fast and carries a symptom bonus, so a demonstrated
    # oscillation out-ranks the symptom rules it explains.
    score = 0.7 * _sat(flaps, 3) + 0.4 * _sat(len(wall) + len(rate), 1)
    peer, knob_name, key = worst_knob
    label = f"{knob_name}[{key or '-'}]@{peer or '?'}"
    chain = (
        f"controller knob {label} revisited values {worst_n}x "
        f"({len(changes)} transitions) -> unstable policy "
        f"-> wall/commit anomalies"
    )
    return {
        "cause": "policy_flap",
        "score": round(min(score, 1.0), 4),
        "peers": [peer] if peer else [],
        "chain": chain,
        "evidence": {
            "policy_changed_events": len(changes),
            "value_revisits": flaps,
            "worst_knob": label,
            "round_wall_alerts": len(wall),
            "commit_rate_alerts": len(rate),
        },
    }


def _rule_shard_zone_degraded(bundle: dict) -> Optional[dict]:
    """Shard-holder loss explaining its own downstream symptoms: the
    ``shard_lost`` flight events are the root evidence, and the shard
    recovery-latency SLO burn / mass-fraction dips they cause are folded
    in as corroboration rather than surfaced as independent hypotheses.
    Same shape as ``policy_flap``: when a zone demonstrably lost shard
    holders, chasing the latency or mass symptoms separately wastes the
    operator's time, so this ranks ABOVE the symptom rules. Recoveries
    that completed (``shard_recovered``) temper the score — a zone that
    re-shards and refetches within budget is the system working."""
    lost = _events_of(bundle, "shard_lost")
    if not lost:
        return None
    recovered = _events_of(bundle, "shard_recovered")
    failed = _events_of(bundle, "shard_recovery_failed")
    slo = _alerts_of(bundle, "slo_burn", key_prefix="shard_recovery_latency")
    mass = _alerts_of(bundle, "mass_frac_drop")
    by_peer = Counter(str(e.get("holder") or e.get("peer") or "?") for e in lost)
    peers = [p for p, _ in by_peer.most_common(3) if p != "?"]
    symptoms = len(slo) + len(mass)
    score = (
        0.7 * _sat(len(lost), 1)
        + 0.4 * _sat(symptoms, 1)
        + 0.3 * _sat(len(failed), 1)
        - 0.2 * _sat(len(recovered), max(len(lost), 1))
    )
    chain = (
        f"shard holder loss ({len(lost)} shard_lost) -> fenced re-shard + "
        f"hedged refetch ({len(recovered)} recovered, {len(failed)} failed) "
        f"-> recovery-latency burn / mass dip ({symptoms} symptom alerts)"
    )
    return {
        "cause": "shard_zone_degraded",
        "score": round(max(min(score, 1.0), 0.0), 4),
        "peers": peers,
        "chain": chain,
        "evidence": {
            "shard_lost_events": len(lost),
            "shard_recovered_events": len(recovered),
            "shard_recovery_failed_events": len(failed),
            "shard_recovery_latency_alerts": len(slo),
            "mass_frac_drop_alerts": len(mass),
            "losses_by_holder": dict(by_peer),
        },
    }


RULES = (
    _rule_shard_zone_degraded,
    _rule_policy_flap,
    _rule_leader_crash_storm,
    _rule_straggler,
    _rule_thin_cross_zone_link,
    _rule_byzantine_contributor,
    _rule_control_plane_outage,
)


def diagnose(bundle: Dict[str, Any]) -> List[dict]:
    """Rank root-cause hypotheses over an evidence bundle.

    ``bundle`` keys (all optional — rules skip absent planes):
      - ``alerts``: list of alert dicts / alert_raised events (``kind``,
        ``key``, ``severity``; flight-event form with ``alert`` instead of
        ``kind`` is normalized here).
      - ``flight``: peer -> list of flight-recorder events.
      - ``quality``: peer -> {score, rounds, flagged} (health rollup form).

    Returns hypotheses sorted by score (desc); empty when no rule found
    any evidence (a healthy swarm diagnoses to nothing, by design)."""
    # Normalize alert_raised flight events into plain alert dicts.
    alerts = []
    for a in bundle.get("alerts") or []:
        if not isinstance(a, dict):
            continue
        if a.get("kind") == "alert_raised" and a.get("alert"):
            alerts.append({**a, "kind": a["alert"]})
        else:
            alerts.append(a)
    norm = dict(bundle)
    norm["alerts"] = alerts
    out = []
    for rule in RULES:
        try:
            hyp = rule(norm)
        except Exception as e:  # noqa: BLE001 — one rule must not kill the report
            hyp = {
                "cause": rule.__name__, "score": 0.0, "peers": [],
                "chain": f"rule failed: {e}", "evidence": {},
            }
        if hyp is not None and hyp["score"] > 0:
            out.append(hyp)
    out.sort(key=lambda h: (-h["score"], h["cause"]))
    return out


def bundle_from_artifact(artifact: dict, scenario: Optional[str] = None) -> dict:
    """Build a diagnose() bundle from a chaos_soak artifact: alert events
    are harvested from every flight recorder in the (sub)tree, firing
    sets from any embedded watchdog/alerts sections."""
    root = artifact
    if scenario:
        for part in scenario.split("."):
            root = root.get(part) or {}
    alerts: List[dict] = []
    flight: Dict[str, list] = {}
    quality: Dict[str, dict] = {}

    def walk(node: Any) -> None:
        if isinstance(node, dict):
            fr = node.get("flight_recorders")
            if isinstance(fr, dict):
                for pid, events in fr.items():
                    if isinstance(events, list):
                        flight.setdefault(str(pid), []).extend(events)
                        alerts.extend(
                            e for e in events
                            if isinstance(e, dict) and e.get("kind") == "alert_raised"
                        )
            al = node.get("alerts")
            if isinstance(al, dict) and isinstance(al.get("firing"), list):
                alerts.extend(a for a in al["firing"] if isinstance(a, dict))
            q = node.get("quality")
            if isinstance(q, dict):
                for pid, rec in q.items():
                    if isinstance(rec, dict) and "score" in rec:
                        quality[str(pid)] = rec
            for v in node.values():
                walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)

    walk(root)
    return {"alerts": alerts, "flight": flight, "quality": quality}


def render(ranked: List[dict]) -> str:
    if not ranked:
        return "doctor: no anomaly evidence found — swarm looks healthy\n"
    lines = ["doctor: ranked root-cause hypotheses", ""]
    for i, h in enumerate(ranked, 1):
        lines.append(
            f"{i}. {h['cause']}  (score {h['score']:.2f})"
            + (f"  peers: {', '.join(h['peers'])}" if h["peers"] else "")
        )
        lines.append(f"   chain: {h['chain']}")
        ev = ", ".join(f"{k}={v}" for k, v in h["evidence"].items()
                       if not isinstance(v, dict))
        if ev:
            lines.append(f"   evidence: {ev}")
    return "\n".join(lines) + "\n"


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("artifact", nargs="?", default=None,
                    help="chaos_soak artifact JSON to diagnose")
    ap.add_argument("--scenario", default=None,
                    help="dotted path into the artifact (e.g. "
                         "watchdog_campaign.scenarios.straggler)")
    ap.add_argument("--bundle", default=None,
                    help="raw evidence-bundle JSON (diagnose() input)")
    ap.add_argument("--json", action="store_true",
                    help="emit the ranked hypotheses as JSON")
    args = ap.parse_args()
    if args.bundle:
        with open(args.bundle) as f:
            bundle = json.load(f)
    elif args.artifact:
        with open(args.artifact) as f:
            artifact = json.load(f)
        bundle = bundle_from_artifact(artifact, args.scenario)
    else:
        ap.error("pass a chaos artifact or --bundle")
        return
    ranked = diagnose(bundle)
    if args.json:
        print(json.dumps(ranked, indent=2))
    else:
        sys.stdout.write(render(ranked))


if __name__ == "__main__":
    main()
