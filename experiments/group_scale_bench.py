#!/usr/bin/env python
"""Group-scale bench: single-group vs rotating multi-group sync averaging.

Measures, at N in {8, 16, 32, 64} volunteers, per averaging round:

  - per-round wall time (each volunteer's ``average()`` call duration),
  - aggregate committed gradient mass/sec (float32 elements whose
    contribution entered a COMMITTED aggregate, per campaign second).

Arms:

  single — the pre-schedule behavior: one rendezvous key, one group per
           epoch (max_group = N so the whole swarm lands on one leader).
           Per-round wall time grows with N: one leader fans out N begins,
           gathers N contributions, and serves N fetches.
  multi  — the rotating group schedule (GroupSchedule, target size 8):
           ~N/8 groups per round, each on its own leader, re-partitioned
           every rotation. Per-round wall time should stay ~flat in N —
           each group's work is bounded by the TARGET size, not the swarm.

The full campaign is MULTI-PROCESS: volunteers are sharded over worker
subprocesses (``--worker``), all joined to one DHT over real localhost
TCP, with rounds aligned on shared wall-clock rotation windows. The
default-suite smoke (tests/test_multigroup.py) runs the in-process
``run_config`` at small N and fails loudly if multi-group per-round wall
time grows with N.

Artifact: experiments/results/group_scale_bench.json (committed).

Usage:
    python experiments/group_scale_bench.py            # full campaign
    python experiments/group_scale_bench.py --quick    # N in {8,16}, fewer rounds
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import re
import statistics
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from distributedvolunteercomputing_tpu.swarm.averager import SyncAverager  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.matchmaking import GroupSchedule  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.membership import SwarmMembership  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.transport import Transport  # noqa: E402

GROUP_TARGET = 8
TREE_ELEMS = 16_384  # 64 KiB f32 per contribution


async def build_node(
    pid: str,
    *,
    boot=None,
    arm: str = "multi",
    n_total: int = 8,
    schedule: GroupSchedule | None = None,
    gather_timeout: float = 12.0,
    join_timeout: float = 8.0,
):
    t = Transport()
    # Long maintenance interval: 64 nodes refreshing buckets every 15s is
    # pure localhost noise at bench scale.
    dht = DHTNode(t, maintenance_interval=120.0)
    await dht.start(bootstrap=[boot] if boot else None)
    mem = SwarmMembership(dht, pid, ttl=30.0)
    await mem.join()
    avg = SyncAverager(
        t, dht, mem,
        min_group=2,
        # single: the whole swarm must fit one group (the bottleneck being
        # measured); multi: cap well above target so hash-arc size skew
        # never truncates a group.
        max_group=n_total if arm == "single" else GROUP_TARGET * 3,
        join_timeout=join_timeout, gather_timeout=gather_timeout,
        group_schedule=schedule if arm == "multi" else None,
    )
    return {"pid": pid, "t": t, "dht": dht, "mem": mem, "avg": avg}


async def teardown(nodes) -> None:
    for nd in nodes:
        try:
            await nd["mem"].leave()
        except Exception:
            pass
        try:
            await nd["dht"].stop()
        except Exception:
            pass
        try:
            await nd["t"].close()
        except Exception:
            pass


def _tree(i: int, elems: int):
    return {"w": np.full((elems,), float(i % 7), np.float32)}


async def _timed_round(nd, i, r, elems, timeout):
    t0 = time.monotonic()
    try:
        res = await asyncio.wait_for(
            nd["avg"].average(_tree(i, elems), round_no=r), timeout=timeout
        )
    except Exception:
        res = None
    return time.monotonic() - t0, res is not None


async def run_config(
    n: int,
    arm: str,
    rounds: int = 5,
    tree_elems: int = TREE_ELEMS,
    group_target: int = GROUP_TARGET,
    gather_timeout: float = 12.0,
) -> dict:
    """In-process form of one (N, arm) cell: N volunteers in one event
    loop over real localhost TCP, ``rounds`` synchronized rounds, the
    rotation pinned per round (no wall-clock dependence — this is what
    the default-suite smoke runs). Returns per-round wall times and the
    committed-mass rate."""
    rot_cell = {"rot": 0}
    nodes = []
    boot = None
    try:
        for i in range(n):
            sched = GroupSchedule(
                target_size=group_target, rotation_s=1000.0,
                clock=lambda: rot_cell["rot"] * 1000.0 + 0.5,
            )
            nd = await build_node(
                f"b{i:03d}", boot=boot, arm=arm, n_total=n, schedule=sched,
                gather_timeout=gather_timeout,
            )
            if boot is None:
                boot = nd["t"].addr
            nodes.append(nd)
        dts, committed = [], 0
        t_start = time.monotonic()
        for r in range(rounds):
            rot_cell["rot"] = r + 1
            results = await asyncio.gather(
                *(
                    _timed_round(
                        nd, i, r, tree_elems,
                        timeout=3.0 * gather_timeout + 30.0,
                    )
                    for i, nd in enumerate(nodes)
                )
            )
            dts.extend(dt for dt, _ in results)
            committed += sum(1 for _, ok in results if ok)
        wall = time.monotonic() - t_start
        groups_seen = sorted(
            {
                gid
                for nd in nodes
                for gid in nd["avg"].group_stats().get("recent", {})
            }
        ) if arm == "multi" else []
    finally:
        await teardown(nodes)
    return _summarize(n, arm, rounds, tree_elems, dts, committed, wall, groups_seen)


def _summarize(n, arm, rounds, tree_elems, dts, committed, wall, groups_seen):
    dts = sorted(dts)
    return {
        "n": n,
        "arm": arm,
        "rounds": rounds,
        "tree_elems": tree_elems,
        "node_rounds": rounds * n,
        "committed_node_rounds": committed,
        "commit_frac": round(committed / max(rounds * n, 1), 4),
        "round_s_median": round(statistics.median(dts), 3) if dts else None,
        "round_s_mean": round(statistics.mean(dts), 3) if dts else None,
        "round_s_p90": round(dts[max(0, int(0.9 * len(dts)) - 1)], 3) if dts else None,
        "campaign_wall_s": round(wall, 2),
        # Committed gradient mass: every float32 element whose contribution
        # entered a committed aggregate, per campaign second.
        "committed_mass_per_s": round(committed * tree_elems / max(wall, 1e-9), 1),
        "groups_seen": groups_seen,
    }


# -- multi-process campaign -------------------------------------------------


async def _worker_main(args) -> None:
    """One worker's shard of the swarm. Rounds align on shared wall-clock
    rotation windows (t0 + r*period), so volunteers across processes
    rendezvous without any cross-process barrier."""
    schedule_kw = dict(
        target_size=args.group_size, rotation_s=args.period, clock=time.time
    )
    boot = None
    if args.boot:
        host, _, port = args.boot.rpartition(":")
        boot = (host, int(port))
    nodes = []
    try:
        for k in range(args.n_nodes):
            i = args.node_offset + k
            nd = await build_node(
                f"b{i:03d}", boot=boot, arm=args.arm, n_total=args.n_total,
                schedule=GroupSchedule(**schedule_kw),
                gather_timeout=args.gather_timeout,
                join_timeout=min(args.period * 0.8, 10.0),
            )
            if boot is None:
                boot = nd["t"].addr
                print(f"BOOT {boot[0]}:{boot[1]}", flush=True)
            nodes.append(nd)
        print("WORKER_READY", flush=True)
        dts, committed = [], 0
        cpu0 = sum(os.times()[:2])
        for r in range(args.rounds):
            target = args.t0 + r * args.period
            delay = target - time.time()
            if delay > 0:
                await asyncio.sleep(delay)
            results = await asyncio.gather(
                *(
                    _timed_round(
                        nd, args.node_offset + k, r, args.tree_elems,
                        # A round must never bleed into the window after
                        # next: the rendezvous key has moved on by then.
                        timeout=2.0 * args.period,
                    )
                    for k, nd in enumerate(nodes)
                )
            )
            dts.extend(dt for dt, _ in results)
            committed += sum(1 for _, ok in results if ok)
        wall = args.rounds * args.period
        groups_seen = sorted(
            {
                gid
                for nd in nodes
                for gid in nd["avg"].group_stats().get("recent", {})
            }
        ) if args.arm == "multi" else []
        print(
            "RESULT "
            + json.dumps(
                {
                    "dts": [round(d, 4) for d in dts],
                    "committed": committed,
                    "wall_s": wall,
                    "groups_seen": groups_seen,
                    # This worker's process CPU over the round campaign:
                    # the host-saturation evidence the verdict needs (on a
                    # few-core sandbox, wall time past saturation measures
                    # the HOST, not the protocol).
                    "cpu_s": round(sum(os.times()[:2]) - cpu0, 3),
                    "n_nodes": args.n_nodes,
                }
            ),
            flush=True,
        )
    finally:
        await teardown(nodes)


def _spawn_worker(extra):
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker", *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def _read_until(proc, pattern, timeout):
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        m = re.match(pattern, line)
        if m:
            return m
    raise RuntimeError(f"worker did not print {pattern!r}")


def run_cell_multiprocess(n, arm, rounds, period, n_workers, args) -> dict:
    """One (N, arm) cell, volunteers sharded over worker subprocesses."""
    n_workers = min(n_workers, max(1, n // 4))
    shard = n // n_workers
    t0 = (int(time.time()) // int(period) + 1) * int(period) + 2 * period
    common = [
        "--arm", arm, "--n-total", str(n), "--rounds", str(rounds),
        "--period", str(period), "--t0", str(t0),
        "--group-size", str(args.group_target),
        "--tree-elems", str(args.tree_elems),
        "--gather-timeout", str(args.gather_timeout),
    ]
    workers = []
    try:
        w0 = _spawn_worker(
            common + ["--n-nodes", str(shard), "--node-offset", "0"]
        )
        workers.append(w0)
        boot = _read_until(w0, r"BOOT (\S+)", 60).group(1)
        for w in range(1, n_workers):
            off = w * shard
            k = shard if w < n_workers - 1 else n - off
            workers.append(
                _spawn_worker(
                    common
                    + ["--n-nodes", str(k), "--node-offset", str(off),
                       "--boot", boot]
                )
            )
        results = []
        # Worst case is every round running to its 2x-period timeout (the
        # single-group arm at large N legitimately does), not one period.
        budget = t0 - time.time() + rounds * 2 * period + 2 * period + 90
        for w in workers:
            out, _ = w.communicate(timeout=budget)
            for line in out.splitlines():
                if line.startswith("RESULT "):
                    results.append(json.loads(line[len("RESULT "):]))
                    break
            else:
                raise RuntimeError(f"worker produced no RESULT:\n{out[-3000:]}")
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
    dts = [d for r in results for d in r["dts"]]
    committed = sum(r["committed"] for r in results)
    wall = max(r["wall_s"] for r in results)
    groups = sorted({g for r in results for g in r["groups_seen"]})
    out = _summarize(n, arm, rounds, args.tree_elems, dts, committed, wall, groups)
    out["workers"] = n_workers
    total_cpu = sum(r.get("cpu_s", 0.0) for r in results)
    out["cpu_s_total"] = round(total_cpu, 2)
    # Per-node-round CPU: the saturation-independent "does per-volunteer
    # work grow with the swarm" number. Worker skew: the single arm's
    # leader-holding worker burns far more than its peers (the O(N)
    # leader work the multi arm removes).
    out["cpu_s_per_node_round"] = round(total_cpu / max(rounds * n, 1), 4)
    shares = [
        r["cpu_s"] / max(r.get("n_nodes", 1), 1)
        for r in results
        if "cpu_s" in r
    ]
    out["cpu_worker_skew"] = round(
        max(shares) / max(min(shares), 1e-9), 2
    ) if shares else None
    # CPU demand one round places on the host. Rounds are bursts at
    # rotation-window starts (the window itself is mostly idle), so
    # comparing this against cores x the measured round wall says whether
    # the wall was CPU-limited: demand >= ~0.85 x cores x wall means the
    # burst kept every core busy for the whole measured duration — the
    # wall is a scheduler-queue reading, not protocol latency.
    out["cpu_demand_per_round_s"] = round(total_cpu / max(rounds, 1), 3)
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ns", type=int, nargs="+", default=[8, 16, 32, 64])
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--group-target", type=int, default=GROUP_TARGET)
    ap.add_argument("--tree-elems", type=int, default=TREE_ELEMS)
    ap.add_argument("--gather-timeout", type=float, default=12.0)
    ap.add_argument("--period", type=float, default=None,
                    help="rotation/round window seconds (default: sized per N)")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=os.path.join(
        REPO, "experiments", "results", "group_scale_bench.json"))
    # worker-mode flags
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--arm", default="multi", help=argparse.SUPPRESS)
    ap.add_argument("--n-nodes", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--node-offset", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--n-total", type=int, default=8, help=argparse.SUPPRESS)
    ap.add_argument("--boot", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--t0", type=float, default=0.0, help=argparse.SUPPRESS)
    ap.add_argument("--group-size", type=int, default=GROUP_TARGET,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.worker:
        args.period = args.period or 10.0
        asyncio.run(_worker_main(args))
        return

    if args.quick:
        args.ns = [8, 16]
        args.rounds = 3

    cells = []
    for n in args.ns:
        for arm in ("single", "multi"):
            # The window must cover the slowest expected round: single-group
            # leader work grows with N (that growth is the measurement).
            period = args.period or (
                10.0 if arm == "multi" else min(10.0 + 0.15 * n, 22.0)
            )
            print(f"[cell] n={n} arm={arm} rounds={args.rounds} "
                  f"period={period}s", flush=True)
            cell = run_cell_multiprocess(
                n, arm, args.rounds, period, args.workers, args
            )
            print(f"[cell] -> median {cell['round_s_median']}s, "
                  f"commit_frac {cell['commit_frac']}, "
                  f"mass/s {cell['committed_mass_per_s']:.0f}, "
                  f"groups {len(cell['groups_seen'])}", flush=True)
            cells.append(cell)

    def cell(n, arm):
        return next(c for c in cells if c["n"] == n and c["arm"] == arm)

    verdict = {}
    ns = sorted(set(args.ns))
    if 16 in ns and 64 in ns:
        m16, m64 = cell(16, "multi"), cell(64, "multi")
        s16, s64 = cell(16, "single"), cell(64, "single")
        flat = m64["round_s_median"] / max(m16["round_s_median"], 1e-9)
        growth = s64["round_s_median"] / max(s16["round_s_median"], 1e-9)
        # Saturation diagnosis: past ~85% host CPU, wall time measures the
        # scheduler's queue, not the protocol — on a 2-core sandbox 64
        # Python volunteers are CPU-bound however cheap a round is. The
        # saturation-independent claims: per-node-round CPU stays flat
        # while N quadruples (per-volunteer work does not grow with the
        # swarm), committed mass/s scales with N (throughput is no longer
        # capped by one leader), and at equal N / equal host load the
        # multi arm beats single outright.
        cores = os.cpu_count() or 1
        cpu_bound = m64["cpu_demand_per_round_s"] >= (
            0.85 * cores * m64["round_s_median"]
        )
        cpu_flat = m64["cpu_s_per_node_round"] / max(
            m16["cpu_s_per_node_round"], 1e-9
        )
        mass_scale = m64["committed_mass_per_s"] / max(
            m16["committed_mass_per_s"], 1e-9
        )
        verdict = {
            "multi_round_ratio_64_over_16": round(flat, 3),
            "single_round_ratio_64_over_16": round(growth, 3),
            # Acceptance: per-round wall time flat (+-20%) N=16 -> N=64
            # under the multi-group schedule — binding wherever the host
            # can actually run 64 volunteers (host_cpu_frac < 0.85).
            "pass_multi_flat_pm20pct": flat <= 1.2,
            "single_grows_with_n": growth > 1.2,
            "host_cpu_bound_at_64": cpu_bound,
            "multi_cpu_demand_per_round_s_64": m64["cpu_demand_per_round_s"],
            "multi_cpu_capacity_per_round_s_64": round(
                cores * m64["round_s_median"], 3
            ),
            "multi_cpu_per_node_round_ratio_64_over_16": round(cpu_flat, 3),
            "multi_mass_scale_64_over_16": round(mass_scale, 3),
            "multi_beats_single_wall_at_64": (
                m64["round_s_median"] <= s64["round_s_median"]
            ),
            # Flat per-volunteer CPU (+-20%) + near-linear mass scaling +
            # outright win at equal load: the same claim, measured in
            # units host saturation cannot distort.
            "pass_multi_flat_cpu_pm20pct": cpu_flat <= 1.2,
            "pass_multi_mass_scales": mass_scale >= 3.0,
        }
        verdict["pass"] = bool(
            verdict["pass_multi_flat_pm20pct"]
            or (
                cpu_bound
                and verdict["pass_multi_flat_cpu_pm20pct"]
                and verdict["pass_multi_mass_scales"]
                and verdict["multi_beats_single_wall_at_64"]
            )
        )
    result = {
        "group_target": args.group_target,
        "tree_elems": args.tree_elems,
        "host_cores": os.cpu_count(),
        "cells": cells,
        "verdict": verdict,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(f"[done] artifact -> {args.out}")
    print(json.dumps(verdict, indent=2))
    if verdict:
        sys.exit(0 if verdict["pass"] else 1)


if __name__ == "__main__":
    main()
