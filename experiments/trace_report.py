#!/usr/bin/env python
"""Trace report: per-round critical-path breakdown from a live swarm.

Runs three REAL multi-process scenarios (volunteers sharded over worker
subprocesses, one DHT over localhost TCP — the group_scale_bench layout),
collects every volunteer's round spans via the ``telemetry.trace`` RPC,
stitches them by trace id (the round key: matchmaking epoch), and emits a
per-round breakdown of where the wall time went:

  committed  — 4 volunteers, plain sync rounds. Leader vantage:
               join -> arm -> encode -> fold -> commit must sum to ~the
               round's wall time (the acceptance bar: coverage >= the
               verdict threshold).
  recovered  — the leader (a0, sorts first, isolated in its own worker)
               SIGKILLs itself mid-stream (DVC_CHAOS_LEADER_DIE_PHASE);
               survivors depose it and commit via a fenced recovery round.
               Member vantage: join -> encode -> wire -> fetch -> recover,
               plus the survivors' flight-recorder events
               (leader_deposed / round_recovered) attached as post-mortem.
  cross_zone — 6 volunteers in 2 zones under the hierarchical schedule
               (cross_zone_every_k=2): intra- and cross-zone rounds appear
               in the same report, labeled by the round span's level attr.

Artifact: experiments/results/trace_report.json (committed). This is the
observability the benches previously asserted blind: when a bench says
"commit latency is X", the report says which phase it lives in.

Usage:
    python experiments/trace_report.py            # full campaign
    python experiments/trace_report.py --quick    # fewer rounds
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from distributedvolunteercomputing_tpu.swarm import telemetry as telemetry_mod  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.averager import SyncAverager  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.matchmaking import GroupSchedule  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.membership import SwarmMembership  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.transport import Transport  # noqa: E402

TREE_ELEMS = 300_000  # ~1.2 MB f32 per contribution: chunked wire, fast rounds
RESULTS = os.path.join(REPO, "experiments", "results")

# Leader-vantage phases, protocol order: sequential by construction in
# SyncAverager.average, so their sum bounds the round wall from below
# ("health" = the post-commit training-health bookkeeping: quality/mass/
# sketch, swarm/health.py — members are already fetching by then, but it
# is inside the leader's round wall and must count toward coverage).
LEADER_PHASES = ("join", "arm", "encode", "fold", "commit", "health")
# Member vantage (the recovered scenario reports from a survivor).
MEMBER_PHASES = ("join", "encode", "wire", "fetch", "recover")


def _tree(i: int):
    return {"w": np.full((TREE_ELEMS,), float(i % 5), np.float32)}


# -- worker half -------------------------------------------------------------


async def _worker_main(args) -> None:
    pids = args.pids.split(",")
    boot = tuple(args.boot.split(":"))
    boot = (boot[0], int(boot[1]))
    vols = []
    for pid in pids:
        t = Transport()
        dht = DHTNode(t, maintenance_interval=120.0)
        await dht.start(bootstrap=[boot])
        extra = {"zone": args.zone} if args.zone else None
        mem = SwarmMembership(dht, pid, ttl=30.0, extra_info=extra)
        await mem.join()
        schedule = None
        if args.group_size:
            schedule = GroupSchedule(
                target_size=args.group_size,
                rotation_s=args.rotation_s,
                min_size=2,
                cross_zone_every_k=args.cross_zone_every_k,
            )
        avg = SyncAverager(
            t, dht, mem,
            min_group=2, max_group=args.max_group,
            join_timeout=8.0, gather_timeout=12.0,
            group_schedule=schedule,
        )
        avg.telemetry.register_rpcs(t)
        vols.append({"pid": pid, "t": t, "dht": dht, "mem": mem, "avg": avg})
    print(
        "WORKER_ADDRS "
        + json.dumps({v["pid"]: list(v["t"].addr) for v in vols}),
        flush=True,
    )
    # Synchronized start: the driver sends "GO <start_at>" on stdin once
    # EVERY worker has advertised (jax import time varies by tens of
    # seconds under sandbox load, so a spawn-time timestamp would skew
    # round rendezvous past the join timeout).
    line = await asyncio.to_thread(sys.stdin.readline)
    try:
        start_at = float(line.split()[1])
    except (IndexError, ValueError):
        start_at = time.time()
    delay = start_at - time.time()
    if delay > 0:
        await asyncio.sleep(delay)
    for r in range(args.rounds):
        res = await asyncio.gather(
            *(
                asyncio.wait_for(
                    v["avg"].average(_tree(i), round_no=r), timeout=60.0
                )
                for i, v in enumerate(vols)
            ),
            return_exceptions=True,
        )
        ok = sum(1 for x in res if x is not None and not isinstance(x, BaseException))
        print(f"WORKER_ROUND {r} ok={ok}/{len(vols)}", flush=True)
        if args.round_gap_s:
            await asyncio.sleep(args.round_gap_s)
    print("WORKER_DONE", flush=True)
    # Stay alive for the driver's telemetry.trace scrapes; the driver
    # SIGTERMs us when it has what it needs.
    try:
        await asyncio.sleep(120.0)
    finally:
        for v in vols:
            try:
                await v["mem"].leave()
            except Exception:
                pass
            try:
                await v["dht"].stop()
            except Exception:
                pass
            await v["t"].close()


# -- driver half -------------------------------------------------------------


def _spawn_worker(extra, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if env_extra:
        env.update(env_extra)
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker"] + extra,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, env=env, cwd=REPO,
    )


def _read_until(proc, tag, timeout=120.0):
    """Read worker stdout lines until one starts with ``tag`` (returned
    without the tag) or the process dies/timeout expires (returns None)."""
    t0 = time.time()
    while time.time() - t0 < timeout:
        line = proc.stdout.readline()
        if not line:
            return None
        line = line.strip()
        if line.startswith(tag):
            return line[len(tag):].strip()
    return None


async def _collect_spans(addrs, timeout=8.0):
    """Dial every (live) volunteer's telemetry.trace / flight / scrape
    RPCs; dead volunteers (the killed leader) simply contribute nothing.
    The scrape's health view carries each peer's bounded post-round
    sketch history — matched across peers by trace id, that is the
    per-round live mixing-error column."""
    t = Transport()
    spans, flights = [], {}
    sketches_by_trace = {}
    try:
        for pid, addr in addrs.items():
            addr = (addr[0], int(addr[1]))
            try:
                ret, _ = await t.call(
                    addr, telemetry_mod.TRACE_METHOD, {}, b"",
                    timeout=timeout, connect_timeout=2.0,
                )
                spans.extend(ret.get("spans") or [])
                ret, _ = await t.call(
                    addr, telemetry_mod.FLIGHT_METHOD, {}, b"",
                    timeout=timeout, connect_timeout=2.0,
                )
                flights[pid] = ret.get("events") or []
                ret, _ = await t.call(
                    addr, telemetry_mod.SCRAPE_METHOD, {}, b"",
                    timeout=timeout, connect_timeout=2.0,
                )
                health = ret.get("health") or {}
                for rec in health.get("sketch_history") or []:
                    if rec.get("trace") and rec.get("v"):
                        sketches_by_trace.setdefault(rec["trace"], []).append(
                            rec["v"]
                        )
            except Exception as e:  # noqa: BLE001 — a dead volunteer is expected here
                print(f"  (no telemetry from {pid}: {type(e).__name__})")
    finally:
        await t.close()
    return spans, flights, sketches_by_trace


def _phase_durs(spans, phases):
    """name -> summed duration over this vantage's spans (fold.push and
    repeated attempts merge by sum — the phase's total residency)."""
    out = {}
    for s in spans:
        if s["name"] in phases and s.get("dur_s") is not None:
            out[s["name"]] = round(out.get(s["name"], 0.0) + s["dur_s"], 6)
    return out


def _breakdown(all_spans, sketches_by_trace=None):
    """Stitch spans by trace id and emit one record per round that has a
    root 'round' span; coverage = sum(vantage phases)/wall from the
    vantage (leader when one committed, else the first member) whose
    phases are sequential by construction. Health columns ride each row:
    ``mass_committed_frac`` from the leader's fold span and
    ``mix_err_sketch`` — the relative dispersion of the peers' post-round
    sketches for THIS trace (swarm/health.py) — so critical-path and
    training-health read from one artifact."""
    from distributedvolunteercomputing_tpu.swarm import health as health_mod

    by_trace = {}
    for s in all_spans:
        by_trace.setdefault(s["trace"], []).append(s)
    rounds = []
    for trace, spans in by_trace.items():
        roots = [s for s in spans if s["name"] == "round"]
        if not roots:
            continue
        leader_roots = [
            s for s in roots if (s.get("attrs") or {}).get("role") == "leader"
        ]
        root = leader_roots[0] if leader_roots else roots[0]
        attrs = root.get("attrs") or {}
        vantage_peer = root["peer"]
        vantage = "leader" if leader_roots else "member"
        mine = [s for s in spans if s["peer"] == vantage_peer]
        phases = _phase_durs(
            mine, LEADER_PHASES if vantage == "leader" else MEMBER_PHASES
        )
        wall = root["dur_s"] or 0.0
        covered = sum(phases.values())
        recovered = any(s["name"] == "recover" for s in spans)
        mass_frac = next(
            (
                (s.get("attrs") or {}).get("mass_frac")
                for s in spans
                if s["name"] == "fold"
                and (s.get("attrs") or {}).get("mass_frac") is not None
            ),
            None,
        )
        mix_err = None
        sketches = (sketches_by_trace or {}).get(trace)
        if sketches and len(sketches) >= 2:
            d = health_mod.sketch_dispersion(
                [np.asarray(v, np.float64) for v in sketches]
            )
            mix_err = d["rel"] if d else None
        rounds.append({
            "trace": trace,
            "key": attrs.get("key"),
            "level": attrs.get("level", "flat"),
            "ok": bool(attrs.get("ok")),
            "recovered": recovered,
            "vantage": vantage,
            "vantage_peer": vantage_peer,
            "n_peers_traced": len({s["peer"] for s in spans}),
            "wall_s": round(wall, 6),
            "phases_s": phases,
            "coverage": round(covered / wall, 4) if wall > 0 else None,
            "mass_committed_frac": mass_frac,
            "mix_err_sketch": mix_err,
            "members": {
                "wire_mean_s": _mean(
                    [s["dur_s"] for s in spans if s["name"] == "wire"]
                ),
                "fetch_mean_s": _mean(
                    [s["dur_s"] for s in spans if s["name"] == "fetch"]
                ),
            },
        })
    rounds.sort(key=lambda r: r["trace"])
    return rounds


def _mean(xs):
    xs = [x for x in xs if x is not None]
    return round(sum(xs) / len(xs), 6) if xs else None


async def _run_scenario(name, workers, rounds, expect_addrs, scrape_grace=2.0):
    """Spawn the driver-side bootstrap DHT + the worker fleet, wait for the
    rounds, scrape spans + flight recorders, tear everything down."""
    boot_t = Transport()
    boot_dht = DHTNode(boot_t)
    await boot_dht.start(bootstrap=None)
    boot = f"{boot_t.addr[0]}:{boot_t.addr[1]}"
    procs = []
    addrs = {}
    try:
        for spec in workers:
            extra = [
                "--pids", ",".join(spec["pids"]),
                "--boot", boot,
                "--rounds", str(rounds),
                "--zone", spec.get("zone", ""),
                "--group-size", str(spec.get("group_size", 0)),
                "--rotation-s", str(spec.get("rotation_s", 3.0)),
                "--cross-zone-every-k", str(spec.get("cross_zone_every_k", 0)),
                "--max-group", str(spec.get("max_group", 16)),
                "--round-gap-s", str(spec.get("round_gap_s", 0.0)),
            ]
            procs.append(
                (_spawn_worker(extra, spec.get("env")), spec)
            )
        # Blocking pipe reads ride worker threads: the driver's own loop
        # must stay free to serve the bootstrap DHT the workers join.
        got_all = await asyncio.gather(
            *(
                asyncio.to_thread(_read_until, proc, "WORKER_ADDRS", 90.0)
                for proc, _ in procs
            )
        )
        for (proc, spec), got in zip(procs, got_all):
            if got is None:
                if spec.get("may_die"):
                    continue
                raise RuntimeError(f"{name}: worker {spec['pids']} never came up")
            addrs.update({p: a for p, a in json.loads(got).items()})
        missing = expect_addrs - set(addrs)
        if missing:
            raise RuntimeError(f"{name}: volunteers never advertised: {missing}")
        start_at = time.time() + 3.0  # membership/announce settle margin
        for proc, _ in procs:
            try:
                proc.stdin.write(f"GO {start_at}\n")
                proc.stdin.flush()
            except Exception:
                pass
        # Wait for round completion on workers that are expected to survive.
        done_all = await asyncio.gather(
            *(
                asyncio.to_thread(_read_until, proc, "WORKER_DONE", 240.0)
                for proc, spec in procs
                if not spec.get("may_die")
            )
        )
        for (proc, spec), done in zip(
            [(p, s) for p, s in procs if not s.get("may_die")], done_all
        ):
            if done is None:
                raise RuntimeError(f"{name}: worker {spec['pids']} died mid-campaign")
        await asyncio.sleep(scrape_grace)  # let trailing spans land
        spans, flights, sketches = await _collect_spans(addrs)
    finally:
        for proc, _ in procs:
            try:
                proc.send_signal(signal.SIGTERM)
            except Exception:
                pass
        for proc, _ in procs:
            try:
                await asyncio.to_thread(proc.wait, 10.0)
            except Exception:
                proc.kill()
        await boot_dht.stop()
        await boot_t.close()
    return spans, flights, sketches


async def campaign(args):
    rounds = 2 if args.quick else 4
    # schema v2: per-round health columns (mass_committed_frac from the
    # leader's fold span, mix_err_sketch from cross-peer sketch matching).
    out = {"schema_version": 2, "tree_elems": TREE_ELEMS, "scenarios": {}}

    # -- committed: plain sync rounds, leader-vantage critical path --------
    print("[committed] 4 volunteers / 2 workers ...")
    spans, _, sketches = await _run_scenario(
        "committed",
        [
            {"pids": ["v0", "v1"]},
            {"pids": ["v2", "v3"]},
        ],
        rounds,
        expect_addrs={"v0", "v1", "v2", "v3"},
    )
    recs = [r for r in _breakdown(spans, sketches) if r["ok"]]
    lead = [r for r in recs if r["vantage"] == "leader"]
    out["scenarios"]["committed"] = {
        "rounds": recs,
        "committed_rounds": len(lead),
        "coverage_min": min((r["coverage"] for r in lead), default=None),
        "phase_means_s": {
            p: _mean([r["phases_s"].get(p) for r in lead]) for p in LEADER_PHASES
        },
        "mass_committed_frac_min": min(
            (r["mass_committed_frac"] for r in lead
             if r["mass_committed_frac"] is not None),
            default=None,
        ),
        "mix_err_sketch_mean": _mean([r["mix_err_sketch"] for r in recs]),
    }
    print(f"[committed] {len(lead)} leader-vantage rounds, coverage_min="
          f"{out['scenarios']['committed']['coverage_min']}, "
          f"mix_err_sketch_mean="
          f"{out['scenarios']['committed']['mix_err_sketch_mean']}")

    # -- recovered: leader SIGKILL mid-stream, survivors' vantage ----------
    print("[recovered] leader a0 dies mid_stream ...")
    spans, flights, sketches = await _run_scenario(
        "recovered",
        [
            {
                "pids": ["a0"], "may_die": True,
                "env": {"DVC_CHAOS_LEADER_DIE_PHASE": "mid_stream"},
            },
            {"pids": ["v1", "v2", "v3"]},
        ],
        1,
        expect_addrs={"v1", "v2", "v3"},
    )
    recs = _breakdown(spans, sketches)
    recovered = [r for r in recs if r["recovered"] and r["ok"]]
    out["scenarios"]["recovered"] = {
        "rounds": recs,
        "recovered_rounds": len(recovered),
        "flight_events": {
            pid: [
                {k: e[k] for k in ("t", "kind") if k in e}
                | {
                    k: e[k]
                    for k in ("leader", "successor", "gen", "reason")
                    if k in e
                }
                for e in evs
                if e["kind"] in (
                    "leader_deposed", "round_recovered", "fence_rejected",
                    "recovery_failed",
                )
            ]
            for pid, evs in flights.items()
        },
    }
    print(f"[recovered] {len(recovered)} rounds committed via recovery")

    # -- cross_zone: hierarchical schedule, intra + cross rounds -----------
    print("[cross_zone] 6 volunteers / 2 zones, cross_zone_every_k=2 ...")
    zone_spec = {
        "group_size": 3, "rotation_s": 3.0, "cross_zone_every_k": 2,
        "max_group": 9, "round_gap_s": 1.0,
    }
    spans, _, sketches = await _run_scenario(
        "cross_zone",
        [
            dict(zone_spec, pids=["z0a", "z0b", "z0c"], zone="dc-a"),
            dict(zone_spec, pids=["z1a", "z1b", "z1c"], zone="dc-b"),
        ],
        max(rounds, 4),
        expect_addrs={"z0a", "z0b", "z0c", "z1a", "z1b", "z1c"},
        scrape_grace=3.0,
    )
    recs = [r for r in _breakdown(spans, sketches) if r["ok"]]
    levels = sorted({r["level"] for r in recs})
    out["scenarios"]["cross_zone"] = {
        "rounds": recs,
        "levels_seen": levels,
        "per_level_wall_mean_s": {
            lv: _mean([r["wall_s"] for r in recs if r["level"] == lv])
            for lv in levels
        },
        # The live-mixing column, per hierarchy level: intra rounds only
        # converge within a group; the cross rounds are where the
        # cross-zone dispersion moves (the health rollup's across_zones
        # signal — chaos_soak --health runs the full convergence A/B).
        "per_level_mix_err_sketch_mean": {
            lv: _mean([r["mix_err_sketch"] for r in recs if r["level"] == lv])
            for lv in levels
        },
    }
    print(f"[cross_zone] {len(recs)} committed rounds, levels={levels}")

    # -- verdict -----------------------------------------------------------
    committed = out["scenarios"]["committed"]
    cov = committed["coverage_min"]
    out["verdict"] = {
        # Leader-vantage phases are sequential by construction, so their
        # sum must account for (nearly) the whole round wall; the slack is
        # scheduler gaps between awaits on a loaded box.
        "pass_committed_critical_path": bool(
            committed["committed_rounds"] >= 1 and cov is not None and cov >= 0.8
        ),
        "pass_recovered_round_traced": bool(
            out["scenarios"]["recovered"]["recovered_rounds"] >= 1
        ),
        "pass_cross_zone_levels": (
            "cross" in out["scenarios"]["cross_zone"]["levels_seen"]
            and "intra" in out["scenarios"]["cross_zone"]["levels_seen"]
        ),
    }
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=os.path.join(RESULTS, "trace_report.json"))
    # worker mode
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--pids", default="")
    ap.add_argument("--boot", default="")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--zone", default="")
    ap.add_argument("--group-size", type=int, default=0)
    ap.add_argument("--rotation-s", type=float, default=3.0)
    ap.add_argument("--cross-zone-every-k", type=int, default=0)
    ap.add_argument("--max-group", type=int, default=16)
    ap.add_argument("--round-gap-s", type=float, default=0.0)
    args = ap.parse_args()
    if args.worker:
        asyncio.run(_worker_main(args))
        return
    result = asyncio.run(campaign(args))
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=2)
    print(json.dumps(result["verdict"], indent=2))
    print(f"wrote {args.out}")
    sys.exit(0 if all(result["verdict"].values()) else 1)


if __name__ == "__main__":
    main()
