#!/usr/bin/env python
"""Outer-optimizer convergence comparison (DiLoCo-style Nesterov vs plain
averaging) at a fixed round cadence and WAN byte budget.

Two identical 2-volunteer sync swarms on the gpt2 proxy (the hardest proxy
in the matrix), --average-every 15 over 90 steps — 6 WAN rounds each, same
bytes — differing ONLY in the outer step. At the same communication budget,
the outer momentum should reach a lower loss (convergence-per-round is the
claim; samples/sec is unaffected by construction since the outer step is a
host-side O(params) transform per round).

Run:  python experiments/outer_opt.py
Results: experiments/results/outer_opt.jsonl (one row per arm).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from run_matrix import RESULTS, TINY_GPT2, record, run_swarm  # noqa: E402

TIMEOUTS = ["--join-timeout", "25", "--gather-timeout", "25"]


def arm(tag: str, extra: list) -> dict:
    base = ["--model", "gpt2_small", *TINY_GPT2, "--averaging", "sync",
            "--average-every", "15", "--steps", "90", "--batch-size", "16",
            "--lr", "0.003", *TIMEOUTS, *extra]
    rows = run_swarm(f"outer_opt/{tag}", [
        (f"{tag}{i}", base + ["--seed", str(i)]) for i in range(2)
    ])
    return record(f"outer_opt_{tag}", rows)


def main() -> None:
    results = {
        "plain": arm("plain", []),
        "nesterov": arm("nesterov", [
            "--outer-optimizer", "nesterov",
            "--outer-lr", "0.7", "--outer-momentum", "0.9",
        ]),
    }
    out = os.path.join(RESULTS, "outer_opt.jsonl")
    with open(out, "w") as fh:
        for tag, agg in results.items():
            fh.write(json.dumps({"arm": tag, **agg}) + "\n")
    delta = results["plain"]["final_loss_mean"] - results["nesterov"]["final_loss_mean"]
    print(f"outer_opt: plain {results['plain']['final_loss_mean']} vs "
          f"nesterov {results['nesterov']['final_loss_mean']} "
          f"(delta {delta:+.4f}; positive = outer wins)")


if __name__ == "__main__":
    main()
