#!/usr/bin/env python
"""Outer-optimizer convergence comparison (DiLoCo-style Nesterov vs plain
averaging) at a fixed round cadence and WAN byte budget.

Two identical 2-volunteer sync swarms on the gpt2 proxy (the hardest proxy
in the matrix), --average-every 15 over 90 steps — 6 WAN rounds each, same
bytes — differing ONLY in the outer step. At the same communication budget,
the outer momentum should reach a lower loss (convergence-per-round is the
claim; samples/sec is unaffected by construction since the outer step is a
host-side O(params) transform per round).

Run:  python experiments/outer_opt.py
Results: experiments/results/outer_opt.jsonl (one row per arm).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from run_matrix import RESULTS, TINY_GPT2, record, run_swarm  # noqa: E402

TIMEOUTS = ["--join-timeout", "25", "--gather-timeout", "25"]


NESTEROV = ["--outer-optimizer", "nesterov",
            "--outer-lr", "0.7", "--outer-momentum", "0.9"]


def arm(tag: str, averaging: list, extra: list) -> dict:
    base = ["--model", "gpt2_small", *TINY_GPT2, *averaging,
            "--average-every", "15", "--steps", "90", "--batch-size", "16",
            "--lr", "0.003", *TIMEOUTS, *extra]
    rows = run_swarm(f"outer_opt/{tag}", [
        (f"{tag}{i}", base + ["--seed", str(i)]) for i in range(2)
    ])
    return record(f"outer_opt_{tag}", rows)


def main() -> None:
    sync = ["--averaging", "sync"]
    # Byzantine pairs the outer step with robust aggregation (config-5's
    # mode); 2 honest peers, trimmed_mean degrades to the mean at n=2 —
    # the point here is composition, the robustness e2e lives in tests.
    byz = ["--averaging", "byzantine", "--method", "trimmed_mean",
           "--min-group", "2"]
    results = {
        "plain": arm("plain", sync, []),
        "nesterov": arm("nesterov", sync, NESTEROV),
        "byz_plain": arm("byz_plain", byz, []),
        "byz_nesterov": arm("byz_nesterov", byz, NESTEROV),
    }
    out = os.path.join(RESULTS, "outer_opt.jsonl")
    with open(out, "w") as fh:
        for tag, agg in results.items():
            fh.write(json.dumps({"arm": tag, **agg}) + "\n")
    for pair in (("plain", "nesterov"), ("byz_plain", "byz_nesterov")):
        delta = results[pair[0]]["final_loss_mean"] - results[pair[1]]["final_loss_mean"]
        print(f"outer_opt: {pair[0]} {results[pair[0]]['final_loss_mean']} vs "
              f"{pair[1]} {results[pair[1]]['final_loss_mean']} "
              f"(delta {delta:+.4f}; positive = outer wins)")


if __name__ == "__main__":
    main()
