#!/usr/bin/env python
"""Shard bench: cross-zone wire bytes per committed round, replicated vs
zone-sharded, at K in {1, 2, 4}.

The sharded swarm's claim (ROADMAP / ISSUE 20 tentpole): partition the
parameter tree into K zone-local shards — one holder per shard per zone —
and a cross-zone rotation averages only YOUR OWN shard with the peer
zones' holders of the same shard. Every volunteer's WAN bill per
committed round is then ~1/K of the replicated swarm's, because the
payload it pushes and pulls is its 1/K slice instead of the full tree.

Arms (one per K; K=1 IS the replicated baseline — no shard tags, full
tree on the wire):

  K=1  — replicated: every volunteer averages the full tree cross-zone.
  K=2  — two shards per zone: each volunteer moves its half.
  K=4  — four shards per zone: each volunteer moves its quarter.

Every config runs 2 zones x K volunteers with a pinned-rotation schedule
where EVERY rotation is a cross-zone one (cross_zone_every_k=1) — the
worst case for the WAN, which is exactly where sharding pays. Cross-zone
bytes are measured from the transport's per-peer counters joined against
the membership zone map (Averager.zone_traffic) — the same live
accounting coord.status rolls up, not a model — and normalized per
committed volunteer-round so configs with different swarm sizes compare
fairly.

The two-zone WAN is modeled with ChaosTransport.set_link (latency +
serialization bandwidth on every cross-zone edge), so round wall time
also reflects the thinner payloads.

Acceptance (asserted loudly by tests/test_sharding.py's bench smoke):
bytes/commit must fall >= 1.5x from K=1 to K=2 and again from K=2 to
K=4 — i.e. ~linearly in K.

Artifact: experiments/results/shard_bench.json (committed).

Usage:
    python experiments/shard_bench.py            # full campaign
    python experiments/shard_bench.py --quick    # smaller tree, 2 rounds
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from distributedvolunteercomputing_tpu.swarm.averager import SyncAverager  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.chaos import ChaosTransport  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.matchmaking import GroupSchedule  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.membership import SwarmMembership  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.sharding import shard_ranges  # noqa: E402

TREE_ELEMS = 131_072         # 512 KiB f32 full tree
ROUNDS = 4
ZONES = ("dc", "home")
# Cross-zone WAN edge (~64 Mbit/s, 30 ms); intra-zone is localhost.
INTER_ZONE_LAT_S = 0.03
INTER_ZONE_BW_BPS = 8e6


async def _teardown(nodes):
    for nd in nodes:
        try:
            await nd["mem"].leave()
        except Exception:
            pass
        try:
            await nd["dht"].stop()
        except Exception:
            pass
        try:
            await nd["t"].close()
        except Exception:
            pass
    ChaosTransport._partitions.clear()
    ChaosTransport._links.clear()


def _xz_sent(nodes):
    return sum(
        nd["avg"].zone_traffic()["cross_zone_bytes_sent"] for nd in nodes
    )


async def run_config(
    k: int,
    *,
    tree_elems: int = TREE_ELEMS,
    rounds: int = ROUNDS,
    links: bool = False,
    inter_lat: float = INTER_ZONE_LAT_S,
    inter_bw: float = INTER_ZONE_BW_BPS,
) -> dict:
    """One K cell, in-process: 2 zones x K volunteers. K=1 replicates the
    full tree; K>1 tags each volunteer with its shard so cross rotations
    rendezvous same-shard holders across zones, each averaging only its
    ``shard_ranges(tree_elems, k)`` slice. Reports cross-zone bytes per
    committed volunteer-round (the per-volunteer WAN bill)."""
    assert k >= 1
    rot_cell = {"rot": 0}
    ranges = shard_ranges(tree_elems, k)
    nodes = []
    boot = None
    try:
        for zi, zone in enumerate(ZONES):
            for s in range(k):
                t = ChaosTransport()
                dht = DHTNode(t, maintenance_interval=120.0)
                await dht.start(bootstrap=[boot] if boot else None)
                boot = boot or t.addr
                extra = {"zone": zone}
                if k > 1:
                    extra["shard"] = s
                pid = f"k{k}z{zi}s{s}"
                mem = SwarmMembership(dht, pid, ttl=30.0, extra_info=extra)
                await mem.join()
                avg = SyncAverager(
                    t, dht, mem,
                    min_group=2, max_group=6,
                    join_timeout=6.0, gather_timeout=10.0,
                    group_schedule=GroupSchedule(
                        target_size=2, rotation_s=1000.0, min_size=2,
                        cross_zone_every_k=1,  # every rotation crosses
                        clock=lambda: rot_cell["rot"] * 1000.0 + 0.5,
                    ),
                )
                nodes.append({
                    "pid": pid, "zone": zone, "shard": s if k > 1 else None,
                    "t": t, "dht": dht, "mem": mem, "avg": avg,
                })
        if links:
            for i, a in enumerate(nodes):
                for b in nodes[i + 1:]:
                    if a["zone"] != b["zone"]:
                        a["t"].set_link(
                            a["t"].addr, b["t"].addr, inter_lat, inter_bw
                        )
        for nd in nodes:
            await nd["mem"].alive_peers()  # prime snapshots + zone maps
        xz0 = _xz_sent(nodes)
        dts, committed = [], 0
        t_start = time.monotonic()

        def payload(nd):
            # Replicated: the full tree. Sharded: your slice only — the
            # whole point of the exercise.
            if nd["shard"] is None:
                elems = tree_elems
            else:
                lo, hi = ranges[nd["shard"]]
                elems = hi - lo
            return {"w": np.full((elems,), 1.0, np.float32)}

        async def one(nd, r):
            t0 = time.monotonic()
            try:
                res = await asyncio.wait_for(
                    nd["avg"].average(payload(nd), round_no=r), timeout=40.0
                )
            except Exception:
                res = None
            return time.monotonic() - t0, res

        for r in range(1, rounds + 1):
            rot_cell["rot"] = r
            results = await asyncio.gather(*(one(nd, r) for nd in nodes))
            for dt, res in results:
                dts.append(dt)
                if res is not None:
                    committed += 1
        wall = time.monotonic() - t_start
        xz_bytes = _xz_sent(nodes) - xz0
        shard_ids = sorted(
            {
                nd["avg"].group_stats().get("group_id", "")
                for nd in nodes
                if nd["shard"] is not None
            }
        )
    finally:
        await _teardown(nodes)
    dts.sort()
    node_rounds = rounds * len(nodes)
    return {
        "k": k, "zones": len(ZONES), "volunteers": len(ZONES) * k,
        "tree_elems": tree_elems, "tree_bytes": tree_elems * 4,
        "slice_bytes": (ranges[0][1] - ranges[0][0]) * 4,
        "rounds": rounds, "links_modeled": links,
        "node_rounds": node_rounds,
        "committed_node_rounds": committed,
        "commit_frac": round(committed / max(node_rounds, 1), 4),
        "round_s_median": round(statistics.median(dts), 4) if dts else None,
        "campaign_wall_s": round(wall, 2),
        "cross_zone_bytes": xz_bytes,
        "xz_bytes_per_commit": round(xz_bytes / max(committed, 1), 1),
        "sharded_group_ids": shard_ids,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--tree-elems", type=int, default=TREE_ELEMS)
    ap.add_argument("--rounds", type=int, default=ROUNDS)
    ap.add_argument("--links", action="store_true",
                    help="model the thin cross-zone WAN edges")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=os.path.join(
        REPO, "experiments", "results", "shard_bench.json"))
    args = ap.parse_args()
    if args.quick:
        args.tree_elems, args.rounds = 32_768, 2

    cells = {}
    for k in (1, 2, 4):
        print(f"[cell] k={k}", flush=True)
        cells[str(k)] = asyncio.run(run_config(
            k, tree_elems=args.tree_elems, rounds=args.rounds,
            links=args.links,
        ))
        c = cells[str(k)]
        print(f"[cell] -> commit_frac {c['commit_frac']}, "
              f"xz B/commit {c['xz_bytes_per_commit']}, "
              f"round median {c['round_s_median']}s", flush=True)

    b1 = cells["1"]["xz_bytes_per_commit"]
    b2 = cells["2"]["xz_bytes_per_commit"]
    b4 = cells["4"]["xz_bytes_per_commit"]
    verdict = {
        "xz_bytes_per_commit_k1": b1,
        "xz_bytes_per_commit_k2": b2,
        "xz_bytes_per_commit_k4": b4,
        "ratio_k1_over_k2": round(b1 / max(b2, 1.0), 2),
        "ratio_k2_over_k4": round(b2 / max(b4, 1.0), 2),
        "ratio_k1_over_k4": round(b1 / max(b4, 1.0), 2),
        # Acceptance: ~linear in K — each doubling of K must keep paying
        # >= 1.5x on the per-volunteer cross-zone wire bill.
        "pass_k2_beats_replicated": b1 / max(b2, 1.0) >= 1.5,
        "pass_k4_beats_k2": b2 / max(b4, 1.0) >= 1.5,
        "pass_all_commit": all(
            c["commit_frac"] >= 0.7 for c in cells.values()
        ),
    }
    verdict["pass"] = bool(
        verdict["pass_k2_beats_replicated"]
        and verdict["pass_k4_beats_k2"]
        and verdict["pass_all_commit"]
    )
    result = {
        "inter_zone_lat_s": INTER_ZONE_LAT_S if args.links else None,
        "inter_zone_bw_bps": INTER_ZONE_BW_BPS if args.links else None,
        "host_cores": os.cpu_count(),
        "cells": cells,
        "verdict": verdict,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(f"[done] artifact -> {args.out}")
    print(json.dumps(verdict, indent=2))
    sys.exit(0 if verdict["pass"] else 1)


if __name__ == "__main__":
    main()
