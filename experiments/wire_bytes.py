#!/usr/bin/env python
"""Measure WAN bytes per averaging round for every wire codec.

2-volunteer grads-mode sync swarm (GradientAverager semantics: one round
per step) for each of f32 / bf16 / q8 / topk, using the transport's own
byte counters (volunteer summary wan_bytes_*), NOT an estimate. Writes
experiments/results/wire_bytes.jsonl and prints a table; BASELINE.md cites
the resulting bytes-per-round ratios.

Run: python experiments/wire_bytes.py [--steps 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from run_matrix import RESULTS, record, run_swarm  # noqa: E402

# Proxy model with enough params (~52k) that payload dominates frame
# overhead; d_hidden=64 -> mnist mlp 28*28*64 + 64*10 weights.
MODEL = ["--model", "mnist_mlp", "--model-override", "d_hidden=64"]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    args = ap.parse_args()

    rows_by_wire = {}
    for wire in ("f32", "bf16", "q8", "topk", "powersgd", "sign"):
        common = MODEL + [
            "--averaging", "sync", "--average-what", "grads", "--wire", wire,
            "--steps", str(args.steps), "--batch-size", "8",
            "--join-timeout", "20", "--gather-timeout", "20",
        ]
        if wire == "topk":
            common += ["--topk-frac", "0.01"]
        rows = run_swarm(
            f"wire_{wire}",
            [(f"{wire}-a", common + ["--seed", "0"]),
             (f"{wire}-b", common + ["--seed", "1"])],
            timeout=240,
        )
        summaries = [s for _, s, _ in rows if s]
        rounds = sum(s["rounds_ok"] for s in summaries) or 1
        sent = sum(s["wan_bytes_sent"] for s in summaries)
        rows_by_wire[wire] = {
            "bytes_sent_total": sent,
            "rounds_ok_total": rounds,
            "bytes_per_round_per_volunteer": sent / rounds,
            "final_loss_mean": sum(s["final_loss"] for s in summaries) / len(summaries),
        }
        record(f"wire_{wire}", rows, extra=rows_by_wire[wire])
        print(f"[wire_{wire}] {json.dumps(rows_by_wire[wire])}", flush=True)

    base = rows_by_wire["f32"]["bytes_per_round_per_volunteer"]
    table = {
        w: {
            **d,
            "vs_f32": round(d["bytes_per_round_per_volunteer"] / base, 4),
        }
        for w, d in rows_by_wire.items()
    }
    out = os.path.join(RESULTS, "wire_bytes.jsonl")
    with open(out, "w") as fh:
        for w, d in table.items():
            fh.write(json.dumps({"wire": w, **d}) + "\n")
    print(json.dumps(table, indent=2))


if __name__ == "__main__":
    main()
