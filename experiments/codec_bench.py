"""Swarm codec bench: host-only vs on-mesh encode/decode + fold throughput.

The committed artifact behind the ISSUE-6 on-mesh data-path rework
(``experiments/results/codec_bench.json``): measures the chip-side half of
an averaging round — the work PRs 2–3 left on single-threaded host numpy —
for the two backends ``ops.mesh_codec`` selects between:

- ``host``  — the pre-rework path: ``native.f32_to_bf16`` per contribution,
  then per-peer ``bf16_to_f32`` decode + ``weighted_sum_inplace`` axpy
  (mean) or per-tile decode + ``ops.robust`` window estimators
  (trimmed_mean) — exactly what the streaming aggregator runs when the
  codec is inactive.
- ``mesh``  — ``MeshCodec``: one fused device pass per op (bitcast + widen
  + fold), the mean path through ``MeshMeanFolder``'s batched
  scatter-add over chunk-grained tiles, the window path through the
  sorting-network estimator with the bf16 decode fused in.

Phases, reported separately and combined (the acceptance line is the
COMBINED encode+fold throughput at 64 MB contributions):

- ``encode``: one volunteer's f32 -> bf16 wire pack of its contribution;
- ``fold``:   the leader consuming all n peers' bf16 wire bytes into the
  round aggregate (decode + mean axpy / window estimator per tile).

Tiles are the transport's wire chunks (1 MiB), matching agg_stream.

Usage:
    python experiments/codec_bench.py          # full grid + artifact
    python experiments/codec_bench.py --quick  # small sanity run

The default tier-1 suite runs a small-shape smoke of this harness
(tests/test_mesh_codec.py::TestCodecBenchSmoke) that FAILS LOUDLY when the
on-mesh arm regresses to (or below) host throughput — the same
regression-guard pattern as the transport and aggregation bench smokes.
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributedvolunteercomputing_tpu.utils.jaxenv import pin_platform  # noqa: E402

RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")
CHUNK_BYTES = 1 << 20  # transport default: tiles == wire chunks


def _best_of(fn, repeats: int):
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def bench_host(bits: np.ndarray, weights: np.ndarray, method: str, kw: dict,
               chunk_bytes: int, repeats: int) -> dict:
    """The host data path: native codec + numpy/native folds, tile-grained
    exactly as the streaming aggregator runs them."""
    from distributedvolunteercomputing_tpu import native
    from distributedvolunteercomputing_tpu.ops import robust

    n_peers, n_elems = bits.shape
    tile = chunk_bytes // 2  # bf16 elements per wire chunk
    src = native.bf16_to_f32(bits[0])  # a representative f32 contribution

    encode_s = _best_of(lambda: native.f32_to_bf16(src), repeats)

    def fold():
        if method == "mean":
            acc = np.zeros(n_elems, np.float32)
            total_w = float(weights.sum())
            for p in range(n_peers):
                for e0 in range(0, n_elems, tile):
                    x = native.bf16_to_f32(bits[p, e0 : e0 + tile])
                    native.weighted_sum_inplace(
                        acc[e0 : e0 + x.size], x, float(weights[p]) / total_w
                    )
            return acc
        out = np.empty(n_elems, np.float32)
        for e0 in range(0, n_elems, tile):
            win = np.stack(
                [native.bf16_to_f32(bits[p, e0 : e0 + tile]) for p in range(n_peers)]
            )
            out[e0 : e0 + win.shape[1]] = robust.aggregate(win, method, **kw)
        return out

    fold_s = _best_of(fold, repeats)
    return {"encode_s": round(encode_s, 6), "fold_s": round(fold_s, 6),
            "result": fold()}


def bench_mesh(bits: np.ndarray, weights: np.ndarray, method: str, kw: dict,
               chunk_bytes: int, repeats: int, codec) -> dict:
    """The on-mesh data path through MeshCodec / MeshMeanFolder."""
    from distributedvolunteercomputing_tpu import native

    n_peers, n_elems = bits.shape
    tile = chunk_bytes // 2
    n_tiles = -(-n_elems // tile)
    src = native.bf16_to_f32(bits[0])

    encode_s = _best_of(lambda: codec.encode_bf16(src), repeats)

    def fold():
        if method == "mean":
            folder = codec.mean_folder(n_elems, tile, n_tiles, "bf16")
            assert folder is not None, "mesh folder unavailable (degraded codec?)"
            total_w = float(weights.sum())
            for p in range(n_peers):
                raw = bits[p]
                for t in range(n_tiles):
                    e0 = t * tile
                    if folder.add(t, float(weights[p]) / total_w,
                                  raw[e0 : e0 + tile].tobytes()):
                        folder.flush()
            return folder.result()
        # PRODUCTION shape for the window path: chunks decode on the host
        # as they arrive (agg_stream fills f32 windows), the fold runs on
        # device — measure exactly that, not the fused decode+fold below.
        out = np.empty(n_elems, np.float32)
        for e0 in range(0, n_elems, tile):
            win = np.stack(
                [native.bf16_to_f32(bits[p, e0 : e0 + tile])
                 for p in range(n_peers)]
            )
            out[e0 : e0 + win.shape[1]] = codec.aggregate(win, method, **kw)
        return out

    fold_s = _best_of(fold, repeats)
    row = {"encode_s": round(encode_s, 6), "fold_s": round(fold_s, 6),
           "result": fold()}
    if method != "mean":
        # The FUSED variant (aggregate_bits: bf16 decode folded into the
        # device estimator) — what a bits-resident window pipeline would
        # get; reported separately so the headline stays the shipped path.
        def fold_fused():
            out = np.empty(n_elems, np.float32)
            for e0 in range(0, n_elems, tile):
                win = np.ascontiguousarray(bits[:, e0 : e0 + tile])
                out[e0 : e0 + win.shape[1]] = codec.aggregate_bits(
                    win, method, **kw
                )
            return out

        row["fold_fused_s"] = round(_best_of(fold_fused, repeats), 6)
    return row


def run_config(n_peers: int, payload_mb: float, method: str,
               chunk_bytes: int = CHUNK_BYTES, repeats: int = 2,
               codec=None) -> dict:
    from distributedvolunteercomputing_tpu import native
    from distributedvolunteercomputing_tpu.ops import mesh_codec

    if codec is None:
        codec = mesh_codec.MeshCodec(backend="mesh")
    n_elems = int(payload_mb * (1 << 20)) // 4
    rng = np.random.default_rng(0)
    weights = rng.uniform(0.5, 2.0, n_peers)
    # Contributions materialize directly as bf16 wire bits: the bench
    # measures the codec+fold path, not the rng.
    bits = np.stack(
        [
            native.f32_to_bf16(rng.standard_normal(n_elems).astype(np.float32))
            for _ in range(n_peers)
        ]
    )
    kw = {"trim": max(1, n_peers // 4)} if method == "trimmed_mean" else {}
    host = bench_host(bits, weights, method, kw, chunk_bytes, repeats)
    mesh = bench_mesh(bits, weights, method, kw, chunk_bytes, repeats, codec)
    # Equivalence is part of the bench contract: a fast wrong answer banks
    # nothing. bf16 decode is exact; fold order differs -> f32 ulp-scale.
    np.testing.assert_allclose(
        mesh.pop("result"), host.pop("result"), rtol=2e-5, atol=1e-5
    )
    payload_bytes = n_elems * 4
    host_s = host["encode_s"] + host["fold_s"]
    mesh_s = mesh["encode_s"] + mesh["fold_s"]
    return {
        "n_peers": n_peers,
        "payload_mb": payload_mb,
        "method": method,
        "host": host,
        "mesh": mesh,
        # throughput over the CONTRIBUTION bytes each phase touches:
        # encode crosses one payload, fold crosses n.
        "host_mb_s": round((payload_mb * (1 + n_peers)) / max(host_s, 1e-9), 1),
        "mesh_mb_s": round((payload_mb * (1 + n_peers)) / max(mesh_s, 1e-9), 1),
        "ratios": {
            "encode": round(host["encode_s"] / max(mesh["encode_s"], 1e-9), 2),
            "fold": round(host["fold_s"] / max(mesh["fold_s"], 1e-9), 2),
            "encode_fold": round(host_s / max(mesh_s, 1e-9), 2),
        },
        "payload_bytes": payload_bytes,
    }


def _feed_mean_folder(folder, bits, weights, tile, n_tiles):
    total_w = float(weights.sum())
    for p in range(bits.shape[0]):
        raw = bits[p]
        for t in range(n_tiles):
            e0 = t * tile
            if folder.add(t, float(weights[p]) / total_w,
                          raw[e0 : e0 + tile].tobytes()):
                folder.flush()
    return folder.result()


def _assert_ring_interpret_equivalence(mesh, n_devices: int) -> None:
    """Correctness half of the fused-arm contract: the PALLAS ring kernel
    (interpret mode — the exact grid schedule and DMA descriptors the
    silicon path compiles) must match the host fold bit-for-bit at a small
    shape. The throughput arms below run the xla lowering; this pins the
    kernel itself inside the same bench run."""
    from distributedvolunteercomputing_tpu import native
    from distributedvolunteercomputing_tpu.ops import mesh_codec

    codec = mesh_codec.MeshCodec(
        mesh=mesh, backend="mesh", pallas="interpret", collective="ring"
    )
    tile, n_tiles = 256 * n_devices, 4
    n_elems = tile * n_tiles
    folder = codec.mean_folder(n_elems, tile, n_tiles, "bf16")
    assert folder.kind == "ring", f"ring folder not selected: {folder.kind}"
    # Pin the pallas interpret lowering regardless of DVC_RING_LOWER.
    folder._lower_cfg, folder._eager = "interpret", False
    rng = np.random.default_rng(3)
    weights = rng.uniform(0.2, 1.0, 3)
    bits = np.stack(
        [native.f32_to_bf16(rng.standard_normal(n_elems).astype(np.float32))
         for _ in range(3)]
    )
    got = _feed_mean_folder(folder, bits, weights, tile, n_tiles)
    ref = np.zeros(n_elems, np.float32)
    total_w = float(weights.sum())
    for p in range(3):
        native.weighted_sum_inplace(
            ref, native.bf16_to_f32(bits[p]), float(weights[p]) / total_w
        )
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-5)
    assert not codec.degraded, f"interpret ring degraded: {codec.degrade_reason}"


def run_fused_config(n_peers: int, payload_mb: float,
                     chunk_bytes: int = CHUNK_BYTES, repeats: int = 2) -> dict:
    """The fused-pipeline arm (ISSUE 18): ring collective folder
    (ops/mesh_collective.py) vs the PR 5 staged folder, BOTH on the same
    multi-device mesh — the mean fold is the only phase that differs, so
    the ratio isolates the fused reduce pipeline. Returns None on a
    1-device mesh, where the ring degenerates to the plain fold and the
    comparison measures nothing."""
    import jax

    from distributedvolunteercomputing_tpu import native
    from distributedvolunteercomputing_tpu.ops import mesh_codec
    from distributedvolunteercomputing_tpu.parallel.mesh import make_mesh

    n_devices = len(jax.devices())
    tile = chunk_bytes // 2
    if n_devices < 2 or tile % n_devices:
        return None
    mesh = make_mesh(dp=n_devices)
    _assert_ring_interpret_equivalence(mesh, n_devices)

    n_elems = int(payload_mb * (1 << 20)) // 4
    n_tiles = -(-n_elems // tile)
    rng = np.random.default_rng(0)
    weights = rng.uniform(0.5, 2.0, n_peers)
    bits = np.stack(
        [native.f32_to_bf16(rng.standard_normal(n_elems).astype(np.float32))
         for _ in range(n_peers)]
    )
    staged = mesh_codec.MeshCodec(mesh=mesh, backend="mesh", collective="off")
    ring = mesh_codec.MeshCodec(mesh=mesh, backend="mesh", collective="ring")

    def fold(codec):
        folder = codec.mean_folder(n_elems, tile, n_tiles, "bf16")
        return _feed_mean_folder(folder, bits, weights, tile, n_tiles)

    # Warm both jit caches AND check xla-lowering equivalence in-bench.
    ref = fold(staged)
    np.testing.assert_allclose(fold(ring), ref, rtol=2e-5, atol=1e-5)
    src = native.bf16_to_f32(bits[0])
    encode_s = _best_of(lambda: ring.encode_bf16(src), repeats)
    staged_s = _best_of(lambda: fold(staged), repeats)
    ring_s = _best_of(lambda: fold(ring), repeats)
    ring_folder = ring.mean_folder(n_elems, tile, n_tiles, "bf16")
    row = {
        "n_peers": n_peers,
        "payload_mb": payload_mb,
        "devices": n_devices,
        "ring_lower": ring_folder._lower_cfg,
        "encode_s": round(encode_s, 6),
        "staged_fold_s": round(staged_s, 6),
        "ring_fold_s": round(ring_s, 6),
        "staged_mb_s": round(payload_mb * n_peers / max(staged_s, 1e-9), 1),
        "ring_mb_s": round(payload_mb * n_peers / max(ring_s, 1e-9), 1),
        "ratios": {
            "fold": round(staged_s / max(ring_s, 1e-9), 2),
            "encode_fold": round(
                (encode_s + staged_s) / max(encode_s + ring_s, 1e-9), 2
            ),
        },
    }
    return row


def run_bench(peers=(8, 16), payloads_mb=(8, 64), methods=("mean", "trimmed_mean"),
              chunk_bytes: int = CHUNK_BYTES, repeats: int = 2) -> dict:
    import jax

    from distributedvolunteercomputing_tpu import native
    from distributedvolunteercomputing_tpu.ops import mesh_codec

    codec = mesh_codec.MeshCodec(backend="mesh")
    rows = []
    for method in methods:
        for n_peers in peers:
            for mb in payloads_mb:
                row = run_config(n_peers, mb, method, chunk_bytes, repeats, codec)
                rows.append(row)
                print(
                    f"{method:12s} n={n_peers:2d} {mb:3g}MB  "
                    f"encode {row['host']['encode_s']*1e3:7.1f}ms -> "
                    f"{row['mesh']['encode_s']*1e3:7.1f}ms "
                    f"({row['ratios']['encode']}x)  "
                    f"fold {row['host']['fold_s']*1e3:8.1f}ms -> "
                    f"{row['mesh']['fold_s']*1e3:8.1f}ms "
                    f"({row['ratios']['fold']}x)  "
                    f"combined {row['ratios']['encode_fold']}x",
                    flush=True,
                )
    fused_rows = []
    for mb in payloads_mb:
        row = run_fused_config(max(peers), mb, chunk_bytes, repeats)
        if row is None:
            print("fused arm skipped: 1-device mesh (ring degenerates to "
                  "the plain fold)", flush=True)
            break
        fused_rows.append(row)
        marker = "" if row["ratios"]["fold"] >= 1.0 else \
            "  ** BELOW STAGED FLOOR **"
        print(
            f"fused        n={row['n_peers']:2d} {mb:3g}MB  "
            f"fold {row['staged_fold_s']*1e3:8.1f}ms -> "
            f"{row['ring_fold_s']*1e3:8.1f}ms "
            f"({row['ratios']['fold']}x vs staged, "
            f"{row['devices']} devices, {row['ring_lower']} lowering)"
            f"{marker}",
            flush=True,
        )
    return {
        "bench": "swarm_codec_host_vs_mesh",
        "host": platform.node(),
        "python": platform.python_version(),
        "unix_time": round(time.time(), 1),
        "chunk_bytes": chunk_bytes,
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
        "native_available": native.available(),
        "codec": codec.stats(),
        "rows": rows,
        # staged-vs-ring on the same mesh; [] when 1-device made the
        # comparison meaningless (never silently measured-as-tied).
        "fused_rows": fused_rows,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true", help="small sanity run")
    ap.add_argument("--out", default=os.path.join(RESULTS, "codec_bench.json"))
    ap.add_argument("--devices", type=int, default=8,
                    help="force at least N host (CPU) devices so the fused "
                         "ring arm has a real mesh to reduce over; ignored "
                         "on platforms with native multi-chip (0 = off)")
    args = ap.parse_args()
    # The bench compares backends, not platforms: run the mesh arm on
    # whatever jax platform is active (CPU in the sandbox, the TPU slice
    # on hardware) and say which in the artifact.
    pin_platform(None, min_host_devices=args.devices or None)
    from distributedvolunteercomputing_tpu import native

    native.ensure_built()
    kw = {}
    if args.quick:
        kw = dict(peers=(4,), payloads_mb=(2,), chunk_bytes=1 << 18, repeats=2)
    result = run_bench(**kw)
    os.makedirs(RESULTS, exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=2)
        fh.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
