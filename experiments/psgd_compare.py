#!/usr/bin/env python
"""Convergence-per-WAN-byte: dense vs top-k vs PowerSGD at transformer scale.

The mnist wire-bytes table (BASELINE.md) measures BYTES well but its loss
column saturates too fast to rank codecs on convergence. This experiment
reuses the topk_warmup harness shape — 2-volunteer grads-mode sync swarms on
the gpt2 proxy, 30 rounds per volunteer — and adds the PowerSGD arms:

  dense   --wire f32
  topk    --wire topk --topk-frac 0.01
  psgd4   --wire powersgd --psgd-rank 4
  psgd8   --wire powersgd --psgd-rank 8

Records final loss AND total WAN bytes per arm. The claim under test
(BASELINE.md codec table, measured on mnist): PowerSGD sits between q8 and
topk on bytes while tracking dense convergence far closer than topk.

Run: python experiments/psgd_compare.py
Results: experiments/results/psgd_compare.jsonl
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from run_matrix import RESULTS, record, run_swarm  # noqa: E402

MODEL = ["--model", "gpt2_small",
         "--model-override", "vocab=256", "--model-override", "max_len=32",
         "--model-override", "d_model=64", "--model-override", "n_heads=2",
         "--model-override", "n_layers=2", "--model-override", "d_ff=128"]
STEPS = int(os.environ.get("DVC_PSGD_STEPS", "30"))  # grads: one round/step


def arm(tag: str, extra: list) -> dict:
    common = MODEL + [
        "--averaging", "sync", "--average-what", "grads",
        "--steps", str(STEPS), "--batch-size", "16", "--lr", "0.003",
        "--join-timeout", "20", "--gather-timeout", "20", *extra,
    ]
    rows = run_swarm(
        f"psgd_compare/{tag}",
        [(f"{tag}-a", common + ["--seed", "0"]),
         (f"{tag}-b", common + ["--seed", "1"])],
        timeout=420,
    )
    summaries = [s for _, s, _ in rows if s]
    agg = record(f"psgd_compare_{tag}", rows)
    agg["wan_bytes_total"] = sum(s["wan_bytes_sent"] for s in summaries)
    return agg


def main() -> None:
    results = {
        "dense": arm("dense", ["--wire", "f32"]),
        "topk": arm("topk", ["--wire", "topk", "--topk-frac", "0.01"]),
        "psgd4": arm("psgd4", ["--wire", "powersgd", "--psgd-rank", "4"]),
        "psgd8": arm("psgd8", ["--wire", "powersgd", "--psgd-rank", "8"]),
        # r5: the 1-bit EF-signSGD rung at transformer scale (the mnist
        # table saturates; this is where codec convergence actually ranks).
        "sign": arm("sign", ["--wire", "sign"]),
    }
    out = os.path.join(RESULTS, "psgd_compare.jsonl")
    with open(out, "w") as fh:
        for tag, agg in results.items():
            fh.write(json.dumps({"arm": tag, **agg}) + "\n")
    for tag, agg in results.items():
        print(f"psgd_compare: {tag:6s} loss {agg['final_loss_mean']:.4f} "
              f"bytes {agg['wan_bytes_total'] / 1e6:.2f}MB "
              f"rounds {agg['rounds_ok_total']}")


if __name__ == "__main__":
    main()
