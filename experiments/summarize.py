#!/usr/bin/env python
"""Render the committed experiment artifacts into one markdown digest.

Reads ONLY what is on disk under experiments/results/ (the same artifacts
BASELINE.md cites) and prints a compact markdown summary — a cross-check
that the prose tables and the jsonl evidence agree, and a quick orientation
for reviewers. Missing artifacts are listed rather than fabricated.

Run: python experiments/summarize.py
"""

from __future__ import annotations

import json
import os

RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")


def _load_json(name):
    try:
        with open(os.path.join(RESULTS, name)) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _load_jsonl(name):
    try:
        with open(os.path.join(RESULTS, name)) as fh:
            return [json.loads(l) for l in fh if l.strip()]
    except (OSError, ValueError):
        return None


def main() -> int:
    missing = []

    print("# Experiment digest (generated from experiments/results/)\n")

    summary = _load_json("summary.json")
    if summary:
        print("## Config matrix (localhost swarms, real entrypoints)\n")
        print("| config | volunteers | finished | rounds ok/skip | crossed | time-to-target |")
        print("|---|---|---|---|---|---|")
        for key in sorted(summary):
            row = summary[key]
            if not isinstance(row, dict):
                continue
            if "volunteers" not in row:  # nested (config8) or derived rows
                for sub, r in row.items():
                    if isinstance(r, dict) and "volunteers" in r:
                        print(f"| {key}/{sub} | {r['volunteers']} | {r.get('finished')} "
                              f"| {r.get('rounds_ok_total')}/{r.get('rounds_skipped_total')} "
                              f"| {r.get('crossed', '—')} | {r.get('time_to_target_s_mean', '—')} |")
                continue
            print(f"| {key} | {row['volunteers']} | {row.get('finished')} "
                  f"| {row.get('rounds_ok_total', '—')}/{row.get('rounds_skipped_total', '—')} "
                  f"| {row.get('crossed', '—')} | {row.get('time_to_target_s_mean', '—')} |")
    else:
        missing.append("summary.json")

    wires = _load_jsonl("wire_bytes.jsonl")
    if wires:
        print("\n## Wire codecs (bytes/round/volunteer)\n")
        print("| wire | bytes | vs f32 | loss @ 8 rounds |")
        print("|---|---|---|---|")
        for w in wires:
            print(f"| {w['wire']} | {w['bytes_per_round_per_volunteer']:.0f} "
                  f"| {w['vs_f32']:.3f} | {w['final_loss_mean']:.3f} |")
    else:
        missing.append("wire_bytes.jsonl")

    psgd = _load_jsonl("psgd_compare.jsonl")
    if psgd:
        print("\n## Codec convergence horizon (gpt2 proxy, latest run)\n")
        print("| arm | final loss | WAN MB | rounds |")
        print("|---|---|---|---|")
        for r in psgd:
            if "arm" in r:
                print(f"| {r['arm']} | {r['final_loss_mean']:.3f} "
                      f"| {r['wan_bytes_total'] / 1e6:.2f} | {r['rounds_ok_total']} |")
    else:
        missing.append("psgd_compare.jsonl")

    s16 = _load_json("scale16.json")
    if s16:
        print("\n## Averaging tier at 16 volunteers\n")
        print("| arm | finished | rounds ok | min/volunteer |")
        print("|---|---|---|---|")
        for tag, agg in s16.items():
            print(f"| {tag} | {agg['finished']}/16 | {agg['rounds_ok_total']} "
                  f"| {agg.get('n_rounds_ok_min', '—')} |")
    else:
        missing.append("scale16.json")

    probe = _load_json("tpu_probe_success.json")
    if probe:
        print("\n## Latest banked TPU probe record\n")
        print(f"- {probe.get('value')} {probe.get('unit')} "
              f"({probe.get('metric')}), est_mfu {probe.get('est_mfu', '—')}, "
              f"recorded {probe.get('recorded_at')}")
    else:
        missing.append("tpu_probe_success.json")

    soak = _load_jsonl("soak.jsonl")
    if soak:
        ok_rows = [r for r in soak if r.get("ok")]
        print(f"\n## Payload soaks: {len(ok_rows)} ok rows "
              f"(latest: {ok_rows[-1]['wire']} {ok_rows[-1]['seconds']}s "
              f"@ loadavg {ok_rows[-1].get('loadavg', '—')})")
    else:
        missing.append("soak.jsonl")

    if missing:
        print("\n## Missing artifacts\n")
        for m in missing:
            print(f"- {m}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
