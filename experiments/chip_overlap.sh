#!/bin/bash
# Chip-window agenda item (VERDICT r3 weak #4): settle the compute/WAN
# overlap criterion ON HARDWARE. The localhost matrix can't — on one shared
# CPU core the "device" compute and the averaging round contend for the same
# cycles, so the measured overlap ratio (0.71-0.75) conflates averaging cost
# with scheduling. On a real chip the device computes while the HOST runs the
# round, which is the whole point of the overlap design (trainer.py
# _launch_overlap_round).
#
# Topology: volunteer A on the TPU chip, volunteer B on CPU (a heterogeneous
# swarm — also exercises mixed-backend averaging, which no committed artifact
# shows yet). Three measurements of A's samples/sec:
#   1. baseline: A alone, no averaging
#   2. overlapped sync rounds with B (the default)
#   3. blocking rounds (--no-overlap)
# Criterion: (2) >= 0.90 x (1).
#
# Run INSIDE a good chip window (chip_watcher.sh finds one):
#   bash experiments/chip_overlap.sh
# Results APPEND to experiments/results/chip_overlap.jsonl; tags already
# recorded are skipped, so a sweep interrupted by a wedge resumes where it
# left off instead of discarding the evidence it already captured.
set -u
cd "$(dirname "$0")/.." || exit 1
OUT=experiments/results/chip_overlap.jsonl
touch "$OUT"
MODEL="--model gpt2_small --model-override n_layers=4 --model-override d_model=256 \
 --model-override n_heads=4 --model-override d_ff=1024 --model-override vocab=8192 \
 --model-override max_len=256"
STEPS="--steps 120 --batch-size 16 --lr 1e-4"
AVG="--averaging sync --average-every 10 --join-timeout 25 --gather-timeout 60"

run_tpu() { # $1=tag  $2...=extra args for the TPU volunteer
    local tag=$1; shift
    if grep -q "\"tag\": \"$tag\",.*\"summary\"" "$OUT"; then
        echo "tag $tag already recorded; skipping"
        return
    fi
    python coordinator.py >"/tmp/co_$tag.log" 2>&1 &
    local cpid=$!
    local addr=""
    for _ in $(seq 60); do  # jax import alone can take tens of seconds under load
        addr=$(grep -o "COORDINATOR_READY .*" "/tmp/co_$tag.log" | awk '{print $2}')
        [ -n "$addr" ] && break
        sleep 2
    done
    if [ -z "$addr" ]; then echo "{\"tag\": \"$tag\", \"error\": \"no coordinator\"}" >>"$OUT"; kill $cpid 2>/dev/null; return; fi
    # CPU peer (only for averaging tags). CPU_EXTRA carries settings both
    # sides must agree on (e.g. --wire: it is part of the schema hash, so
    # a mixed-wire pair would reject each other's rounds).
    local bpid=""
    if [ "$tag" != "baseline" ]; then
        JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= python run_volunteer.py \
            --coordinator "$addr" --peer-id cpu-peer $MODEL $STEPS $AVG \
            ${CPU_EXTRA:-} --seed 1 \
            >"/tmp/vb_$tag.log" 2>&1 &
        bpid=$!
    fi
    # TPU volunteer (default platform = the axon chip; 25 min cap)
    timeout 1500 python run_volunteer.py --coordinator "$addr" --peer-id tpu-vol \
        $MODEL $STEPS --seed 0 "$@" >"/tmp/va_$tag.log" 2>&1
    local sps
    sps=$(grep -o 'VOLUNTEER_DONE .*' "/tmp/va_$tag.log" | sed 's/VOLUNTEER_DONE //')
    # Machine-state context per row (r4 VERDICT weak #6: two committed
    # baseline rows differed 4x with nothing recording WHY — without load
    # context the file is useless as a comparison anchor).
    local ctx
    ctx="\"loadavg\": \"$(cut -d' ' -f1-3 /proc/loadavg)\", \"recorded_at\": \"$(date -u +%FT%TZ)\""
    if [ -n "$sps" ]; then
        echo "{\"tag\": \"$tag\", $ctx, \"summary\": $sps}" >>"$OUT"
    else
        # JSON-escape the log tail properly (backslashes/control chars in a
        # traceback would otherwise produce an unparseable jsonl line).
        tail -c 200 "/tmp/va_$tag.log" \
            | python -c "import json,sys,os; print(json.dumps({\"tag\": \"$tag\", \"loadavg\": \"%.2f %.2f %.2f\" % os.getloadavg(), \"error\": sys.stdin.read()}))" \
            >>"$OUT"
    fi
    # Scoped cleanup: kill only THIS run's processes (a blanket pkill would
    # take down unrelated e2e/matrix volunteers running elsewhere).
    kill $cpid $bpid 2>/dev/null
    sleep 2
}

run_tpu baseline --averaging none
run_tpu overlap $AVG --overlap
run_tpu blocking $AVG --no-overlap
# On-mesh data path arm (ISSUE 6): same overlapped topology with the swarm
# codec + tile folds forced onto the TPU volunteer's device mesh and the
# bf16 wire active (the codec's hot path). Compares against `overlap`
# (host data path) for the end-to-end samples/sec/chip win the ROADMAP
# item's acceptance asks for; the CPU peer keeps the host backend but
# must share the wire (schema hash).
CPU_EXTRA="--wire bf16" run_tpu overlap_mesh $AVG --overlap --wire bf16 --mesh-codec mesh
# Fused ring arm (ISSUE 18): same on-mesh topology with the fused
# decode+fold+forward ring collective enabled on the TPU volunteer
# (--mesh-collective ring; it engages when the local mesh has >= 2 devices,
# and falls back to the staged folder — identical numerics — on one). The
# overlap_mesh row above is its staged-path control in the same window.
CPU_EXTRA="--wire bf16" run_tpu overlap_fused $AVG --overlap --wire bf16 --mesh-codec mesh --mesh-collective ring
CPU_EXTRA=""
echo "chip_overlap done:"
cat "$OUT"
