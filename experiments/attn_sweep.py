"""Attention-impl crossover sweep on hardware: XLA fused core vs Pallas flash.

chip_probe's attn_ab stage answers "which core wins at the flagship bench
shape" (one point: T=1024 f32 -> xla). The `auto` routing needs more than one
point: the flash kernel's claim is O(T) HBM traffic vs the XLA core's O(T^2)
score matrix, so there should be a sequence length where flash takes over.
This sweep measures fwd+bwd time for both impls across T (token budget held
~constant: B = max(1, 8192 // T)) in bf16 (the training dtype) and f32, and
writes per-config rows + the measured crossover to
experiments/results/attn_sweep.json. The routing threshold in
ops/attention.py cites this artifact.

Run only in a live chip window (backend init hangs when the chip is wedged).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def time_impl(attention, jax, jnp, impl, B, H, T, D, dtype):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, H, T, D), dtype)
    k = jax.random.normal(ks[1], (B, H, T, D), dtype)
    v = jax.random.normal(ks[2], (B, H, T, D), dtype)
    attention.set_attention_impl(impl)
    try:
        # Timing on the tunneled axon platform (see experiments/
        # timing_diag.py): block_until_ready does NOT wait for execution
        # (~0.03ms "times" at any shape, ~1000x the chip's FLOP rate), and
        # per-call device round-trips swamp kernel time. The only reliable
        # recipe, matching the full-model bench's methodology:
        #   - chain iterations inside ONE compiled fori_loop (no execution
        #     can be elided or cache-served; all three grads feed the carry
        #     so dk/dv aren't dead-code-eliminated),
        #   - return only a SCALAR and fetch it to host (device_get is the
        #     one call observed to synchronize),
        #   - run two iteration counts and difference the wall times, which
        #     cancels upload latency + dispatch + fetch overhead.
        def loss(q, k, v):
            o = attention.attention_core_local(q, k, v, causal=True)
            return o.astype(jnp.float32).sum()

        def chained(iters):
            def run(q, k, v):
                def body(_, qkv):
                    q, k, v = qkv
                    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

                    def renorm(x, g):
                        # Keep magnitudes stable across iterations.
                        return (g / (jnp.float32(1e-6) + jnp.abs(g).max())).astype(x.dtype)

                    return (renorm(q, dq), renorm(k, dk), renorm(v, dv))

                q, k, v = jax.lax.fori_loop(0, iters, body, (q, k, v))
                return q.astype(jnp.float32).sum()

            return jax.jit(run)

        n_lo, n_hi = 4, 24
        f_lo, f_hi = chained(n_lo), chained(n_hi)
        t0 = time.monotonic()
        float(f_lo(q, k, v))
        float(f_hi(q, k, v))
        # Compile + 28 executed iterations — a warmup figure, not pure
        # compile time (at large T the execution share dominates).
        first_calls_s = time.monotonic() - t0

        def timed(fn):
            t0 = time.perf_counter()
            float(fn(q, k, v))
            return time.perf_counter() - t0

        # Interleave the arms (lo, hi, lo, hi, ...) so a monotonic drift in
        # tunnel latency hits both arms alike, and take per-arm minima:
        # robust to one-off stalls.
        lo_times, hi_times = [], []
        for _ in range(3):
            lo_times.append(timed(f_lo))
            hi_times.append(timed(f_hi))
        dt_ms = (min(hi_times) - min(lo_times)) / (n_hi - n_lo) * 1e3
        if dt_ms <= 0.05:
            # A differenced time at or below dispatch noise means a stalled
            # lo arm swallowed the signal — a non-measurement, not a fast
            # kernel. Report it as failed so winner/crossover (and the
            # routing constants that cite them) can't be decided by noise.
            return {
                "ok": False,
                "error": f"non-positive/noise differenced time ({dt_ms:.4f} ms)"
                         " — tunnel stall during the lo arm",
            }
        return {
            "ok": True,
            "first_calls_s": round(first_calls_s, 2),
            "fwd_bwd_ms": round(dt_ms, 3),
        }
    except Exception as err:  # noqa: BLE001 — one impl failing IS a result
        return {"ok": False, "error": f"{type(err).__name__}: {str(err)[:200]}"}
    finally:
        attention.set_attention_impl("auto")


def main():
    import jax
    import jax.numpy as jnp

    from distributedvolunteercomputing_tpu.ops import attention

    H, D = 12, 64
    rows = []
    for dtype_name in ("bfloat16", "float32"):
        dtype = jnp.dtype(dtype_name)
        for T in (512, 1024, 2048, 4096, 8192):
            B = max(1, 8192 // T)
            row = {"dtype": dtype_name, "B": B, "H": H, "T": T, "D": D}
            for impl in ("xla", "flash"):
                print(f"sweep {dtype_name} T={T} B={B} {impl} ...", flush=True)
                row[impl] = time_impl(attention, jax, jnp, impl, B, H, T, D, dtype)
            # Block-shape tuning arms (r4 VERDICT #4: flash lost to XLA in
            # bf16 at T=512-2048 — the r5 kernel fixed the dtype path; these
            # arms measure whether bigger blocks buy more at the previously
            # losing shapes). DVC_FLASH_BLOCK_* is read at trace time and
            # time_impl builds fresh jits per arm, so each setting compiles
            # its own program.
            if dtype_name == "bfloat16" and T <= 2048:
                for bq, bk in ((256, 256), (512, 512)):
                    if bq > T:
                        continue
                    label = f"flash_b{bq}x{bk}"
                    print(f"sweep {dtype_name} T={T} B={B} {label} ...", flush=True)
                    os.environ["DVC_FLASH_BLOCK_Q"] = str(bq)
                    os.environ["DVC_FLASH_BLOCK_K"] = str(bk)
                    try:
                        row[label] = time_impl(
                            attention, jax, jnp, "flash", B, H, T, D, dtype
                        )
                    finally:
                        os.environ.pop("DVC_FLASH_BLOCK_Q", None)
                        os.environ.pop("DVC_FLASH_BLOCK_K", None)
            if row["xla"].get("ok") and row["flash"].get("ok"):
                row["winner"] = min(("xla", "flash"), key=lambda i: row[i]["fwd_bwd_ms"])
                row["speedup_flash"] = round(
                    row["xla"]["fwd_bwd_ms"] / row["flash"]["fwd_bwd_ms"], 3
                )
            print(f"  -> {json.dumps(row)}", flush=True)
            rows.append(row)
    # Crossover per dtype: smallest T from which flash wins at EVERY larger
    # measured T (suffix-win). A flash compile failure at some T also breaks
    # the suffix — routing to a kernel that may not compile is never right.
    crossover = {}
    for dtype_name in ("bfloat16", "float32"):
        drows = sorted(
            (r for r in rows if r["dtype"] == dtype_name), key=lambda r: r["T"]
        )
        best = None
        for r in reversed(drows):  # largest T first; stop at first non-win
            if r.get("winner") == "flash":
                best = r["T"]
            else:
                break
        crossover[dtype_name] = best
    out = {
        "device_kind": jax.devices()[0].device_kind,
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "rows": rows,
        "flash_wins_from_T": crossover,
    }
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results", "attn_sweep.json"
    )
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(f"wrote {path}")
    print(json.dumps(crossover))


if __name__ == "__main__":
    main()
