"""Staged escalation probe for the flaky axon chip.

Runs progressively bigger programs in ONE process, printing per-stage wall
times, so hangs are attributed to a stage instead of "the bench failed".
History (BENCH_r01/r02/r03 + judge bisect): backend init can raise or hang;
big-program compile/alloc can hang; the same config passes in some fresh
processes, and once passed in a process that compiled smaller configs first.
This probe IS that smaller-configs-first process: if the warmup-ladder
hypothesis is right, the gpt2 stage should pass here more often than cold.

Usage:
    python experiments/chip_probe.py [max_stage]   # staged escalation probe
    python experiments/chip_probe.py serve         # persistent warm worker
    python experiments/chip_probe.py ping          # is a worker alive?
"""

from __future__ import annotations

import json
import os
import sys
import time

# The probe is launched as `python experiments/chip_probe.py`, so sys.path[0]
# is experiments/ — put the repo root first so the package imports without an
# install step (the workdir is re-provisioned between rounds).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_RESULTS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")


def _epoch_path() -> str:
    return os.path.join(_RESULTS_DIR, "backend_epoch.json")


def _stamp_epoch(device_kind: str) -> str:
    """Record that a live backend was observed NOW; return its epoch id.

    The epoch names one continuous stretch of proven backend liveness:
    re-stamping within DVC_BENCH_EPOCH_TTL keeps the same id (the chip
    stayed observably alive), past it a fresh id is minted. bench.py's
    recorded-probe fallback replays a cached measurement only when the
    record's stamped epoch is still the current, alive one — the BENCH_r02
    fix, where a 57.5 samples/sec figure cached before a wedge headlined a
    round whose chip was long dead.
    """
    now = time.time()
    ttl = float(os.environ.get("DVC_BENCH_EPOCH_TTL", "900"))
    epoch = None
    try:
        with open(_epoch_path()) as fh:
            cur = json.load(fh)
        if now - float(cur.get("alive_at", 0)) <= ttl and cur.get("epoch"):
            epoch = cur["epoch"]
    except (OSError, ValueError, TypeError):
        pass
    if epoch is None:
        epoch = f"{int(now)}-{os.getpid()}"
    os.makedirs(_RESULTS_DIR, exist_ok=True)
    tmp = _epoch_path() + ".tmp"
    with open(tmp, "w") as fh:
        json.dump({"epoch": epoch, "alive_at": now, "device_kind": device_kind}, fh)
    os.replace(tmp, _epoch_path())
    return epoch


STAGES = []


def stage(name):
    def deco(fn):
        STAGES.append((name, fn))
        return fn

    return deco


@stage("backend_init")
def _backend(ctx):
    import jax

    ctx["jax"] = jax
    devs = jax.devices()
    # Liveness epoch: the backend answered, so the current alive-window
    # extends through NOW (see _stamp_epoch / bench.py _recorded_probe).
    _stamp_epoch(devs[0].device_kind)
    return f"{devs[0].device_kind} x{len(devs)}"


@stage("tiny_matmul")
def _matmul(ctx):
    jax = ctx["jax"]
    import jax.numpy as jnp

    x = jnp.ones((1024, 1024), dtype=jnp.bfloat16)
    y = (x @ x).block_until_ready()
    return f"sum={float(y.sum()):.0f}"


@stage("mlp_step")
def _mlp(ctx):
    jax = ctx["jax"]
    from distributedvolunteercomputing_tpu.models import get_model
    from distributedvolunteercomputing_tpu.training.optim import make_optimizer
    from distributedvolunteercomputing_tpu.training.steps import TrainState, make_train_step

    b = get_model("mnist_mlp")
    tx = make_optimizer("adamw", lr=1e-3)
    params = b.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, tx, jax.random.PRNGKey(1))
    step = make_train_step(b.loss_fn, tx)
    batch = b.make_batch(jax.random.PRNGKey(2), 8)
    st, m = step(st, batch)
    return f"loss={float(m['loss']):.3f}"


@stage("gpt2_tiny_step")
def _gpt2_tiny(ctx):
    jax = ctx["jax"]
    from distributedvolunteercomputing_tpu.models import get_model
    from distributedvolunteercomputing_tpu.training.optim import make_optimizer
    from distributedvolunteercomputing_tpu.training.steps import TrainState, make_train_step

    b = get_model("gpt2_small", n_layers=2, d_model=256, n_heads=4, max_len=128)
    tx = make_optimizer("adamw", lr=1e-4)
    params = b.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, tx, jax.random.PRNGKey(1))
    step = make_train_step(b.loss_fn, tx)
    batch = b.make_batch(jax.random.PRNGKey(2), 8)
    st, m = step(st, batch)
    return f"loss={float(m['loss']):.3f}"


@stage("gpt2_small_init")
def _gpt2_init(ctx):
    jax = ctx["jax"]
    from distributedvolunteercomputing_tpu.models import get_model

    b = get_model("gpt2_small")
    params = b.init(jax.random.PRNGKey(1))
    n = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    ctx["gpt2"] = (b, params)
    return f"{n / 1e6:.1f}M params"


def _timed_loop(step, st, batch, iters):
    """The bench hot loop: `iters` compiled steps, scalar-materialized at
    the end (host copy surfaces deferred OOM; block_until_ready may not)."""
    t0 = time.perf_counter()
    for _ in range(iters):
        st, m = step(st, batch)
    loss = float(m["loss"])
    return st, loss, time.perf_counter() - t0


def _bench_payload(jax, bundle, n_params, batch_size, sps, loss, source,
                   model_name="gpt2_small"):
    """Bench-grade record shared by the gpt2_small_step stage and the warm
    worker — identical shape so bench.py's consumers can't tell them apart
    except by the `source` line and the liveness epoch stamp."""
    device_kind = jax.devices()[0].device_kind
    payload = {
        "metric": f"samples/sec/volunteer-chip ({model_name}, bs={batch_size})",
        "value": round(sps, 3),
        "unit": "samples/sec/chip",
        "batch_size": batch_size,
        "n_params": n_params,
        "device_kind": device_kind,
        "loss": round(loss, 4),
        "source": source,
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    seq_len = getattr(bundle.config, "max_len", None)
    if seq_len:
        payload["tokens_per_sec_chip"] = round(sps * seq_len, 1)
        # est_mfu via the same 6ND convention as bench.py (lower bound:
        # remat recompute not counted). Repo root is on sys.path already.
        try:
            from bench import _peak_flops

            peak = _peak_flops(device_kind)
            if peak:
                payload["est_mfu"] = round(
                    6.0 * n_params * payload["tokens_per_sec_chip"] / peak, 4
                )
        except Exception:
            pass
    # The measurement itself is proof of backend liveness: stamp the epoch
    # and tie the record to it, so a future round can tell "this backend,
    # still alive" from "a number cached before the chip wedged".
    payload["backend_epoch"] = _stamp_epoch(device_kind)
    return payload


def _write_probe_record(payload) -> str:
    os.makedirs(_RESULTS_DIR, exist_ok=True)
    out = os.path.join(_RESULTS_DIR, "tpu_probe_success.json")
    tmp = out + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, out)
    return out


@stage("gpt2_small_step")
def _gpt2_step(ctx):
    jax = ctx["jax"]
    from distributedvolunteercomputing_tpu.training.optim import make_optimizer
    from distributedvolunteercomputing_tpu.training.steps import TrainState, make_train_step

    b, params = ctx["gpt2"]
    n_params = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    tx = make_optimizer("adamw", lr=1e-4)
    st = TrainState.create(params, tx, jax.random.PRNGKey(2))
    step = make_train_step(b.loss_fn, tx)
    batch_size = 8
    batch = b.make_batch(jax.random.PRNGKey(0), batch_size)
    st, _, _ = _timed_loop(step, st, batch, 3)  # warmup + deferred-OOM check
    iters = 20
    st, loss, dt = _timed_loop(step, st, batch, iters)
    sps = batch_size * iters / dt
    # A full bench-grade measurement in the process that proved the chip
    # alive: record it so the round has a real TPU number even if the chip
    # wedges again before the driver's end-of-round bench.py run.
    payload = _bench_payload(
        jax, b, n_params, batch_size, sps, loss,
        source="experiments/chip_probe.py (staged warm-up ladder)",
    )
    out = _write_probe_record(payload)
    return f"loss={loss:.3f} {sps:.2f} samples/s -> {out}"


@stage("attn_ab_flash_vs_xla")
def _attn_ab(ctx):
    """A/B the compiled Pallas flash kernel vs the fused-XLA attention core
    on hardware: fwd+bwd at flagship bench shapes (gpt2_small heads:
    B=8, H=12, T=1024, D=64, causal). Records per-impl compile + step time
    and the cross-impl numeric diff to results/attn_ab.json so the default
    "auto" routing is backed by measurement, not hypothesis.
    Timing delegates to experiments/attn_sweep.time_impl — the chained-
    fori_loop + scalar-fetch + differenced-iteration recipe, the only one
    that reflects real execution on the tunneled axon runtime (open-loop
    block_until_ready timing returns ~0.03ms at any shape; see
    experiments/timing_diag.py and the round-4 bench A/B, where the full
    model ran FASTER with the kernel the open-loop timing called slower).
    Runs AFTER the bench-grade record stage on purpose: a Mosaic hang in
    this stage must not cost the round its samples/sec number."""
    import json

    jax = ctx["jax"]
    import jax.numpy as jnp

    from experiments.attn_sweep import time_impl
    from distributedvolunteercomputing_tpu.ops import attention

    B, H, T, D = (
        int(x) for x in os.environ.get("DVC_PROBE_AB_SHAPE", "8,12,1024,64").split(",")
    )
    results = {"shapes": f"B{B} H{H} T{T} D{D} causal f32",
               "device_kind": jax.devices()[0].device_kind,
               "methodology": "chained fori_loop, scalar fetch, differenced iters",
               "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    for impl in ("xla", "flash"):
        results[impl] = time_impl(attention, jax, jnp, impl, B, H, T, D, jnp.float32)
    # Numeric cross-check (one fwd+dq per impl; correctness, not timing).
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, H, T, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, T, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, T, D), jnp.float32)
    outs = {}
    for impl in ("xla", "flash"):
        if not results[impl].get("ok"):
            continue
        attention.set_attention_impl(impl)
        try:
            def loss(q, k, v):
                o = attention.attention_core_local(q, k, v, causal=True)
                return o.astype(jnp.float32).sum(), o

            # Keep the tensors on device; only scalars cross the tunnel.
            outs[impl] = jax.jit(
                jax.value_and_grad(loss, argnums=(0,), has_aux=True)
            )(q, k, v)
        except Exception as err:  # noqa: BLE001 — don't lose the timings
            results[impl]["crosscheck_error"] = f"{type(err).__name__}: {str(err)[:200]}"
        finally:
            attention.set_attention_impl("auto")
    if len(outs) == 2:
        (_, out_x), grads_x = outs["xla"]
        (_, out_f), grads_f = outs["flash"]
        try:
            results["max_abs_diff_fwd"] = float(jnp.max(jnp.abs(out_x - out_f)))
            results["max_abs_diff_dq"] = float(
                jnp.max(jnp.abs(grads_x[0] - grads_f[0]))
            )
        except Exception as err:  # noqa: BLE001 — don't lose the timings
            results["crosscheck_error"] = f"{type(err).__name__}: {str(err)[:200]}"
    if results.get("xla", {}).get("ok") and results.get("flash", {}).get("ok"):
        results["winner"] = min(
            ("xla", "flash"), key=lambda i: results[i]["fwd_bwd_ms"]
        )
    elif results.get("xla", {}).get("ok"):
        results["winner"] = "xla (flash failed)"
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results", "attn_ab.json"
    )
    with open(out_path, "w") as fh:
        json.dump(results, fh, indent=1)
    summary = {
        i: (f"{results[i]['fwd_bwd_ms']}ms" if results.get(i, {}).get("ok") else "FAIL")
        for i in ("xla", "flash")
    }
    return f"{summary} -> {out_path}"


# ------------------------------------------------- persistent warm worker ----

_DEFAULT_SOCK = "/tmp/dvc_warm_backend.sock"


def _sock_path() -> str:
    return os.environ.get("DVC_BENCH_WORKER_SOCK", _DEFAULT_SOCK)


def request_worker(req: dict, timeout: float = 10.0) -> dict | None:
    """Client half: one JSON-line request to the warm worker, or None on any
    miss (no socket, wedged worker, garbage reply). Imports nothing heavy —
    bench.py calls this BEFORE deciding whether to pay the fresh-child
    ladder, so it must stay cheap and side-effect free."""
    import socket

    try:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(timeout)
        s.connect(_sock_path())
        s.sendall((json.dumps(req) + "\n").encode())
        raw = b""
        while not raw.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            raw += chunk
        s.close()
        return json.loads(raw.decode() or "null")
    except (OSError, ValueError):
        return None


def ping_worker() -> int:
    resp = request_worker({"cmd": "ping"}, timeout=10.0)
    print(json.dumps(resp or {"ok": False, "error": "no worker"}))
    return 0 if resp and resp.get("ok") else 1


class WarmBackendWorker:
    """Long-lived bench server: pay backend init + the flagship XLA compile
    ONCE, then serve bench requests over a unix socket for the rest of the
    round.

    Motivation (BENCH_r01..r03): the dominant cost AND the dominant failure
    mode both live in cold start — backend init raises or hangs, the
    flagship compile takes tens of seconds, and the same config passes in a
    process that compiled smaller programs first. A worker that rode out one
    successful warm-up is the best place to take the round-end measurement:
    the compiled step is cached, so a bench request is just the timed hot
    loop (~seconds), taken NOW, on a backend that is provably alive.

    Protocol: one JSON line per connection at DVC_BENCH_WORKER_SOCK.
      {"cmd": "ping"}               -> {"ok": true, "epoch", "device_kind", "model"}
      {"cmd": "bench", "iters": N}  -> {"ok": true, "payload": <bench record>}
    Liveness: every served request re-stamps results/backend_epoch.json and
    an idle heartbeat re-stamps every DVC_WORKER_HEARTBEAT (120s), so cached
    probe records stay epoch-current exactly as long as the worker is
    healthy. Self-watchdog: a request still in flight past
    DVC_WORKER_REQ_DEADLINE (420s) means the backend wedged mid-request —
    the worker os._exit(3)s so window_watcher.sh's cold-restart line can
    replace it instead of banking silence.
    """

    def __init__(self, model_name: str = "gpt2_small", batch_size: int = 8):
        self.model_name = model_name
        self.batch_size = batch_size
        self._busy_since: float | None = None

    def warm(self) -> None:
        import jax
        import jax.numpy as jnp

        from distributedvolunteercomputing_tpu.models import get_model
        from distributedvolunteercomputing_tpu.training.optim import make_optimizer
        from distributedvolunteercomputing_tpu.training.steps import TrainState, make_train_step

        self.jax = jax
        self.device_kind = jax.devices()[0].device_kind
        self.epoch = _stamp_epoch(self.device_kind)
        # r03 warm-up ladder: a small compile first raises the flagship's
        # odds on this chip (judge-bisected, see module docstring).
        x = jnp.ones((1024, 1024), dtype=jnp.bfloat16)
        float((x @ x).sum())
        b = get_model(self.model_name)
        tx = make_optimizer("adamw", lr=1e-4)
        params = b.init(jax.random.PRNGKey(1))
        self.n_params = sum(int(p.size) for p in jax.tree_util.tree_leaves(params))
        st = TrainState.create(params, tx, jax.random.PRNGKey(2))
        del params  # donated into the first step
        step = make_train_step(b.loss_fn, tx)
        batch = b.make_batch(jax.random.PRNGKey(0), self.batch_size)
        st, loss, _ = _timed_loop(step, st, batch, 3)  # compile + deferred-OOM check
        self.bundle, self.step, self.state, self.batch = b, step, st, batch
        self.epoch = _stamp_epoch(self.device_kind)
        print(
            f"warm-worker: compiled step cached ({self.n_params / 1e6:.1f}M params, "
            f"{self.device_kind}, warm loss={loss:.3f})",
            flush=True,
        )

    def handle(self, req: dict) -> dict:
        cmd = req.get("cmd", "ping")
        if cmd == "ping":
            self.epoch = _stamp_epoch(self.device_kind)
            return {
                "ok": True,
                "epoch": self.epoch,
                "device_kind": self.device_kind,
                "model": self.model_name,
                "batch_size": self.batch_size,
            }
        if cmd == "bench":
            iters = max(int(req.get("iters", 20)), 1)
            self.state, loss, dt = _timed_loop(self.step, self.state, self.batch, iters)
            sps = self.batch_size * iters / dt
            payload = _bench_payload(
                self.jax, self.bundle, self.n_params, self.batch_size, sps, loss,
                source="experiments/chip_probe.py (persistent warm worker)",
                model_name=self.model_name,
            )
            # Keep the on-disk record fresh too: if the chip wedges between
            # this request and the round-end bench, the replay fallback now
            # holds THIS measurement, stamped with a still-alive epoch.
            _write_probe_record(payload)
            return {"ok": True, "payload": payload}
        return {"ok": False, "error": f"unknown cmd {cmd!r}"}

    def serve(self) -> int:
        import socket
        import threading

        deadline = float(os.environ.get("DVC_WORKER_REQ_DEADLINE", "420"))

        def _watchdog():
            while True:
                time.sleep(5.0)
                busy = self._busy_since
                if busy is not None and time.monotonic() - busy > deadline:
                    print(
                        f"warm-worker: request wedged past {deadline:.0f}s; "
                        "exiting hard for a cold restart",
                        flush=True,
                    )
                    os._exit(3)

        threading.Thread(target=_watchdog, daemon=True).start()

        try:
            self.warm()
        except Exception as err:
            print(
                f"warm-worker FAIL warm-up: {type(err).__name__}: {str(err)[:300]}",
                flush=True,
            )
            return 1

        def _heartbeat():
            hb = max(float(os.environ.get("DVC_WORKER_HEARTBEAT", "120")), 1.0)
            while True:
                time.sleep(hb)
                if self._busy_since is None:
                    try:
                        # A heartbeat is an assertion the backend ANSWERS, not
                        # just that this process exists: a trivial device op
                        # must complete before the epoch may be extended.
                        float(self.jax.numpy.zeros(()) + 1.0)
                        self.epoch = _stamp_epoch(self.device_kind)
                    except Exception:
                        print("warm-worker: heartbeat device op failed; exiting", flush=True)
                        os._exit(3)

        threading.Thread(target=_heartbeat, daemon=True).start()

        path = _sock_path()
        try:
            os.unlink(path)
        except OSError:
            pass
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(1)
        print(f"warm-worker: serving on {path} (epoch {self.epoch})", flush=True)
        while True:
            conn, _ = srv.accept()
            with conn:
                resp: dict
                try:
                    conn.settimeout(10.0)
                    raw = b""
                    while not raw.endswith(b"\n"):
                        chunk = conn.recv(65536)
                        if not chunk:
                            break
                        raw += chunk
                    self._busy_since = time.monotonic()
                    resp = self.handle(json.loads(raw.decode() or "{}"))
                except Exception as err:
                    resp = {
                        "ok": False,
                        "error": f"{type(err).__name__}: {str(err)[:300]}",
                    }
                finally:
                    self._busy_since = None
                try:
                    conn.sendall((json.dumps(resp) + "\n").encode())
                except OSError:
                    pass


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "serve":
        return WarmBackendWorker(
            model_name=os.environ.get("DVC_BENCH_MODEL", "gpt2_small"),
            batch_size=int(os.environ.get("DVC_BENCH_BATCH", "8")),
        ).serve()
    if len(sys.argv) > 1 and sys.argv[1] == "ping":
        return ping_worker()
    max_stage = int(sys.argv[1]) if len(sys.argv) > 1 else len(STAGES)
    ctx: dict = {}
    t_start = time.monotonic()
    for i, (name, fn) in enumerate(STAGES[:max_stage]):
        t0 = time.monotonic()
        print(f"probe [{t0 - t_start:6.1f}s] stage {i}: {name} ...", flush=True)
        try:
            info = fn(ctx)
        except Exception as err:
            print(f"probe FAIL {name}: {type(err).__name__}: {str(err)[:300]}", flush=True)
            return 1
        print(
            f"probe [{time.monotonic() - t_start:6.1f}s] stage {i}: {name} OK "
            f"({time.monotonic() - t0:.1f}s) {info}",
            flush=True,
        )
    print("probe: ALL STAGES PASSED", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
