"""Staged escalation probe for the flaky axon chip.

Runs progressively bigger programs in ONE process, printing per-stage wall
times, so hangs are attributed to a stage instead of "the bench failed".
History (BENCH_r01/r02/r03 + judge bisect): backend init can raise or hang;
big-program compile/alloc can hang; the same config passes in some fresh
processes, and once passed in a process that compiled smaller configs first.
This probe IS that smaller-configs-first process: if the warmup-ladder
hypothesis is right, the gpt2 stage should pass here more often than cold.

Usage: python experiments/chip_probe.py [max_stage]
"""

from __future__ import annotations

import os
import sys
import time

# The probe is launched as `python experiments/chip_probe.py`, so sys.path[0]
# is experiments/ — put the repo root first so the package imports without an
# install step (the workdir is re-provisioned between rounds).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STAGES = []


def stage(name):
    def deco(fn):
        STAGES.append((name, fn))
        return fn

    return deco


@stage("backend_init")
def _backend(ctx):
    import jax

    ctx["jax"] = jax
    devs = jax.devices()
    return f"{devs[0].device_kind} x{len(devs)}"


@stage("tiny_matmul")
def _matmul(ctx):
    jax = ctx["jax"]
    import jax.numpy as jnp

    x = jnp.ones((1024, 1024), dtype=jnp.bfloat16)
    y = (x @ x).block_until_ready()
    return f"sum={float(y.sum()):.0f}"


@stage("mlp_step")
def _mlp(ctx):
    jax = ctx["jax"]
    from distributedvolunteercomputing_tpu.models import get_model
    from distributedvolunteercomputing_tpu.training.optim import make_optimizer
    from distributedvolunteercomputing_tpu.training.steps import TrainState, make_train_step

    b = get_model("mnist_mlp")
    tx = make_optimizer("adamw", lr=1e-3)
    params = b.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, tx, jax.random.PRNGKey(1))
    step = make_train_step(b.loss_fn, tx)
    batch = b.make_batch(jax.random.PRNGKey(2), 8)
    st, m = step(st, batch)
    return f"loss={float(m['loss']):.3f}"


@stage("gpt2_tiny_step")
def _gpt2_tiny(ctx):
    jax = ctx["jax"]
    from distributedvolunteercomputing_tpu.models import get_model
    from distributedvolunteercomputing_tpu.training.optim import make_optimizer
    from distributedvolunteercomputing_tpu.training.steps import TrainState, make_train_step

    b = get_model("gpt2_small", n_layers=2, d_model=256, n_heads=4, max_len=128)
    tx = make_optimizer("adamw", lr=1e-4)
    params = b.init(jax.random.PRNGKey(0))
    st = TrainState.create(params, tx, jax.random.PRNGKey(1))
    step = make_train_step(b.loss_fn, tx)
    batch = b.make_batch(jax.random.PRNGKey(2), 8)
    st, m = step(st, batch)
    return f"loss={float(m['loss']):.3f}"


@stage("gpt2_small_init")
def _gpt2_init(ctx):
    jax = ctx["jax"]
    from distributedvolunteercomputing_tpu.models import get_model

    b = get_model("gpt2_small")
    params = b.init(jax.random.PRNGKey(1))
    n = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    ctx["gpt2"] = (b, params)
    return f"{n / 1e6:.1f}M params"


@stage("gpt2_small_step")
def _gpt2_step(ctx):
    import json

    jax = ctx["jax"]
    from distributedvolunteercomputing_tpu.training.optim import make_optimizer
    from distributedvolunteercomputing_tpu.training.steps import TrainState, make_train_step

    b, params = ctx["gpt2"]
    n_params = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    tx = make_optimizer("adamw", lr=1e-4)
    st = TrainState.create(params, tx, jax.random.PRNGKey(2))
    step = make_train_step(b.loss_fn, tx)
    batch_size = 8
    batch = b.make_batch(jax.random.PRNGKey(0), batch_size)
    for _ in range(3):
        st, m = step(st, batch)
    loss = float(m["loss"])  # materialize: surfaces deferred OOM before timing
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        st, m = step(st, batch)
    loss = float(m["loss"])
    dt = time.perf_counter() - t0
    sps = batch_size * iters / dt
    # A full bench-grade measurement in the process that proved the chip
    # alive: record it so the round has a real TPU number even if the chip
    # wedges again before the driver's end-of-round bench.py run.
    payload = {
        "metric": f"samples/sec/volunteer-chip (gpt2_small, bs={batch_size})",
        "value": round(sps, 3),
        "unit": "samples/sec/chip",
        "batch_size": batch_size,
        "n_params": n_params,
        "device_kind": jax.devices()[0].device_kind,
        "loss": round(loss, 4),
        "tokens_per_sec_chip": round(sps * b.config.max_len, 1),
        "source": "experiments/chip_probe.py (staged warm-up ladder)",
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    # est_mfu via the same 6ND convention as bench.py (lower bound: remat
    # recompute not counted). Repo root is already on sys.path (module top).
    try:
        from bench import _peak_flops

        peak = _peak_flops(jax.devices()[0].device_kind)
        if peak:
            payload["est_mfu"] = round(
                6.0 * n_params * payload["tokens_per_sec_chip"] / peak, 4
            )
    except Exception:
        pass
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results", "tpu_probe_success.json")
    with open(out, "w") as fh:
        json.dump(payload, fh)
    return f"loss={loss:.3f} {sps:.2f} samples/s -> {out}"


@stage("attn_ab_flash_vs_xla")
def _attn_ab(ctx):
    """A/B the compiled Pallas flash kernel vs the fused-XLA attention core
    on hardware: fwd+bwd at flagship bench shapes (gpt2_small heads:
    B=8, H=12, T=1024, D=64, causal). Records per-impl compile + step time
    and the cross-impl numeric diff to results/attn_ab.json so the default
    "auto" routing is backed by measurement, not hypothesis.
    Timing delegates to experiments/attn_sweep.time_impl — the chained-
    fori_loop + scalar-fetch + differenced-iteration recipe, the only one
    that reflects real execution on the tunneled axon runtime (open-loop
    block_until_ready timing returns ~0.03ms at any shape; see
    experiments/timing_diag.py and the round-4 bench A/B, where the full
    model ran FASTER with the kernel the open-loop timing called slower).
    Runs AFTER the bench-grade record stage on purpose: a Mosaic hang in
    this stage must not cost the round its samples/sec number."""
    import json

    jax = ctx["jax"]
    import jax.numpy as jnp

    from experiments.attn_sweep import time_impl
    from distributedvolunteercomputing_tpu.ops import attention

    B, H, T, D = (
        int(x) for x in os.environ.get("DVC_PROBE_AB_SHAPE", "8,12,1024,64").split(",")
    )
    results = {"shapes": f"B{B} H{H} T{T} D{D} causal f32",
               "device_kind": jax.devices()[0].device_kind,
               "methodology": "chained fori_loop, scalar fetch, differenced iters",
               "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    for impl in ("xla", "flash"):
        results[impl] = time_impl(attention, jax, jnp, impl, B, H, T, D, jnp.float32)
    # Numeric cross-check (one fwd+dq per impl; correctness, not timing).
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, H, T, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, T, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, T, D), jnp.float32)
    outs = {}
    for impl in ("xla", "flash"):
        if not results[impl].get("ok"):
            continue
        attention.set_attention_impl(impl)
        try:
            def loss(q, k, v):
                o = attention.attention_core_local(q, k, v, causal=True)
                return o.astype(jnp.float32).sum(), o

            # Keep the tensors on device; only scalars cross the tunnel.
            outs[impl] = jax.jit(
                jax.value_and_grad(loss, argnums=(0,), has_aux=True)
            )(q, k, v)
        except Exception as err:  # noqa: BLE001 — don't lose the timings
            results[impl]["crosscheck_error"] = f"{type(err).__name__}: {str(err)[:200]}"
        finally:
            attention.set_attention_impl("auto")
    if len(outs) == 2:
        (_, out_x), grads_x = outs["xla"]
        (_, out_f), grads_f = outs["flash"]
        try:
            results["max_abs_diff_fwd"] = float(jnp.max(jnp.abs(out_x - out_f)))
            results["max_abs_diff_dq"] = float(
                jnp.max(jnp.abs(grads_x[0] - grads_f[0]))
            )
        except Exception as err:  # noqa: BLE001 — don't lose the timings
            results["crosscheck_error"] = f"{type(err).__name__}: {str(err)[:200]}"
    if results.get("xla", {}).get("ok") and results.get("flash", {}).get("ok"):
        results["winner"] = min(
            ("xla", "flash"), key=lambda i: results[i]["fwd_bwd_ms"]
        )
    elif results.get("xla", {}).get("ok"):
        results["winner"] = "xla (flash failed)"
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results", "attn_ab.json"
    )
    with open(out_path, "w") as fh:
        json.dump(results, fh, indent=1)
    summary = {
        i: (f"{results[i]['fwd_bwd_ms']}ms" if results.get(i, {}).get("ok") else "FAIL")
        for i in ("xla", "flash")
    }
    return f"{summary} -> {out_path}"


def main() -> int:
    max_stage = int(sys.argv[1]) if len(sys.argv) > 1 else len(STAGES)
    ctx: dict = {}
    t_start = time.monotonic()
    for i, (name, fn) in enumerate(STAGES[:max_stage]):
        t0 = time.monotonic()
        print(f"probe [{t0 - t_start:6.1f}s] stage {i}: {name} ...", flush=True)
        try:
            info = fn(ctx)
        except Exception as err:
            print(f"probe FAIL {name}: {type(err).__name__}: {str(err)[:300]}", flush=True)
            return 1
        print(
            f"probe [{time.monotonic() - t_start:6.1f}s] stage {i}: {name} OK "
            f"({time.monotonic() - t0:.1f}s) {info}",
            flush=True,
        )
    print("probe: ALL STAGES PASSED", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
