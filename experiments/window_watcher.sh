#!/bin/bash
# Round-4 second-session watcher: the first chip window (03:48-04:38) already
# produced the bench-grade record + attention A/B; what it did NOT finish is
# the hardware overlap sweep (chip_overlap.sh hung when the chip re-wedged
# mid-run at the overlap tag). This watcher waits for the NEXT window with
# the same exponential backoff chip_watcher.sh uses (SIGKILLing clients
# mid-init is the one thing observed to extend wedges, so probe gently),
# then: (1) resumes chip_overlap.sh (tag-resumable: baseline is recorded,
# overlap/blocking remain), (2) refreshes the bench-grade probe record so
# the round-end fallback stays fresh. Exits when the overlap jsonl has all
# three summary tags or after MAX_LOOPS probes.
cd "$(dirname "$0")/.." || exit 1
LOG=experiments/results/window_watcher.log
OUT=experiments/results/chip_overlap.jsonl
echo "$(date +%T) window_watcher start" >>"$LOG"
SLEEP=120
LOOPS=0
done_tags() { grep -c '"summary"' "$OUT" 2>/dev/null || echo 0; }
while [ "$(done_tags)" -lt 3 ] && [ "$LOOPS" -lt 60 ]; do
    LOOPS=$((LOOPS + 1))
    if timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$(date +%T) chip ALIVE -> resume chip_overlap" >>"$LOG"
        bash experiments/chip_overlap.sh >>"$LOG" 2>&1
        echo "$(date +%T) chip_overlap rc=$? tags=$(done_tags)" >>"$LOG"
        if [ "$(done_tags)" -ge 3 ]; then
            echo "$(date +%T) refreshing probe record" >>"$LOG"
            timeout 900 python experiments/chip_probe.py >>"$LOG" 2>&1
            break
        fi
        SLEEP=120
    else
        echo "$(date +%T) wedged; next probe in ${SLEEP}s" >>"$LOG"
        sleep "$SLEEP"
        SLEEP=$((SLEEP * 2))
        [ "$SLEEP" -gt 1800 ] && SLEEP=1800
    fi
done
echo "$(date +%T) window_watcher exit (tags=$(done_tags), loops=$LOOPS)" >>"$LOG"
