#!/bin/bash
# Round-4 second-session watcher (v3). The 03:48-04:38 window already
# produced the bench-grade record + attention A/B; this watcher waits for
# the NEXT window (exponential backoff — SIGKILLing clients mid-init is the
# one thing observed to extend wedges, so probe gently) and runs the
# remaining hardware agenda in VALUE order, cheapest-and-most-load-bearing
# first. Each step is banked exactly once (done-markers / artifact checks),
# so later passes only retry what is still missing:
#   1. chip_probe.py         — refresh the bench-grade probe record (~2 min)
#   2. step_scan_probe.py    — dispatch-vs-compute attribution (~4 min)
#   3. bench spc=8 child     — does scan-per-dispatch beat 59.07? (~2 min)
#   4. chip_overlap.sh       — hardware overlap criterion (tag-resumable,
#                              15-30 min; baseline tag already recorded)
# Exits when the overlap sweep has all three tags or after MAX probes.
cd "$(dirname "$0")/.." || exit 1
R=experiments/results
LOG=$R/window_watcher.log
OUT=$R/chip_overlap.jsonl
START_TS=$(date +%s)
echo "$(date +%T) window_watcher v3 start" >>"$LOG"
SLEEP=120
LOOPS=0
done_tags() {
    local c
    c=$(grep -c '"summary"' "$OUT" 2>/dev/null) || c=0
    echo "$c"
}
fresh() { # $1=path — exists and newer than watcher start
    [ -f "$1" ] && [ "$(stat -c %Y "$1" 2>/dev/null || echo 0)" -ge "$START_TS" ]
}
while [ "$LOOPS" -lt 60 ]; do
    LOOPS=$((LOOPS + 1))
    if timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$(date +%T) chip ALIVE -> window agenda" >>"$LOG"
        if ! fresh "$R/tpu_probe_success.json"; then
            timeout 900 python experiments/chip_probe.py >>"$LOG" 2>&1
            echo "$(date +%T) probe rc=$?" >>"$LOG"
        fi
        if ! fresh "$R/step_scan_probe.json"; then
            timeout 600 python experiments/step_scan_probe.py >>"$LOG" 2>&1
            echo "$(date +%T) scan_probe rc=$?" >>"$LOG"
        fi
        if ! fresh "$R/bench_spc8.json"; then
            # Temp + mv: a later wedged pass must not truncate a banked
            # result with a stdout redirect.
            if DVC_BENCH_CHILD=1 DVC_BENCH_REMAT=0 DVC_BENCH_STEPS_PER_CALL=8 \
                timeout 400 python bench.py >"$R/.bench_spc8.tmp" 2>>"$LOG"; then
                mv "$R/.bench_spc8.tmp" "$R/bench_spc8.json"
                echo "$(date +%T) bench_spc8 banked" >>"$LOG"
            else
                echo "$(date +%T) bench_spc8 rc!=0 (kept old artifact if any)" >>"$LOG"
            fi
        fi
        if [ "$(done_tags)" -lt 3 ]; then
            bash experiments/chip_overlap.sh >>"$LOG" 2>&1
            echo "$(date +%T) chip_overlap rc=$? tags=$(done_tags)" >>"$LOG"
        fi
        if [ "$(done_tags)" -ge 3 ]; then
            break
        fi
        SLEEP=120
    else
        echo "$(date +%T) wedged; next probe in ${SLEEP}s" >>"$LOG"
        sleep "$SLEEP"
        SLEEP=$((SLEEP * 2))
        [ "$SLEEP" -gt 1800 ] && SLEEP=1800
    fi
done
echo "$(date +%T) window_watcher v3 exit (tags=$(done_tags), loops=$LOOPS)" >>"$LOG"
