#!/bin/bash
# Round-5 watcher (v4). VERDICT r4 job #1: turn the single est_mfu=0.229
# datapoint into a defended perf curve. This watcher waits for a chip
# window (exponential backoff — SIGKILLing clients mid-init is the one
# thing observed to extend wedges, so probe gently) and runs the round-5
# hardware agenda in VALUE order. Every arm is banked exactly once
# (tmp+mv with done-marker artifacts), so later passes only retry what is
# still missing; a window that closes mid-agenda loses nothing banked.
#
# Agenda (VERDICT r4 directives in parentheses):
#   1. chip_probe.py        — fresh bench-grade record + per-op flash/xla
#                             A/B (the record is the round-end fallback)
#   2. bench accum4         — effective bs=32 via grad accumulation at
#                             micro-bs 8: the larger-batch MFU arm that
#                             dodges the tunnel's large-HLO 500 (#1a)
#   3. bench e2e A/B        — flash vs xla back-to-back, same config, to
#                             root-cause the +2.5% op vs +15.6% e2e
#                             inconsistency (#3); per-op half comes from
#                             the probe's attn_ab stage in the same window
#   4. bench spc8           — dispatch amortization arm (#1b)
#   5. bench accum2         — effective bs=16 rung (#1a)
#   6. bench bf16           — bf16-params rerun (#1d)
#   7. bench remat-on       — vs the remat-off default rung → remat
#                             attribution pair (#1c)
#   8. gpt2_medium          — second model scale on chip (#5)
#   9. step_scan_probe.py   — dispatch-vs-compute attribution
#  10. chip_trace.py        — one jax.profiler trace (#1e)
#  11. chip_overlap.sh      — hardware overlap criterion, tag-resumable.
#                             PROMOTED: runs right after the probe (and
#                             retried here) per VERDICT r5 §92 — four
#                             rounds old, a short window must not starve it
cd "$(dirname "$0")/.." || exit 1
R=experiments/results
LOG=$R/window_watcher.log
OUT=$R/chip_overlap.jsonl
START_TS=$(date +%s)
echo "$(date +%T) window_watcher v4 start (round-5 agenda)" >>"$LOG"
SLEEP=120
LOOPS=0
done_tags() {
    local c
    c=$(grep -c '"summary"' "$OUT" 2>/dev/null) || c=0
    echo "$c"
}
fresh() { # $1=path — exists and newer than watcher start
    [ -f "$1" ] && [ "$(stat -c %Y "$1" 2>/dev/null || echo 0)" -ge "$START_TS" ]
}
worker_alive() { # does the persistent warm-backend worker answer a ping?
    timeout 20 python experiments/chip_probe.py ping >/dev/null 2>&1
}
ensure_worker() { # start — or kill-and-cold-restart — the warm worker
    # The worker (chip_probe.py serve) holds an initialized backend and a
    # compiled flagship step so the round-end bench.py gets a LIVE number
    # in seconds instead of a cold-start lottery. Watchdog line: a worker
    # process that exists but won't answer a ping has a wedged backend —
    # kill it hard and cold-start a fresh one in this alive window.
    if pgrep -f "chip_probe.py serve" >/dev/null 2>&1; then
        if worker_alive; then return 0; fi
        echo "$(date +%T) warm worker wedged (ping dead); killing for cold restart" >>"$LOG"
        pkill -9 -f "chip_probe.py serve" 2>/dev/null
        sleep 2
    fi
    nohup python experiments/chip_probe.py serve >>"$R/warm_worker.log" 2>&1 &
    echo "$(date +%T) warm worker (re)started pid $!" >>"$LOG"
}
bench_arm() { # $1=name $2=timeout $3...=env VAR=val pairs
    local name=$1 tmo=$2
    shift 2
    fresh "$R/bench_$name.json" && return 0
    if env DVC_BENCH_CHILD=1 "$@" \
        timeout "$tmo" python bench.py >"$R/.bench_$name.tmp" 2>>"$LOG"; then
        # Bank only a real measurement (value > 0); diagnostics stay in tmp.
        if grep -q '"status": "live"' "$R/.bench_$name.tmp"; then
            mv "$R/.bench_$name.tmp" "$R/bench_$name.json"
            echo "$(date +%T) bench_$name banked: $(tail -c 300 "$R/bench_$name.json")" >>"$LOG"
            return 0
        fi
    fi
    echo "$(date +%T) bench_$name failed (rc=$? or no live json)" >>"$LOG"
    return 1
}
while [ "$LOOPS" -lt 80 ]; do
    LOOPS=$((LOOPS + 1))
    if timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$(date +%T) chip ALIVE -> round-5 window agenda (loadavg $(cut -d' ' -f1-3 /proc/loadavg))" >>"$LOG"
        if ! fresh "$R/tpu_probe_success.json"; then
            timeout 900 python experiments/chip_probe.py >>"$LOG" 2>&1
            echo "$(date +%T) probe rc=$?" >>"$LOG"
        fi
        ensure_worker
        # Overlap criterion PROMOTED above the bench arms (VERDICT r5 §92:
        # four rounds old, last in the agenda meant every short window
        # sacrificed it — it now runs second, right after the probe).
        if [ "$(done_tags)" -lt 3 ]; then
            bash experiments/chip_overlap.sh >>"$LOG" 2>&1
            echo "$(date +%T) chip_overlap rc=$? tags=$(done_tags)" >>"$LOG"
        fi
        bench_arm accum4 420 DVC_BENCH_REMAT=0 DVC_BENCH_ACCUM=4 DVC_BENCH_CHILD_DEADLINE=400
        bench_arm ab_flash 300 DVC_BENCH_REMAT=0 DVC_ATTN_IMPL=flash DVC_BENCH_TRY_SPC=0 DVC_BENCH_CHILD_DEADLINE=280
        bench_arm ab_xla 300 DVC_BENCH_REMAT=0 DVC_ATTN_IMPL=xla DVC_BENCH_TRY_SPC=0 DVC_BENCH_CHILD_DEADLINE=280
        bench_arm spc8 400 DVC_BENCH_REMAT=0 DVC_BENCH_STEPS_PER_CALL=8 DVC_BENCH_CHILD_DEADLINE=380
        bench_arm accum2 360 DVC_BENCH_REMAT=0 DVC_BENCH_ACCUM=2 DVC_BENCH_CHILD_DEADLINE=340
        bench_arm bf16 300 DVC_BENCH_REMAT=0 DVC_BENCH_PARAM_DTYPE=bfloat16 DVC_BENCH_CHILD_DEADLINE=280
        bench_arm bf16_flash 300 DVC_BENCH_REMAT=0 DVC_BENCH_PARAM_DTYPE=bfloat16 DVC_ATTN_IMPL=flash DVC_BENCH_CHILD_DEADLINE=280
        bench_arm remat_on 300 DVC_BENCH_CHILD_DEADLINE=280
        bench_arm medium 500 DVC_BENCH_MODEL=gpt2_medium DVC_BENCH_REMAT=0 DVC_BENCH_CHILD_DEADLINE=480
        bench_arm medium_accum2 500 DVC_BENCH_MODEL=gpt2_medium DVC_BENCH_REMAT=0 DVC_BENCH_ACCUM=2 DVC_BENCH_CHILD_DEADLINE=480
        if ! fresh "$R/step_scan_probe.json"; then
            timeout 600 python experiments/step_scan_probe.py >>"$LOG" 2>&1
            echo "$(date +%T) scan_probe rc=$?" >>"$LOG"
        fi
        if ! fresh "$R/attn_sweep.json"; then
            # r5 kernel redesign (grid-streamed K/V, native-dtype MXU):
            # re-measure the per-op sweep — bf16 short-T and the long-T
            # compiles are the two things the redesign targets.
            timeout 1800 python experiments/attn_sweep.py >>"$LOG" 2>&1
            echo "$(date +%T) attn_sweep rc=$?" >>"$LOG"
        fi
        if ! fresh "$R/chip_trace.json"; then
            timeout 400 python experiments/chip_trace.py >>"$LOG" 2>&1
            echo "$(date +%T) chip_trace rc=$?" >>"$LOG"
        fi
        if [ "$(done_tags)" -lt 3 ]; then
            # Second chance within the same window if the promoted early
            # run above was cut short.
            bash experiments/chip_overlap.sh >>"$LOG" 2>&1
            echo "$(date +%T) chip_overlap retry rc=$? tags=$(done_tags)" >>"$LOG"
        fi
        if [ "$(done_tags)" -ge 3 ] && fresh "$R/bench_accum4.json" \
            && fresh "$R/bench_ab_flash.json" && fresh "$R/bench_ab_xla.json" \
            && fresh "$R/attn_sweep.json"; then
            echo "$(date +%T) full agenda banked; watcher exiting" >>"$LOG"
            break
        fi
        SLEEP=120
    else
        echo "$(date +%T) wedged; next probe in ${SLEEP}s" >>"$LOG"
        sleep "$SLEEP"
        SLEEP=$((SLEEP * 2))
        [ "$SLEEP" -gt 1800 ] && SLEEP=1800
    fi
done
echo "$(date +%T) window_watcher v4 exit (tags=$(done_tags), loops=$LOOPS)" >>"$LOG"
