#!/usr/bin/env python
"""Heterogeneous-swarm cadence A/B: step-count vs wall-clock rounds.

Two volunteers with REAL step-rate skew — the slow peer carries
DVC_STEP_DELAY_MS=120 (the heterogeneity injection hook; on a shared
localhost core batch-size spreads don't skew step rates, per-step overhead
dominates) — run the same params-mode sync workload twice:

  step      --average-every 40          (the classic cadence)
  interval  --average-interval-s 4     (absolute wall-clock boundaries)

Under the step cadence the fast peer reaches step multiples far earlier
each window and the skew GROWS cumulatively (fast finishes its 240 steps
while the slow peer is mid-run), so later rendezvous miss join_timeout and
rounds skip. Under the interval cadence both peers cross the same absolute
boundary within milliseconds for the whole overlap of their runs.
Records per-arm rounds_ok/skipped and per-peer samples/sec to
experiments/results/interval_ab.jsonl.

Run: python experiments/interval_ab.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from run_matrix import RESULTS, record, run_swarm  # noqa: E402

MODEL = ["--model", "mnist_mlp", "--model-override", "d_hidden=256"]
BASE = ["--steps", "240", "--batch-size", "16", "--lr", "0.005",
        "--join-timeout", "6", "--gather-timeout", "15"]
SLOW_DELAY_MS = "120"  # slow peer: ~8 steps/s vs the fast peer's ~25+


def arm(tag: str, cadence: list) -> dict:
    common = MODEL + BASE + ["--averaging", "sync", *cadence]
    rows = run_swarm(
        f"interval_ab/{tag}",
        [("fast", common + ["--seed", "0"]),
         ("slow", common + ["--seed", "1"])],
        timeout=600,
        slow_peer=("slow", SLOW_DELAY_MS),
    )
    agg = record(f"interval_ab_{tag}", rows)
    agg["per_peer"] = {
        pid: {"sps": round(s["samples_per_sec"], 2),
              "rounds_ok": s["rounds_ok"], "rounds_skipped": s["rounds_skipped"]}
        for pid, s, _ in rows if s
    }
    return agg


def main() -> None:
    results = {
        "step": arm("step", ["--average-every", "40"]),
        "interval": arm("interval", ["--average-interval-s", "4"]),
    }
    out = os.path.join(RESULTS, "interval_ab.jsonl")
    with open(out, "w") as fh:
        for tag, agg in results.items():
            fh.write(json.dumps({"arm": tag, **agg}) + "\n")
    for tag, agg in results.items():
        print(f"interval_ab: {tag:8s} ok {agg['rounds_ok_total']} "
              f"skipped {agg['rounds_skipped_total']} {agg['per_peer']}")


if __name__ == "__main__":
    main()
