#!/usr/bin/env python
"""Top-k sparsity warmup convergence comparison (DGC-style).

The measured 80-round codec comparison (BASELINE.md) shows topk@1%
converging behind dense — expected at that fraction, and Deep Gradient
Compression's standard remedy is a sparsity WARMUP: ship (nearly) dense
gradients for the first rounds, ramp to the aggressive fraction as training
stabilizes. Three 2-volunteer grads-mode sync swarms, 30 rounds per
volunteer each:

  dense   --wire f32
  topk    --wire topk --topk-frac 0.01
  warmup  --wire topk --topk-frac 0.01 --topk-warmup-rounds 15

Records final loss AND total WAN bytes per arm (the warmup's cost is the
denser early rounds — the honest tradeoff belongs in the artifact).

Run: python experiments/topk_warmup.py
Results: experiments/results/topk_warmup.jsonl
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from run_matrix import RESULTS, record, run_swarm  # noqa: E402

# The gpt2 proxy, not the mnist MLP: the blob task saturates to ~0 loss for
# every codec within 40 rounds, hiding the effect this experiment exists to
# show (all three arms measured 0.0000 on mnist).
MODEL = ["--model", "gpt2_small",
         "--model-override", "vocab=256", "--model-override", "max_len=32",
         "--model-override", "d_model=64", "--model-override", "n_heads=2",
         "--model-override", "n_layers=2", "--model-override", "d_ff=128"]
STEPS = 30  # grads mode: one round per step


def arm(tag: str, extra: list) -> dict:
    common = MODEL + [
        "--averaging", "sync", "--average-what", "grads",
        "--steps", str(STEPS), "--batch-size", "16", "--lr", "0.003",
        "--join-timeout", "20", "--gather-timeout", "20", *extra,
    ]
    rows = run_swarm(
        f"topk_warmup/{tag}",
        [(f"{tag}-a", common + ["--seed", "0"]),
         (f"{tag}-b", common + ["--seed", "1"])],
        timeout=420,
    )
    summaries = [s for _, s, _ in rows if s]
    agg = record(f"topk_warmup_{tag}", rows)
    agg["wan_bytes_total"] = sum(s["wan_bytes_sent"] for s in summaries)
    return agg


def main() -> None:
    results = {
        "dense": arm("dense", ["--wire", "f32"]),
        "topk": arm("topk", ["--wire", "topk", "--topk-frac", "0.01"]),
        "warmup": arm("warmup", ["--wire", "topk", "--topk-frac", "0.01",
                                 "--topk-warmup-rounds", "15"]),
    }
    out = os.path.join(RESULTS, "topk_warmup.jsonl")
    with open(out, "w") as fh:
        for tag, agg in results.items():
            fh.write(json.dumps({"arm": tag, **agg}) + "\n")
    for tag, agg in results.items():
        print(f"topk_warmup: {tag:6s} loss {agg['final_loss_mean']:.4f} "
              f"bytes {agg['wan_bytes_total'] / 1e6:.2f}MB "
              f"rounds {agg['rounds_ok_total']}")


if __name__ == "__main__":
    main()
