#!/bin/bash
# Poll the flaky axon chip; the moment a fresh process can init the backend,
# run the staged probe (experiments/chip_probe.py) which both tests the
# warm-up-ladder hypothesis and, on full success, records a bench-grade
# samples/sec number to experiments/results/tpu_probe_success.json.
#
# Background: the chip answers some fresh processes and wedges for hours at a
# time (BENCH_r01..r03 history). This watcher turns "hope bench.py catches a
# good window at round end" into "catch any good window all session".
cd /root/repo || exit 1
mkdir -p experiments/results
LOG=experiments/results/chip_watcher.log
OUT=experiments/results/tpu_probe_success.json
# A record left over from a previous round must not satisfy this round's
# loop (the workdir persists across rounds) — set it aside at startup.
[ -f "$OUT" ] && mv "$OUT" "$OUT.prev"
echo "$(date +%T) watcher start" >>"$LOG"
while [ ! -f "$OUT" ]; do
    if timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$(date +%T) chip ALIVE -> staged probe" >>"$LOG"
        timeout 900 python experiments/chip_probe.py >>"$LOG" 2>&1
        echo "$(date +%T) probe rc=$?" >>"$LOG"
    else
        echo "$(date +%T) wedged (init no answer in 150s)" >>"$LOG"
    fi
    [ -f "$OUT" ] || sleep 90
done
echo "$(date +%T) SUCCESS recorded; watcher exiting" >>"$LOG"
