#!/bin/bash
# Poll the flaky axon chip; the moment a fresh process can init the backend,
# run the staged probe (experiments/chip_probe.py) which both tests the
# warm-up-ladder hypothesis and, on full success, records a bench-grade
# samples/sec number to experiments/results/tpu_probe_success.json.
#
# Background: the chip answers some fresh processes and wedges for hours at a
# time (BENCH_r01..r03 history). This watcher turns "hope bench.py catches a
# good window at round end" into "catch any good window all session".
#
# Probe cadence backs off exponentially (4 min -> 32 min cap) while the chip
# stays wedged: the round-3 session's ONE good window came BEFORE the
# watcher existed, and 13+ hours of constant ~4-minute probe cycles — each
# of which SIGKILLs a client mid-backend-init when the timeout fires —
# never saw another. Killing a client mid-init is the one thing observed to
# EXTEND wedges (memory: axon-chip-quirks), so aggressive polling may have
# been keeping the chip down. Backoff trades detection latency (<= 32 min,
# cheap against a multi-hour window) for real recovery gaps. Any successful
# init resets the cadence to fast.
cd /root/repo || exit 1
mkdir -p experiments/results
LOG=experiments/results/chip_watcher.log
OUT=experiments/results/tpu_probe_success.json
# A record left over from a previous round must not satisfy this round's
# loop (the workdir persists across rounds) — set it aside at startup.
[ -f "$OUT" ] && mv "$OUT" "$OUT.prev"
echo "$(date +%T) watcher start (backoff mode)" >>"$LOG"
SLEEP=90
while [ ! -f "$OUT" ]; do
    if timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$(date +%T) chip ALIVE -> staged probe" >>"$LOG"
        timeout 900 python experiments/chip_probe.py >>"$LOG" 2>&1
        echo "$(date +%T) probe rc=$?" >>"$LOG"
        SLEEP=90  # chip is answering: go back to fast cadence
    else
        echo "$(date +%T) wedged (init no answer in 150s); next probe in ${SLEEP}s" >>"$LOG"
        [ -f "$OUT" ] || sleep "$SLEEP"
        SLEEP=$((SLEEP * 2))
        [ "$SLEEP" -gt 1800 ] && SLEEP=1800
        continue
    fi
    [ -f "$OUT" ] || sleep "$SLEEP"
done
echo "$(date +%T) SUCCESS recorded; watcher exiting" >>"$LOG"
