"""Leader aggregation bench: streaming tile pipeline vs materialize-then-aggregate.

The committed artifact behind the ISSUE-4 streaming-aggregation rework
(``experiments/results/aggregation_bench.json``): measures the LEADER's
peak held bytes and commit latency for the two ways of consuming a round's
contributions, at the aggregation layer (no sockets — the wire is PR 2's
job; this isolates what happens to verified chunks after the transport
hands them over):

- ``materialize`` — the pre-rework path: every peer's contribution is
  decoded into a dense f32 buffer on arrival and HELD; the deadline commit
  then either axpy-loops them (mean) or pays a second O(N·D) copy via
  ``np.stack`` for the robust estimator.
- ``streaming``   — ``swarm.agg_stream.StreamingAggregator``: each chunk
  folds on arrival (mean: straight into the O(D) accumulator; window
  methods: into the in-flight [N, tile] window, aggregated the moment all
  peers' copies of that tile are in), so the commit only closes the tail.

Chunks are fed round-robin across peers in the transport's wire order —
the arrival schedule a concurrently-pushing group actually produces.

Peak-held accounting is explicit, not sampled: the materialize arm's peak
is its held dense buffers plus the stack copy at commit; the streaming
arm's is the aggregator's own high-water tracking (result buffer included
for both arms' fairness).

Usage:
    python experiments/aggregation_bench.py          # full grid + artifact
    python experiments/aggregation_bench.py --quick  # small sanity run

The default tier-1 suite runs a small-shape smoke of this harness
(tests/test_agg_stream.py::TestAggregationBenchSmoke), so a regression in
streaming commit latency or peak-held bytes fails loudly without this
script.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import platform
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributedvolunteercomputing_tpu.ops import robust  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.agg_stream import (  # noqa: E402
    StreamingAggregator,
    TilePool,
)
from distributedvolunteercomputing_tpu import native  # noqa: E402

RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")

CHUNK_BYTES = 1 << 20  # the transport default: tiles == wire chunks


def _contributions(n_peers: int, n_elems: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    weights = rng.uniform(0.5, 2.0, n_peers).astype(np.float64)
    bufs = rng.standard_normal((n_peers, n_elems)).astype(np.float32)
    return weights, bufs


def _wire_chunks(buf: np.ndarray, chunk_bytes: int):
    """(offset, bytes) pieces exactly as the transport's chunk framing
    would deliver them (f32 wire)."""
    raw = buf.view(np.uint8)
    return [
        (off, raw[off : off + chunk_bytes].tobytes())
        for off in range(0, raw.nbytes, chunk_bytes)
    ]


def bench_materialize(
    weights: np.ndarray, bufs: np.ndarray, method: str, kw: dict, chunk_bytes: int
) -> dict:
    """The pre-rework leader: decode-and-hold per peer, aggregate at commit."""
    n_peers, n_elems = bufs.shape
    held = []
    t_start = time.perf_counter()
    for p in range(n_peers):  # arrival: decode each contribution, hold it
        chunks = _wire_chunks(bufs[p], chunk_bytes)
        dense = np.empty(n_elems, np.float32)
        raw = dense.view(np.uint8)
        for off, data in chunks:
            raw[off : off + len(data)] = np.frombuffer(data, np.uint8)
        held.append(dense)
    t_arrived = time.perf_counter()
    peak = n_peers * n_elems * 4
    if method == "mean":
        total_w = float(weights.sum())
        acc = np.zeros(n_elems, np.float32)
        for p in range(n_peers):
            native.weighted_sum_inplace(acc, held[p], float(weights[p]) / total_w)
        result = acc
        peak += n_elems * 4  # accumulator alongside the held buffers
    else:
        stack = np.stack(held)  # the second O(N·D) copy the rework removes
        result = robust.aggregate(stack, method, **kw)
        peak += n_peers * n_elems * 4 + n_elems * 4
    t_done = time.perf_counter()
    return {
        "peak_bytes_held": peak,
        "commit_s": round(t_done - t_arrived, 6),
        "wall_s": round(t_done - t_start, 6),
        "result": result,
    }


async def bench_streaming(
    weights: np.ndarray, bufs: np.ndarray, method: str, kw: dict, chunk_bytes: int
) -> dict:
    """The streaming pipeline: chunks fold as they arrive (round-robin
    across peers — the concurrent-push arrival order), commit closes the tail."""
    n_peers, n_elems = bufs.shape
    peers = [f"p{i}" for i in range(n_peers)]
    agg = StreamingAggregator(
        n_elems, peers, method, "f32", chunk_bytes,
        kw_fn=lambda n, _kw=kw: dict(_kw),
        pool=TilePool(),  # fresh pool: the bench measures THIS run's peak
    )
    sinks = [
        agg.make_sink(peers[p], float(weights[p]), n_elems * 4)
        for p in range(n_peers)
    ]
    per_peer = [_wire_chunks(bufs[p], chunk_bytes) for p in range(n_peers)]
    n_chunks = len(per_peer[0])
    t_start = time.perf_counter()
    for c in range(n_chunks):  # round-robin arrival across peers
        for p in range(n_peers):
            off, data = per_peer[p][c]
            sinks[p](off, n_elems * 4, data)
        await asyncio.sleep(0)  # let early tile jobs run, as the loop would
    for s in sinks:
        s.close(True)
    t_arrived = time.perf_counter()
    agg.freeze()
    result = await agg.finalize(peers)
    t_done = time.perf_counter()
    return {
        "peak_bytes_held": agg.peak_bytes_held,
        "commit_s": round(t_done - t_arrived, 6),
        "wall_s": round(t_done - t_start, 6),
        "tiles_early": agg.tiles_early,
        "tiles_deadline": agg.tiles_deadline,
        "agg_busy_s": round(agg.busy_s, 6),
        "result": result,
    }


async def run_config(
    n_peers: int, payload_mb: float, method: str, chunk_bytes: int = CHUNK_BYTES
) -> dict:
    n_elems = int(payload_mb * (1 << 20)) // 4
    weights, bufs = _contributions(n_peers, n_elems)
    kw = {"trim": max(1, n_peers // 4)} if method == "trimmed_mean" else {}
    mat = bench_materialize(weights, bufs, method, kw, chunk_bytes)
    stream = await bench_streaming(weights, bufs, method, kw, chunk_bytes)
    # Equivalence is part of the bench contract: a fast wrong answer banks
    # nothing.
    np.testing.assert_allclose(
        stream.pop("result"), mat.pop("result"), rtol=2e-5, atol=1e-6
    )
    return {
        "n_peers": n_peers,
        "payload_mb": payload_mb,
        "method": method,
        "materialize": mat,
        "streaming": stream,
        "ratios": {
            "peak_bytes_held": round(
                mat["peak_bytes_held"] / max(stream["peak_bytes_held"], 1), 2
            ),
            "commit_latency": round(
                mat["commit_s"] / max(stream["commit_s"], 1e-9), 2
            ),
        },
    }


async def run_bench(
    peers=(8, 16), payloads_mb=(8, 64), methods=("mean", "trimmed_mean"),
    chunk_bytes: int = CHUNK_BYTES,
) -> dict:
    rows = []
    for method in methods:
        for n_peers in peers:
            for mb in payloads_mb:
                row = await run_config(n_peers, mb, method, chunk_bytes)
                rows.append(row)
                print(
                    f"{method:12s} n={n_peers:2d} {mb:3g}MB  "
                    f"peak {row['materialize']['peak_bytes_held'] >> 20}MB -> "
                    f"{row['streaming']['peak_bytes_held'] >> 20}MB "
                    f"({row['ratios']['peak_bytes_held']}x)  "
                    f"commit {row['materialize']['commit_s'] * 1e3:.1f}ms -> "
                    f"{row['streaming']['commit_s'] * 1e3:.1f}ms "
                    f"({row['ratios']['commit_latency']}x)",
                    flush=True,
                )
    return {
        "bench": "leader_aggregation_streaming_vs_materialize",
        "host": platform.node(),
        "python": platform.python_version(),
        "unix_time": round(time.time(), 1),
        "chunk_bytes": chunk_bytes,
        "native_available": native.available(),
        "rows": rows,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true", help="small sanity run")
    ap.add_argument("--out", default=os.path.join(RESULTS, "aggregation_bench.json"))
    args = ap.parse_args()
    kw = {}
    if args.quick:
        kw = dict(peers=(4,), payloads_mb=(2,), chunk_bytes=1 << 18)
    result = asyncio.run(run_bench(**kw))
    os.makedirs(RESULTS, exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=2)
        fh.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
