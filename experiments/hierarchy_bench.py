#!/usr/bin/env python
"""Hierarchy bench: flat vs hierarchical scheduling on a two-zone WAN.

The hierarchical schedule's claim (ROADMAP item 1 / ISSUE 8): on a swarm
with locality structure — zones of volunteers on fast intra-zone links,
thin+far cross-zone links — intra-zone groups every rotation plus
cross-zone mixing every k-th rotation reach the SAME global mixing error
as the flat zone-blind grid while moving a fraction of the cross-zone
bytes, because only 1/k of rotations put gradient mass on the WAN.

Arms (both run until mixing error <= the target, so the byte comparison
is at EQUAL mixing error):

  flat — the PR-7 single-level grid (zones advertised but ignored):
         every rotation's hashed arcs span zones, so every committed
         round moves cross-zone bytes.
  hier — the two-level grid (--cross-zone-every-k): intra rotations
         never cross a zone boundary (zero cross-zone payload bytes);
         every k-th rotation runs the flat grid to mix zone means.

Cross-zone bytes are measured from the transport's per-peer counters
joined against the membership zone map (Averager.zone_traffic), i.e. the
same live accounting coord.status rolls up — not a model.

A second experiment measures BANDWIDTH-WEIGHTED LEADER ELECTION: a
4-volunteer group where one peer has a fat uplink (per-pair ChaosTransport
links) runs rounds with and without bandwidth advertisements; the
advertised arm must elect the fat peer and cut median round wall time
(every member's bulk push rides the fat edge instead of a thin one).

The two-zone WAN itself is simulated with ChaosTransport.set_link
(per-peer-pair latency + serialization bandwidth), composing with the
existing fault machinery.

Artifact: experiments/results/hierarchy_bench.json (committed).

Usage:
    python experiments/hierarchy_bench.py            # full campaign
    python experiments/hierarchy_bench.py --quick    # smaller N, looser target
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from distributedvolunteercomputing_tpu.swarm.averager import SyncAverager  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.chaos import ChaosTransport  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.matchmaking import GroupSchedule  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.membership import SwarmMembership  # noqa: E402

GROUP_TARGET = 3
TREE_ELEMS = 32_768          # 128 KiB f32 per contribution
TARGET_ERR = 5e-3            # relative global-mean deviation both arms must reach
CROSS_EVERY_K = 3
# Two-zone WAN model (bytes/s; latencies s). Cross-zone: a thin, far link
# (~64 Mbit/s, 30 ms). Intra-zone: fast and near (left unmodeled =
# localhost). The asymmetry is what the hierarchy exploits.
INTER_ZONE_LAT_S = 0.03
INTER_ZONE_BW_BPS = 8e6


async def build_node(pid, zone, *, boot, schedule, extra=None,
                     gather_timeout=10.0, join_timeout=6.0):
    t = ChaosTransport()
    dht = DHTNode(t, maintenance_interval=120.0)
    await dht.start(bootstrap=[boot] if boot else None)
    mem = SwarmMembership(
        dht, pid, ttl=30.0, extra_info={"zone": zone, **(extra or {})}
    )
    await mem.join()
    avg = SyncAverager(
        t, dht, mem,
        min_group=2, max_group=3 * GROUP_TARGET,
        join_timeout=join_timeout, gather_timeout=gather_timeout,
        group_schedule=schedule,
    )
    return {"pid": pid, "zone": zone, "t": t, "dht": dht, "mem": mem,
            "avg": avg}


async def teardown(nodes):
    for nd in nodes:
        try:
            await nd["mem"].leave()
        except Exception:
            pass
        try:
            await nd["dht"].stop()
        except Exception:
            pass
        try:
            await nd["t"].close()
        except Exception:
            pass
    ChaosTransport._partitions.clear()
    ChaosTransport._links.clear()


def _link_cross_zone(nodes, lat, bw):
    """Model every cross-zone edge as a thin, far link (both directions:
    set_link is pairwise and each endpoint applies its outbound half)."""
    for i, a in enumerate(nodes):
        for b in nodes[i + 1:]:
            if a["zone"] != b["zone"]:
                a["t"].set_link(a["t"].addr, b["t"].addr, lat, bw)


def _xz_sent(nodes):
    """Total cross-zone bytes on the wire (each byte counted once, at its
    sender), via the same zone_traffic accounting coord.status rolls up."""
    return sum(
        nd["avg"].zone_traffic()["cross_zone_bytes_sent"] for nd in nodes
    )


async def run_config(
    n: int,
    arm: str,
    *,
    group_target: int = GROUP_TARGET,
    tree_elems: int = TREE_ELEMS,
    target_err: float = TARGET_ERR,
    max_rounds: int = 15,
    cross_every_k: int = CROSS_EVERY_K,
    links: bool = True,
    inter_lat: float = INTER_ZONE_LAT_S,
    inter_bw: float = INTER_ZONE_BW_BPS,
) -> dict:
    """One (N, arm) cell, in-process: N volunteers split over two zones,
    rotations pinned per round, values adopted from committed results so
    the mixing error is the REAL protocol's, not a simulation's. Runs
    until the error hits ``target_err`` (or max_rounds) and reports
    cross-zone bytes per committed round."""
    assert arm in ("flat", "hier")
    rot_cell = {"rot": 0}
    k = cross_every_k if arm == "hier" else 0
    nodes = []
    boot = None
    try:
        for i in range(n):
            zone = "dc" if i < n // 2 else "home"
            sched = GroupSchedule(
                target_size=group_target, rotation_s=1000.0, min_size=2,
                cross_zone_every_k=k,
                clock=lambda: rot_cell["rot"] * 1000.0 + 0.5,
            )
            nd = await build_node(
                f"b{i:03d}", zone, boot=boot, schedule=sched,
            )
            if boot is None:
                boot = nd["t"].addr
            nodes.append(nd)
        if links:
            _link_cross_zone(nodes, inter_lat, inter_bw)
        for nd in nodes:
            await nd["mem"].alive_peers()  # prime snapshots + zone maps
        vals = {i: float(i) for i in range(n)}
        gmean = statistics.mean(vals.values())
        spread = max(vals.values()) - min(vals.values())
        xz0 = _xz_sent(nodes)
        dts, committed = [], 0
        err_hist = []
        t_start = time.monotonic()

        async def one(i, nd, r):
            t0 = time.monotonic()
            try:
                res = await asyncio.wait_for(
                    nd["avg"].average(
                        {"w": np.full((tree_elems,), vals[i], np.float32)},
                        round_no=r,
                    ),
                    timeout=40.0,
                )
            except Exception:
                res = None
            return time.monotonic() - t0, res

        rounds_used = 0
        for r in range(1, max_rounds + 1):
            rot_cell["rot"] = r
            rounds_used = r
            results = await asyncio.gather(
                *(one(i, nd, r) for i, nd in enumerate(nodes))
            )
            for i, (dt, res) in enumerate(results):
                dts.append(dt)
                if res is not None:
                    committed += 1
                    vals[i] = float(res["w"][0])
            err = max(abs(v - gmean) for v in vals.values()) / spread
            err_hist.append(round(err, 6))
            if err <= target_err:
                break
        wall = time.monotonic() - t_start
        xz_bytes = _xz_sent(nodes) - xz0
        levels = {}
        for nd in nodes:
            for lv, rec in nd["avg"].group_stats().get("levels", {}).items():
                agg = levels.setdefault(lv, {"rounds_ok": 0, "rounds_skipped": 0})
                agg["rounds_ok"] += rec.get("rounds_ok", 0)
                agg["rounds_skipped"] += rec.get("rounds_skipped", 0)
    finally:
        await teardown(nodes)
    dts.sort()
    return {
        "n": n, "arm": arm, "group_target": group_target,
        "tree_elems": tree_elems, "tree_bytes": tree_elems * 4,
        "cross_zone_every_k": k, "links_modeled": links,
        "target_err": target_err, "rounds_used": rounds_used,
        "mix_err_hist": err_hist, "mix_err_final": err_hist[-1],
        "node_rounds": rounds_used * n,
        "committed_node_rounds": committed,
        "commit_frac": round(committed / max(rounds_used * n, 1), 4),
        "round_s_median": round(statistics.median(dts), 4) if dts else None,
        "round_s_p90": round(dts[max(0, int(0.9 * len(dts)) - 1)], 4) if dts else None,
        "campaign_wall_s": round(wall, 2),
        "cross_zone_bytes": xz_bytes,
        "xz_bytes_per_commit": round(xz_bytes / max(committed, 1), 1),
        "levels": levels,
    }


# -- bandwidth-weighted leader election experiment ---------------------------

THIN_BW_BPS = 1e6      # home uplink (~8 Mbit/s): 1 MiB push ~ 1.05 s
FAT_BW_BPS = 1e8       # DC uplink: same push ~ 10 ms
LEADER_TREE_ELEMS = 262_144  # 1 MiB f32


async def run_leader_config(weighted: bool, rounds: int = 6) -> dict:
    """4 volunteers, one group, one FAT peer (every edge touching it is
    fast; thin-thin edges are slow). ``weighted`` advertises bw_up so the
    fat peer self-elects; unweighted falls back to smallest-id (a thin
    peer). Median round wall time is the comparison. The schedule is
    attached but never splits (target > N), so rounds run the classic
    single-group rendezvous while the per-group gauges record who led."""
    nodes = []
    boot = None
    try:
        for i in range(4):
            fat = i == 3  # ids sort v0 < v1 < v2 < v3: unweighted elects v0
            extra = {}
            if weighted:
                extra["bw_up"] = FAT_BW_BPS if fat else THIN_BW_BPS
            nd = await build_node(
                f"v{i}", "z", boot=boot,
                schedule=GroupSchedule(target_size=8, rotation_s=1000.0),
                extra=extra,
            )
            if boot is None:
                boot = nd["t"].addr
            nodes.append(nd)
        for i, a in enumerate(nodes):
            for j, b in enumerate(nodes[i + 1:], start=i + 1):
                bw = THIN_BW_BPS if (i != 3 and j != 3) else FAT_BW_BPS
                a["t"].set_link(a["t"].addr, b["t"].addr, 0.005, bw)
        for nd in nodes:
            await nd["mem"].alive_peers()  # snapshots carry the adverts
        dts = []
        for r in range(1, rounds + 1):
            t0 = time.monotonic()
            results = await asyncio.gather(
                *(
                    nd["avg"].average(
                        {"w": np.full((LEADER_TREE_ELEMS,), float(i), np.float32)},
                        round_no=r,
                    )
                    for i, nd in enumerate(nodes)
                ),
                return_exceptions=True,
            )
            dts.append(time.monotonic() - t0)
            ok = sum(1 for res in results if not isinstance(res, Exception)
                     and res is not None)
            if ok < 2:
                raise RuntimeError(f"leader arm round {r}: only {ok} commits")
        leaders = sorted(
            nd["pid"] for nd in nodes
            if nd["avg"].group_stats().get("rounds_led", 0) > 0
        )
    finally:
        await teardown(nodes)
    dts.sort()
    return {
        "weighted": weighted,
        "rounds": rounds,
        "tree_bytes": LEADER_TREE_ELEMS * 4,
        "leaders_observed": leaders,
        "round_s_median": round(statistics.median(dts), 4),
        "round_s_mean": round(statistics.mean(dts), 4),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=12)
    ap.add_argument("--group-target", type=int, default=GROUP_TARGET)
    ap.add_argument("--tree-elems", type=int, default=TREE_ELEMS)
    ap.add_argument("--target-err", type=float, default=TARGET_ERR)
    ap.add_argument("--max-rounds", type=int, default=18)
    ap.add_argument("--cross-every-k", type=int, default=CROSS_EVERY_K)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=os.path.join(
        REPO, "experiments", "results", "hierarchy_bench.json"))
    args = ap.parse_args()
    if args.quick:
        args.n, args.tree_elems, args.target_err = 8, 16_384, 5e-2

    cells = {}
    for arm in ("flat", "hier"):
        print(f"[cell] n={args.n} arm={arm}", flush=True)
        cells[arm] = asyncio.run(run_config(
            args.n, arm, group_target=args.group_target,
            tree_elems=args.tree_elems, target_err=args.target_err,
            max_rounds=args.max_rounds, cross_every_k=args.cross_every_k,
        ))
        c = cells[arm]
        print(f"[cell] -> rounds {c['rounds_used']}, err {c['mix_err_final']}, "
              f"xz B/commit {c['xz_bytes_per_commit']}, "
              f"round median {c['round_s_median']}s", flush=True)

    print("[leader] weighted vs unweighted", flush=True)
    leader = {
        "unweighted": asyncio.run(run_leader_config(False)),
        "weighted": asyncio.run(run_leader_config(True)),
    }
    for k, v in leader.items():
        print(f"[leader] {k}: median {v['round_s_median']}s "
              f"leaders {v['leaders_observed']}", flush=True)

    flat, hier = cells["flat"], cells["hier"]
    bytes_ratio = flat["xz_bytes_per_commit"] / max(
        hier["xz_bytes_per_commit"], 1.0
    )
    wall_ratio = (
        leader["weighted"]["round_s_median"]
        / max(leader["unweighted"]["round_s_median"], 1e-9)
    )
    verdict = {
        # Acceptance: >= 2x fewer cross-zone bytes per committed round at
        # equal mixing error (both arms ran to the same target).
        "xz_bytes_per_commit_flat": flat["xz_bytes_per_commit"],
        "xz_bytes_per_commit_hier": hier["xz_bytes_per_commit"],
        "xz_bytes_ratio_flat_over_hier": round(bytes_ratio, 2),
        "pass_bytes_2x": bytes_ratio >= 2.0,
        "pass_equal_error": (
            flat["mix_err_final"] <= args.target_err
            and hier["mix_err_final"] <= args.target_err
        ),
        # Bandwidth-weighted leaders: fat peer elected, round wall down.
        "leader_weighted_round_s_median": leader["weighted"]["round_s_median"],
        "leader_unweighted_round_s_median": leader["unweighted"]["round_s_median"],
        "leader_wall_ratio_weighted_over_unweighted": round(wall_ratio, 3),
        "pass_leader_elects_fat_peer": (
            leader["weighted"]["leaders_observed"] == ["v3"]
        ),
        "pass_leader_wall_reduced": wall_ratio <= 0.85,
    }
    verdict["pass"] = bool(
        verdict["pass_bytes_2x"]
        and verdict["pass_equal_error"]
        and verdict["pass_leader_elects_fat_peer"]
        and verdict["pass_leader_wall_reduced"]
    )
    result = {
        "inter_zone_lat_s": INTER_ZONE_LAT_S,
        "inter_zone_bw_bps": INTER_ZONE_BW_BPS,
        "thin_bw_bps": THIN_BW_BPS,
        "fat_bw_bps": FAT_BW_BPS,
        "host_cores": os.cpu_count(),
        "cells": cells,
        "leader": leader,
        "verdict": verdict,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(f"[done] artifact -> {args.out}")
    print(json.dumps(verdict, indent=2))
    sys.exit(0 if verdict["pass"] else 1)


if __name__ == "__main__":
    main()
