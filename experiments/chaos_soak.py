#!/usr/bin/env python
"""Chaos soak: deadline-bounded averaging vs a x10-delayed straggler,
plus the leader-FAILOVER arm (``--failover``): the sync leader killed at
each instrumented round phase, survivors committing via epoch-fenced
recovery (ISSUE 5 acceptance).

The resilience layer's proving ground (ISSUE 1 acceptance): a 4-volunteer
swarm with ONE peer delayed x10 under a seeded fault schedule must

  1. complete >= 95% of averaging rounds within the round budget via
     partial-participation (deadline) commit — measured against a BLOCKING
     baseline in the same run (deadline machinery off, same fault active);
  2. have the phi-accrual failure detector suspect (and the leader's
     policy pre-exclude) the injected straggler within 3 rounds of fault
     onset;
  3. (training phase, subprocess volunteers) still cross the target loss
     with the straggler injected.

Three phases, one process-local swarm (real localhost TCP, real DHT,
real matchmaking — the same stack tests/test_averaging.py drives):

  warmup   — all 4 healthy: policies learn tight deadlines, detectors
             learn ~1s heartbeat gaps.
  faulted  — fault onset: the straggler's outbound RPCs gain a scheduled
             delay of 10x the healthy round time (FaultSchedule, seeded)
             and its heartbeat cadence stretches x10 (a stalled peer whose
             membership record does NOT TTL-expire — the window where phi
             is the only liveness signal). Honest rounds must keep
             committing at their learned deadlines with 3/4 participants.
  blocking — same fault, deadline machinery disabled (the pre-tentpole
             behavior): every round now waits on the straggler's delayed
             push, measuring what the deadline commit saves.

Artifact: experiments/results/chaos_soak.json (committed — the numbers
quoted in docs/resilience.md come from it).

Failover arm (``--failover``, artifact experiments/results/chaos_failover.json):
a 4-volunteer swarm (+ a dedicated bootstrap node that never leads) where the
LEADER is killed — transport torn down mid-round, round task aborted — at each
of the four instrumented phases (pre_arm, mid_stream, post_partial_commit,
pre_fetch), N rounds per phase. Survivors must commit via the epoch-fenced
recovery round (>= 95%), no survivor may stall past 2x the learned deadline
(+ formation/detection overhead), and a fencing scenario proves a revived
ex-leader's stale generation-0 serve — and a stale generation-0 push to the
successor — is rejected.

Usage:
    python experiments/chaos_soak.py                  # full campaign + training
    python experiments/chaos_soak.py --quick          # short campaign, no training
    python experiments/chaos_soak.py --no-train       # campaign only
    python experiments/chaos_soak.py --failover       # leader-failover campaign
    python experiments/chaos_soak.py --failover --quick
    python experiments/chaos_soak.py --health         # training-health campaign
                                                      # (ISSUE 12: byzantine
                                                      # attribution, mass
                                                      # accounting, live mixing
                                                      # error vs direct)
    python experiments/chaos_soak.py --adaptive       # adaptive-controller
                                                      # campaign (ISSUE 15:
                                                      # closed-loop policy vs
                                                      # every fixed config
                                                      # across the scenario
                                                      # matrix)
    python experiments/chaos_soak.py --watchdog       # watchdog campaign
                                                      # (ISSUE 13: each fault
                                                      # class raises its
                                                      # matching alert, clears
                                                      # on heal, zero false
                                                      # positives on the
                                                      # control arm, doctor
                                                      # ranks the true cause)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import re
import statistics
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from distributedvolunteercomputing_tpu.swarm.averager import SyncAverager  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.chaos import (  # noqa: E402
    ChaosTransport,
    FaultSchedule,
    fault_event,
)
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.failure_detector import (  # noqa: E402
    PhiAccrualDetector,
)
from distributedvolunteercomputing_tpu.swarm.membership import (  # noqa: E402
    PEERS_KEY,
    SwarmMembership,
)
from distributedvolunteercomputing_tpu.swarm.resilience import (  # noqa: E402
    ResiliencePolicy,
)
from distributedvolunteercomputing_tpu.swarm.transport import (  # noqa: E402
    RPCError,
    Transport,
)

STRAGGLER = "v3"  # sorts last: v0 always leads

from distributedvolunteercomputing_tpu.swarm.control_plane import (  # noqa: E402
    ControlPlaneClient,
    ControlPlaneReplica,
)
from distributedvolunteercomputing_tpu.swarm.matchmaking import GroupSchedule  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.sharding import (  # noqa: E402
    ShardManager,
    ShardMap,
    shard_slice,
)


def tree_for(i: int, size: int = 2048):
    return {"w": np.full((size,), float(i), np.float32)}


async def build_swarm(seed: int, gather_timeout: float):
    """4 volunteers: v0..v2 honest (detector + policy attached), v3 the
    future straggler on a ChaosTransport driven by a seeded schedule."""
    vols = []
    boot = None
    schedule = FaultSchedule([], seed=seed)  # events injected at onset
    for i in range(4):
        pid = f"v{i}"
        if pid == STRAGGLER:
            t = ChaosTransport(schedule=schedule)
        else:
            t = Transport()
        dht = DHTNode(t)
        await dht.start(bootstrap=[boot] if boot else None)
        if boot is None:
            boot = t.addr
        fd = policy = None
        if pid != STRAGGLER:
            fd = PhiAccrualDetector(bootstrap_s=2.0)
            policy = ResiliencePolicy(
                max_deadline_s=gather_timeout, min_deadline_s=1.0,
                preexclude_misses=3, failure_detector=fd,
            )
        mem = SwarmMembership(dht, pid, ttl=3.0, failure_detector=fd)
        await mem.join()
        avg = SyncAverager(
            t, dht, mem,
            min_group=3, max_group=4,
            join_timeout=8.0, gather_timeout=gather_timeout,
            resilience=policy, failure_detector=fd,
        )
        vols.append({
            "pid": pid, "t": t, "dht": dht, "mem": mem, "avg": avg,
            "fd": fd, "policy": policy,
        })
    return vols, schedule


async def run_round(vols, r, include_straggler, timeout=60.0):
    """One synchronized round over ``vols`` (honest subset or all four);
    returns the leader's (dt, result, budget_before)."""
    players = [v for v in vols if include_straggler or v["pid"] != STRAGGLER]
    leader = vols[0]
    budget = leader["avg"]._round_budget()
    t0 = time.monotonic()
    results = await asyncio.gather(
        *(
            asyncio.wait_for(
                v["avg"].average(tree_for(i), round_no=r), timeout=timeout
            )
            for i, v in enumerate(players)
        ),
        return_exceptions=True,
    )
    dt = time.monotonic() - t0
    lead_res = results[0]
    if isinstance(lead_res, BaseException):
        lead_res = None
    return dt, lead_res, budget


async def straggler_loop(straggler, stop: asyncio.Event):
    """Free-running straggler: a stalled peer is not synchronized with the
    swarm — it keeps trying rounds on its own crawling schedule, its stale
    matchmaking announce keeps it a formation candidate, and its begin
    handler stays reachable (inbound RPCs are not delayed)."""
    r = 10_000
    while not stop.is_set():
        r += 1
        try:
            await asyncio.wait_for(
                straggler["avg"].average(tree_for(3), round_no=r), timeout=30.0
            )
        except Exception:
            pass
        try:
            await asyncio.wait_for(asyncio.shield(stop.wait()), timeout=0.2)
        except asyncio.TimeoutError:
            pass


async def campaign(args):
    gather_timeout = 12.0
    vols, schedule = await build_swarm(args.seed, gather_timeout)
    honest = [v for v in vols if v["pid"] != STRAGGLER]
    straggler = vols[3]
    leader = vols[0]
    out = {"seed": args.seed}
    try:
        # -- phase 1: healthy warmup --------------------------------------
        warm_dts = []
        for r in range(args.warmup_rounds):
            dt, res, _ = await run_round(vols, r, include_straggler=True)
            assert res is not None, f"healthy warmup round {r} failed"
            warm_dts.append(dt)
        healthy_mean = statistics.mean(warm_dts)
        healthy_p95 = sorted(warm_dts)[max(0, int(0.95 * len(warm_dts)) - 1)]
        # Round-trip overhead allowance for the within-budget accounting:
        # the budget bounds the GATHER; formation (announce + settle) rides
        # on top in every round, healthy or not.
        overhead = max(healthy_p95, 1.0)
        out["healthy"] = {
            "rounds": len(warm_dts),
            "mean_round_s": round(healthy_mean, 3),
            "p95_round_s": round(healthy_p95, 3),
            "learned_deadline_s": round(leader["policy"].round_budget(), 3),
        }
        print(f"[warmup] {len(warm_dts)} rounds, mean {healthy_mean:.2f}s, "
              f"learned deadline {leader['policy'].round_budget():.2f}s")

        # -- fault onset ---------------------------------------------------
        # The straggler becomes x10 slow: every outbound RPC gains a
        # scheduled delay of 10x the healthy round time, and its heartbeat
        # cadence stretches x10 (ttl 3 -> 30: the record stays ALIVE, so
        # the binary TTL never fires — only phi can see the stall).
        delay = 10.0 * healthy_mean
        schedule.events = [fault_event(0.0, float("inf"), "delay", delay)]
        schedule.start()
        straggler["mem"].ttl = 30.0
        # Bridge announce: the last ttl=3 record must not expire before the
        # first slow beat (10s) or honest peers would forget + re-learn.
        await straggler["dht"].store(
            PEERS_KEY, straggler["mem"]._record(), subkey=STRAGGLER, ttl=30.0
        )
        print(f"[onset] straggler delay {delay:.2f}s/call, heartbeat x10")

        # -- phase 2: faulted, deadline-bounded ---------------------------
        stop = asyncio.Event()
        strag_task = asyncio.create_task(straggler_loop(straggler, stop))
        rounds = []
        suspect_round = preexclude_round = None
        degraded_before = leader["avg"].rounds_degraded
        for r in range(args.warmup_rounds, args.warmup_rounds + args.faulted_rounds):
            # Rounds ride a training cadence, not back-to-back: the pause is
            # the local-compute window between averaging points.
            await asyncio.sleep(args.round_cadence_s)
            dt, res, budget = await run_round(vols, r, include_straggler=False)
            degraded_now = leader["avg"].rounds_degraded
            rec = {
                "round": r,
                "dt_s": round(dt, 3),
                "budget_s": round(budget, 3),
                "committed": res is not None,
                "within_budget": res is not None and dt <= budget + overhead,
                "degraded_commit": degraded_now > degraded_before,
                "preexcluded": list(leader["avg"].matchmaker.last_preexcluded),
                "phi": round(min(leader["fd"].phi(STRAGGLER), 99.0), 2),
            }
            degraded_before = degraded_now
            idx = len(rounds)
            if suspect_round is None and leader["fd"].suspect(STRAGGLER):
                suspect_round = idx + 1  # 1-based: "within N rounds of onset"
            if preexclude_round is None and rec["preexcluded"] == [STRAGGLER]:
                preexclude_round = idx + 1
            rounds.append(rec)
        stop.set()
        strag_task.cancel()
        try:
            await strag_task
        except (asyncio.CancelledError, Exception):
            pass
        committed = [r for r in rounds if r["committed"]]
        within = [r for r in rounds if r["within_budget"]]
        out["faulted_deadline"] = {
            "rounds": len(rounds),
            "committed": len(committed),
            "within_budget": len(within),
            "within_budget_frac": round(len(within) / len(rounds), 4),
            "degraded_commits": sum(r["degraded_commit"] for r in rounds),
            "mean_round_s": round(
                statistics.mean(r["dt_s"] for r in rounds), 3
            ),
            "overhead_allowance_s": round(overhead, 3),
            "detector_suspect_after_rounds": suspect_round,
            "leader_preexcludes_after_rounds": preexclude_round,
            "straggler_phi_final": rounds[-1]["phi"],
            "per_round": rounds,
        }
        print(f"[faulted/deadline] {len(within)}/{len(rounds)} within budget "
              f"({100.0 * len(within) / len(rounds):.1f}%), straggler "
              f"suspected after {suspect_round} round(s), pre-excluded "
              f"after {preexclude_round} round(s)")

        # -- phase 3: faulted, BLOCKING baseline --------------------------
        # Deadline machinery off (the pre-tentpole behavior): rounds wait
        # for the straggler's delayed push up to the full gather budget.
        for v in vols:
            v["avg"].resilience = None
            v["avg"].round_deadline_s = None
            v["avg"].matchmaker.exclude = None
        blocking = []
        base = args.warmup_rounds + args.faulted_rounds
        for r in range(base, base + args.blocking_rounds):
            dt, res, _ = await run_round(
                vols, r, include_straggler=True,
                timeout=3.0 * gather_timeout + 3.0 * delay,
            )
            blocking.append({
                "round": r, "dt_s": round(dt, 3), "committed": res is not None,
            })
        mean_blocking = statistics.mean(b["dt_s"] for b in blocking)
        out["faulted_blocking"] = {
            "rounds": len(blocking),
            "mean_round_s": round(mean_blocking, 3),
            "per_round": blocking,
        }
        mean_deadline = out["faulted_deadline"]["mean_round_s"]
        out["round_time_ratio_blocking_over_deadline"] = round(
            mean_blocking / max(mean_deadline, 1e-9), 2
        )
        print(f"[faulted/blocking] mean round {mean_blocking:.2f}s vs "
              f"deadline-bounded {mean_deadline:.2f}s "
              f"({out['round_time_ratio_blocking_over_deadline']}x)")
        out["flight_recorders"] = _flight_dumps(vols)
    finally:
        for v in vols:
            try:
                await v["mem"].leave()
            except Exception:
                pass
            try:
                await v["dht"].stop()
            except Exception:
                pass
            await v["t"].close()
    return out


# -- leader-failover campaign (ISSUE 5 acceptance) -------------------------

PHASES = ("pre_arm", "mid_stream", "post_partial_commit", "pre_fetch")


async def build_failover_swarm(gather_timeout: float):
    """Bootstrap node (bare DHT, never averages — killing the leader must
    not take the rendezvous down with it) + 4 volunteers with detector and
    policy attached, mirroring --resilience production wiring. v0 sorts
    first and leads every round it joins."""
    boot_t = Transport()
    boot_dht = DHTNode(boot_t)
    await boot_dht.start(bootstrap=None)
    vols = []
    for i in range(4):
        pid = f"v{i}"
        t = Transport()
        dht = DHTNode(t)
        await dht.start(bootstrap=[boot_t.addr])
        fd = PhiAccrualDetector(bootstrap_s=2.0)
        policy = ResiliencePolicy(
            max_deadline_s=gather_timeout, min_deadline_s=1.0,
            preexclude_misses=3, failure_detector=fd,
        )
        mem = SwarmMembership(dht, pid, ttl=10.0, failure_detector=fd)
        await mem.join()
        avg = SyncAverager(
            t, dht, mem,
            min_group=2, max_group=4,
            join_timeout=8.0, gather_timeout=gather_timeout,
            resilience=policy, failure_detector=fd,
        )
        vols.append({
            "pid": pid, "t": t, "dht": dht, "mem": mem, "avg": avg,
            "fd": fd, "policy": policy,
        })
    return (boot_t, boot_dht), vols


def _install_kill(vol, phase):
    async def die():
        await vol["t"].close()
        raise RuntimeError("chaos: leader killed")

    vol["avg"]._phase_hooks[phase] = die


async def _revive_leader(vols):
    """Bring v0 back for the next kill round: transport re-opened on the
    same port, stale round state discarded, and — campaign-only — the
    survivors' deposition strikes cleared so v0 is handed the lead again
    (in production the DEPOSED_LEADER_TTL_S strike is exactly what this
    campaign must bypass to kill the same leader 20 times)."""
    leader = vols[0]
    leader["avg"]._phase_hooks.clear()
    for st in leader["avg"]._rounds.values():
        if st.stream is not None:
            st.stream.fence()
    leader["avg"]._rounds.clear()
    await leader["t"].start()
    await leader["mem"].join()  # immediate re-announce
    for v in vols[1:]:
        v["avg"]._deposed_leaders.pop("v0", None)
        v["fd"]._failed.pop("v0", None)
        v["policy"].peers.pop("v0", None)


async def _timed_average(v, i, r):
    t0 = time.monotonic()
    try:
        res = await asyncio.wait_for(
            v["avg"].average(tree_for(i), round_no=r), timeout=90.0
        )
    except BaseException as e:  # noqa: BLE001 — campaign records, never raises
        return time.monotonic() - t0, e
    return time.monotonic() - t0, res


def _flight_dumps(vols, max_events: int = 200) -> dict:
    """Per-volunteer flight-recorder dumps (swarm/telemetry.py) attached to
    every campaign artifact: a failed verdict ships its own post-mortem —
    depositions, fence rejections, degrades, backoff transitions — instead
    of asking the operator to reproduce the run with more logging."""
    out = {}
    for v in vols:
        avg = v.get("avg")
        if avg is None or getattr(avg, "telemetry", None) is None:
            continue
        events = avg.telemetry.recorder.dump()
        out[v["pid"]] = events[-max_events:]
    return out


async def failover_campaign(args):
    gather_timeout = 8.0
    out = {
        "seed": args.seed,
        "rounds_per_phase": args.failover_rounds,
        "phases": {},
    }
    for phase in PHASES:
        boot, vols = await build_failover_swarm(gather_timeout)
        recs = []
        try:
            # Healthy warmup: learn deadlines + formation overhead.
            warm_dts = []
            for r in range(2):
                dts = await asyncio.gather(
                    *(_timed_average(v, i, r) for i, v in enumerate(vols))
                )
                assert all(
                    not isinstance(res, BaseException) and res is not None
                    for _, res in dts
                ), f"healthy warmup round {r} failed in phase {phase}"
                warm_dts.append(max(dt for dt, _ in dts))
            # Formation + deposition-detection allowance on top of the
            # 2x-deadline stall bound: matchmaking settle/fan-out rides in
            # every round, and a follower waits RECOVERY_BEGIN_WAIT_S for
            # the successor's begin in the worst case.
            overhead = max(max(warm_dts), 1.0) + SyncAverager.RECOVERY_BEGIN_WAIT_S
            for k in range(args.failover_rounds):
                r = 100 + k
                budget = vols[1]["avg"]._round_budget()
                rec_before = [v["avg"].rounds_recovered for v in vols[1:]]
                _install_kill(vols[0], phase)
                results = await asyncio.gather(
                    *(_timed_average(v, i, r) for i, v in enumerate(vols))
                )
                surv = results[1:]
                surv_ok = [
                    res is not None and not isinstance(res, BaseException)
                    for _, res in surv
                ]
                recovered = [
                    v["avg"].rounds_recovered - b
                    for v, b in zip(vols[1:], rec_before)
                ]
                max_dt = max(dt for dt, _ in surv)
                recs.append({
                    "round": k,
                    "budget_s": round(budget, 3),
                    "survivors_committed": sum(surv_ok),
                    "recovered": sum(1 for x in recovered if x > 0),
                    "committed_via_recovery": all(surv_ok)
                    and all(x > 0 for x in recovered),
                    "max_survivor_dt_s": round(max_dt, 3),
                    "within_stall_bound": max_dt <= 2.0 * budget + overhead,
                })
                await _revive_leader(vols)
                await asyncio.sleep(0.3)  # let the re-announce settle
            flight = _flight_dumps(vols)
        finally:
            for v in vols:
                try:
                    await v["mem"].leave()
                except Exception:
                    pass
                try:
                    await v["dht"].stop()
                except Exception:
                    pass
                try:
                    await v["t"].close()
                except Exception:
                    pass
            try:
                await boot[1].stop()
            except Exception:
                pass
            await boot[0].close()
        ok = [r for r in recs if r["committed_via_recovery"]]
        within = [r for r in recs if r["within_stall_bound"]]
        out["phases"][phase] = {
            "rounds": len(recs),
            "committed_via_recovery": len(ok),
            "recovery_frac": round(len(ok) / max(len(recs), 1), 4),
            "within_stall_bound": len(within),
            "overhead_allowance_s": round(overhead, 3),
            "per_round": recs,
            # Post-mortem evidence: every survivor's flight-recorder ring
            # (leader_deposed / round_recovered / fence_rejected events).
            "flight_recorders": flight,
        }
        print(f"[failover/{phase}] {len(ok)}/{len(recs)} rounds committed "
              f"via recovery, {len(within)}/{len(recs)} within stall bound")

    out["fencing"] = await fencing_scenario()
    print(f"[failover/fencing] stale serve rejected: "
          f"{out['fencing']['stale_serve_rejected']}, stale push rejected: "
          f"{out['fencing']['stale_push_rejected']}")
    return out


async def fencing_scenario():
    """The acceptance fencing proof: leader becomes unreachable mid-round
    (process alive — it commits a stale generation-0 round), survivors
    recover at generation 1, the ex-leader heals, and both its stale SERVE
    and a stale generation-0 PUSH to the successor are rejected."""
    boot, vols = await build_failover_swarm(8.0)
    res = {
        "survivors_recovered": False,
        "stale_serve_rejected": False,
        "stale_push_rejected": False,
    }
    try:
        leader = vols[0]

        async def sever():
            await leader["t"].close()  # unreachable, NOT killed

        leader["avg"]._phase_hooks["mid_stream"] = sever
        results = await asyncio.gather(
            *(_timed_average(v, i, 1) for i, v in enumerate(vols))
        )
        res["survivors_recovered"] = all(
            r is not None and not isinstance(r, BaseException)
            for _, r in results[1:]
        ) and all(v["avg"].rounds_recovered >= 1 for v in vols[1:])
        await leader["t"].start()  # heal
        stale = [e for e, st in leader["avg"]._rounds.items() if st.gen == 0]
        successor = vols[1]
        cur = [e for e, st in successor["avg"]._rounds.items() if st.gen == 1]
        if stale:
            try:
                await vols[2]["t"].call(
                    leader["t"].addr, "sync.fetch",
                    {"epoch": stale[0], "fence": 1}, timeout=10.0,
                )
            except RPCError as e:
                res["stale_serve_rejected"] = "fencing mismatch" in str(e)
        if cur:
            try:
                await vols[2]["t"].call(
                    successor["t"].addr, "sync.contribute",
                    {"epoch": cur[0], "fence": 0, "peer": "v2", "weight": 1.0,
                     "token": "stale", "schema": successor["avg"]._schema},
                    b"\x00" * 8, timeout=10.0,
                )
            except RPCError as e:
                res["stale_push_rejected"] = "fencing mismatch" in str(e)
    finally:
        # The fencing proof's own post-mortem: the successor's recorder
        # shows the fence_rejected events the assertions above rode on.
        res["flight_recorders"] = _flight_dumps(vols)
        for v in vols:
            try:
                await v["mem"].leave()
            except Exception:
                pass
            try:
                await v["dht"].stop()
            except Exception:
                pass
            try:
                await v["t"].close()
            except Exception:
                pass
        try:
            await boot[1].stop()
        except Exception:
            pass
        await boot[0].close()
    return res


# -- multi-group campaign (ISSUE 7 acceptance) ------------------------------


def _pinned_schedule(rot_cell, target):
    """Schedule whose rotation the campaign advances explicitly (a shared
    cell instead of wall clock), so each kill round runs against a KNOWN
    partition."""
    return GroupSchedule(
        target_size=target, rotation_s=1000.0,
        clock=lambda: rot_cell["rot"] * 1000.0 + 0.5,
    )


async def _make_mg_node(pid, boot, rot_cell, target, gather_timeout):
    t = Transport()
    dht = DHTNode(t)
    await dht.start(bootstrap=[boot])
    fd = PhiAccrualDetector(bootstrap_s=2.0)
    policy = ResiliencePolicy(
        max_deadline_s=gather_timeout, min_deadline_s=1.0,
        preexclude_misses=3, failure_detector=fd,
    )
    mem = SwarmMembership(dht, pid, ttl=10.0, failure_detector=fd)
    await mem.join()
    avg = SyncAverager(
        t, dht, mem,
        min_group=2, max_group=3 * target,
        join_timeout=8.0, gather_timeout=gather_timeout,
        resilience=policy, failure_detector=fd,
        group_schedule=_pinned_schedule(rot_cell, target),
    )
    return {"pid": pid, "t": t, "dht": dht, "mem": mem, "avg": avg,
            "fd": fd, "policy": policy}


def _find_rot(pids, target, start, need_big=True):
    """Next rotation whose partition has every group formable (>= 2) and —
    when a kill is planned — at least one group with >= 3 members (the
    victim's group must keep min_group survivors after the leader dies)."""
    rot = start
    while True:
        groups = GroupSchedule.partition(pids, rot, target)
        if (
            len(groups) >= 2
            and all(len(g) >= 2 for g in groups)
            and (not need_big or any(len(g) >= 3 for g in groups))
        ):
            return rot, groups
        rot += 1


async def _revive_mg(vol, vols):
    vol["avg"]._phase_hooks.clear()
    for st in vol["avg"]._rounds.values():
        if st.stream is not None:
            st.stream.fence()
    vol["avg"]._rounds.clear()
    await vol["t"].start()
    await vol["mem"].join()
    for v in vols:
        if v is vol:
            continue
        v["avg"]._deposed_leaders.pop(vol["pid"], None)
        v["fd"]._failed.pop(vol["pid"], None)
        v["policy"].peers.pop(vol["pid"], None)


async def multigroup_campaign(args):
    """Multi-group churn arm (``--multigroup``): an 8-volunteer swarm on a
    rotating 3-ish-sized group schedule. Each kill round, ONE group's
    leader dies mid-stream; the acceptance bar is that every OTHER group's
    round commits on time with zero failover activity (the kill stays
    group-local), while the victim's own survivors recover via the
    epoch-fenced failover from PR 4. A flash-crowd join burst lands
    mid-campaign and the next rotations must absorb the newcomers.
    Artifact: experiments/results/chaos_multigroup.json."""
    gather_timeout = 8.0
    target = 3
    rot_cell = {"rot": 0}
    boot_t = Transport()
    boot_dht = DHTNode(boot_t)
    await boot_dht.start(bootstrap=None)
    vols = []
    out = {"seed": args.seed, "kill_rounds": args.multigroup_rounds,
           "group_target": target, "per_round": []}
    try:
        for i in range(8):
            vols.append(await _make_mg_node(
                f"m{i}", boot_t.addr, rot_cell, target, gather_timeout
            ))
        pid_of = {v["pid"]: v for v in vols}

        # Healthy warmup: learn deadlines + formation overhead, prove the
        # schedule itself commits.
        warm_dts = []
        rot = 1
        for r in range(2):
            rot, _ = _find_rot([v["pid"] for v in vols], target, rot,
                               need_big=False)
            rot_cell["rot"] = rot
            results = await asyncio.gather(
                *(_timed_average(v, i, r) for i, v in enumerate(vols))
            )
            assert all(
                res is not None and not isinstance(res, BaseException)
                for _, res in results
            ), f"healthy multigroup warmup round {r} failed"
            warm_dts.append(max(dt for dt, _ in results))
            rot += 1
        overhead = max(max(warm_dts), 1.0) + SyncAverager.RECOVERY_BEGIN_WAIT_S
        out["warmup_max_round_s"] = round(max(warm_dts), 3)

        burst_at = args.multigroup_rounds // 2
        joined_burst = False
        for k in range(args.multigroup_rounds):
            if k == burst_at and not joined_burst:
                # Flash crowd: 4 volunteers join between rounds; the next
                # rotation's partition includes them immediately.
                for i in range(8, 12):
                    vols.append(await _make_mg_node(
                        f"m{i}", boot_t.addr, rot_cell, target, gather_timeout
                    ))
                pid_of = {v["pid"]: v for v in vols}
                joined_burst = True
                # Newcomers are visible to the split once every volunteer's
                # membership snapshot has refreshed — one heartbeat
                # interval (ttl/3), the TTL-membership system's propagation
                # resolution. Rotating before that measures a stale-view
                # divergence the schedule already tolerates (underfilled
                # rounds), not the flash-crowd absorption being asserted.
                await asyncio.sleep(vols[0]["mem"].ttl / 3.0 + 0.7)
            pids = [v["pid"] for v in vols]
            rot, groups = _find_rot(pids, target, rot)
            rot_cell["rot"] = rot
            victim_group = next(g for g in groups if len(g) >= 3)
            victim = pid_of[min(victim_group)]  # smallest id leads its group
            others = [
                v for v in vols
                if v["pid"] not in victim_group
            ]
            survivors = [
                pid_of[p] for p in victim_group if p != victim["pid"]
            ]
            budget = others[0]["avg"]._round_budget()
            before = {
                v["pid"]: (v["avg"].leaders_deposed, v["avg"].rounds_recovered)
                for v in vols
            }
            _install_kill(victim, "mid_stream")
            results = await asyncio.gather(
                *(_timed_average(v, i, 100 + k) for i, v in enumerate(vols))
            )
            by_pid = {v["pid"]: res for v, res in zip(vols, results)}
            other_ok = [
                by_pid[v["pid"]][1] is not None
                and not isinstance(by_pid[v["pid"]][1], BaseException)
                for v in others
            ]
            other_max_dt = max(by_pid[v["pid"]][0] for v in others)
            other_failover_clean = all(
                (v["avg"].leaders_deposed, v["avg"].rounds_recovered)
                == before[v["pid"]]
                for v in others
            )
            surv_recovered = sum(
                v["avg"].rounds_recovered > before[v["pid"]][1]
                for v in survivors
            )
            out["per_round"].append({
                "round": k,
                "rot": rot,
                "n_groups": len(groups),
                "victim": victim["pid"],
                "victim_group_size": len(victim_group),
                "others_committed": sum(other_ok),
                "others_total": len(others),
                "others_all_committed": all(other_ok),
                "others_max_dt_s": round(other_max_dt, 3),
                "others_within_budget": other_max_dt <= budget + overhead,
                "others_failover_clean": other_failover_clean,
                "survivors_recovered": surv_recovered,
                "survivors_total": len(survivors),
                "after_join_burst": joined_burst,
            })
            await _revive_mg(victim, vols)
            await asyncio.sleep(0.3)
            rot += 1

        recs = out["per_round"]
        out["verdict_inputs"] = {
            "others_unaffected_rounds": sum(
                r["others_all_committed"]
                and r["others_within_budget"]
                and r["others_failover_clean"]
                for r in recs
            ),
            "rounds": len(recs),
            "local_recovery_rounds": sum(
                r["survivors_recovered"] > 0 for r in recs
            ),
            "burst_rounds_committed": sum(
                r["others_all_committed"] for r in recs if r["after_join_burst"]
            ),
            "burst_rounds": sum(1 for r in recs if r["after_join_burst"]),
            "max_groups_seen": max(r["n_groups"] for r in recs),
        }
        out["flight_recorders"] = _flight_dumps(vols)
    finally:
        for v in vols:
            try:
                await v["mem"].leave()
            except Exception:
                pass
            try:
                await v["dht"].stop()
            except Exception:
                pass
            try:
                await v["t"].close()
            except Exception:
                pass
        try:
            await boot_dht.stop()
        except Exception:
            pass
        await boot_t.close()
    return out


# -- swarm-sharded campaign (ISSUE 20 acceptance) ----------------------------

SHARD_SOAK_NS = "soak/params"
SHARD_SOAK_ELEMS = 4096
SHARD_SOAK_ZONES = ("dc", "eu", "home")
# Per-zone id prefixes: the first member of each pair is searched to own
# shard 0 under the zone's HRW map, and dc's "a" prefix sorts before every
# other id so the dc shard-0 holder LEADS the shard-0 trio in every round
# (leader election falls back to smallest id absent bandwidth adverts).
SHARD_SOAK_PREFIX = {"dc": ("a", "d"), "eu": ("e", "f"), "home": ("h", "i")}
SHARD_KILL_PHASES = ("pre_arm", "mid_stream", "post_partial_commit")


def _shard_soak_ids(zone, k=2):
    """Deterministic suffix search: a member pair for ``zone`` whose HRW
    map splits the two shards 1/1 with the first-prefix member on shard 0
    (HRW gives no balance guarantee for 2 members — the campaign needs a
    KNOWN victim/mate split, so it picks ids that hash into one)."""
    pa, pb = SHARD_SOAK_PREFIX[zone]
    domain = f"{zone}|{SHARD_SOAK_NS}"
    for t in range(4000):
        a, b = f"{pa}{t:03d}", f"{pb}{t:03d}"
        m = ShardMap(members=(a, b), k=k, gen=0, domain=domain)
        if m.shards_of(a) == [0] and m.shards_of(b) == [1]:
            return a, b
    raise AssertionError(f"no balanced pair for zone {zone}")


def _shard_pinned_schedule(rot_cell, target=3):
    return GroupSchedule(
        target_size=target, rotation_s=1000.0, min_size=2,
        cross_zone_every_k=1,  # every pinned rotation crosses zones
        clock=lambda: rot_cell["rot"] * 1000.0 + 0.5,
    )


async def _make_shard_node(pid, zone, boot, rot_cell, gather_timeout):
    t = Transport()
    dht = DHTNode(t)
    await dht.start(bootstrap=[boot] if boot else None)
    fd = PhiAccrualDetector(bootstrap_s=2.0)
    policy = ResiliencePolicy(
        max_deadline_s=gather_timeout, min_deadline_s=1.0,
        preexclude_misses=3, failure_detector=fd,
    )
    mem = SwarmMembership(
        dht, pid, ttl=10.0, failure_detector=fd, extra_info={"zone": zone}
    )
    await mem.join()
    sm = ShardManager(
        t, dht, mem, pid,
        n_elems=SHARD_SOAK_ELEMS, k=2,
        namespace=SHARD_SOAK_NS, zone=zone, resilience=policy,
    )
    avg = SyncAverager(
        t, dht, mem,
        min_group=2, max_group=6,
        join_timeout=8.0, gather_timeout=gather_timeout,
        resilience=policy, failure_detector=fd,
        group_schedule=_shard_pinned_schedule(rot_cell),
        shard_manager=sm,
    )
    return {"pid": pid, "zone": zone, "t": t, "dht": dht, "mem": mem,
            "avg": avg, "sm": sm, "fd": fd, "policy": policy}


async def _timed_shard_average(v, value, r):
    """Round payload = this volunteer's OWN shard slice of a full-tree
    vector (the ~1/K wire contract: a sharded round never moves the whole
    tree)."""
    sm = v["sm"]
    vec = np.full((SHARD_SOAK_ELEMS,), float(value), np.float32)
    payload = {"w": shard_slice(vec, sm.ranges, sm.primary_shard())}
    t0 = time.monotonic()
    try:
        res = await asyncio.wait_for(
            v["avg"].average(payload, round_no=r), timeout=90.0
        )
    except BaseException as e:  # noqa: BLE001 — campaign records, never raises
        return time.monotonic() - t0, e
    return time.monotonic() - t0, res


async def shard_campaign(args):
    """Swarm-sharded arm (``--shard``): 3 zones x 2 shard-holders on the
    zone-sharded schedule (one cross-zone trio per shard). Each kill
    round, the shard-0 trio's LEADER (dc's shard-0 holder) dies at an
    instrumented phase — cycling the pre_arm / mid_stream /
    post_partial_commit matrix — after mutating its held shard so the
    recovery check is bytes-for-bytes meaningful. The bar, per round:

      - the shard-1 trio commits with ZERO failover activity (the loss
        stays shard-local),
      - the shard-0 survivors commit THROUGH the loss via the PR-4
        failover under shard-scoped keys, with the recovery leader's
        balanced mass report bucketing the dead holder as lost,
      - the dc mate re-shards (fenced gen+1) and recovers the victim's
        LATEST shard bytes from its runner-up replica — the no-epoch-
        restart property: post-mutation state survives, nobody falls
        back to the epoch-0 seed — with the recovery latency recorded.

    Artifact: experiments/results/chaos_shard.json."""
    gather_timeout = 8.0
    rot_cell = {"rot": 0}
    boot_t = Transport()
    boot_dht = DHTNode(boot_t)
    await boot_dht.start(bootstrap=None)
    base = np.arange(SHARD_SOAK_ELEMS, dtype=np.float32)
    vols = []
    out = {"seed": args.seed, "kill_rounds": args.shard_rounds,
           "zones": list(SHARD_SOAK_ZONES), "k": 2,
           "tree_elems": SHARD_SOAK_ELEMS, "per_round": []}
    try:
        by_zone = {}
        for zone in SHARD_SOAK_ZONES:
            pa, pb = _shard_soak_ids(zone)
            by_zone[zone] = (pa, pb)
            for pid in (pa, pb):
                vols.append(await _make_shard_node(
                    pid, zone, boot_t.addr, rot_cell, gather_timeout,
                ))
        pid_of = {v["pid"]: v for v in vols}
        victim = pid_of[by_zone["dc"][0]]
        mate = pid_of[by_zone["dc"][1]]
        survivors = [pid_of[by_zone[z][0]] for z in ("eu", "home")]
        s1_trio = [pid_of[by_zone[z][1]] for z in SHARD_SOAK_ZONES]

        # Synchronized first shard adoption: every node sees its full
        # zone pair, so the two zone-mates compute the SAME gen-0 map
        # (spawning order must not skew generations within a zone).
        for v in vols:
            await v["mem"].alive_peers()
        await asyncio.gather(*(v["sm"].reshard(recover=False) for v in vols))
        for v in vols:
            for s in v["sm"].owned():
                v["sm"].store.put(s, shard_slice(base, v["sm"].ranges, s).copy())
            await v["sm"].announce()
        # Runner-up replicas via the real fenced fetch path, and a
        # membership re-announce so the shard adverts propagate before
        # the first rotation partitions on them.
        await asyncio.gather(*(v["sm"].refresh_replicas() for v in vols))
        for v in vols:
            await v["mem"].join()
        for v in vols:
            # The shard adverts postdate the priming snapshot above — drop
            # it so the first rotation partitions on fresh records.
            v["mem"].invalidate_snapshot()
            await v["mem"].alive_peers()
        lo0, hi0 = victim["sm"].ranges[0]

        # Healthy warmup: both shard trios commit on the pinned schedule.
        rot = 1
        for r in range(2):
            rot_cell["rot"] = rot
            results = await asyncio.gather(
                *(_timed_shard_average(v, i, r) for i, v in enumerate(vols))
            )
            assert all(
                res is not None and not isinstance(res, BaseException)
                for _, res in results
            ), f"healthy sharded warmup round {r} failed"
            rot += 1
        out["warmup_rounds"] = 2

        for k in range(args.shard_rounds):
            phase = SHARD_KILL_PHASES[k % len(SHARD_KILL_PHASES)]
            rot_cell["rot"] = rot
            # Mutate the doomed holder's shard and push the change to its
            # runner-up replica (the commit-time refresh), so recovery
            # has to produce THESE bytes — not the epoch-0 seed.
            expect_s0 = base[lo0:hi0] + float(k + 1)
            victim["sm"].store.put(0, expect_s0.copy())
            await mate["sm"].refresh_replicas()
            before = {
                v["pid"]: (v["avg"].leaders_deposed, v["avg"].rounds_recovered,
                           v["avg"].rounds_ok)
                for v in vols
            }
            before_mass = {
                v["pid"]: (v["avg"].health.mass_rounds
                           if v["avg"].health is not None else 0)
                for v in vols
            }
            _install_kill(victim, phase)
            results = await asyncio.gather(
                *(_timed_shard_average(v, 100 + i, 100 + k)
                  for i, v in enumerate(vols))
            )
            by_pid = {v["pid"]: res for v, res in zip(vols, results)}
            s1_ok = [
                by_pid[v["pid"]][1] is not None
                and not isinstance(by_pid[v["pid"]][1], BaseException)
                for v in s1_trio
            ]
            s1_clean = all(
                (v["avg"].leaders_deposed, v["avg"].rounds_recovered)
                == before[v["pid"]][:2]
                for v in s1_trio
            )
            surv_ok = [
                by_pid[v["pid"]][1] is not None
                and not isinstance(by_pid[v["pid"]][1], BaseException)
                for v in survivors
            ]
            surv_recovered = sum(
                v["avg"].rounds_recovered > before[v["pid"]][1]
                for v in survivors
            )
            # The recovery leader's balanced mass report: every armed slot
            # in exactly one bucket (the sums close), the dead leader's
            # weight in a LOST bucket, and the shard rollup tagged.
            mass_balanced = lost_bucketed = False
            shard_tags = []
            for v in survivors:
                h = v["avg"].health
                if h is None or h.mass_rounds <= before_mass[v["pid"]]:
                    continue
                m = h._last_mass or {}
                total = (
                    m.get("included_weight", 0.0)
                    + m.get("recovered_weight", 0.0)
                    + m.get("excluded_weight", 0.0)
                    + m.get("aborted_weight", 0.0)
                )
                mass_balanced = (
                    abs(total - m.get("armed_weight", -1.0)) <= 2e-6
                )
                # Informational: a deposed leader never armed a slot in
                # its deposer's aggregation, so recovery rounds usually
                # have NO lost bucket (the mid-stream-abort bucketing is
                # the aggregation-level property test's job) — what this
                # path guarantees is a balanced, shard-tagged report.
                lost_bucketed = (
                    m.get("excluded_slots", 0) + m.get("aborted_slots", 0)
                ) >= 1
                shard_tags = sorted((m.get("by_shard") or {}).keys())
                break
            # Fenced re-shard + recovery on the zone mate: the victim's
            # shard must come back bytes-for-bytes at its LATEST state.
            gen_before = mate["sm"].map.gen
            rec_before = mate["sm"].recoveries
            t0 = time.monotonic()
            await mate["sm"].reshard(
                members=[mate["pid"]], reason="sigkill"
            )
            recovery_s = time.monotonic() - t0
            got = mate["sm"].store.get(0, allow_replica=False)
            recovered_equal = got is not None and np.array_equal(
                got, expect_s0
            )
            out["per_round"].append({
                "round": k,
                "rot": rot,
                "phase": phase,
                "victim": victim["pid"],
                "s1_all_committed": all(s1_ok),
                "s1_failover_clean": s1_clean,
                "s0_survivors_committed": all(surv_ok),
                "s0_survivors_recovered": surv_recovered,
                "mass_balanced": mass_balanced,
                "lost_mass_bucketed": lost_bucketed,
                "mass_shard_tags": shard_tags,
                "reshard_gen": mate["sm"].map.gen,
                "reshard_gen_bumped": mate["sm"].map.gen > gen_before,
                "shard_recoveries": mate["sm"].recoveries - rec_before,
                "shard_recovery_s": round(recovery_s, 4),
                "shard_recovered_equal": recovered_equal,
                "shard_missing_after": len(mate["sm"].missing()),
                "survivors_rounds_ok_grew": all(
                    v["avg"].rounds_ok > before[v["pid"]][2]
                    for v in survivors
                ),
            })
            # Revive the victim for the next kill round (campaign-only
            # scaffolding, like _revive_mg's deposition-strike bypass): a
            # real rebooted holder re-syncs map + bytes through its
            # maintenance autopilot; the campaign re-adopts the zone's
            # live map directly so every round measures the SAME fenced
            # kill, not a cold rejoin.
            await _revive_mg(victim, vols)
            arr = mate["sm"].store.get(0)
            await mate["sm"].reshard(
                members=[victim["pid"], mate["pid"]], reason="revive"
            )
            victim["sm"].map = mate["sm"].map
            victim["sm"].advertise()
            if arr is not None:
                victim["sm"].store.put(0, arr.copy())
            await victim["sm"].announce()
            await victim["mem"].join()
            for v in vols:
                v["mem"].invalidate_snapshot()
                await v["mem"].alive_peers()
            await asyncio.sleep(0.3)
            rot += 1

        recs = out["per_round"]
        out["verdict_inputs"] = {
            "rounds": len(recs),
            "committed_through_loss_rounds": sum(
                r["s0_survivors_committed"] and r["s0_survivors_recovered"] > 0
                for r in recs
            ),
            "shard_local_rounds": sum(
                r["s1_all_committed"] and r["s1_failover_clean"] for r in recs
            ),
            "shard_recovered_rounds": sum(
                r["shard_recovered_equal"]
                and r["reshard_gen_bumped"]
                and r["shard_missing_after"] == 0
                for r in recs
            ),
            "mass_balanced_rounds": sum(
                bool(r["mass_balanced"] and r["mass_shard_tags"])
                for r in recs
            ),
            "no_epoch_restart_rounds": sum(
                r["shard_recovered_equal"] and r["survivors_rounds_ok_grew"]
                for r in recs
            ),
            "recovery_latency_s": {
                "max": max(r["shard_recovery_s"] for r in recs),
                "mean": round(
                    statistics.mean(r["shard_recovery_s"] for r in recs), 4
                ),
            },
        }
        out["flight_recorders"] = _flight_dumps(vols)
    finally:
        for v in vols:
            try:
                await v["mem"].leave()
            except Exception:
                pass
            try:
                await v["dht"].stop()
            except Exception:
                pass
            try:
                await v["t"].close()
            except Exception:
                pass
        try:
            await boot_dht.stop()
        except Exception:
            pass
        await boot_t.close()
    return out


def shard_verdict(camp: dict) -> dict:
    vi = camp["verdict_inputs"]
    n = vi["rounds"]
    return {
        # Every kill round's shard-0 survivors committed via failover.
        "pass_rounds_commit_through_loss": (
            vi["committed_through_loss_rounds"] == n
        ),
        # Every round: fenced gen bump + the LATEST shard bytes back on
        # the zone mate with nothing missing.
        "pass_shard_recovered": vi["shard_recovered_rounds"] == n,
        # Every round's recovery leader shipped a balanced mass report
        # (buckets close on armed weight) with the per-shard rollup.
        "pass_mass_balanced": vi["mass_balanced_rounds"] == n,
        # Recovery preserved post-mutation state and the survivors' round
        # counters kept growing — nobody restarted the epoch.
        "pass_no_epoch_restart": vi["no_epoch_restart_rounds"] == n,
        # The kill stays shard-local: the other shard's trio never saw it.
        "pass_shard_local": vi["shard_local_rounds"] == n,
        "rounds": n,
        "recovery_latency_s": vi["recovery_latency_s"],
    }


# -- control-plane campaign (ISSUE 9 acceptance) ----------------------------


async def _spawn_replica(rid, boot, interval=0.5):
    t = Transport()
    d = DHTNode(t)
    await d.start(bootstrap=[boot] if boot else None)
    rep = ControlPlaneReplica(t, d, rid=rid, interval=interval)
    await rep.start()
    return {"rid": rid, "t": t, "dht": d, "rep": rep}


async def _kill_replica(r):
    """SIGKILL at the protocol level: no retire, no tombstone — the socket
    just goes away mid-service."""
    try:
        await r["rep"].stop()
    except Exception:
        pass
    try:
        await r["dht"].stop()
    except Exception:
        pass
    await r["t"].close()


async def _make_cp_vol(pid, boot, rot_cell, target, gather_timeout):
    """A multigroup volunteer wired to the replicated control plane:
    batched heartbeats through its shard-owner replica, report gauges
    riding each beat, rendezvous reads through the replica cache."""
    v = await _make_mg_node(pid, boot, rot_cell, target, gather_timeout)
    cp = ControlPlaneClient(v["t"], v["dht"], pid)
    v["mem"].control_plane = cp

    def report(v=v, pid=pid):
        return {
            "peer": pid, "step": 0, "samples_per_sec": 1.0,
            "groups": v["avg"].group_stats(),
        }

    v["mem"].report_source = report
    v["avg"].control_plane = cp
    v["avg"].matchmaker.rendezvous_get = cp.rendezvous_get
    v["cp"] = cp
    await cp.refresh(force=True)
    return v


async def controlplane_campaign(args):
    """Control-plane arm (``--controlplane``): 8 volunteers on a rotating
    group schedule, batched-heartbeating through 3 elected coordinator
    replicas. Each kill round, the ACTIVE replica (election rank 0 — the
    one owning the first key range and serving the most traffic) is
    SIGKILLed while that rotation's averaging rounds are IN FLIGHT. The
    acceptance bar: every rotation's groups keep matching and committing
    (zero missed rotations), every volunteer's next heartbeat stays
    batched (failover, not direct-DHT regression), and a COMPLETE
    coord.status (all 8 alive + multigroup rollup) is served by a
    surviving replica within one heartbeat interval of the kill.
    Artifact: experiments/results/chaos_controlplane.json."""
    gather_timeout = 8.0
    target = 3
    heartbeat_ttl = 10.0  # _make_mg_node's membership ttl
    hb_interval = heartbeat_ttl / 3.0
    rot_cell = {"rot": 0}
    boot_t = Transport()
    boot_dht = DHTNode(boot_t)
    await boot_dht.start(bootstrap=None)
    reps = []
    vols = []
    out = {
        "seed": args.seed,
        "kill_rounds": args.controlplane_rounds,
        "n_volunteers": 8,
        "n_replicas": 3,
        "heartbeat_interval_s": hb_interval,
        "per_round": [],
    }
    try:
        rep0 = ControlPlaneReplica(boot_t, boot_dht, rid="cp-r00", interval=0.5)
        await rep0.start()
        reps.append({"rid": "cp-r00", "t": boot_t, "dht": boot_dht, "rep": rep0})
        for i in (1, 2):
            reps.append(await _spawn_replica(f"cp-r{i:02d}", boot_t.addr))
        for i in range(8):
            vols.append(await _make_cp_vol(
                f"c{i}", boot_t.addr, rot_cell, target, gather_timeout
            ))
        # Beat until every volunteer's snapshot shows the full swarm: the
        # first beat round registers everyone with its shard owner, but a
        # snapshot is only complete once each replica's flush has reached
        # the DHT and the serving replicas' views refreshed (tick-paced
        # with 3 replicas) — the group schedule needs ALIGNED views
        # before the first rotation, and fixed beat counts race the ticks.
        for _ in range(30):
            for v in vols:
                await v["mem"]._beat_once()
            snaps = [
                await v["mem"].alive_peers(max_age=30.0) for v in vols
            ]
            if all(len(s) == len(vols) for s in snaps):
                break
            await asyncio.sleep(0.4)
        else:
            raise AssertionError("volunteer snapshots never converged")
        assert all(v["mem"].batched_beats >= 1 for v in vols), (
            "control-plane campaign requires batched beats from round one"
        )

        pids = [v["pid"] for v in vols]
        rot = 1
        # Healthy warmup rotations: schedule + batched control plane
        # commit together before any kill.
        for r in range(2):
            rot, _ = _find_rot(pids, target, rot, need_big=False)
            rot_cell["rot"] = rot
            results = await asyncio.gather(
                *(_timed_average(v, i, r) for i, v in enumerate(vols))
            )
            assert all(
                res is not None and not isinstance(res, BaseException)
                for _, res in results
            ), f"healthy control-plane warmup round {r} failed"
            for v in vols:
                await v["mem"]._beat_once()
            rot += 1

        next_rid = 3
        for k in range(args.controlplane_rounds):
            rot, groups = _find_rot(pids, target, rot, need_big=False)
            rot_cell["rot"] = rot
            # The ACTIVE replica = election rank 0 among the live set.
            reps.sort(key=lambda r: r["rid"])
            victim, survivors = reps[0], reps[1:]
            beats_before = {v["pid"]: v["mem"].batched_beats for v in vols}
            # Fire the rotation's rounds, then SIGKILL the active replica
            # while they are in flight.
            round_tasks = [
                asyncio.ensure_future(_timed_average(v, i, 100 + k))
                for i, v in enumerate(vols)
            ]
            await asyncio.sleep(0.15)
            t_kill = time.monotonic()
            await _kill_replica(victim)
            results = await asyncio.gather(*round_tasks)
            committed = sum(
                res is not None and not isinstance(res, BaseException)
                for _, res in results
            )
            # Every volunteer's next beat must fail over and STAY batched.
            for v in vols:
                await v["mem"]._beat_once()
            still_batched = sum(
                v["mem"].batched_beats > beats_before[v["pid"]] for v in vols
            )
            # Probe a surviving replica until it serves a COMPLETE status.
            surv_addr = survivors[0]["t"].addr
            status = None
            status_dt = None
            while time.monotonic() - t_kill < 4 * hb_interval:
                try:
                    ret, _ = await vols[0]["t"].call(
                        surv_addr, "coord.status", {},
                        timeout=3.0, connect_timeout=1.0,
                    )
                    if ret.get("n_alive", 0) >= 8:
                        status = ret
                        status_dt = time.monotonic() - t_kill
                        break
                except Exception:
                    pass
                await asyncio.sleep(0.2)
            out["per_round"].append({
                "round": k,
                "rot": rot,
                "n_groups": len(groups),
                "killed_rid": victim["rid"],
                "vols_committed": int(committed),
                "rotation_all_committed": committed == len(vols),
                "beats_failed_over_batched": int(still_batched),
                "status_failover_s": (
                    round(status_dt, 3) if status_dt is not None else None
                ),
                "status_alive": status["n_alive"] if status else None,
                "status_rollup_ok": bool(
                    status and status.get("multigroup")
                    and status["multigroup"].get("rounds_ok_total", 0) > 0
                ),
                "served_by": (
                    status["control_plane"]["rid"] if status else None
                ),
            })
            reps.remove(victim)
            # Replace the corpse (bootstrapped via a volunteer — the dead
            # replica may have been the original bootstrap node) so the
            # set stays at 3 for the next kill.
            reps.append(await _spawn_replica(
                f"cp-r{next_rid:02d}", vols[0]["t"].addr
            ))
            next_rid += 1
            rot += 1

        recs = out["per_round"]
        out["verdict_inputs"] = {
            "rounds": len(recs),
            "rotations_all_committed": sum(
                r["rotation_all_committed"] for r in recs
            ),
            "beats_all_failed_over": sum(
                r["beats_failed_over_batched"] == len(vols) for r in recs
            ),
            "status_served_rounds": sum(
                r["status_failover_s"] is not None for r in recs
            ),
            "status_within_heartbeat_rounds": sum(
                r["status_failover_s"] is not None
                and r["status_failover_s"] <= hb_interval
                for r in recs
            ),
            "max_status_failover_s": max(
                (r["status_failover_s"] for r in recs
                 if r["status_failover_s"] is not None),
                default=None,
            ),
            "rollup_ok_rounds": sum(r["status_rollup_ok"] for r in recs),
        }
        out["flight_recorders"] = _flight_dumps(vols)
    finally:
        for v in vols:
            try:
                await v["mem"].leave()
            except Exception:
                pass
            try:
                await v["dht"].stop()
            except Exception:
                pass
            try:
                await v["t"].close()
            except Exception:
                pass
        for r in reps:
            try:
                await _kill_replica(r)
            except Exception:
                pass
    return out


# -- training phase (subprocess volunteers, real entrypoints) --------------


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def training_phase(args):
    """4 real volunteers (run_volunteer.py) with --resilience, one stepping
    x10 slow (DVC_STEP_DELAY_MS): the swarm must still cross target loss."""
    coord = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "coordinator.py")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=_env(),
    )
    addr = None
    deadline = time.time() + 30
    while time.time() < deadline:
        line = coord.stdout.readline()
        m = re.match(r"COORDINATOR_READY (\S+)", line or "")
        if m:
            addr = m.group(1)
            break
    if addr is None:
        coord.kill()
        raise RuntimeError("coordinator did not become ready")
    common = [
        "--coordinator", addr, "--model", "mnist_mlp",
        "--model-override", "d_hidden=16",
        "--averaging", "sync", "--average-every", "10",
        "--batch-size", "16", "--lr", "0.01",
        "--steps", str(args.train_steps),
        "--target-loss", "1.0", "--target-mode", "record",
        "--min-group", "2", "--max-group", "4",
        "--join-timeout", "20", "--gather-timeout", "20",
        "--resilience", "--round-deadline-s", "5",
    ]
    vols = []
    try:
        for i in range(4):
            env = _env()
            if i == 3:  # the straggler steps x10 slower than its peers
                env["DVC_STEP_DELAY_MS"] = "150"
            vols.append(subprocess.Popen(
                [sys.executable, os.path.join(REPO, "run_volunteer.py"),
                 "--peer-id", f"t{i}", "--seed", str(i), *common],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=env,
            ))
        summaries = []
        for v in vols:
            out_text, _ = v.communicate(timeout=600)
            for line in out_text.splitlines():
                if line.startswith("VOLUNTEER_DONE "):
                    summaries.append(json.loads(line[len("VOLUNTEER_DONE "):]))
                    break
            else:
                raise AssertionError(f"no VOLUNTEER_DONE:\n{out_text[-3000:]}")
    finally:
        coord.kill()
        for v in vols:
            if v.poll() is None:
                v.kill()
    honest = summaries[:3]
    crossed = [s.get("target_crossed_step") for s in honest]
    return {
        "volunteers": 4,
        "straggler_step_delay_ms": 150,
        "steps": args.train_steps,
        "rounds_ok_total": sum(s.get("rounds_ok", 0) for s in summaries),
        "rounds_degraded_total": sum(
            s.get("rounds_degraded", 0) for s in summaries
        ),
        "final_losses": [round(s["final_loss"], 4) for s in summaries],
        "target_crossed_steps_honest": crossed,
        "target_crossed": all(c is not None for c in crossed),
    }


async def mesh_degrade_campaign(args):
    """Degraded-slice arm (``--mesh-degrade``, ISSUE-6 satellite): a
    bf16 swarm whose LEADER runs the on-mesh codec; mid-campaign its local
    device mesh "shrinks" (injected device failure). Every round —
    including the one the failure lands in — must COMMIT with the correct
    average, the codec must degrade to the host backend exactly once, and
    the degrade must be visible in stats. Artifact:
    experiments/results/chaos_mesh_degrade.json."""
    from distributedvolunteercomputing_tpu.ops import mesh_codec

    async def make_node(peer_id, codec, boot=None):
        t = ChaosTransport(seed=args.seed)
        dht = DHTNode(t)
        await dht.start(bootstrap=[boot] if boot else None)
        mem = SwarmMembership(dht, peer_id, ttl=10.0)
        await mem.join()
        avg = SyncAverager(
            t, dht, mem, join_timeout=4.0, gather_timeout=8.0,
            wire="bf16", mesh_codec=codec,
        )
        return t, avg

    codec_a = mesh_codec.MeshCodec(backend="mesh")
    codec_b = mesh_codec.MeshCodec(backend="host")
    ta, avg_a = await make_node("m0", codec_a)
    tb, avg_b = await make_node("m1", codec_b, boot=ta.addr)
    n_elems = 200_000  # > chunk threshold: rounds stream tile-by-tile
    rounds = max(args.mesh_degrade_rounds, 3)
    degrade_at = rounds // 2
    committed = 0
    correct = 0
    backend_log = []
    t0 = time.monotonic()
    try:
        for r in range(rounds):
            if r == degrade_at:
                codec_a.inject_failure(1)  # the slice dies HERE, mid-training
            res = await asyncio.gather(
                avg_a.average({"w": np.full((n_elems,), 1.0, np.float32)}, r),
                avg_b.average({"w": np.full((n_elems,), 3.0, np.float32)}, r),
            )
            ok = res[0] is not None and res[1] is not None
            committed += int(ok)
            if ok and np.allclose(res[0]["w"], 2.0, rtol=1e-2):
                correct += 1
            backend_log.append(codec_a.stats()["backend"])
    finally:
        await ta.close()
        await tb.close()
    stats = codec_a.stats()
    return {
        "rounds": rounds,
        "degrade_at_round": degrade_at,
        "committed": committed,
        "correct": correct,
        "wall_s": round(time.monotonic() - t0, 2),
        "backend_per_round": backend_log,
        "leader_codec": stats,
        "pass_all_committed": committed == rounds,
        "pass_all_correct": correct == rounds,
        "pass_degraded_once": stats["degraded"] and stats["fallbacks"] == 1,
        "pass_host_after_degrade": all(
            b == "host" for b in backend_log[degrade_at:]
        ),
    }


# -- training-health campaign (ISSUE 12 acceptance) --------------------------


async def _teardown_vols(vols):
    for v in vols:
        try:
            await v["mem"].leave()
        except Exception:
            pass
        try:
            await v["dht"].stop()
        except Exception:
            pass
        try:
            await v["t"].close()
        except Exception:
            pass
    ChaosTransport._partitions.clear()
    ChaosTransport._links.clear()


async def _build_health_swarm(n, *, method="trimmed_mean", min_group=3,
                              gather_timeout=10.0, round_deadline_s=None,
                              chaos_last=False, seed=0, hedge=False):
    """n volunteers with the (default-on) health probe; v0 sorts first and
    leads every round. ``chaos_last`` puts the LAST peer on a
    ChaosTransport so the campaign can delay it mid-run.

    Hedged recovery (ISSUE 14) is PINNED OFF here by default: these
    campaigns measure the deadline-DROP telemetry (lost-mass events, the
    mass_frac_drop alert, the doctor's straggler rule), which the hedger
    exists to make disappear — the --tail campaign is where it is on."""
    vols, boot = [], None
    schedule = FaultSchedule([], seed=seed)
    for i in range(n):
        pid = f"v{i}"
        t = ChaosTransport(schedule=schedule) if (chaos_last and i == n - 1) else Transport()
        dht = DHTNode(t)
        await dht.start(bootstrap=[boot] if boot else None)
        if boot is None:
            boot = t.addr
        mem = SwarmMembership(dht, pid, ttl=10.0)
        await mem.join()
        avg = SyncAverager(
            t, dht, mem, min_group=min_group, max_group=n,
            join_timeout=8.0, gather_timeout=gather_timeout,
            round_deadline_s=round_deadline_s, method=method,
            hedge=hedge,
        )
        vols.append({"pid": pid, "t": t, "dht": dht, "mem": mem, "avg": avg})
    return vols, schedule


async def _byz_attribution_phase(args):
    """One peer runs with DVC_CHAOS_CONTRIB_SCALE (the volunteer tier's
    byzantine knob: well-formed frames, values scaled by x — the case
    CRCs can't catch). The leader's quality score must flag it within
    <= 10 committed rounds with ZERO honest flags across the campaign."""
    # The same knob the subprocess volunteer tier reads
    # (Volunteer._averager_callback): this in-process campaign applies the
    # scale to the byz peer's tree by hand with identical semantics, so
    # the env var only OVERRIDES the default factor here — it is never
    # set, which would leak the fault into unrelated Volunteers.
    scale = float(os.environ.get("DVC_CHAOS_CONTRIB_SCALE") or "0") or 8.0
    n = 5
    byz = f"v{n - 1}"  # sorts last: never leads, always a member
    vols, _ = await _build_health_swarm(n, method="trimmed_mean", min_group=4)
    rounds = []
    flagged_round = None
    false_positives = set()
    try:
        committed = 0
        for r in range(args.health_rounds):
            trees = []
            for i in range(n):
                tree = tree_for(i)
                if vols[i]["pid"] == byz:
                    # Exactly Volunteer._averager_callback's env semantics:
                    # the real tree scaled by DVC_CHAOS_CONTRIB_SCALE.
                    tree = {k: v * scale for k, v in tree.items()}
                trees.append(tree)
            res = await asyncio.gather(
                *(
                    asyncio.wait_for(
                        vols[i]["avg"].average(trees[i], round_no=r), timeout=60.0
                    )
                    for i in range(n)
                ),
                return_exceptions=True,
            )
            ok = res[0] is not None and not isinstance(res[0], BaseException)
            committed += int(ok)
            lead_health = vols[0]["avg"].telemetry.health
            flagged_now = lead_health.flagged_peers()
            if flagged_round is None and byz in flagged_now:
                flagged_round = committed  # "within N committed rounds"
            for v in vols:
                for p in v["avg"].telemetry.health.flagged_peers():
                    if p != byz:
                        false_positives.add(p)
            rounds.append({
                "round": r,
                "committed": ok,
                "flagged": flagged_now,
                "byz_score": lead_health.quality_score(byz),
                "honest_scores": {
                    v["pid"]: lead_health.quality_score(v["pid"])
                    for v in vols[:-1]
                },
            })
        lead_health = vols[0]["avg"].telemetry.health
        out = {
            "contrib_scale": scale,
            "byz_peer": byz,
            "rounds": len(rounds),
            "committed_rounds": committed,
            "flagged_after_committed_rounds": flagged_round,
            "honest_false_positives": sorted(false_positives),
            "byz_score_final": lead_health.quality_score(byz),
            "leader_summary_quality": (lead_health.summary() or {}).get("quality"),
            "flag_events": vols[0]["avg"].telemetry.recorder.dump(
                kinds=["peer_quality_flagged"]
            ),
            "membership_flagged_field": vols[0]["mem"].extra_info.get(
                "health_flagged"
            ),
            "per_round": rounds,
        }
        out["flight_recorders"] = _flight_dumps(vols)
    finally:
        await _teardown_vols(vols)
    return out


async def _mass_accounting_phase(args):
    """Deadline-dropped straggler: v3's outbound RPCs gain a delay past
    the static round deadline, so the leader commits without it — the
    lost mass must show up as mass_lost_at_deadline flight events and a
    slot_committed_frac < 1, with the report balanced every round."""
    vols, schedule = await _build_health_swarm(
        4, method="mean", min_group=3, gather_timeout=8.0,
        round_deadline_s=2.0, chaos_last=True, seed=args.seed,
    )
    straggler = vols[-1]
    rounds = []
    try:
        # Healthy warmup: every slot included, frac 1.0.
        for r in range(3):
            await asyncio.gather(
                *(
                    asyncio.wait_for(
                        v["avg"].average(tree_for(i), round_no=r), timeout=60.0
                    )
                    for i, v in enumerate(vols)
                ),
                return_exceptions=True,
            )
        lead_health = vols[0]["avg"].telemetry.health
        warm_mass = (lead_health.summary() or {}).get("mass", {}).get("last")
        # Fault onset: the straggler's every outbound RPC (join included)
        # now takes 4s — inside the join window, past the 2s deadline.
        schedule.events = [fault_event(0.0, float("inf"), "delay", 4.0)]
        schedule.start()
        base = 3
        for r in range(base, base + args.health_rounds):
            res = await asyncio.gather(
                *(
                    asyncio.wait_for(
                        v["avg"].average(tree_for(i), round_no=r), timeout=60.0
                    )
                    for i, v in enumerate(vols)
                ),
                return_exceptions=True,
            )
            ok = res[0] is not None and not isinstance(res[0], BaseException)
            mass = (lead_health.summary() or {}).get("mass", {}).get("last")
            if mass:
                balanced = abs(
                    mass["included_weight"] + mass["excluded_weight"]
                    + mass["aborted_weight"] - mass["armed_weight"]
                ) < 1e-6
            else:
                balanced = None
            rounds.append({
                "round": r,
                "committed": ok,
                "mass": mass,
                "balanced": balanced,
            })
        events = vols[0]["avg"].telemetry.recorder.dump(
            kinds=["mass_lost_at_deadline"]
        )
        dropped = [
            rec for rec in rounds
            if rec["committed"] and rec["mass"]
            and rec["mass"]["slot_committed_frac"] < 1.0
        ]
        out = {
            "rounds": len(rounds),
            "warmup_mass": warm_mass,
            "dropped_rounds": len(dropped),
            "all_balanced": all(r["balanced"] for r in rounds if r["mass"]),
            "straggler_named_in_events": any(
                straggler["pid"] in (e.get("excluded") or []) for e in events
            ),
            "mass_lost_events": events[-20:],
            "per_round": rounds,
        }
        out["flight_recorders"] = _flight_dumps(vols)
    finally:
        await _teardown_vols(vols)
    return out


async def _mixing_error_phase(args, arm: str):
    """Two-zone swarm under the hierarchical schedule, rotations pinned:
    ``intra_only`` never crosses a zone boundary (cross_zone_every_k far
    beyond the campaign), ``hier`` crosses every 3rd rotation. Per
    rotation the campaign records the DIRECT relative dispersion (from
    the true per-node values — the hierarchy bench's offline criterion)
    and the SKETCH-based dispersion from each node's health monitor (what
    coord.status["health"] serves live), globally and across zone means."""
    assert arm in ("intra_only", "hier")
    from distributedvolunteercomputing_tpu.swarm import health as health_mod

    n, tree_elems, group_target = 8, 16_384, 3
    k = 10**6 if arm == "intra_only" else 3
    rot_cell = {"rot": 0}
    vols, boot = [], None
    zones = {}
    try:
        for i in range(n):
            zone = "dc" if i < n // 2 else "home"
            pid = f"b{i:03d}"
            zones[pid] = zone
            sched = GroupSchedule(
                target_size=group_target, rotation_s=1000.0, min_size=2,
                cross_zone_every_k=k,
                clock=lambda: rot_cell["rot"] * 1000.0 + 0.5,
            )
            t = ChaosTransport()
            dht = DHTNode(t, maintenance_interval=120.0)
            await dht.start(bootstrap=[boot] if boot else None)
            if boot is None:
                boot = t.addr
            mem = SwarmMembership(
                dht, pid, ttl=30.0, extra_info={"zone": zone}
            )
            await mem.join()
            avg = SyncAverager(
                t, dht, mem, min_group=2, max_group=3 * group_target,
                join_timeout=6.0, gather_timeout=10.0, group_schedule=sched,
            )
            vols.append({"pid": pid, "t": t, "dht": dht, "mem": mem,
                         "avg": avg, "zone": zone})
        for v in vols:
            await v["mem"].alive_peers()  # prime snapshots + zone maps
        vals = {i: float(i) for i in range(n)}

        def direct_disp(values):
            stack = np.stack(
                [np.full(64, val, np.float64) for val in values]
            )
            dev = stack - stack.mean(axis=0)[None, :]
            rms = float(np.sqrt((dev * dev).sum(axis=1).mean()))
            norm = float(np.sqrt((stack * stack).sum(axis=1).mean()))
            return rms / norm if norm > 0 else 0.0

        def sketch_disp(sks):
            d = health_mod.sketch_dispersion(sks)
            return d["rel"] if d else None

        # Seed every monitor with its INITIAL params so rotation-0 skips
        # still have a sketch consistent with the node's current values.
        for i, v in enumerate(vols):
            v["avg"].telemetry.health.note_sketch(
                np.full(tree_elems, vals[i], np.float32), trace="init"
            )
        history = []
        # k=3 crosses at rotations 3, 6, 9, ...: the campaign needs >= 3
        # cross rotations for the hier arm's convergence bar to be fair.
        rot_rounds = 9 if args.quick else max(12, args.health_rounds)
        for r in range(1, rot_rounds + 1):
            rot_cell["rot"] = r
            results = await asyncio.gather(
                *(
                    asyncio.wait_for(
                        v["avg"].average(
                            {"w": np.full((tree_elems,), vals[i], np.float32)},
                            round_no=r,
                        ),
                        timeout=40.0,
                    )
                    for i, v in enumerate(vols)
                ),
                return_exceptions=True,
            )
            for i, res in enumerate(results):
                if res is not None and not isinstance(res, BaseException):
                    vals[i] = float(res["w"][0])
            sketches = [
                np.asarray(
                    (v["avg"].telemetry.health.last_sketch() or {}).get("v"),
                    np.float64,
                )
                for v in vols
                if v["avg"].telemetry.health.last_sketch() is not None
            ]
            zone_vals = {
                z: [vals[i] for i in range(n) if vols[i]["zone"] == z]
                for z in ("dc", "home")
            }
            zone_sks = {}
            for v, i in zip(vols, range(n)):
                sk = v["avg"].telemetry.health.last_sketch()
                if sk is not None:
                    zone_sks.setdefault(v["zone"], []).append(
                        np.asarray(sk["v"], np.float64)
                    )
            history.append({
                "rot": r,
                "direct_rel": round(direct_disp(list(vals.values())), 6),
                "sketch_rel": round(sketch_disp(sketches) or 0.0, 6),
                "direct_cross_rel": round(direct_disp(
                    [float(np.mean(zone_vals["dc"])),
                     float(np.mean(zone_vals["home"]))]
                ), 6),
                "sketch_cross_rel": round(sketch_disp(
                    [np.stack(v).mean(axis=0) for v in zone_sks.values()]
                    if len(zone_sks) == 2 else []
                ) or 0.0, 6),
            })
    finally:
        await _teardown_vols(vols)
    return {
        "arm": arm,
        "cross_zone_every_k": k,
        "n": n,
        "rotations": len(history),
        "history": history,
        "cross_rel_first": history[0]["direct_cross_rel"],
        "cross_rel_final_direct": history[-1]["direct_cross_rel"],
        "cross_rel_final_sketch": history[-1]["sketch_cross_rel"],
    }


# Documented tolerance for sketch-vs-direct agreement: the JL projection
# at dim=64 distorts pairwise norms ~1/sqrt(2*64) per pair; averaged over
# 8 peers the dispersion estimate lands well inside 25% relative (+ a
# small absolute grace for near-converged rounds where both are ~0).
HEALTH_SKETCH_TOL_REL = 0.25
HEALTH_SKETCH_TOL_ABS = 0.02


async def health_campaign(args):
    out = {"seed": args.seed}
    print("[health/byz] 5 volunteers, one at DVC_CHAOS_CONTRIB_SCALE ...")
    out["byz_attribution"] = await _byz_attribution_phase(args)
    b = out["byz_attribution"]
    print(f"[health/byz] flagged after {b['flagged_after_committed_rounds']} "
          f"committed rounds, false positives {b['honest_false_positives']}")
    print("[health/mass] deadline-dropped straggler ...")
    out["mass_accounting"] = await _mass_accounting_phase(args)
    m = out["mass_accounting"]
    print(f"[health/mass] {m['dropped_rounds']} dropped rounds, "
          f"balanced={m['all_balanced']}, "
          f"straggler named={m['straggler_named_in_events']}")
    print("[health/mixing] two-zone sketch-vs-direct, intra_only vs k=3 ...")
    out["mixing"] = {
        "intra_only": await _mixing_error_phase(args, "intra_only"),
        "hier": await _mixing_error_phase(args, "hier"),
    }
    for arm, rec in out["mixing"].items():
        print(f"[health/mixing] {arm}: cross-zone rel "
              f"{rec['cross_rel_first']} -> {rec['cross_rel_final_direct']} "
              f"(sketch {rec['cross_rel_final_sketch']})")
    return out


def health_verdict(result: dict) -> dict:
    b = result["byz_attribution"]
    m = result["mass_accounting"]
    hier = result["mixing"]["hier"]
    intra = result["mixing"]["intra_only"]
    # Sketch trustworthiness: on every recorded rotation, the live sketch
    # dispersion tracks the direct computation within the documented
    # tolerance — in BOTH arms (converging and stalling trends).
    sketch_ok = all(
        abs(h["sketch_rel"] - h["direct_rel"])
        <= HEALTH_SKETCH_TOL_REL * h["direct_rel"] + HEALTH_SKETCH_TOL_ABS
        for rec in (hier, intra)
        for h in rec["history"]
    )
    return {
        "pass_byz_flagged_within_10": (
            b["flagged_after_committed_rounds"] is not None
            and b["flagged_after_committed_rounds"] <= 10
        ),
        "pass_zero_false_positives": not b["honest_false_positives"],
        "pass_mass_balanced": bool(m["all_balanced"]),
        "pass_mass_loss_visible": (
            m["dropped_rounds"] > 0 and m["straggler_named_in_events"]
        ),
        "pass_sketch_matches_direct": sketch_ok,
        # k=3 must converge the cross-zone dispersion; intra-only must
        # visibly fail to (the gap the cross rotations exist to close).
        "pass_hier_converges_cross_zone": (
            hier["cross_rel_final_direct"] <= 0.25 * hier["cross_rel_first"]
        ),
        "pass_intra_only_stalls_cross_zone": (
            intra["cross_rel_final_direct"] >= 0.5 * intra["cross_rel_first"]
            and intra["cross_rel_final_sketch"]
            >= 2.0 * max(hier["cross_rel_final_sketch"], 1e-6)
        ),
        "byz_flagged_after_committed_rounds": b["flagged_after_committed_rounds"],
        "sketch_tol": {
            "rel": HEALTH_SKETCH_TOL_REL, "abs": HEALTH_SKETCH_TOL_ABS,
        },
    }


# -- tail-optimal campaign (ISSUE 14 acceptance) -----------------------------
#
# Hedged per-tile recovery vs the drop-the-straggler baseline at the SAME
# static round deadline, under the heavy-tailed set_link model: the hedged
# arm must commit >= TAIL_LOST_MASS_BAR x less lost gradient mass, with
# round-wall p99 within TAIL_WALL_TOL of baseline, the mass-report buckets
# (included/recovered/excluded/aborted) summing exactly to armed mass
# every round, and the hedge decisions visible as spans + flight events in
# the attached recorder dumps.

TAIL_LOST_MASS_BAR = 1.5
TAIL_WALL_TOL = 0.10
TAIL_N_ELEMS = 16_384      # 64 KiB f32 -> 16 tiles at chunk_bytes=4096
TAIL_DEADLINE_S = 2.5

TAIL_SCENARIOS = {
    # x10 straggler: the straggler<->leader link draws a Pareto(1.3) tail
    # on its BULK transfers (min_bytes gates the draw to payload-bearing
    # calls — control RPCs ride the base latency, the classic slow-
    # uplink straggler) — the median push lands well inside the deadline,
    # the fat tail (x10 and beyond, capped where a real stack would
    # abort the flow) blows it ~1 round in 4; the hedged refetch request
    # is meta-sized and the reply rides the unshaped return path.
    "straggler_x10": dict(
        latency_s=0.15,
        jitter={
            "dist": "pareto", "scale": 2.0, "alpha": 1.3,
            "cap": 6.0, "min_bytes": 32_768,
        },
    ),
    # thin link: serialization alone (64 KiB at 24 KB/s) blows the
    # deadline deterministically; the refetch REQUEST is meta-sized (no
    # serialization term) and the straggler's response rides the
    # unshaped return path — so recovery lands where the push cannot.
    "thin_link": dict(
        latency_s=0.2, bw_bps=24_000.0,
        jitter={"dist": "lognormal", "scale": 0.15, "sigma": 0.8, "cap": 4.0},
    ),
}


async def _build_tail_swarm(n, *, hedge, seed):
    """n volunteers on ChaosTransports with 4 KiB wire chunks (16 tiles at
    the campaign payload) and a STATIC round deadline, so the hedged and
    drop arms run under identical commit times — the acceptance bar's
    'same round deadline' clause, by construction."""
    vols, boot = [], None
    for i in range(n):
        pid = f"v{i}"
        t = ChaosTransport(chunk_bytes=4096, seed=seed * 101 + i)
        dht = DHTNode(t)
        await dht.start(bootstrap=[boot] if boot else None)
        if boot is None:
            boot = t.addr
        mem = SwarmMembership(dht, pid, ttl=10.0)
        await mem.join()
        avg = SyncAverager(
            t, dht, mem, min_group=3, max_group=n, join_timeout=8.0,
            gather_timeout=10.0, round_deadline_s=TAIL_DEADLINE_S,
            method="mean", hedge=hedge,
        )
        vols.append({"pid": pid, "t": t, "dht": dht, "mem": mem, "avg": avg})
    return vols


async def _tail_arm(args, scenario, *, hedge):
    n = 4
    vols = await _build_tail_swarm(n, hedge=hedge, seed=args.seed)
    leader, straggler = vols[0], vols[-1]
    rounds = []
    try:
        # Healthy warmup (links unshaped) — the deadline is static, so
        # this just settles membership and the transport pools.
        for r in range(2):
            await asyncio.gather(
                *(
                    asyncio.wait_for(
                        v["avg"].average(
                            tree_for(i, size=TAIL_N_ELEMS), round_no=r
                        ),
                        timeout=60.0,
                    )
                    for i, v in enumerate(vols)
                ),
                return_exceptions=True,
            )
        straggler["t"].set_link(
            leader["t"].addr, straggler["t"].addr, **TAIL_SCENARIOS[scenario]
        )
        lead_health = vols[0]["avg"].telemetry.health

        async def timed_avg(v, i, r):
            t0 = time.monotonic()
            try:
                res = await asyncio.wait_for(
                    v["avg"].average(tree_for(i, size=TAIL_N_ELEMS), round_no=r),
                    timeout=60.0,
                )
            except BaseException as e:  # noqa: BLE001 — campaign bookkeeping
                return e, time.monotonic() - t0
            return res, time.monotonic() - t0

        for r in range(2, 2 + args.tail_rounds):
            mass_cursor = lead_health.mass_rounds
            res = await asyncio.gather(
                *(timed_avg(v, i, r) for i, v in enumerate(vols))
            )
            # Leader-vantage round wall: the deadline-bounded commit path
            # (the straggler's OWN wall reflects its slow link equally in
            # both arms, with per-draw variance that isn't the round's).
            wall = res[0][1]
            ok = res[0][0] is not None and not isinstance(res[0][0], BaseException)
            fresh = lead_health.mass_rounds > mass_cursor
            mass = (
                (lead_health.summary() or {}).get("mass", {}).get("last")
                if fresh else None
            )
            if mass:
                balanced = abs(
                    mass["included_weight"] + mass["recovered_weight"]
                    + mass["excluded_weight"] + mass["aborted_weight"]
                    - mass["armed_weight"]
                ) < 1e-6
                lost_slots = mass["excluded_slots"] + mass["aborted_slots"]
                recovered_slots = mass["recovered_slots"]
            else:
                # No commit this round: the whole round's mass is lost
                # (a skipped round produces nothing for anyone). Scoring
                # it as armed-slots lost keeps the arms comparable when
                # one arm rescues entire rounds the other skips.
                balanced = None
                lost_slots = n
                recovered_slots = 0
            rounds.append({
                "round": r,
                "committed": ok,
                "wall_s": round(wall, 3),
                "mass": mass,
                "balanced": balanced,
                "lost_slots": lost_slots,
                "recovered_slots": recovered_slots,
            })
        walls = sorted(r["wall_s"] for r in rounds)
        p99 = (
            walls[min(len(walls) - 1, int(round(0.99 * (len(walls) - 1))))]
            if walls else None
        )
        with_mass = [r for r in rounds if r["mass"]]
        hedge_spans = [
            s for s in vols[0]["avg"].telemetry.tracer.spans()
            if s["name"] == "hedge"
        ]
        out = {
            "hedge": hedge,
            "scenario": scenario,
            "rounds": len(rounds),
            "committed": sum(r["committed"] for r in rounds),
            "lost_slots_total": sum(r["lost_slots"] for r in rounds),
            "lost_weight_total": round(
                sum(
                    r["mass"]["excluded_weight"] + r["mass"]["aborted_weight"]
                    for r in with_mass
                ), 6,
            ),
            "recovered_slots_total": sum(r["recovered_slots"] for r in rounds),
            "recovered_weight_total": round(
                sum(r["mass"]["recovered_weight"] for r in with_mass), 6
            ),
            "all_balanced": all(r["balanced"] for r in with_mass),
            "wall_p99_s": p99,
            "hedge_stats": vols[0]["avg"].stats().get("hedge"),
            "hedge_spans": hedge_spans[-40:],
            "per_round": rounds,
        }
        out["flight_recorders"] = _flight_dumps(vols)
        return out
    finally:
        await _teardown_vols(vols)


async def tail_campaign(args):
    out = {
        "seed": args.seed,
        "deadline_s": TAIL_DEADLINE_S,
        "payload_elems": TAIL_N_ELEMS,
        "scenarios": {},
    }
    for scen in TAIL_SCENARIOS:
        print(f"[tail/{scen}] drop baseline ...")
        drop = await _tail_arm(args, scen, hedge=False)
        print(f"[tail/{scen}] hedged arm ...")
        hedged = await _tail_arm(args, scen, hedge=True)
        out["scenarios"][scen] = {"drop": drop, "hedged": hedged}
        print(
            f"[tail/{scen}] lost slots drop={drop['lost_slots_total']} "
            f"hedged={hedged['lost_slots_total']} "
            f"(recovered {hedged['recovered_slots_total']}), "
            f"wall p99 {drop['wall_p99_s']}s -> {hedged['wall_p99_s']}s"
        )
    return out


def tail_verdict(result: dict) -> dict:
    verdict = {
        "lost_mass_bar": TAIL_LOST_MASS_BAR,
        "wall_tol": TAIL_WALL_TOL,
    }
    for scen, rec in result["scenarios"].items():
        d, h = rec["drop"], rec["hedged"]
        ratio = d["lost_slots_total"] / max(h["lost_slots_total"], 1e-9)
        verdict[f"{scen}_lost_ratio"] = round(min(ratio, 999.0), 2)
        # The scenario is only meaningful if the baseline actually loses
        # mass at this deadline...
        verdict[f"pass_{scen}_baseline_loses"] = d["lost_slots_total"] > 0
        # ...and the headline bar: >= 1.5x less lost mass, same deadline.
        verdict[f"pass_{scen}_lost_mass_reduction"] = ratio >= TAIL_LOST_MASS_BAR
        verdict[f"pass_{scen}_wall_p99_within_tol"] = (
            h["wall_p99_s"] is not None
            and d["wall_p99_s"] is not None
            and h["wall_p99_s"] <= d["wall_p99_s"] * (1.0 + TAIL_WALL_TOL)
        )
        verdict[f"pass_{scen}_mass_balanced"] = bool(
            d["all_balanced"] and h["all_balanced"]
        )
        flights = h.get("flight_recorders") or {}
        verdict[f"pass_{scen}_hedge_visible"] = (
            len(h["hedge_spans"]) > 0
            and any(
                e.get("kind") == "hedge_issued"
                for evs in flights.values() for e in evs
            )
            and any(
                e.get("kind") == "mass_recovered_by_hedge"
                for evs in flights.values() for e in evs
            )
        )
    return verdict


# -- adaptive-controller campaign (ISSUE 15 acceptance) ----------------------
#
# The closed-loop controller vs EVERY fixed configuration, per scenario
# (>= 4: flash-crowd join burst, mass departure, thin/partitioned
# cross-zone WAN, heavy-tailed straggler mix), scored on committed
# gradient mass per wall second — the committed-round rate weighted by
# what each commit actually carried, so an arm that commits fast-but-
# empty (tight static deadline cutting live peers) cannot out-score one
# that commits full-but-slow (loose static deadline waiting out corpses),
# and the adaptive arm must beat BOTH. The decision trail (policy_changed
# events + evidence) must be visible in the attached flight-recorder
# dumps; the two-zone slow-WAN scenario must additionally show the
# per-level deadline split (cross > intra); and a healthy control arm
# must record ZERO policy transitions after warm-up.

from distributedvolunteercomputing_tpu.swarm import controller as controller_mod  # noqa: E402
from distributedvolunteercomputing_tpu.swarm import telemetry as telemetry_mod  # noqa: E402

ADAPT_N_ELEMS = 16_384     # 64 KiB f32 pushes -> 16 tiles at chunk_bytes=4096
ADAPT_CEIL_S = 8.0         # deadline ceiling == the loose arm's static budget
ADAPT_TIGHT_S = 1.2        # the tight arm's static budget

# The fixed configurations every scenario runs against: every policy knob
# hand-set (no resilience policy, no controller — the pre-ISSUE-15 stack
# with a static deadline; hedging stays at its static defaults, which IS
# today's fixed configuration).
ADAPT_FIXED_ARMS = {
    "fixed_tight": ADAPT_TIGHT_S,
    "fixed_loose": ADAPT_CEIL_S,
}

# Scoring: each arm free-runs its volunteers for the same measurement
# window and is scored on TWO axes — committed gradient mass per second
# (the committed-round rate weighted by what each commit carried) and the
# committed fraction of ARMED mass (quality). The verdict is a dominance
# rule, not a single scalar: the adaptive arm must out-RATE every fixed
# arm, except a fixed arm that only out-rates it by SHEDDING armed mass
# the adaptive arm kept (committed_frac more than ADAPT_FRAC_TOL below
# adaptive's) is disqualified on the quality axis — a tight static
# deadline that wins wall-clock by cutting live peers' gradients every
# round is not a configuration a training run can actually use.
ADAPT_FRAC_TOL = 0.05
ADAPT_MIN_FRAC = 0.9


async def _build_adaptive_vol(
    pid, boot, *, adaptive, deadline_s=None, zone="", sched=None,
    max_group=8, min_group=2, ttl=10.0, seed=0,
):
    t = ChaosTransport(chunk_bytes=4096, seed=seed)
    dht = DHTNode(t)
    await dht.start(bootstrap=[boot] if boot else None)
    tele = telemetry_mod.Telemetry(peer_id=pid)
    fd = policy = ctrl = None
    kw = {}
    if adaptive:
        fd = PhiAccrualDetector(bootstrap_s=2.0)
        policy = ResiliencePolicy(
            max_deadline_s=ADAPT_CEIL_S, min_deadline_s=1.0,
            preexclude_misses=3, failure_detector=fd,
        )
        ctrl = controller_mod.SwarmController(policy=policy, telemetry=tele)
    else:
        kw["round_deadline_s"] = deadline_s
    mem = SwarmMembership(
        dht, pid, ttl=ttl, failure_detector=fd,
        extra_info={"zone": zone} if zone else None,
    )
    await mem.join()
    avg = SyncAverager(
        t, dht, mem,
        min_group=min_group, max_group=max_group,
        join_timeout=4.0, gather_timeout=ADAPT_CEIL_S, method="mean",
        resilience=policy, failure_detector=fd, controller=ctrl,
        telemetry=tele, group_schedule=sched,
        **kw,
    )
    return {
        "pid": pid, "t": t, "dht": dht, "mem": mem, "avg": avg,
        "fd": fd, "policy": policy, "ctrl": ctrl, "tele": tele,
    }


def _adaptive_mass_totals(vols):
    """Scenario-cumulative gradient-mass buckets summed across every
    vantage's health counters (each group's round is counted once, by its
    leader)."""
    tot = {"included": 0.0, "recovered": 0.0, "excluded": 0.0, "aborted": 0.0}
    for v in vols:
        ctr = v["tele"].registry.counter("swarm.health.mass_weight_total")
        for oc in tot:
            tot[oc] += ctr.value(outcome=oc)
    return tot


class _VolLoop:
    """One volunteer free-running averaging rounds until stopped — the
    production shape (a trainer hitting its cadence back-to-back), so an
    arm's slow rounds directly cost it committed mass within the shared
    measurement window, with no cross-arm gather synchronization to
    launder the cost through."""

    def __init__(self, v, i):
        self.v = v
        self.i = i
        self.stop = asyncio.Event()
        self.task = None
        self.rounds = 0

    def start(self):
        self.task = asyncio.create_task(self._run())

    async def _run(self):
        r = self.i * 100_000
        while not self.stop.is_set():
            r += 1
            try:
                await asyncio.wait_for(
                    self.v["avg"].average(
                        tree_for(self.i, size=ADAPT_N_ELEMS), round_no=r
                    ),
                    timeout=30.0,
                )
            except asyncio.CancelledError:
                # Cancellation is terminal, stop flag or not: the
                # mid-round "SIGKILL" (exodus cancels the task under an
                # armed round) and asyncio.run's shutdown sweep both rely
                # on it. Swallowing it here left a corpse loop spinning
                # on a closed transport and hung the campaign's shutdown.
                raise
            except BaseException:
                if self.stop.is_set():
                    return
            self.rounds += 1
            try:
                await asyncio.wait_for(self.stop.wait(), timeout=0.05)
            except asyncio.TimeoutError:
                pass

    async def halt(self):
        self.stop.set()
        if self.task is not None:
            try:
                await asyncio.wait_for(self.task, timeout=35.0)
            except (asyncio.TimeoutError, Exception):
                self.task.cancel()
                try:
                    await self.task
                except BaseException:
                    pass


async def _adaptive_window(loops, duration_s):
    """One measurement window: mass-counter deltas over exactly
    ``duration_s`` of free running (snapshots taken at the window edges
    while the loops keep going, so every arm is scored on the same
    wall-clock denominator)."""
    vols = [lp.v for lp in loops]
    mass0 = _adaptive_mass_totals(vols)
    await asyncio.sleep(duration_s)
    mass1 = _adaptive_mass_totals(vols)
    return {oc: round(mass1[oc] - mass0[oc], 6) for oc in mass1}


def _adaptive_arm_summary(arm, vols, *, mass, window_s):
    committed = mass["included"] + mass["recovered"]
    armed = sum(mass.values())
    ctrl_vols = [v for v in vols if v["ctrl"] is not None]
    out = {
        "arm": arm,
        "window_s": round(window_s, 3),
        "mass": mass,
        "committed_weight": round(committed, 6),
        "armed_weight": round(armed, 6),
        "weight_per_s": (
            round(committed / window_s, 4) if window_s > 0 else 0.0
        ),
        "committed_frac": round(committed / armed, 4) if armed > 0 else None,
    }
    if ctrl_vols:
        out["transitions_total"] = sum(
            v["ctrl"].transitions_total for v in ctrl_vols
        )
        out["controller"] = {
            v["pid"]: v["ctrl"].summary() for v in ctrl_vols
        }
        out["deadlines"] = {
            v["pid"]: v["policy"].deadlines() for v in ctrl_vols
            if v["policy"] is not None
        }
    out["flight_recorders"] = _flight_dumps(vols)
    return out


def _policy_changed_events(arm_rec):
    return [
        e for evs in (arm_rec.get("flight_recorders") or {}).values()
        for e in evs if e.get("kind") == "policy_changed"
    ]


# The flash-crowd / heavy-tail straggler: a fat Pareto tail on bulk
# transfers (median ~x2 extra, mean ~x5, capped where a real stack would
# abort the flow); control RPCs ride the base latency.
ADAPT_STRAGGLER_LINK = dict(
    latency_s=0.15,
    jitter={
        "dist": "pareto", "scale": 2.5, "alpha": 1.1,
        "cap": 10.0, "min_bytes": 32_768,
    },
)


async def _halt_all(loops):
    for lp in loops:
        lp.stop.set()
    for lp in loops:
        await lp.halt()


async def _adaptive_flash_crowd(args, arm):
    """Scenario 1 — flash-crowd join burst: a 3-volunteer DC core joined
    mid-window by 5 newcomers on slow home links, one with a heavy Pareto
    uplink tail. A tight static deadline cuts the live newcomers' mass
    every round; a loose one waits out the straggler's tail every round;
    the adaptive arm learns a budget that fits the live crowd and lets
    its regime-floored hedger chase the tail."""
    vols = []
    boot = None
    for i in range(8):
        v = await _build_adaptive_vol(
            f"v{i}", boot, adaptive=(arm == "adaptive"),
            deadline_s=ADAPT_FIXED_ARMS.get(arm), seed=args.seed * 101 + i,
        )
        boot = boot or v["t"].addr
        vols.append(v)
    core, crowd = vols[:3], vols[3:]
    loops = [_VolLoop(v, i) for i, v in enumerate(vols)]
    try:
        for lp in loops[:3]:
            lp.start()
        await asyncio.sleep(args.adaptive_warmup_s)
        # The burst: newcomers on 0.35s / 96 KB/s home links; the last
        # one's bulk transfers draw the Pareto tail.
        for c in crowd:
            for o in core:
                c["t"].set_link(
                    c["t"].addr, o["t"].addr,
                    latency_s=0.35, bw_bps=96_000.0,
                )
        crowd[-1]["t"].set_link(
            crowd[-1]["t"].addr, core[0]["t"].addr, **ADAPT_STRAGGLER_LINK,
        )
        for lp in loops[3:]:
            lp.start()
        await asyncio.sleep(4.0)  # let the burst land before scoring
        mass = await _adaptive_window(loops, args.adaptive_window_s)
        return _adaptive_arm_summary(
            arm, vols, mass=mass, window_s=args.adaptive_window_s,
        )
    finally:
        await _halt_all(loops)
        await _teardown_vols(vols)


async def _adaptive_mass_departure(args, arm):
    """Scenario 2 — mass departure: an 8-volunteer swarm on a rotating
    target-4 schedule loses four volunteers, one every ~2.5 s, each
    SIGKILL-style MID-ROUND (transport torn down under an armed round).
    Two of the survivors sit on slow residential links — the ordinary
    WAN heterogeneity a static deadline has to price in. The long
    membership TTL keeps the corpses in everyone's expected splits for
    ~10 s, so big scheduled groups pay formation grace and deadline
    waits; the adaptive arm pre-excludes the suspects and keeps its
    learned deadline at the live swarm's speed. (Measured: that
    substrate absorbs the kills so well the survivors' failure EWMAs
    never reach the churn band — the adaptive arm wins with ZERO
    transitions, which is the hysteresis contract holding under fault;
    the decision-trail verdict therefore reads the flash-crowd /
    thin-WAN / heavy-tail arms, where a knob demonstrably moves.)"""
    vols = []
    boot = None
    for i in range(8):
        v = await _build_adaptive_vol(
            f"v{i}", boot, adaptive=(arm == "adaptive"),
            deadline_s=ADAPT_FIXED_ARMS.get(arm),
            sched=GroupSchedule(target_size=4, rotation_s=2.0),
            max_group=8, ttl=30.0, seed=args.seed * 103 + i,
        )
        boot = boot or v["t"].addr
        vols.append(v)
    # Slow-but-alive survivors: v1 and v2 push at ~1.7 s to everyone.
    for s in (vols[1], vols[2]):
        for o in vols:
            if o is not s:
                s["t"].set_link(
                    s["t"].addr, o["t"].addr,
                    latency_s=0.3, bw_bps=48_000.0,
                )
    loops = [_VolLoop(v, i) for i, v in enumerate(vols)]
    victims = loops[4:]

    async def exodus():
        for lp in victims:
            await asyncio.sleep(2.5)
            # SIGKILL mid-round: cancel the loop under its armed round
            # and tear the transport down — no leave, no tombstone.
            if lp.task is not None:
                lp.task.cancel()
            try:
                await lp.v["t"].close()
            except Exception:
                pass

    kill_task = None
    try:
        for lp in loops:
            lp.start()
        await asyncio.sleep(args.adaptive_warmup_s)
        kill_task = asyncio.create_task(exodus())
        mass = await _adaptive_window(loops, args.adaptive_window_s)
        await kill_task
        survivors = [lp.v for lp in loops[:4]]
        return _adaptive_arm_summary(
            arm, survivors, mass=mass, window_s=args.adaptive_window_s,
        )
    finally:
        if kill_task is not None and not kill_task.done():
            kill_task.cancel()
        await _halt_all(loops[:4])
        for lp in victims:
            if lp.task is not None:
                lp.task.cancel()
        await _teardown_vols(vols)


# Modeled cross-zone bandwidth advertisement for the thin-WAN scenario:
# below the controller's PAIR_BW_FLOOR so the cadence gate can fire. The
# set_link model shapes wall time but not measured EWMAs (its documented
# fidelity limit), so the campaign injects the advertisement through the
# averager's pluggable bw_probe — the hierarchy_bench extra_info pattern.
ADAPT_XZONE_BW = 48_000.0


async def _adaptive_thin_wan(args, arm):
    """Scenario 3 — thin/partitioned cross-zone WAN: a two-zone swarm
    (4 dc + 2 home) on a k=2 hierarchical schedule whose cross-zone
    links serialize 64 KiB pushes at ~3 s. The adaptive arm splits its
    learned deadline by level (cross > intra — the ISSUE-15 acceptance)
    and relaxes the learned per-pair cross cadence off the thin-pair
    bandwidth gate, so most of its rounds are fast intra commits; fixed
    arms either cut every cross push (tight) or pay the full WAN wait
    every second rotation (loose)."""
    zones = ["dc"] * 4 + ["home"] * 2
    vols = []
    boot = None
    for i in range(6):
        v = await _build_adaptive_vol(
            f"v{i}", boot, adaptive=(arm == "adaptive"),
            deadline_s=ADAPT_FIXED_ARMS.get(arm), zone=zones[i],
            sched=GroupSchedule(
                target_size=3, rotation_s=2.0, cross_zone_every_k=2,
            ),
            max_group=8, seed=args.seed * 107 + i,
        )
        boot = boot or v["t"].addr
        vols.append(v)
    addr_zone = {tuple(v["t"].addr): zones[i] for i, v in enumerate(vols)}
    for i, v in enumerate(vols):
        for j, w in enumerate(vols):
            if j <= i or zones[i] == zones[j]:
                continue
            v["t"].set_link(
                v["t"].addr, w["t"].addr,
                latency_s=0.4, bw_bps=24_000.0,
                jitter={
                    "dist": "lognormal", "scale": 0.2, "sigma": 0.6,
                    "cap": 3.0, "min_bytes": 32_768,
                },
            )
        if v["ctrl"] is not None:
            # Modeled bandwidth advertisement (see ADAPT_XZONE_BW).
            myz = zones[i]

            def probe(addr, myz=myz):
                z = addr_zone.get((str(addr[0]), int(addr[1])))
                return ADAPT_XZONE_BW if (z and z != myz) else 20e6

            v["avg"].bw_probe = probe
    loops = [_VolLoop(v, i) for i, v in enumerate(vols)]
    try:
        for lp in loops:
            lp.start()
        await asyncio.sleep(args.adaptive_warmup_s + 4.0)
        mass = await _adaptive_window(loops, args.adaptive_window_s)
        out = _adaptive_arm_summary(
            arm, vols, mass=mass, window_s=args.adaptive_window_s,
        )
        if arm == "adaptive":
            # The per-level deadline acceptance reads the dc leader's
            # policy: cross rounds on the thin WAN must have learned a
            # bigger budget than intra rounds on the fat LAN.
            out["leader_deadlines"] = vols[0]["policy"].deadlines()
            out["applied_k"] = {
                v["pid"]: v["ctrl"].cross_zone_k() for v in vols
            }
        return out
    finally:
        await _halt_all(loops)
        await _teardown_vols(vols)


async def _adaptive_heavy_tail(args, arm):
    """Scenario 4 — heavy-tailed straggler mix: one of four volunteers
    behind a congested uplink (0.8 s base latency + the Pareto bulk
    tail). The tight arm's budget is too short for even a hedged
    recovery to cross the link — it sheds the straggler's armed mass
    every round; the loose arm waits out every capped draw; the adaptive
    arm learns a budget the regime-floored hedger can recover inside."""
    vols = []
    boot = None
    for i in range(4):
        v = await _build_adaptive_vol(
            f"v{i}", boot, adaptive=(arm == "adaptive"),
            deadline_s=ADAPT_FIXED_ARMS.get(arm), max_group=4,
            seed=args.seed * 109 + i,
        )
        boot = boot or v["t"].addr
        vols.append(v)
    loops = [_VolLoop(v, i) for i, v in enumerate(vols)]
    try:
        for lp in loops:
            lp.start()
        await asyncio.sleep(args.adaptive_warmup_s)
        vols[-1]["t"].set_link(
            vols[0]["t"].addr, vols[-1]["t"].addr,
            latency_s=0.8,
            jitter=dict(ADAPT_STRAGGLER_LINK["jitter"]),
        )
        await asyncio.sleep(2.0)
        mass = await _adaptive_window(loops, args.adaptive_window_s)
        return _adaptive_arm_summary(
            arm, vols, mass=mass, window_s=args.adaptive_window_s,
        )
    finally:
        await _halt_all(loops)
        await _teardown_vols(vols)


async def _adaptive_control_arm(args):
    """Healthy control arm: 4 volunteers, adaptive stack on, no faults.
    The acceptance bar is ZERO policy transitions after warm-up — the
    hysteresis bands must hold against ordinary localhost jitter."""
    vols = []
    boot = None
    for i in range(4):
        v = await _build_adaptive_vol(
            f"v{i}", boot, adaptive=True, max_group=4,
            seed=args.seed * 113 + i,
        )
        boot = boot or v["t"].addr
        vols.append(v)
    loops = [_VolLoop(v, i) for i, v in enumerate(vols)]
    try:
        for lp in loops:
            lp.start()
        await asyncio.sleep(args.adaptive_warmup_s)
        warm = sum(v["ctrl"].transitions_total for v in vols)
        mass = await _adaptive_window(loops, args.adaptive_window_s)
        after = sum(v["ctrl"].transitions_total for v in vols)
        committed = mass["included"] + mass["recovered"]
        return {
            "window_s": args.adaptive_window_s,
            "committed_weight": round(committed, 6),
            "weight_per_s": round(committed / args.adaptive_window_s, 4),
            "transitions_warmup": warm,
            "transitions_after_warmup": after - warm,
            "flight_recorders": _flight_dumps(vols),
        }
    finally:
        await _halt_all(loops)
        await _teardown_vols(vols)


ADAPT_SCENARIOS = {
    "flash_crowd": _adaptive_flash_crowd,
    "mass_departure": _adaptive_mass_departure,
    "thin_wan": _adaptive_thin_wan,
    "heavy_tail": _adaptive_heavy_tail,
}


async def adaptive_campaign(args):
    out = {
        "seed": args.seed,
        "payload_elems": ADAPT_N_ELEMS,
        "fixed_arms": dict(ADAPT_FIXED_ARMS),
        "ceil_s": ADAPT_CEIL_S,
        "scenarios": {},
    }
    for scen, fn in ADAPT_SCENARIOS.items():
        arms = {}
        for arm in ("fixed_tight", "fixed_loose", "adaptive"):
            print(f"[adaptive/{scen}] {arm} arm ...")
            arms[arm] = await fn(args, arm)
            print(
                f"[adaptive/{scen}] {arm}: "
                f"{arms[arm]['committed_weight']:.1f}/"
                f"{arms[arm]['armed_weight']:.1f} weight in "
                f"{arms[arm]['window_s']:.1f}s -> "
                f"{arms[arm]['weight_per_s']:.3f} w/s "
                f"(frac {arms[arm]['committed_frac']})"
            )
        out["scenarios"][scen] = {"arms": arms}
    print("[adaptive/control] healthy arm, zero-transition bar ...")
    out["control_arm"] = await _adaptive_control_arm(args)
    print(
        f"[adaptive/control] transitions after warm-up: "
        f"{out['control_arm']['transitions_after_warmup']}"
    )
    return out


def adaptive_verdict(result: dict) -> dict:
    verdict = {
        "frac_tol": ADAPT_FRAC_TOL,
        "min_frac": ADAPT_MIN_FRAC,
    }
    for scen, rec in result["scenarios"].items():
        arms = rec["arms"]
        ad = arms["adaptive"]
        verdict[f"{scen}_weight_per_s"] = {
            a: arms[a]["weight_per_s"] for a in arms
        }
        verdict[f"{scen}_committed_frac"] = {
            a: arms[a]["committed_frac"] for a in arms
        }
        # The headline bar (two-axis dominance, see the scoring note by
        # ADAPT_FRAC_TOL): the adaptive arm must hold its own armed mass
        # AND beat every fixed arm on committed-mass rate — except a
        # fixed arm that only out-rates it by SHEDDING armed mass the
        # adaptive arm kept, which fails the quality axis instead.
        ad_frac = ad["committed_frac"] or 0.0
        beats = []
        for a, rec_a in arms.items():
            if a == "adaptive":
                continue
            frac_a = rec_a["committed_frac"] or 0.0
            beats.append(
                ad["weight_per_s"] > rec_a["weight_per_s"]
                or frac_a < ad_frac - ADAPT_FRAC_TOL
            )
        verdict[f"pass_{scen}_adaptive_beats_every_fixed"] = (
            ad_frac >= ADAPT_MIN_FRAC and all(beats)
        )
    # The decision trail: policy_changed events (reason + evidence) in
    # the adaptive arms' attached flight recorders for the scenarios
    # whose winning mechanism IS a policy decision — the flash-crowd
    # regime shift, the thin-WAN cadence/deadline split, and the
    # heavy-tail regime cycle (churn at onset, calm again once the
    # learned budget absorbs the tail). Mass departure is deliberately
    # NOT on this list: its kills are absorbed by pre-exclusion +
    # group-local failover without any knob needing to move, so the
    # adaptive arm's ZERO transitions there are the hysteresis contract
    # holding under fault (the control arm's property, under fire) —
    # demanding a trail would reward flapping.
    for scen in ("flash_crowd", "thin_wan", "heavy_tail"):
        evs = _policy_changed_events(
            result["scenarios"][scen]["arms"]["adaptive"]
        )
        verdict[f"pass_{scen}_decision_trail"] = bool(evs) and all(
            e.get("reason") and isinstance(e.get("evidence"), dict)
            for e in evs
        )
    # Per-level deadline split on the two-zone slow WAN: cross > intra.
    dl = result["scenarios"]["thin_wan"]["arms"]["adaptive"].get(
        "leader_deadlines"
    ) or {}
    verdict["leader_deadlines"] = dl
    verdict["pass_cross_deadline_exceeds_intra"] = bool(
        dl.get("cross") and dl.get("intra") and dl["cross"] > dl["intra"]
    )
    verdict["pass_control_zero_transitions"] = (
        result["control_arm"]["transitions_after_warmup"] == 0
    )
    return verdict


# -- watchdog campaign (ISSUE 13 acceptance) ---------------------------------
#
# Every injected fault class must raise its MATCHING alert within
# WATCHDOG_RAISE_BOUND rounds/rotations of onset, clear within
# WATCHDOG_CLEAR_BOUND of heal, the healthy control arm must raise ZERO
# alerts, and the root-cause doctor must rank the true cause first.

WATCHDOG_RAISE_BOUND = 8
WATCHDOG_CLEAR_BOUND = 12

sys.path.insert(0, os.path.join(REPO, "experiments"))
from doctor_report import diagnose  # noqa: E402

from distributedvolunteercomputing_tpu.swarm import health as health_mod  # noqa: E402
from distributedvolunteercomputing_tpu.swarm import watchdog as watchdog_mod  # noqa: E402


def _wd_wire(vols, bandwidths=None):
    """Wire each volunteer's watchdog probes the way Volunteer.start does
    (health-driven mass + quality probes; per-level round walls feed via
    the tracer hook automatically). The commit-rate probe is left off in
    campaign arms: the campaign ticks per ROUND, not per 5s beat, which
    couples the rate series to round-wall jitter — the rate detector is
    covered by its unit tests and the production wiring."""
    for v in vols:
        tele = v["avg"].telemetry
        tele.watchdog.wire_volunteer(
            health=tele.health, bandwidths=bandwidths,
        )


def _wd_tick(vols):
    for v in vols:
        v["avg"].telemetry.watchdog.tick()


def _wd_firing(vols, kind, key=None):
    """Volunteers currently firing `kind` (optionally key-filtered)."""
    out = []
    for v in vols:
        for a in v["avg"].telemetry.watchdog.alerts():
            if a["kind"] == kind and (key is None or a["key"] == key):
                out.append(v["pid"])
                break
    return out


def _wd_raised_total(vols):
    return sum(v["avg"].telemetry.watchdog.raised_total for v in vols)


def _wd_bundle(vols, extra_alerts=(), quality=None):
    """Doctor evidence bundle: every alert_raised flight event + the full
    flight rings + the (leader's) quality map."""
    flight = _flight_dumps(vols)
    alerts = [
        e for events in flight.values() for e in events
        if e.get("kind") == "alert_raised"
    ]
    alerts.extend(extra_alerts)
    return {"alerts": alerts, "flight": flight, "quality": quality or {}}


async def _wd_killstorm_scenario(args):
    """Fault class 1 — leader SIGKILL storm: v0 killed mid-stream every
    round in a min_group=4 swarm, so the 3 survivors sit BELOW the
    formation floor and epoch-fenced recovery cannot re-commit — the
    committed-round rate collapses to zero while depositions pile up.
    (With min_group=2 the PR-4 fast-fail recovery re-commits in ~ms —
    the kill is a wall-clock non-event, which is exactly why the rate,
    not the wall, is this fault's matching signal.)
    Matching alert: commit_rate_collapse. Doctor: leader_crash_storm."""
    gather_timeout = 8.0
    boot_t = Transport()
    boot_dht = DHTNode(boot_t)
    await boot_dht.start(bootstrap=None)
    vols = []
    for i in range(4):
        pid = f"v{i}"
        t = Transport()
        dht = DHTNode(t)
        await dht.start(bootstrap=[boot_t.addr])
        fd = PhiAccrualDetector(bootstrap_s=2.0)
        policy = ResiliencePolicy(
            max_deadline_s=gather_timeout, min_deadline_s=1.0,
            preexclude_misses=3, failure_detector=fd,
        )
        mem = SwarmMembership(dht, pid, ttl=10.0, failure_detector=fd)
        await mem.join()
        avg = SyncAverager(
            t, dht, mem,
            min_group=4, max_group=4,  # full group or no commit
            join_timeout=8.0, gather_timeout=gather_timeout,
            resilience=policy, failure_detector=fd,
        )
        vols.append({"pid": pid, "t": t, "dht": dht, "mem": mem, "avg": avg,
                     "fd": fd, "policy": policy})
    boot = (boot_t, boot_dht)
    _wd_wire(vols)
    # Commit-rate probe at the campaign's per-round tick cadence: the
    # delta of rounds_ok per tick (1 healthy, 0 when the storm blocks the
    # commit) through the public observe() API.
    for v in vols:
        state = {}

        def probe(now, dt, v=v, state=state):
            ok = v["avg"].rounds_ok
            prev = state.get("ok")
            state["ok"] = ok
            if prev is not None:
                v["avg"].telemetry.watchdog.observe(
                    "commit_rate_collapse", float(ok - prev)
                )

        v["avg"].telemetry.watchdog.add_probe(probe)
    rec = {"phase_rounds": [], "raised_after": None, "cleared_after": None}
    try:
        for r in range(6):  # healthy warmup: rate baseline arms at 1/round
            await asyncio.gather(
                *(_timed_average(v, i, r) for i, v in enumerate(vols))
            )
            _wd_tick(vols)
        assert not _wd_firing(vols, "commit_rate_collapse"), (
            "rate alert fired during healthy warmup"
        )
        storm = max(args.watchdog_rounds, 6)
        for k in range(storm):
            _install_kill(vols[0], "mid_stream")
            await asyncio.gather(
                *(_timed_average(v, i, 100 + k) for i, v in enumerate(vols))
            )
            await _revive_leader(vols)
            await asyncio.sleep(0.3)
            _wd_tick(vols)
            firing = _wd_firing(vols[1:], "commit_rate_collapse")
            rec["phase_rounds"].append({"round": k, "firing": firing})
            if rec["raised_after"] is None and firing:
                rec["raised_after"] = k + 1
        for k in range(WATCHDOG_CLEAR_BOUND):  # heal: no more kills
            await asyncio.gather(
                *(_timed_average(v, i, 200 + k) for i, v in enumerate(vols))
            )
            _wd_tick(vols)
            if not _wd_firing(vols, "commit_rate_collapse"):
                rec["cleared_after"] = k + 1
                break
        rec["bundle"] = _wd_bundle(vols)
        rec["diagnosis"] = diagnose(rec["bundle"])
        rec["flight_recorders"] = rec["bundle"]["flight"]
    finally:
        for v in vols:
            try:
                await v["mem"].leave()
            except Exception:
                pass
            try:
                await v["dht"].stop()
            except Exception:
                pass
            try:
                await v["t"].close()
            except Exception:
                pass
        try:
            await boot[1].stop()
        except Exception:
            pass
        await boot[0].close()
    return rec


async def _wd_straggler_scenario(args):
    """Fault class 2 — x10 straggler under a static round deadline: the
    leader commits without the late peer, losing its slot's mass every
    round. Matching alert: mass_frac_drop. Doctor: straggler_deadline_drop."""
    vols, schedule = await _build_health_swarm(
        4, method="mean", min_group=3, gather_timeout=8.0,
        round_deadline_s=2.0, chaos_last=True, seed=args.seed,
    )
    _wd_wire(vols)
    straggler = vols[-1]
    rec = {"phase_rounds": [], "raised_after": None, "cleared_after": None}

    async def one_round(r):
        await asyncio.gather(
            *(
                asyncio.wait_for(
                    v["avg"].average(tree_for(i), round_no=r), timeout=60.0
                )
                for i, v in enumerate(vols)
            ),
            return_exceptions=True,
        )
        _wd_tick(vols)

    try:
        for r in range(6):  # healthy warmup: mass baseline arms at 1.0
            await one_round(r)
        assert not _wd_firing(vols, "mass_frac_drop"), (
            "mass alert fired during healthy warmup"
        )
        # Onset: every outbound straggler RPC now takes 4s — past the 2s
        # round deadline, inside the join window.
        schedule.events = [fault_event(0.0, float("inf"), "delay", 4.0)]
        schedule.start()
        for k in range(max(args.watchdog_rounds, 6)):
            await one_round(100 + k)
            firing = _wd_firing(vols, "mass_frac_drop")
            rec["phase_rounds"].append({"round": k, "firing": firing})
            if rec["raised_after"] is None and firing:
                rec["raised_after"] = k + 1
        # Heal: the delay is lifted; frac returns to 1.0 and the alert
        # must clear (hysteresis, not latching).
        schedule.events = []
        for k in range(WATCHDOG_CLEAR_BOUND):
            await one_round(200 + k)
            if not _wd_firing(vols, "mass_frac_drop"):
                rec["cleared_after"] = k + 1
                break
        rec["straggler"] = straggler["pid"]
        rec["bundle"] = _wd_bundle(vols)
        rec["diagnosis"] = diagnose(rec["bundle"])
        rec["flight_recorders"] = rec["bundle"]["flight"]
    finally:
        await _teardown_vols(vols)
    return rec


async def _wd_thinlink_scenario(args):
    """Fault class 3 — thin cross-zone link: a two-zone swarm on the
    hierarchical schedule (cross every 2nd rotation) whose cross-zone
    links gain a latency past the join budget, so cross rounds fail while
    intra rounds stay healthy. Matching alerts: round_wall_inflation at
    level=cross (volunteer-side) AND mixing_stall (replica-side, over the
    health rollup's across-zone sketch dispersion). Doctor:
    thin_cross_zone_link.

    Bandwidth advertisements are INJECTED on fault (the documented
    set_link fidelity limit: the link model shapes wall time, not the
    receiver's measured arrival rate — hierarchy_bench injects the same
    way), so the per-peer bandwidth-collapse detector sees the drop the
    production EWMA would."""
    n, elems, target, k_cross = 6, 8192, 3, 2
    rot_cell = {"rot": 0}
    bw_cell = {"dc<->home": 8e6}
    vols, boot = [], None
    rec = {
        "rotations": [], "wall_raised_after": None, "stall_raised_after": None,
        "wall_cleared_after": None, "stall_cleared_after": None,
    }
    sw = watchdog_mod.SwarmWatchdog()
    # The replica-side watchdog is evaluated once per ROTATION on a
    # synthetic clock advancing 1s per rotation: production rotations are
    # seconds apart, while this pinned-rotation campaign can spin several
    # per second — fast enough to race the evaluator's real-time
    # MIN_TICK_SPACING guard and skip exactly the post-cross observations
    # the stall detector needs to see.
    sw_clock = {"t": 1000.0}
    rng = np.random.default_rng(args.seed)
    # Per-zone parameter drift, switched on at fault onset: volunteers
    # keep TRAINING while the cross-zone links are thin, so zone means
    # keep diverging (+/- per rotation) with nothing to reconverge them —
    # which is exactly what the stall detector watches for. During heal
    # the drift continues but cross rotations out-mix it, so the
    # dispersion drops back under the stall floor and the alert clears.
    drift = {"on": False, "step": 0.4}
    try:
        for i in range(n):
            zone = "dc" if i < n // 2 else "home"
            sched = GroupSchedule(
                target_size=target, rotation_s=1000.0, min_size=2,
                cross_zone_every_k=k_cross,
                clock=lambda: rot_cell["rot"] * 1000.0 + 0.5,
            )
            t = ChaosTransport()
            dht = DHTNode(t, maintenance_interval=120.0)
            await dht.start(bootstrap=[boot] if boot else None)
            if boot is None:
                boot = t.addr
            mem = SwarmMembership(dht, f"z{i:02d}", ttl=30.0,
                                  extra_info={"zone": zone})
            await mem.join()
            avg = SyncAverager(
                t, dht, mem, min_group=2, max_group=3 * target,
                join_timeout=4.0, gather_timeout=6.0, group_schedule=sched,
            )
            vols.append({"pid": f"z{i:02d}", "t": t, "dht": dht, "mem": mem,
                         "avg": avg, "zone": zone})
        _wd_wire(vols, bandwidths=lambda: dict(bw_cell))
        for v in vols:
            await v["mem"].alive_peers()
        vals = {i: (1.0 if i < n // 2 else 9.0) for i in range(n)}
        dc = [v for v in vols if v["zone"] == "dc"]
        home = [v for v in vols if v["zone"] == "home"]

        async def rotation(r, phase):
            rot_cell["rot"] = r
            if drift["on"]:
                for i in range(n):
                    vals[i] += drift["step"] if i < n // 2 else -drift["step"]
            results = await asyncio.gather(
                *(
                    asyncio.wait_for(
                        v["avg"].average(
                            {"w": np.full(
                                (elems,),
                                vals[i] + rng.normal(0.0, 0.02),
                                np.float32,
                            )},
                            round_no=r,
                        ),
                        timeout=40.0,
                    )
                    for i, v in enumerate(vols)
                ),
                return_exceptions=True,
            )
            for i, res in enumerate(results):
                if res is not None and not isinstance(res, BaseException):
                    vals[i] = float(res["w"][0])
            _wd_tick(vols)
            reports = [
                {
                    "peer": v["pid"],
                    "recv_t": time.time(),
                    "health": v["avg"].telemetry.health.summary(),
                    "watchdog": v["avg"].telemetry.watchdog.summary(),
                }
                for v in vols
            ]
            roll = health_mod.rollup_status(reports)
            sw_clock["t"] += 1.0
            sw.evaluate(reports, health=roll, now=sw_clock["t"])
            across = ((roll or {}).get("mixing") or {}).get("across_zones")
            rec["rotations"].append({
                "rot": r,
                "phase": phase,
                "level": "cross" if r % k_cross == 0 else "intra",
                "across_rel": (across or {}).get("rel"),
                "wall_firing": _wd_firing(vols, "round_wall_inflation",
                                          key="cross"),
                "stall_firing": sw.stall.firing(),
                "bw_firing": _wd_firing(vols, "peer_bw_collapse"),
            })

        rot = 1
        for _ in range(9):  # healthy warmup: 4 cross rotations arm baselines
            await rotation(rot, "warmup")
            rot += 1
        assert not any(
            h["wall_firing"] or h["stall_firing"] for h in rec["rotations"]
        ), "watchdog fired during healthy warmup"
        # Onset: every cross-zone call now pays 6s — past the 4s join
        # budget — and the advertised cross-zone bandwidth collapses.
        for a in dc:
            for b in home:
                a["t"].set_link(a["t"].addr, b["t"].addr, latency_s=6.0)
        bw_cell["dc<->home"] = 1e5
        drift["on"] = True
        onset = len(rec["rotations"])
        for _ in range(2 * WATCHDOG_RAISE_BOUND):
            await rotation(rot, "fault")
            rot += 1
            h = rec["rotations"][-1]
            if rec["wall_raised_after"] is None and h["wall_firing"]:
                rec["wall_raised_after"] = len(rec["rotations"]) - onset
            if rec["stall_raised_after"] is None and h["stall_firing"]:
                rec["stall_raised_after"] = len(rec["rotations"]) - onset
            if rec["wall_raised_after"] and rec["stall_raised_after"]:
                break
        # Heal: links cleared, bandwidth recovers; both alerts must clear.
        vols[0]["t"].clear_links()
        bw_cell["dc<->home"] = 8e6
        healed = len(rec["rotations"])
        for _ in range(2 * WATCHDOG_CLEAR_BOUND):
            await rotation(rot, "heal")
            rot += 1
            h = rec["rotations"][-1]
            if rec["wall_cleared_after"] is None and not h["wall_firing"]:
                rec["wall_cleared_after"] = len(rec["rotations"]) - healed
            if rec["stall_cleared_after"] is None and not h["stall_firing"]:
                rec["stall_cleared_after"] = len(rec["rotations"]) - healed
            if rec["wall_cleared_after"] and rec["stall_cleared_after"]:
                break
        extra = [
            {**a, "peer": "swarm-watchdog"}
            for a in sw.alerts_status([], time.time())["firing"]
        ]
        # The stall alert may already have CLEARED here (that is the heal
        # assertion) — harvest its raise from the replica-side recorder
        # surrogate: sw recorded no flight ring, so reconstruct from the
        # firing history instead.
        if any(h["stall_firing"] for h in rec["rotations"]):
            extra.append({
                "kind": "mixing_stall", "key": "", "severity": "warn",
                "peer": "swarm-watchdog", "value": 0.0, "baseline": 0.0,
                "since": 0.0,
            })
        rec["bundle"] = _wd_bundle(vols, extra_alerts=extra)
        rec["diagnosis"] = diagnose(rec["bundle"])
        rec["flight_recorders"] = rec["bundle"]["flight"]
    finally:
        await _teardown_vols(vols)
    return rec


async def _wd_byzantine_scenario(args):
    """Fault class 4 — byzantine contributor: one peer ships its tree
    scaled x8 (well-formed frames, garbage values). The health monitor's
    quality score flags it; the watchdog turns the flag into a per-peer
    alert. Matching alert: byzantine_contributor. Doctor:
    byzantine_contributor naming the peer."""
    scale = 8.0
    n = 5
    byz = f"v{n - 1}"
    vols, _ = await _build_health_swarm(n, method="trimmed_mean", min_group=4)
    _wd_wire(vols)
    rec = {"phase_rounds": [], "raised_after": None, "cleared_after": None,
           "byz_peer": byz}

    async def one_round(r, scaled):
        trees = []
        for i in range(n):
            tree = tree_for(i)
            if scaled and vols[i]["pid"] == byz:
                tree = {k: v * scale for k, v in tree.items()}
            trees.append(tree)
        await asyncio.gather(
            *(
                asyncio.wait_for(
                    vols[i]["avg"].average(trees[i], round_no=r), timeout=60.0
                )
                for i in range(n)
            ),
            return_exceptions=True,
        )
        _wd_tick(vols)

    try:
        for r in range(4):  # honest warmup
            await one_round(r, scaled=False)
        assert not _wd_firing(vols, "byzantine_contributor"), (
            "byzantine alert fired during honest warmup"
        )
        for k in range(max(args.watchdog_rounds, 6)):
            await one_round(100 + k, scaled=True)
            firing = _wd_firing(vols, "byzantine_contributor", key=byz)
            rec["phase_rounds"].append({"round": k, "firing": firing})
            if rec["raised_after"] is None and firing:
                rec["raised_after"] = k + 1
        for k in range(WATCHDOG_CLEAR_BOUND):  # heal: honest again
            await one_round(200 + k, scaled=False)
            if not _wd_firing(vols, "byzantine_contributor"):
                rec["cleared_after"] = k + 1
                break
        lead_health = vols[0]["avg"].telemetry.health
        quality = (lead_health.summary() or {}).get("quality") or {}
        rec["bundle"] = _wd_bundle(vols, quality=quality)
        rec["diagnosis"] = diagnose(rec["bundle"])
        rec["flight_recorders"] = rec["bundle"]["flight"]
    finally:
        await _teardown_vols(vols)
    return rec


async def _wd_control_arm(args):
    """The healthy control arm: same stack, no fault. ZERO alerts may be
    raised across the whole arm (warm-up gating + hysteresis working),
    and the doctor must find nothing to diagnose."""
    vols, _ = await _build_health_swarm(4, method="mean", min_group=3)
    _wd_wire(vols)
    try:
        for r in range(max(args.watchdog_rounds, 6) + 6):
            await asyncio.gather(
                *(
                    asyncio.wait_for(
                        v["avg"].average(tree_for(i), round_no=r), timeout=60.0
                    )
                    for i, v in enumerate(vols)
                ),
                return_exceptions=True,
            )
            _wd_tick(vols)
        rec = {
            "rounds": max(args.watchdog_rounds, 6) + 6,
            "alerts_raised_total": _wd_raised_total(vols),
            "firing": [
                a for v in vols for a in v["avg"].telemetry.watchdog.alerts()
            ],
            "diagnosis": diagnose(_wd_bundle(vols)),
        }
        rec["flight_recorders"] = _flight_dumps(vols)
    finally:
        await _teardown_vols(vols)
    return rec


async def _wd_status_plane_check():
    """coord.status["slo"] / ["alerts"] live under the pinned schema, with
    a volunteer-reported firing alert visible in the rollup and age_s
    stamps on every section — asserted here so the artifact carries the
    live-status proof, not just in-process detector state."""
    from distributedvolunteercomputing_tpu.swarm import telemetry as telemetry_mod

    t = Transport()
    dht = DHTNode(t)
    await dht.start(bootstrap=None)
    rep = ControlPlaneReplica(t, dht, rid="cp0", interval=0.5)
    await rep.start()
    try:
        tele = telemetry_mod.Telemetry(peer_id="w0")
        tele.tracer.record("round", "tr", 0.0, 0.4, level="flat", ok=True)
        # Force one firing alert through the real detector path.
        det = tele.watchdog.detectors["mass_frac_drop"]
        for i in range(det.warmup + 1):
            tele.watchdog.observe("mass_frac_drop", 1.0)
        for _ in range(det.min_breaches):
            tele.watchdog.observe("mass_frac_drop", 0.4)
        report = {
            "peer": "w0", "samples_per_sec": 1.0,
            "telemetry": tele.summary(),
            "health": tele.health.summary(),
            "watchdog": tele.watchdog.summary(),
        }
        await rep._rpc_report(report, b"")
        status, _ = await rep._rpc_status({}, b"")
        await asyncio.sleep(0.3)
        status, _ = await rep._rpc_status({}, b"")  # 2nd eval: rate deltas

        def walk(schema, obj, path):
            for key, typ in schema.items():
                assert key in obj, f"missing {path}{key}"
                typs = typ if isinstance(typ, tuple) else (typ,)
                assert isinstance(obj[key], typs), (
                    f"{path}{key}: {type(obj[key]).__name__}"
                )

        for section, schema in watchdog_mod.STATUS_WATCHDOG_SCHEMA.items():
            assert isinstance(status.get(section), dict), f"{section} missing"
            walk(schema, status[section], f"{section}.")
        for name, obj in status["slo"]["objectives"].items():
            walk(watchdog_mod.STATUS_SLO_OBJECTIVE_SCHEMA, obj, f"slo.{name}.")
        for a in status["alerts"]["firing"]:
            walk(watchdog_mod.ALERT_SCHEMA, a, "alerts.firing.")
        firing_kinds = {a["kind"] for a in status["alerts"]["firing"]}
        assert "mass_frac_drop" in firing_kinds, (
            "volunteer-reported alert missing from the status rollup"
        )
        assert isinstance(status["telemetry"].get("age_s"), float)
        assert isinstance(status["health"].get("age_s"), float)
        return {
            "schema_ok": True,
            "slo": status["slo"],
            "alerts": status["alerts"],
            "telemetry_age_s": status["telemetry"]["age_s"],
            "health_age_s": status["health"]["age_s"],
        }
    finally:
        await rep.stop()
        await dht.stop()
        await t.close()


async def watchdog_campaign(args):
    out = {"seed": args.seed, "raise_bound": WATCHDOG_RAISE_BOUND,
           "clear_bound": WATCHDOG_CLEAR_BOUND, "scenarios": {}}
    print("[watchdog/killstorm] leader killed mid-stream every round ...")
    out["scenarios"]["killstorm"] = await _wd_killstorm_scenario(args)
    s = out["scenarios"]["killstorm"]
    print(f"[watchdog/killstorm] raised after {s['raised_after']} rounds, "
          f"cleared after {s['cleared_after']}, top diagnosis "
          f"{(s['diagnosis'] or [{}])[0].get('cause')}")
    print("[watchdog/straggler] x10 straggler vs 2s deadline ...")
    out["scenarios"]["straggler"] = await _wd_straggler_scenario(args)
    s = out["scenarios"]["straggler"]
    print(f"[watchdog/straggler] raised after {s['raised_after']} rounds, "
          f"cleared after {s['cleared_after']}, top diagnosis "
          f"{(s['diagnosis'] or [{}])[0].get('cause')}")
    print("[watchdog/thinlink] two-zone swarm, 6s cross-zone latency ...")
    out["scenarios"]["thinlink"] = await _wd_thinlink_scenario(args)
    s = out["scenarios"]["thinlink"]
    print(f"[watchdog/thinlink] wall raised after {s['wall_raised_after']}, "
          f"stall after {s['stall_raised_after']}, top diagnosis "
          f"{(s['diagnosis'] or [{}])[0].get('cause')}")
    print("[watchdog/byzantine] one x8-scaled contributor ...")
    out["scenarios"]["byzantine"] = await _wd_byzantine_scenario(args)
    s = out["scenarios"]["byzantine"]
    print(f"[watchdog/byzantine] raised after {s['raised_after']} rounds, "
          f"cleared after {s['cleared_after']}, top diagnosis "
          f"{(s['diagnosis'] or [{}])[0].get('cause')}")
    print("[watchdog/control] healthy arm, zero-alert bar ...")
    out["control_arm"] = await _wd_control_arm(args)
    print(f"[watchdog/control] alerts raised: "
          f"{out['control_arm']['alerts_raised_total']}")
    out["status_plane"] = await _wd_status_plane_check()
    print("[watchdog/status] slo/alerts live under the pinned schema")
    return out


def watchdog_verdict(result: dict) -> dict:
    sc = result["scenarios"]

    def top(s):
        d = s.get("diagnosis") or []
        return d[0]["cause"] if d else None

    def bounded(v, bound):
        return v is not None and v <= bound

    rb, cb = result["raise_bound"], result["clear_bound"]
    return {
        "pass_killstorm_alert": bounded(sc["killstorm"]["raised_after"], rb),
        "pass_killstorm_clear": bounded(sc["killstorm"]["cleared_after"], cb),
        "pass_killstorm_diagnosis": top(sc["killstorm"]) == "leader_crash_storm",
        "pass_straggler_alert": bounded(sc["straggler"]["raised_after"], rb),
        "pass_straggler_clear": bounded(sc["straggler"]["cleared_after"], cb),
        "pass_straggler_diagnosis": (
            top(sc["straggler"]) == "straggler_deadline_drop"
            and sc["straggler"]["straggler"] in (
                sc["straggler"]["diagnosis"][0]["peers"]
                if sc["straggler"]["diagnosis"] else []
            )
        ),
        "pass_thinlink_alerts": (
            bounded(sc["thinlink"]["wall_raised_after"], 2 * rb)
            and bounded(sc["thinlink"]["stall_raised_after"], 2 * rb)
        ),
        "pass_thinlink_clear": (
            bounded(sc["thinlink"]["wall_cleared_after"], 2 * cb)
            and bounded(sc["thinlink"]["stall_cleared_after"], 2 * cb)
        ),
        "pass_thinlink_diagnosis": top(sc["thinlink"]) == "thin_cross_zone_link",
        "pass_byzantine_alert": bounded(sc["byzantine"]["raised_after"], rb),
        "pass_byzantine_clear": bounded(sc["byzantine"]["cleared_after"], cb),
        "pass_byzantine_diagnosis": (
            top(sc["byzantine"]) == "byzantine_contributor"
            and sc["byzantine"]["byz_peer"] in (
                sc["byzantine"]["diagnosis"][0]["peers"]
                if sc["byzantine"]["diagnosis"] else []
            )
        ),
        "pass_control_arm_zero_alerts": (
            result["control_arm"]["alerts_raised_total"] == 0
            and not result["control_arm"]["diagnosis"]
        ),
        "pass_status_schema_live": result["status_plane"]["schema_ok"],
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--warmup-rounds", type=int, default=10)
    ap.add_argument("--faulted-rounds", type=int, default=25)
    ap.add_argument("--blocking-rounds", type=int, default=6)
    ap.add_argument("--round-cadence-s", type=float, default=0.75,
                    help="local-compute pause between faulted rounds")
    ap.add_argument("--train-steps", type=int, default=120)
    ap.add_argument("--no-train", action="store_true")
    ap.add_argument("--quick", action="store_true",
                    help="short campaign, no training phase")
    ap.add_argument("--failover", action="store_true",
                    help="run the leader-failover arm instead (kill-at-phase "
                         "matrix + fencing scenario)")
    ap.add_argument("--failover-rounds", type=int, default=20,
                    help="kill rounds per phase in the failover arm")
    ap.add_argument("--mesh-degrade", action="store_true",
                    help="run the degraded-slice arm instead: the leader's "
                         "on-mesh codec loses its device mesh mid-campaign "
                         "and must fall back to host without failing a round")
    ap.add_argument("--mesh-degrade-rounds", type=int, default=10,
                    help="averaging rounds in the mesh-degrade arm")
    ap.add_argument("--multigroup", action="store_true",
                    help="run the multi-group churn arm instead: rotating "
                         "group schedule, one group's leader killed "
                         "mid-round per kill round (other groups must "
                         "commit unaffected), plus a flash-crowd join "
                         "burst mid-campaign")
    ap.add_argument("--multigroup-rounds", type=int, default=6,
                    help="kill rounds in the multigroup arm")
    ap.add_argument("--shard", action="store_true",
                    help="run the swarm-sharded arm instead (ISSUE 20): "
                         "3 zones x 2 shard-holders on the zone-sharded "
                         "schedule; each kill round the shard-0 trio's "
                         "leader dies at a cycled phase (pre_arm / "
                         "mid_stream / post_partial_commit) after "
                         "mutating its shard — the other shard's trio "
                         "must commit untouched, the survivors must "
                         "commit through the loss with balanced mass, "
                         "and the zone mate must re-shard (fenced gen+1) "
                         "and recover the LATEST shard bytes from its "
                         "replica without an epoch restart")
    ap.add_argument("--shard-rounds", type=int, default=6,
                    help="kill rounds in the shard arm")
    ap.add_argument("--controlplane", action="store_true",
                    help="run the control-plane arm instead: volunteers "
                         "batch-heartbeating through 3 elected coordinator "
                         "replicas; the ACTIVE replica is SIGKILLed while "
                         "each rotation's averaging rounds are in flight "
                         "(swarm must keep matching/committing with zero "
                         "missed rotations; coord.status must be served by "
                         "a survivor within one heartbeat interval)")
    ap.add_argument("--controlplane-rounds", type=int, default=4,
                    help="replica-kill rounds in the control-plane arm")
    ap.add_argument("--health", action="store_true",
                    help="run the training-health arm instead (ISSUE 12): "
                         "a DVC_CHAOS_CONTRIB_SCALE byzantine peer must be "
                         "flagged by the contribution-quality score within "
                         "10 committed rounds with zero honest false "
                         "positives; a deadline-dropped straggler's lost "
                         "gradient mass must balance and surface as "
                         "mass_lost_at_deadline events; and the live "
                         "sketch-based mixing error must track the direct "
                         "computation on a two-zone swarm where intra-only "
                         "rotations stall cross-zone dispersion and k=3 "
                         "converges it")
    ap.add_argument("--health-rounds", type=int, default=12,
                    help="rounds per phase in the health arm")
    ap.add_argument("--watchdog", action="store_true",
                    help="run the watchdog arm instead (ISSUE 13): each "
                         "injected fault class (leader kill storm, x10 "
                         "straggler, thin cross-zone link, byzantine "
                         "contributor) must raise its MATCHING alert "
                         "within the documented round bound and clear "
                         "after heal; a healthy control arm must raise "
                         "zero alerts; and the root-cause doctor "
                         "(experiments/doctor_report.py) must rank the "
                         "true cause first — with coord.status slo/alerts "
                         "live under the pinned schema")
    ap.add_argument("--watchdog-rounds", type=int, default=8,
                    help="fault rounds per scenario in the watchdog arm")
    ap.add_argument("--tail", action="store_true",
                    help="run the tail-optimal arm instead (ISSUE 14): "
                         "hedged per-tile recovery vs the drop-the-"
                         "straggler baseline at the SAME static round "
                         "deadline under the heavy-tailed set_link model "
                         "(x10 Pareto straggler + thin-link scenarios); "
                         "the hedged arm must commit >=1.5x less lost "
                         "gradient mass with round-wall p99 within 10%, "
                         "balanced mass buckets every round, and hedge "
                         "decisions visible as spans + flight events")
    ap.add_argument("--tail-rounds", type=int, default=12,
                    help="faulted rounds per scenario arm in the tail arm")
    ap.add_argument("--adaptive", action="store_true",
                    help="run the adaptive-controller arm instead (ISSUE "
                         "15): the closed-loop controller vs every fixed "
                         "configuration across a >=4-scenario matrix "
                         "(flash-crowd join burst, mass departure, thin "
                         "cross-zone WAN, heavy-tailed straggler mix), "
                         "scored on committed gradient mass per second; "
                         "the adaptive arm must beat every fixed arm per "
                         "scenario, show its policy_changed decision "
                         "trail in the attached flight recorders, split "
                         "its learned deadline per level (cross > intra "
                         "on the slow WAN), and hold ZERO transitions on "
                         "the healthy control arm")
    ap.add_argument("--adaptive-window", type=float, default=45.0,
                    dest="adaptive_window_s",
                    help="measurement window (seconds) per scenario arm in "
                         "the adaptive campaign — every arm free-runs its "
                         "volunteers for exactly this long and is scored "
                         "on committed gradient mass per second")
    ap.add_argument("--adaptive-warmup", type=float, default=6.0,
                    dest="adaptive_warmup_s",
                    help="healthy warm-up (seconds) before fault onset in "
                         "each adaptive-campaign arm")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    if args.out is None:
        args.out = os.path.join(
            REPO, "experiments", "results",
            "chaos_failover.json" if args.failover
            else "chaos_mesh_degrade.json" if args.mesh_degrade
            else "chaos_multigroup.json" if args.multigroup
            else "chaos_shard.json" if args.shard
            else "chaos_controlplane.json" if args.controlplane
            else "chaos_health.json" if args.health
            else "chaos_watchdog.json" if args.watchdog
            else "chaos_tail.json" if args.tail
            else "chaos_adaptive.json" if args.adaptive
            else "chaos_soak.json",
        )
    if args.quick:
        args.warmup_rounds = 6
        args.faulted_rounds = 10
        args.blocking_rounds = 3
        args.failover_rounds = 5
        args.mesh_degrade_rounds = 4
        args.multigroup_rounds = 3
        args.shard_rounds = 3
        args.controlplane_rounds = 2
        args.health_rounds = 8
        args.watchdog_rounds = 6
        args.tail_rounds = 6
        args.adaptive_window_s = 25.0
        args.no_train = True

    if args.shard:
        result = {"shard_campaign": asyncio.run(shard_campaign(args))}
        result["verdict"] = shard_verdict(result["shard_campaign"])
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[done] artifact -> {args.out}")
        print(json.dumps(result["verdict"], indent=2))
        ok = all(v for k, v in result["verdict"].items() if k.startswith("pass_"))
        sys.exit(0 if ok else 1)

    if args.adaptive:
        result = {"adaptive_campaign": asyncio.run(adaptive_campaign(args))}
        result["verdict"] = adaptive_verdict(result["adaptive_campaign"])
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[done] artifact -> {args.out}")
        print(json.dumps(result["verdict"], indent=2))
        ok = all(v for k, v in result["verdict"].items() if k.startswith("pass_"))
        sys.exit(0 if ok else 1)

    if args.tail:
        result = {"tail_campaign": asyncio.run(tail_campaign(args))}
        result["verdict"] = tail_verdict(result["tail_campaign"])
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[done] artifact -> {args.out}")
        print(json.dumps(result["verdict"], indent=2))
        ok = all(v for k, v in result["verdict"].items() if k.startswith("pass_"))
        sys.exit(0 if ok else 1)

    if args.watchdog:
        result = {"watchdog_campaign": asyncio.run(watchdog_campaign(args))}
        result["verdict"] = watchdog_verdict(result["watchdog_campaign"])
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[done] artifact -> {args.out}")
        print(json.dumps(result["verdict"], indent=2))
        ok = all(v for k, v in result["verdict"].items() if k.startswith("pass_"))
        sys.exit(0 if ok else 1)

    if args.health:
        result = {"health_campaign": asyncio.run(health_campaign(args))}
        result["verdict"] = health_verdict(result["health_campaign"])
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[done] artifact -> {args.out}")
        print(json.dumps(result["verdict"], indent=2))
        ok = all(v for k, v in result["verdict"].items() if k.startswith("pass_"))
        sys.exit(0 if ok else 1)

    if args.controlplane:
        result = {
            "controlplane_campaign": asyncio.run(controlplane_campaign(args))
        }
        cp = result["controlplane_campaign"]["verdict_inputs"]
        result["verdict"] = {
            # The acceptance bar: a coordinator-replica SIGKILL mid-round
            # is a NON-EVENT for the data plane...
            "pass_zero_missed_rotations": (
                cp["rotations_all_committed"] == cp["rounds"]
            ),
            # ...heartbeats fail over (stay batched) instead of regressing
            # to per-message DHT traffic...
            "pass_beats_fail_over": (
                cp["beats_all_failed_over"] == cp["rounds"]
            ),
            # ...and a surviving replica serves a complete status (all
            # volunteers alive + multigroup rollup) within one heartbeat
            # interval of the kill.
            "pass_status_within_heartbeat": (
                cp["status_within_heartbeat_rounds"] == cp["rounds"]
            ),
            "pass_rollup_served": cp["rollup_ok_rounds"] == cp["rounds"],
            "max_status_failover_s": cp["max_status_failover_s"],
        }
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[done] artifact -> {args.out}")
        print(json.dumps(result["verdict"], indent=2))
        ok = all(v for k, v in result["verdict"].items() if k.startswith("pass_"))
        sys.exit(0 if ok else 1)

    if args.multigroup:
        result = {"multigroup_campaign": asyncio.run(multigroup_campaign(args))}
        mg = result["multigroup_campaign"]["verdict_inputs"]
        result["verdict"] = {
            # The acceptance bar: a group-leader kill never delays or
            # taints any OTHER group's round in the same rotation.
            "pass_other_groups_unaffected": (
                mg["others_unaffected_rounds"] == mg["rounds"]
            ),
            "pass_local_recovery": (
                mg["local_recovery_rounds"] >= 0.8 * mg["rounds"]
            ),
            "pass_flash_crowd": (
                mg["burst_rounds"] > 0
                and mg["burst_rounds_committed"] == mg["burst_rounds"]
                and mg["max_groups_seen"] >= 4
            ),
        }
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[done] artifact -> {args.out}")
        print(json.dumps(result["verdict"], indent=2))
        sys.exit(0 if all(result["verdict"].values()) else 1)

    if args.mesh_degrade:
        result = {"mesh_degrade_campaign": asyncio.run(mesh_degrade_campaign(args))}
        mc = result["mesh_degrade_campaign"]
        result["verdict"] = {
            k: v for k, v in mc.items() if k.startswith("pass_")
        }
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[done] artifact -> {args.out}")
        print(json.dumps(result["verdict"], indent=2))
        sys.exit(0 if all(result["verdict"].values()) else 1)

    if args.failover:
        result = {"failover_campaign": asyncio.run(failover_campaign(args))}
        fc = result["failover_campaign"]
        fracs = [p["recovery_frac"] for p in fc["phases"].values()]
        stall_ok = all(
            p["within_stall_bound"] == p["rounds"] for p in fc["phases"].values()
        )
        result["verdict"] = {
            "recovery_frac_min": min(fracs),
            "pass_95pct_recovery": min(fracs) >= 0.95,
            "pass_stall_bound": stall_ok,
            "pass_fencing": (
                fc["fencing"]["survivors_recovered"]
                and fc["fencing"]["stale_serve_rejected"]
                and fc["fencing"]["stale_push_rejected"]
            ),
        }
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"[done] artifact -> {args.out}")
        print(json.dumps(result["verdict"], indent=2))
        ok = all(v for k, v in result["verdict"].items() if k.startswith("pass_"))
        sys.exit(0 if ok else 1)

    result = {"campaign": asyncio.run(campaign(args))}
    if not args.no_train:
        print("[training] 4 subprocess volunteers, one x10-slow stepper ...")
        result["training"] = training_phase(args)
        print(f"[training] target crossed: {result['training']['target_crossed']}, "
              f"final losses {result['training']['final_losses']}")

    fd = result["campaign"]["faulted_deadline"]
    result["verdict"] = {
        "within_budget_frac": fd["within_budget_frac"],
        "pass_95pct_within_budget": fd["within_budget_frac"] >= 0.95,
        "detector_suspect_after_rounds": fd["detector_suspect_after_rounds"],
        "pass_detector_within_3_rounds": (
            fd["detector_suspect_after_rounds"] is not None
            and fd["detector_suspect_after_rounds"] <= 3
        ),
        "round_time_ratio_blocking_over_deadline": result["campaign"][
            "round_time_ratio_blocking_over_deadline"
        ],
    }
    if "training" in result:
        result["verdict"]["pass_target_crossed_under_fault"] = result[
            "training"]["target_crossed"]

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(f"[done] artifact -> {args.out}")
    print(json.dumps(result["verdict"], indent=2))
    ok = all(v for k, v in result["verdict"].items() if k.startswith("pass_"))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
