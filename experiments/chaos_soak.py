#!/usr/bin/env python
"""Chaos soak: deadline-bounded averaging vs a x10-delayed straggler.

The resilience layer's proving ground (ISSUE 1 acceptance): a 4-volunteer
swarm with ONE peer delayed x10 under a seeded fault schedule must

  1. complete >= 95% of averaging rounds within the round budget via
     partial-participation (deadline) commit — measured against a BLOCKING
     baseline in the same run (deadline machinery off, same fault active);
  2. have the phi-accrual failure detector suspect (and the leader's
     policy pre-exclude) the injected straggler within 3 rounds of fault
     onset;
  3. (training phase, subprocess volunteers) still cross the target loss
     with the straggler injected.

Three phases, one process-local swarm (real localhost TCP, real DHT,
real matchmaking — the same stack tests/test_averaging.py drives):

  warmup   — all 4 healthy: policies learn tight deadlines, detectors
             learn ~1s heartbeat gaps.
  faulted  — fault onset: the straggler's outbound RPCs gain a scheduled
             delay of 10x the healthy round time (FaultSchedule, seeded)
             and its heartbeat cadence stretches x10 (a stalled peer whose
             membership record does NOT TTL-expire — the window where phi
             is the only liveness signal). Honest rounds must keep
             committing at their learned deadlines with 3/4 participants.
  blocking — same fault, deadline machinery disabled (the pre-tentpole
             behavior): every round now waits on the straggler's delayed
             push, measuring what the deadline commit saves.

Artifact: experiments/results/chaos_soak.json (committed — the numbers
quoted in docs/resilience.md come from it).

Usage:
    python experiments/chaos_soak.py                  # full campaign + training
    python experiments/chaos_soak.py --quick          # short campaign, no training
    python experiments/chaos_soak.py --no-train       # campaign only
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import re
import statistics
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from distributedvolunteercomputing_tpu.swarm.averager import SyncAverager  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.chaos import (  # noqa: E402
    ChaosTransport,
    FaultSchedule,
    fault_event,
)
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.failure_detector import (  # noqa: E402
    PhiAccrualDetector,
)
from distributedvolunteercomputing_tpu.swarm.membership import (  # noqa: E402
    PEERS_KEY,
    SwarmMembership,
)
from distributedvolunteercomputing_tpu.swarm.resilience import (  # noqa: E402
    ResiliencePolicy,
)
from distributedvolunteercomputing_tpu.swarm.transport import Transport  # noqa: E402

STRAGGLER = "v3"  # sorts last: v0 always leads


def tree_for(i: int, size: int = 2048):
    return {"w": np.full((size,), float(i), np.float32)}


async def build_swarm(seed: int, gather_timeout: float):
    """4 volunteers: v0..v2 honest (detector + policy attached), v3 the
    future straggler on a ChaosTransport driven by a seeded schedule."""
    vols = []
    boot = None
    schedule = FaultSchedule([], seed=seed)  # events injected at onset
    for i in range(4):
        pid = f"v{i}"
        if pid == STRAGGLER:
            t = ChaosTransport(schedule=schedule)
        else:
            t = Transport()
        dht = DHTNode(t)
        await dht.start(bootstrap=[boot] if boot else None)
        if boot is None:
            boot = t.addr
        fd = policy = None
        if pid != STRAGGLER:
            fd = PhiAccrualDetector(bootstrap_s=2.0)
            policy = ResiliencePolicy(
                max_deadline_s=gather_timeout, min_deadline_s=1.0,
                preexclude_misses=3, failure_detector=fd,
            )
        mem = SwarmMembership(dht, pid, ttl=3.0, failure_detector=fd)
        await mem.join()
        avg = SyncAverager(
            t, dht, mem,
            min_group=3, max_group=4,
            join_timeout=8.0, gather_timeout=gather_timeout,
            resilience=policy, failure_detector=fd,
        )
        vols.append({
            "pid": pid, "t": t, "dht": dht, "mem": mem, "avg": avg,
            "fd": fd, "policy": policy,
        })
    return vols, schedule


async def run_round(vols, r, include_straggler, timeout=60.0):
    """One synchronized round over ``vols`` (honest subset or all four);
    returns the leader's (dt, result, budget_before)."""
    players = [v for v in vols if include_straggler or v["pid"] != STRAGGLER]
    leader = vols[0]
    budget = leader["avg"]._round_budget()
    t0 = time.monotonic()
    results = await asyncio.gather(
        *(
            asyncio.wait_for(
                v["avg"].average(tree_for(i), round_no=r), timeout=timeout
            )
            for i, v in enumerate(players)
        ),
        return_exceptions=True,
    )
    dt = time.monotonic() - t0
    lead_res = results[0]
    if isinstance(lead_res, BaseException):
        lead_res = None
    return dt, lead_res, budget


async def straggler_loop(straggler, stop: asyncio.Event):
    """Free-running straggler: a stalled peer is not synchronized with the
    swarm — it keeps trying rounds on its own crawling schedule, its stale
    matchmaking announce keeps it a formation candidate, and its begin
    handler stays reachable (inbound RPCs are not delayed)."""
    r = 10_000
    while not stop.is_set():
        r += 1
        try:
            await asyncio.wait_for(
                straggler["avg"].average(tree_for(3), round_no=r), timeout=30.0
            )
        except Exception:
            pass
        try:
            await asyncio.wait_for(asyncio.shield(stop.wait()), timeout=0.2)
        except asyncio.TimeoutError:
            pass


async def campaign(args):
    gather_timeout = 12.0
    vols, schedule = await build_swarm(args.seed, gather_timeout)
    honest = [v for v in vols if v["pid"] != STRAGGLER]
    straggler = vols[3]
    leader = vols[0]
    out = {"seed": args.seed}
    try:
        # -- phase 1: healthy warmup --------------------------------------
        warm_dts = []
        for r in range(args.warmup_rounds):
            dt, res, _ = await run_round(vols, r, include_straggler=True)
            assert res is not None, f"healthy warmup round {r} failed"
            warm_dts.append(dt)
        healthy_mean = statistics.mean(warm_dts)
        healthy_p95 = sorted(warm_dts)[max(0, int(0.95 * len(warm_dts)) - 1)]
        # Round-trip overhead allowance for the within-budget accounting:
        # the budget bounds the GATHER; formation (announce + settle) rides
        # on top in every round, healthy or not.
        overhead = max(healthy_p95, 1.0)
        out["healthy"] = {
            "rounds": len(warm_dts),
            "mean_round_s": round(healthy_mean, 3),
            "p95_round_s": round(healthy_p95, 3),
            "learned_deadline_s": round(leader["policy"].round_budget(), 3),
        }
        print(f"[warmup] {len(warm_dts)} rounds, mean {healthy_mean:.2f}s, "
              f"learned deadline {leader['policy'].round_budget():.2f}s")

        # -- fault onset ---------------------------------------------------
        # The straggler becomes x10 slow: every outbound RPC gains a
        # scheduled delay of 10x the healthy round time, and its heartbeat
        # cadence stretches x10 (ttl 3 -> 30: the record stays ALIVE, so
        # the binary TTL never fires — only phi can see the stall).
        delay = 10.0 * healthy_mean
        schedule.events = [fault_event(0.0, float("inf"), "delay", delay)]
        schedule.start()
        straggler["mem"].ttl = 30.0
        # Bridge announce: the last ttl=3 record must not expire before the
        # first slow beat (10s) or honest peers would forget + re-learn.
        await straggler["dht"].store(
            PEERS_KEY, straggler["mem"]._record(), subkey=STRAGGLER, ttl=30.0
        )
        print(f"[onset] straggler delay {delay:.2f}s/call, heartbeat x10")

        # -- phase 2: faulted, deadline-bounded ---------------------------
        stop = asyncio.Event()
        strag_task = asyncio.create_task(straggler_loop(straggler, stop))
        rounds = []
        suspect_round = preexclude_round = None
        degraded_before = leader["avg"].rounds_degraded
        for r in range(args.warmup_rounds, args.warmup_rounds + args.faulted_rounds):
            # Rounds ride a training cadence, not back-to-back: the pause is
            # the local-compute window between averaging points.
            await asyncio.sleep(args.round_cadence_s)
            dt, res, budget = await run_round(vols, r, include_straggler=False)
            degraded_now = leader["avg"].rounds_degraded
            rec = {
                "round": r,
                "dt_s": round(dt, 3),
                "budget_s": round(budget, 3),
                "committed": res is not None,
                "within_budget": res is not None and dt <= budget + overhead,
                "degraded_commit": degraded_now > degraded_before,
                "preexcluded": list(leader["avg"].matchmaker.last_preexcluded),
                "phi": round(min(leader["fd"].phi(STRAGGLER), 99.0), 2),
            }
            degraded_before = degraded_now
            idx = len(rounds)
            if suspect_round is None and leader["fd"].suspect(STRAGGLER):
                suspect_round = idx + 1  # 1-based: "within N rounds of onset"
            if preexclude_round is None and rec["preexcluded"] == [STRAGGLER]:
                preexclude_round = idx + 1
            rounds.append(rec)
        stop.set()
        strag_task.cancel()
        try:
            await strag_task
        except (asyncio.CancelledError, Exception):
            pass
        committed = [r for r in rounds if r["committed"]]
        within = [r for r in rounds if r["within_budget"]]
        out["faulted_deadline"] = {
            "rounds": len(rounds),
            "committed": len(committed),
            "within_budget": len(within),
            "within_budget_frac": round(len(within) / len(rounds), 4),
            "degraded_commits": sum(r["degraded_commit"] for r in rounds),
            "mean_round_s": round(
                statistics.mean(r["dt_s"] for r in rounds), 3
            ),
            "overhead_allowance_s": round(overhead, 3),
            "detector_suspect_after_rounds": suspect_round,
            "leader_preexcludes_after_rounds": preexclude_round,
            "straggler_phi_final": rounds[-1]["phi"],
            "per_round": rounds,
        }
        print(f"[faulted/deadline] {len(within)}/{len(rounds)} within budget "
              f"({100.0 * len(within) / len(rounds):.1f}%), straggler "
              f"suspected after {suspect_round} round(s), pre-excluded "
              f"after {preexclude_round} round(s)")

        # -- phase 3: faulted, BLOCKING baseline --------------------------
        # Deadline machinery off (the pre-tentpole behavior): rounds wait
        # for the straggler's delayed push up to the full gather budget.
        for v in vols:
            v["avg"].resilience = None
            v["avg"].round_deadline_s = None
            v["avg"].matchmaker.exclude = None
        blocking = []
        base = args.warmup_rounds + args.faulted_rounds
        for r in range(base, base + args.blocking_rounds):
            dt, res, _ = await run_round(
                vols, r, include_straggler=True,
                timeout=3.0 * gather_timeout + 3.0 * delay,
            )
            blocking.append({
                "round": r, "dt_s": round(dt, 3), "committed": res is not None,
            })
        mean_blocking = statistics.mean(b["dt_s"] for b in blocking)
        out["faulted_blocking"] = {
            "rounds": len(blocking),
            "mean_round_s": round(mean_blocking, 3),
            "per_round": blocking,
        }
        mean_deadline = out["faulted_deadline"]["mean_round_s"]
        out["round_time_ratio_blocking_over_deadline"] = round(
            mean_blocking / max(mean_deadline, 1e-9), 2
        )
        print(f"[faulted/blocking] mean round {mean_blocking:.2f}s vs "
              f"deadline-bounded {mean_deadline:.2f}s "
              f"({out['round_time_ratio_blocking_over_deadline']}x)")
    finally:
        for v in vols:
            try:
                await v["mem"].leave()
            except Exception:
                pass
            try:
                await v["dht"].stop()
            except Exception:
                pass
            await v["t"].close()
    return out


# -- training phase (subprocess volunteers, real entrypoints) --------------


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def training_phase(args):
    """4 real volunteers (run_volunteer.py) with --resilience, one stepping
    x10 slow (DVC_STEP_DELAY_MS): the swarm must still cross target loss."""
    coord = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "coordinator.py")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=_env(),
    )
    addr = None
    deadline = time.time() + 30
    while time.time() < deadline:
        line = coord.stdout.readline()
        m = re.match(r"COORDINATOR_READY (\S+)", line or "")
        if m:
            addr = m.group(1)
            break
    if addr is None:
        coord.kill()
        raise RuntimeError("coordinator did not become ready")
    common = [
        "--coordinator", addr, "--model", "mnist_mlp",
        "--model-override", "d_hidden=16",
        "--averaging", "sync", "--average-every", "10",
        "--batch-size", "16", "--lr", "0.01",
        "--steps", str(args.train_steps),
        "--target-loss", "1.0", "--target-mode", "record",
        "--min-group", "2", "--max-group", "4",
        "--join-timeout", "20", "--gather-timeout", "20",
        "--resilience", "--round-deadline-s", "5",
    ]
    vols = []
    try:
        for i in range(4):
            env = _env()
            if i == 3:  # the straggler steps x10 slower than its peers
                env["DVC_STEP_DELAY_MS"] = "150"
            vols.append(subprocess.Popen(
                [sys.executable, os.path.join(REPO, "run_volunteer.py"),
                 "--peer-id", f"t{i}", "--seed", str(i), *common],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=env,
            ))
        summaries = []
        for v in vols:
            out_text, _ = v.communicate(timeout=600)
            for line in out_text.splitlines():
                if line.startswith("VOLUNTEER_DONE "):
                    summaries.append(json.loads(line[len("VOLUNTEER_DONE "):]))
                    break
            else:
                raise AssertionError(f"no VOLUNTEER_DONE:\n{out_text[-3000:]}")
    finally:
        coord.kill()
        for v in vols:
            if v.poll() is None:
                v.kill()
    honest = summaries[:3]
    crossed = [s.get("target_crossed_step") for s in honest]
    return {
        "volunteers": 4,
        "straggler_step_delay_ms": 150,
        "steps": args.train_steps,
        "rounds_ok_total": sum(s.get("rounds_ok", 0) for s in summaries),
        "rounds_degraded_total": sum(
            s.get("rounds_degraded", 0) for s in summaries
        ),
        "final_losses": [round(s["final_loss"], 4) for s in summaries],
        "target_crossed_steps_honest": crossed,
        "target_crossed": all(c is not None for c in crossed),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--warmup-rounds", type=int, default=10)
    ap.add_argument("--faulted-rounds", type=int, default=25)
    ap.add_argument("--blocking-rounds", type=int, default=6)
    ap.add_argument("--round-cadence-s", type=float, default=0.75,
                    help="local-compute pause between faulted rounds")
    ap.add_argument("--train-steps", type=int, default=120)
    ap.add_argument("--no-train", action="store_true")
    ap.add_argument("--quick", action="store_true",
                    help="short campaign, no training phase")
    ap.add_argument("--out", default=os.path.join(
        REPO, "experiments", "results", "chaos_soak.json"))
    args = ap.parse_args()
    if args.quick:
        args.warmup_rounds = 6
        args.faulted_rounds = 10
        args.blocking_rounds = 3
        args.no_train = True

    result = {"campaign": asyncio.run(campaign(args))}
    if not args.no_train:
        print("[training] 4 subprocess volunteers, one x10-slow stepper ...")
        result["training"] = training_phase(args)
        print(f"[training] target crossed: {result['training']['target_crossed']}, "
              f"final losses {result['training']['final_losses']}")

    fd = result["campaign"]["faulted_deadline"]
    result["verdict"] = {
        "within_budget_frac": fd["within_budget_frac"],
        "pass_95pct_within_budget": fd["within_budget_frac"] >= 0.95,
        "detector_suspect_after_rounds": fd["detector_suspect_after_rounds"],
        "pass_detector_within_3_rounds": (
            fd["detector_suspect_after_rounds"] is not None
            and fd["detector_suspect_after_rounds"] <= 3
        ),
        "round_time_ratio_blocking_over_deadline": result["campaign"][
            "round_time_ratio_blocking_over_deadline"
        ],
    }
    if "training" in result:
        result["verdict"]["pass_target_crossed_under_fault"] = result[
            "training"]["target_crossed"]

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(f"[done] artifact -> {args.out}")
    print(json.dumps(result["verdict"], indent=2))
    ok = all(v for k, v in result["verdict"].items() if k.startswith("pass_"))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
