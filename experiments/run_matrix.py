#!/usr/bin/env python
"""The five-config experiment matrix (BASELINE.json:7-11), as real localhost
swarms through the actual CLI entrypoints.

Each config launches a coordinator + N `run_volunteer.py` processes on
127.0.0.1 (CPU backend — the swarm/averaging tier is host-side by design;
SURVEY.md §1 maps the WAN tier to DCN, not the chip), records every
volunteer's VOLUNTEER_DONE summary plus wall-clock into
``experiments/results/config{N}.jsonl``, and writes a machine-readable
``experiments/results/summary.json`` whose rows back BASELINE.md.

Model sizes are scaled-down proxies (SURVEY.md §7 step 6 prescribes proxy
models in the sandbox); the averaging MODES and swarm shapes are the real
thing:

  1  mnist_mlp          1 volunteer   local SGD (no averaging)
  2  cifar10_resnet18   2 volunteers  synchronous GradientAverager
  3  bert_mlm           4 volunteers  async gossip
  4  gpt2_small         4 volunteers  butterfly, heterogeneous speeds
                                      (per-volunteer batch sizes)
  5  llama_lora         4 volunteers  byzantine (trimmed mean) + kill -9 churn

Config 0 is the overlap throughput experiment (VERDICT r2 #2): a
2-volunteer sync swarm at --average-every 10 with overlapped rounds must
sustain >= 90% of the single-volunteer no-averaging samples/sec.

Configs 6-7 re-run configs 1-2 through the REAL-data path: a deterministic
.npz (experiments/make_npz.py) driven via --data, plus the separate
held-out eval stream (config 6).

Configs 2-5 carry a per-proxy --target-loss in record mode, so every row
reports time-to-target-loss alongside fixed-budget throughput.

Run:  python experiments/run_matrix.py            # all configs
      python experiments/run_matrix.py --config 3 # one config
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(REPO, "experiments", "results")


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    # Keep the axon TPU plugin's backend discovery away from subprocesses
    # (a wedged relay would hang every volunteer at import time).
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def start_coordinator():
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "coordinator.py")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=_env(),
    )
    deadline = time.time() + 30
    while time.time() < deadline:
        line = proc.stdout.readline() or ""
        if line.startswith("COORDINATOR_READY "):
            return proc, line.split()[1]
    proc.kill()
    raise RuntimeError("coordinator did not become ready")


def start_volunteer(coord, peer_id, args, extra_env=None):
    env = _env()
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(
        [
            sys.executable, os.path.join(REPO, "run_volunteer.py"),
            "--coordinator", coord, "--peer-id", peer_id, *args,
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )


def wait_done(proc, timeout):
    out, _ = proc.communicate(timeout=timeout)
    for line in out.splitlines():
        if line.startswith("VOLUNTEER_DONE "):
            return json.loads(line[len("VOLUNTEER_DONE "):]), out
    return None, out


def run_swarm(
    name, vol_specs, timeout=600, kill_after=None, chaos_peer=None, slow_peer=None,
    tolerate_missing=False,
):
    """Launch a swarm; vol_specs = [(peer_id, [cli args]), ...].

    ``kill_after``: (seconds, peer_index) — SIGKILL that volunteer mid-run
    (the config-5 churn). ``chaos_peer``: (peer_id, scale) — that volunteer
    contributes its tree scaled by ``scale`` (the DVC_CHAOS_CONTRIB_SCALE
    byzantine fault-injection hook). ``slow_peer``: (peer_id, delay_ms) —
    that volunteer's steps are slowed by the DVC_STEP_DELAY_MS heterogeneity
    hook. Returns (peer_id, summary|None, wall_s).
    """

    def _extra_env(pid):
        env = {}
        if chaos_peer and pid == chaos_peer[0]:
            env["DVC_CHAOS_CONTRIB_SCALE"] = chaos_peer[1]
        if slow_peer and pid == slow_peer[0]:
            env["DVC_STEP_DELAY_MS"] = slow_peer[1]
        return env or None

    coord, addr = start_coordinator()
    t0 = time.monotonic()
    rows = []
    try:
        vols = [
            (pid, start_volunteer(addr, pid, args, extra_env=_extra_env(pid)))
            for pid, args in vol_specs
        ]
        if kill_after is not None:
            delay, idx = kill_after
            time.sleep(delay)
            print(f"[{name}] kill -9 {vols[idx][0]} (churn injection)", flush=True)
            vols[idx][1].send_signal(signal.SIGKILL)
        for pid, proc in vols:
            try:
                summary, out = wait_done(proc, timeout=timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                summary, out = None, "(timeout)"
            if summary is None and (kill_after is None or pid != vols[kill_after[1]][0]):
                tail = "\n".join(out.splitlines()[-15:])
                if not tolerate_missing:
                    raise RuntimeError(
                        f"[{name}] volunteer {pid} produced no summary:\n{tail}"
                    )
                # Straggler-tolerant mode (scale16's 16-contended-process
                # regime): record the survivor data, mark this one dead.
                print(f"[{name}] volunteer {pid} produced no summary (recorded as dead):\n{tail}", flush=True)
            rows.append((pid, summary, time.monotonic() - t0))
    finally:
        coord.kill()
        for _, proc in vols:
            if proc.poll() is None:
                proc.kill()
    return rows


def record(config_key, rows, extra=None):
    os.makedirs(RESULTS, exist_ok=True)
    path = os.path.join(RESULTS, f"{config_key}.jsonl")
    with open(path, "w") as fh:
        for pid, summary, wall in rows:
            fh.write(json.dumps({"peer": pid, "wall_s": round(wall, 2), **(summary or {"dead": True})}) + "\n")
        if extra:
            fh.write(json.dumps({"derived": extra}) + "\n")
    alive = [s for _, s, _ in rows if s]
    agg = {
        "volunteers": len(rows),
        "finished": len(alive),
        "samples_per_sec_per_volunteer": round(
            sum(s["samples_per_sec"] for s in alive) / max(len(alive), 1), 2
        ),
        "final_loss_mean": round(sum(s["final_loss"] for s in alive) / max(len(alive), 1), 4),
        "wall_s_max": round(max(w for _, _, w in rows), 1),
        "rounds_ok_total": sum(int(s.get("rounds_ok", 0)) for s in alive),
        "rounds_skipped_total": sum(int(s.get("rounds_skipped", 0)) for s in alive),
    }
    # Time-to-target-loss (the metric's second half, BASELINE.json:2): each
    # volunteer reports its first crossing of the per-config target; the row
    # aggregates mean crossing wall time over the volunteers that crossed.
    with_target = [s for s in alive if s.get("target_loss") is not None]
    if with_target:
        crossed = [s["target_crossed_s"] for s in with_target
                   if s.get("target_crossed_s") is not None]
        agg["target_loss"] = with_target[0]["target_loss"]
        agg["crossed"] = f"{len(crossed)}/{len(with_target)}"
        agg["time_to_target_s_mean"] = (
            round(sum(crossed) / len(crossed), 2) if crossed else None
        )
    if extra:
        agg.update(extra)
    print(f"[{config_key}] {json.dumps(agg)}", flush=True)
    return agg


# --------------------------------------------------------------- configs ----

TINY_RESNET = ["--model-override", "stage_sizes=[1,1]", "--model-override", "widths=[8,16]",
               "--model-override", "stem_width=8", "--model-override", "groups=2"]
TINY_BERT = ["--model-override", "vocab=256", "--model-override", "max_len=32",
             "--model-override", "d_model=64", "--model-override", "n_heads=2",
             "--model-override", "n_layers=2", "--model-override", "d_ff=128"]
TINY_GPT2 = ["--model-override", "vocab=256", "--model-override", "max_len=32",
             "--model-override", "d_model=64", "--model-override", "n_heads=2",
             "--model-override", "n_layers=2", "--model-override", "d_ff=128"]
TINY_LLAMA = ["--model-override", "vocab=256", "--model-override", "max_len=32",
              "--model-override", "d_model=64", "--model-override", "n_heads=4",
              "--model-override", "n_kv_heads=4", "--model-override", "n_layers=2",
              "--model-override", "d_ff=128", "--model-override", "lora_rank=4"]
TIMEOUTS = ["--join-timeout", "25", "--gather-timeout", "25"]

# Per-proxy time-to-target targets (VERDICT r3 #4): the loss the dense-f32
# run reached at the fixed 60-step budget in the committed round-3 matrix
# (summary.json final_loss_mean, rounded up one notch so a healthy run
# crosses just before the end). Config 1 keeps its stop-at-target semantics;
# configs 2-5 train the full budget and RECORD the first crossing.
def _target(loss: float) -> list:
    return ["--target-loss", str(loss), "--target-mode", "record"]


def config1():
    rows = run_swarm("config1", [
        ("solo", ["--model", "mnist_mlp", "--averaging", "none", "--steps", "300",
                  "--batch-size", "32", "--lr", "0.01", "--target-loss", "0.15"]),
    ])
    return record("config1_mnist_localsgd", rows)


def config2():
    common = ["--model", "cifar10_resnet18", *TINY_RESNET, "--averaging", "sync",
              "--average-every", "10", "--steps", "60", "--batch-size", "16",
              "--lr", "0.005", *TIMEOUTS, *_target(2.3)]
    rows = run_swarm("config2", [
        (f"res{i}", common + ["--seed", str(i)]) for i in range(2)
    ])
    return record("config2_resnet_sync", rows)


def config3():
    common = ["--model", "bert_mlm", *TINY_BERT, "--averaging", "gossip",
              "--average-every", "10", "--steps", "60", "--batch-size", "16",
              "--lr", "0.003", *TIMEOUTS, *_target(5.6)]
    rows = run_swarm("config3", [
        (f"bert{i}", common + ["--seed", str(i)]) for i in range(4)
    ])
    return record("config3_bert_gossip", rows)


def _config4_swarm(name: str, cadence: list) -> list:
    """Config 4's swarm — heterogeneous volunteers: same data budget per
    optimizer step is not required by butterfly, each contributes its own
    weight. The speed spread comes from per-volunteer batch sizes (a v4-8
    vs v5e-4 swarm in miniature, BASELINE.json:10). ONE roster shared by
    the step-cadence and wall-clock-cadence arms, so 'same swarm, only the
    cadence differs' holds by construction."""
    base = ["--model", "gpt2_small", *TINY_GPT2, "--averaging", "butterfly",
            *cadence, "--lr", "0.003", *TIMEOUTS, *_target(4.4)]
    return run_swarm(name, [
        ("fast0", base + ["--steps", "60", "--batch-size", "8", "--seed", "0"]),
        ("fast1", base + ["--steps", "60", "--batch-size", "8", "--seed", "1"]),
        ("slow0", base + ["--steps", "60", "--batch-size", "32", "--seed", "2"]),
        ("slow1", base + ["--steps", "60", "--batch-size", "32", "--seed", "3"]),
    ])


def config4():
    rows = _config4_swarm("config4", ["--average-every", "10"])
    return record("config4_gpt2_butterfly_hetero", rows)


def config4b():
    """Config 4 on the WALL-CLOCK cadence (r4 VERDICT #6). The step cadence
    parks fast volunteers at every rendezvous once speeds diverge —
    interval_ab measured it completing ZERO rounds under an 8x speed
    spread while the interval cadence ran at full speed. Identical swarm
    (shared roster, _config4_swarm); only the cadence flag differs
    (boundaries at absolute 20s multiples of swarm-consensus time, rounds
    weighted by steps-since-merge). Measured 2026-07-31: crossed 3/4 ->
    4/4, rounds 18/6 -> 56/0, time-to-target 299 -> 232 s."""
    rows = _config4_swarm("config4b", ["--average-interval-s", "20"])
    return record("config4b_gpt2_butterfly_hetero_interval", rows)


def config5():
    common = ["--model", "llama_lora", *TINY_LLAMA, "--averaging", "byzantine",
              "--method", "trimmed_mean", "--average-every", "8", "--steps", "64",
              "--batch-size", "8", "--lr", "0.005", "--min-group", "2",
              *TIMEOUTS, *_target(6.1)]
    rows = run_swarm(
        "config5",
        [(f"lora{i}", common + ["--seed", str(i)]) for i in range(4)],
        kill_after=(25.0, 3),  # churn: one volunteer dies un-gracefully
    )
    return record("config5_llama_lora_byzantine_churn", rows)


def _ensure_npz(task: str) -> str:
    """Generate the deterministic dataset file (experiments/make_npz.py) if
    it isn't there yet; returns its path. Regenerable data — not committed."""
    path = os.path.join(RESULTS, f"data_{task}.npz")
    if not os.path.exists(path):
        subprocess.run(
            [sys.executable, os.path.join(REPO, "experiments", "make_npz.py"),
             "--task", task, "--out", path],
            check=True, env=_env(),
        )
    return path


def config6_file_mnist():
    """Config 1 driven through the REAL-data path (--data .npz): file load,
    per-peer shuffle sharding, and the separate held-out eval stream
    (--eval-every) all exercised end to end."""
    path = _ensure_npz("mnist")
    rows = run_swarm("config6", [
        ("solo-file", ["--model", "mnist_mlp", "--averaging", "none",
                       "--data", path, "--steps", "300", "--batch-size", "32",
                       "--lr", "0.01", "--target-loss", "0.15",
                       "--eval-every", "50", "--eval-batches", "4"]),
    ])
    return record("config6_mnist_localsgd_file", rows)


def config7_file_resnet():
    """Config 2 over the file-backed data path: 2-volunteer sync swarm where
    both volunteers shard the SAME .npz's shuffle order per peer id."""
    path = _ensure_npz("cifar10")
    common = ["--model", "cifar10_resnet18", *TINY_RESNET, "--averaging", "sync",
              "--data", path, "--average-every", "10", "--steps", "60",
              "--batch-size", "16", "--lr", "0.005", *TIMEOUTS, *_target(2.3)]
    rows = run_swarm("config7", [
        (f"resf{i}", common + ["--seed", str(i)]) for i in range(2)
    ])
    return record("config7_resnet_sync_file", rows)


def config0_overlap():
    """Overlap throughput: 2-volunteer sync at --average-every 10 must hold
    >= 90% of the no-averaging samples/sec (VERDICT r2 #2 done-criterion).

    The no-averaging baseline is TWO concurrent volunteers (averaging none):
    on a shared localhost the processes contend for the same cores, so a
    single-process baseline would charge that contention to the averager.
    The blocking variant (--no-overlap) runs too, so the JSONL records what
    the overlap actually buys."""
    base = ["--model", "mnist_mlp", "--model-override", "d_hidden=512",
            "--steps", "120", "--batch-size", "32", "--lr", "0.005"]

    def mean_sps(rows):
        return sum(s["samples_per_sec"] for _, s, _ in rows if s) / len(rows)

    none_rows = run_swarm("overlap/baseline", [
        ("none0", base + ["--averaging", "none"]),
        ("none1", base + ["--averaging", "none"]),
    ])
    sync = base + ["--averaging", "sync", "--average-every", "10", *TIMEOUTS]
    ov_rows = run_swarm("overlap/overlapped", [
        ("ov0", sync + ["--overlap", "--seed", "0"]),
        ("ov1", sync + ["--overlap", "--seed", "1"]),
    ])
    bl_rows = run_swarm("overlap/blocking", [
        ("bl0", sync + ["--no-overlap", "--seed", "0"]),
        ("bl1", sync + ["--no-overlap", "--seed", "1"]),
    ])
    base_sps, ov_sps, bl_sps = mean_sps(none_rows), mean_sps(ov_rows), mean_sps(bl_rows)
    agg = record(
        "config0_overlap_throughput", none_rows + ov_rows + bl_rows,
        extra={
            "baseline_sps": round(base_sps, 2),
            "overlap_sps": round(ov_sps, 2),
            "blocking_sps": round(bl_sps, 2),
            "overlap_throughput_ratio": round(ov_sps / base_sps, 3),
            "blocking_throughput_ratio": round(bl_sps / base_sps, 3),
        },
    )
    return agg


def config8_kitchen_sink_r4():
    """Round-4 second-session feature composition as ONE swarm: PowerSGD
    grad wire is grads-mode-only while the outer optimizer and wall-clock
    cadence are params-mode, so this runs the params-mode trio — byzantine
    (trimmed-mean) aggregation x DiLoCo outer Nesterov x --average-interval-s
    x --steps-per-call — on the gpt2 proxy with kill -9 churn, proving the
    new features compose with each other AND with the robust path under
    failure. A separate 3-volunteer grads-mode arm (2 honest + 1
    chaos-scaled — the minimum where trimmed mean can actually reject the
    byzantine row) runs powersgd under byzantine aggregation."""
    common = ["--model", "gpt2_small", *TINY_GPT2, "--averaging", "byzantine",
              "--method", "trimmed_mean", "--average-interval-s", "8",
              "--steps-per-call", "4", "--outer-optimizer", "nesterov",
              "--steps", "120", "--batch-size", "8", "--lr", "0.003",
              "--min-group", "2", *TIMEOUTS, *_target(4.4)]
    rows = run_swarm(
        "config8/params_trio",
        [(f"sink{i}", common + ["--seed", str(i)]) for i in range(4)],
        timeout=900,  # 4 contending volunteers + wall-clock rounds
        kill_after=(30.0, 3),  # churn under the new cadence
    )
    agg = record("config8_outer_interval_spc_byz_churn", rows)

    # Shared base for the grads-mode byzantine-with-attacker arms. The
    # EXPLICIT trim=1 is load-bearing (r5 review finding): the derived
    # default trim = n_peers//4 is ZERO for the 3-peer groups these arms
    # spend most rounds in (3 starters, or 4 minus a kill), i.e. a plain
    # mean that would include the attacker at full weight — the explicit
    # value is clamped per-round to what the group size admits.
    byz_grads = ["--model", "gpt2_small", *TINY_GPT2, "--averaging", "byzantine",
                 "--method", "trimmed_mean", "--method-kw", "trim=1",
                 "--average-what", "grads",
                 "--steps", "30", "--batch-size", "8", "--lr", "0.003",
                 "--min-group", "2", *TIMEOUTS]
    gcommon = byz_grads + ["--wire", "powersgd", "--psgd-rank", "4"]
    grows = run_swarm(
        "config8/psgd_byz",
        [("psgd0", gcommon + ["--seed", "0"]),
         ("psgd1", gcommon + ["--seed", "1"]),
         ("psgd2", gcommon + ["--seed", "2"])],
        chaos_peer=("psgd2", "-3.0"),  # byzantine-valued contributions
    )
    agg2 = record("config8_psgd_byzantine_wire", grows)

    # r5: the 1-bit sign wire under the same byzantine-with-attacker shape
    # AND kill -9 churn — grads mode, a x-3 chaos peer plus an un-graceful
    # death among four volunteers; honest survivors must converge.
    scommon = byz_grads + ["--wire", "sign"]
    srows = run_swarm(
        "config8/sign_byz",
        [(f"sgn{i}", scommon + ["--seed", str(i)]) for i in range(4)],
        chaos_peer=("sgn3", "-3.0"),
        kill_after=(25.0, 2),
    )
    agg3 = record("config8_sign_byzantine_churn", srows)
    return {"params_trio": agg, "psgd_byz": agg2, "sign_byz": agg3}


CONFIGS = {
    0: config0_overlap, 1: config1, 2: config2, 3: config3, 4: config4, 5: config5,
    6: config6_file_mnist, 7: config7_file_resnet, 8: config8_kitchen_sink_r4,
    9: config4b,  # config 4's wall-clock-cadence arm (r4 VERDICT #6)
}


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", type=int, default=None, choices=sorted(CONFIGS),
                    help="run one config (default: all)")
    args = ap.parse_args()
    todo = [args.config] if args.config is not None else sorted(CONFIGS)
    summary = {}
    for n in todo:
        t0 = time.monotonic()
        summary[f"config{n}"] = CONFIGS[n]()
        summary[f"config{n}"]["experiment_wall_s"] = round(time.monotonic() - t0, 1)
    os.makedirs(RESULTS, exist_ok=True)
    path = os.path.join(RESULTS, "summary.json")
    existing = {}
    if os.path.exists(path):
        with open(path) as fh:
            existing = json.load(fh)
    existing.update(summary)
    with open(path, "w") as fh:
        json.dump(existing, fh, indent=1, sort_keys=True)
    print(json.dumps(existing, indent=1, sort_keys=True))


if __name__ == "__main__":
    main()
