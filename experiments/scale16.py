#!/usr/bin/env python
"""The averaging tier at 16 volunteers — max_group's upper bound, run for
real (r4 VERDICT #8: the DHT was tested at 16 nodes, the averaging tier
only at 4-8; "supports up to 16" was an untested claim).

Two arms through the real CLI entrypoints on localhost:
  butterfly16 — a full 16-member hypercube round is 4 pairwise-exchange
                stages; 16 is the largest group the default max_group
                admits and the shape where stage bookkeeping is busiest.
  gossip16    — 16-peer partner selection pressure: every round each
                volunteer picks a partner from 15 candidates; overlap and
                xid-dedup machinery see their densest traffic.

Tiny-MLP proxy (the matrix's standard scaling-down; SURVEY.md §7 step 6 —
the averaging tier is host-side and model-size-independent except payload
bytes, which the soaks cover at scale separately). Everything shares one
CPU core in the sandbox, so steps are deliberately few and timeouts wide;
the assertion of interest is rounds_ok > 0 on (nearly) every volunteer
with a converging mean loss, recorded to
experiments/results/scale16_{butterfly,gossip}.jsonl + summary.json.

Run: python experiments/scale16.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from experiments.run_matrix import (  # noqa: E402
    RESULTS, record, run_swarm,
)

TINY_MLP = ["--model", "mnist_mlp", "--model-override", "d_hidden=32"]
# 16 processes share one core: joins straggle, so rendezvous windows are
# wide and the step budget small (the tier under test is averaging, not
# throughput).
TIMEOUTS = ["--join-timeout", "40", "--gather-timeout", "40"]


def arm(tag: str, averaging: str, extra: list, cadence: list = None) -> dict:
    base = [
        *TINY_MLP, "--averaging", averaging,
        *(cadence or ["--average-every", "15"]),
        # 16 cold jax processes on one sandbox core: the first ~60 steps
        # race process startup, so a 60-step budget left late joiners with
        # zero rounds. 240 steps gives every volunteer several windows
        # AFTER the whole swarm is up.
        "--steps", "240", "--batch-size", "16", "--lr", "0.01",
        "--max-group", "16", *TIMEOUTS, *extra,
    ]
    rows = run_swarm(
        f"scale16/{tag}",
        [(f"v{i:02d}", base + ["--seed", str(i)]) for i in range(16)],
        timeout=900,
        # One straggler among 16 contended processes must cost one row,
        # not the whole two-arm run.
        tolerate_missing=True,
    )
    agg = record(f"scale16_{tag}", rows)
    agg["n_rounds_ok_min"] = min(
        (s["rounds_ok"] for _, s, _ in rows if s), default=0
    )
    return agg


def main() -> int:
    out = {}
    # Butterfly on the step cadence at n=16 reproduced the config-4 disease
    # at scale (14 ok / 16 skipped, min 0 per volunteer — committed in the
    # first scale16_butterfly.jsonl): 16 one-core processes step at wildly
    # different instantaneous rates, so step-count boundaries never line
    # up. The wall-clock cadence is the cure config 4b proved; butterfly
    # runs on it here.
    out["butterfly"] = arm(
        "butterfly", "butterfly", ["--min-group", "4"],
        cadence=["--average-interval-s", "20"],
    )
    out["gossip"] = arm("gossip", "gossip", [])
    path = os.path.join(RESULTS, "scale16.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    for tag, agg in out.items():
        print(
            f"scale16 {tag:10s}: finished {agg['finished']}/16, "
            f"rounds_ok_total {agg['rounds_ok_total']}, "
            f"min per-volunteer {agg['n_rounds_ok_min']}, "
            f"loss {agg['final_loss_mean']}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
