"""Is the flagship step time dispatch-bound or compute-bound?

bench.py's measure loop issues one jitted step per Python call; on the
tunneled axon runtime each call is an HTTP dispatch. The 20 calls chain
through the donated TrainState, so IF the runtime pipelines async dispatches
the tunnel latency hides and the measured 135ms/step is real compute. This
probe settles it: run the same train step (a) as bench does, one dispatch
per step, and (b) as a lax.scan of N steps inside ONE compiled call — no
per-step dispatch at all. If (b) is meaningfully faster per step, bench
under-reports the chip and a multi-step mode is worth shipping; if equal,
the step is compute-bound and the MFU work moves to the step itself.

Writes experiments/results/step_scan_probe.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from distributedvolunteercomputing_tpu.models import get_model
from distributedvolunteercomputing_tpu.training.optim import make_optimizer
from distributedvolunteercomputing_tpu.training.steps import TrainState, make_train_step

BS = int(os.environ.get("DVC_PROBE_BATCH", "8"))
ITERS = 20
SCAN_N = 10


def main():
    bundle = get_model("gpt2_small", remat=False)
    tx = make_optimizer("adamw", lr=1e-4)
    params = bundle.init(jax.random.PRNGKey(1))
    n_params = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    state = TrainState.create(params, tx, jax.random.PRNGKey(2))
    del params
    step = make_train_step(bundle.loss_fn, tx)
    batch = bundle.make_batch(jax.random.PRNGKey(0), BS)
    print(f"built {n_params/1e6:.1f}M params", flush=True)

    # (a) bench-style: one dispatch per step, sync once at the end.
    for _ in range(3):
        state, m = step(state, batch)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, m = step(state, batch)
    loss_a = float(m["loss"])
    per_step_a = (time.perf_counter() - t0) / ITERS
    print(f"(a) per-dispatch: {per_step_a*1e3:.1f} ms/step loss={loss_a:.3f}", flush=True)

    # (b) scan-over-steps: SCAN_N steps in one compiled call, built on the
    # same traced body the jitted step uses (training/steps.py
    # train_step_body), so (a) and (b) run identical math.
    from distributedvolunteercomputing_tpu.training.steps import train_step_body

    def multi(state):
        def body(s, _):
            s2, mm = train_step_body(bundle.loss_fn, tx, s, batch)
            return s2, mm["loss"]

        return jax.lax.scan(body, state, None, length=SCAN_N)

    multi_j = jax.jit(multi, donate_argnums=(0,))
    t0 = time.monotonic()
    state, losses = multi_j(state)
    float(losses[-1])
    compile_s = time.monotonic() - t0
    t0 = time.perf_counter()
    state, losses = multi_j(state)
    loss_b = float(losses[-1])
    per_step_b = (time.perf_counter() - t0) / SCAN_N
    print(
        f"(b) scanned: {per_step_b*1e3:.1f} ms/step (compile+first {compile_s:.1f}s) "
        f"loss={loss_b:.3f}",
        flush=True,
    )

    out = {
        "device_kind": jax.devices()[0].device_kind,
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "batch_size": BS,
        "per_dispatch_ms": round(per_step_a * 1e3, 2),
        "scanned_ms": round(per_step_b * 1e3, 2),
        "dispatch_overhead_ms": round((per_step_a - per_step_b) * 1e3, 2),
        "samples_per_sec_dispatch": round(BS / per_step_a, 2),
        "samples_per_sec_scanned": round(BS / per_step_b, 2),
    }
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results", "step_scan_probe.json"
    )
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
