"""Transport bench: pooled multiplexed connections vs per-call connects.

The committed artifact behind the ISSUE-3 transport rewrite
(``experiments/results/transport_bench.json``): measures RPC round-trip
throughput at small payloads (where the per-call TCP dial used to dominate
— every heartbeat, DHT ping, clock probe, and matchmaking begin paid one)
and large-payload goodput (which must NOT regress under chunked framing),
for the pooled transport against the v1 per-call-connect behavior
(``Transport(pooled=False)``).

Scenarios, each run in both modes over real localhost TCP:
- ``seq_small``:  N sequential small-payload RPCs (the latency-bound shape
                  of heartbeats/DHT traffic) -> RPCs/sec;
- ``conc_small``: batches of K concurrent small RPCs (the fan-out shape of
                  byzantine pushes and begin fan-outs) -> RPCs/sec;
- ``large``:      M transfers of a multi-MB payload (an averaging
                  contribution) -> MB/s goodput.

Usage:
    python experiments/transport_bench.py            # full run + artifact
    python experiments/transport_bench.py --quick    # small sanity run

The default tier-1 suite runs a fast smoke of the same harness
(tests/test_transport_pool.py::TestTransportBenchSmoke), so an RPC
throughput regression fails loudly without this script.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import platform
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributedvolunteercomputing_tpu.swarm.transport import Transport  # noqa: E402

RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")


async def _bench_mode(
    pooled: bool,
    *,
    seq_calls: int,
    payload_bytes: int,
    concurrency: int,
    conc_batches: int,
    large_mb: int,
    large_transfers: int,
) -> dict:
    server = Transport()

    async def echo(args, payload):
        return {"ok": True}, b""  # ack-only: the bench measures transport, not memcpy

    async def sink(args, payload):
        return {"n": len(payload)}, b""

    server.register("echo", echo)
    server.register("sink", sink)
    addr = await server.start()
    client = Transport(pooled=pooled)
    out: dict = {
        "pooled": pooled,
        "seq_calls": seq_calls,
        "payload_bytes": payload_bytes,
        "concurrency": concurrency,
        "conc_batches": conc_batches,
        "large_mb": large_mb,
        "large_transfers": large_transfers,
    }
    try:
        payload = os.urandom(payload_bytes)
        # Warmup (compile/caches/first dial out of the measured window).
        for _ in range(5):
            await client.call(addr, "echo", {}, payload)

        t0 = time.perf_counter()
        for _ in range(seq_calls):
            await client.call(addr, "echo", {}, payload)
        dt = time.perf_counter() - t0
        out["seq_small_rps"] = round(seq_calls / dt, 1)
        out["seq_small_mean_ms"] = round(1e3 * dt / seq_calls, 4)

        t0 = time.perf_counter()
        for _ in range(conc_batches):
            await asyncio.gather(
                *(client.call(addr, "echo", {}, payload) for _ in range(concurrency))
            )
        dt = time.perf_counter() - t0
        out["conc_small_rps"] = round(conc_batches * concurrency / dt, 1)

        big = os.urandom(large_mb << 20)
        # One unmeasured transfer to settle buffers.
        await client.call(addr, "sink", {}, big, timeout=120)
        t0 = time.perf_counter()
        for _ in range(large_transfers):
            ret, _ = await client.call(addr, "sink", {}, big, timeout=120)
            assert ret["n"] == len(big)
        dt = time.perf_counter() - t0
        out["large_goodput_mb_s"] = round(large_transfers * large_mb / dt, 1)
        out["connects"] = client.connects
        out["rpcs"] = client.rpcs_sent
        out["bytes_sent"] = client.bytes_sent
    finally:
        await client.close()
        await server.close()
    return out


async def run_bench(
    seq_calls: int = 2000,
    payload_bytes: int = 256,
    concurrency: int = 16,
    conc_batches: int = 50,
    large_mb: int = 8,
    large_transfers: int = 6,
) -> dict:
    kw = dict(
        seq_calls=seq_calls,
        payload_bytes=payload_bytes,
        concurrency=concurrency,
        conc_batches=conc_batches,
        large_mb=large_mb,
        large_transfers=large_transfers,
    )
    per_call = await _bench_mode(False, **kw)
    pooled = await _bench_mode(True, **kw)
    ratios = {
        "seq_small_rps": round(pooled["seq_small_rps"] / per_call["seq_small_rps"], 2),
        "conc_small_rps": round(pooled["conc_small_rps"] / per_call["conc_small_rps"], 2),
        "large_goodput_mb_s": round(
            pooled["large_goodput_mb_s"] / per_call["large_goodput_mb_s"], 2
        ),
    }
    return {
        "bench": "transport_pooled_vs_per_call",
        "host": platform.node(),
        "python": platform.python_version(),
        "unix_time": round(time.time(), 1),
        "per_call": per_call,
        "pooled": pooled,
        "ratios": ratios,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true", help="small sanity run")
    ap.add_argument("--out", default=os.path.join(RESULTS, "transport_bench.json"))
    ap.add_argument("--seq-calls", type=int, default=None)
    ap.add_argument("--large-mb", type=int, default=None)
    args = ap.parse_args()
    kw = {}
    if args.quick:
        kw = dict(seq_calls=300, conc_batches=10, large_mb=2, large_transfers=2)
    if args.seq_calls is not None:
        kw["seq_calls"] = args.seq_calls
    if args.large_mb is not None:
        kw["large_mb"] = args.large_mb
    result = asyncio.run(run_bench(**kw))
    os.makedirs(RESULTS, exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=2)
        fh.write("\n")
    print(json.dumps(result["ratios"], indent=2))
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
