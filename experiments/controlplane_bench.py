#!/usr/bin/env python
"""Control-plane load bench (ISSUE 9 acceptance): joins/sec and
heartbeats/sec served, per-message vs batched, plus the volunteer-side
message-count reduction from heartbeat batching.

Three measurements over one in-process mesh (real localhost TCP, real DHT —
the same stack the swarm tests drive), N=16 volunteers + one coordinator
replica:

1. **msgs/interval** — RPC messages ONE volunteer spends per heartbeat
   interval: the direct path (K-replica DHT store fan-out + peers-snapshot
   lookup) vs the batched path (one coalesced ``cp.exchange``). The
   acceptance bar is a >= 4x reduction at N=16.
2. **joins/sec** — sustained join throughput the control plane serves
   (announce + first snapshot), C concurrent clients: per-message
   (``dht.store`` + ``dht.get``) vs batched (one join exchange).
3. **heartbeats/sec** — sustained beat throughput: per-message
   (``dht.store`` + ``coord.report``) vs batched (one exchange carrying
   both).

Artifact: experiments/results/controlplane_bench.json (the numbers quoted
in docs/PERFORMANCE.md). The default-suite smoke twin lives in
tests/test_control_plane.py (message counts only — deterministic).

Usage:
    python experiments/controlplane_bench.py            # full bench
    python experiments/controlplane_bench.py --quick
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from distributedvolunteercomputing_tpu.swarm.control_plane import (  # noqa: E402
    ControlPlaneClient,
    ControlPlaneReplica,
)
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode  # noqa: E402
from distributedvolunteercomputing_tpu.swarm.membership import (  # noqa: E402
    PEERS_KEY,
    SwarmMembership,
)
from distributedvolunteercomputing_tpu.swarm.transport import Transport  # noqa: E402

N_VOLUNTEERS = 16


async def _mesh(n):
    nodes = []
    boot = None
    for _ in range(n):
        t = Transport()
        d = DHTNode(t, maintenance_interval=0.0)
        await d.start(bootstrap=[boot] if boot else None)
        if boot is None:
            boot = t.addr
        nodes.append((t, d))
    return nodes


async def _teardown(nodes):
    for t, d in nodes:
        try:
            await d.stop()
        except Exception:
            pass
        try:
            await t.close()
        except Exception:
            pass


def _report_for(pid):
    return {"peer": pid, "step": 3, "samples_per_sec": 10.0}


async def bench_msgs_per_interval(nodes, rep):
    """One volunteer's RPC spend per heartbeat interval, both modes, all
    N=16 volunteers measured (the batching headline number)."""
    members = []
    for i, (t, d) in enumerate(nodes[1:]):
        m = SwarmMembership(d, f"vol-{i:02d}", ttl=60.0,
                            report_source=lambda pid=f"vol-{i:02d}": _report_for(pid))
        m.keep_snapshot_fresh = True
        await m.join()
        members.append(m)
    direct = []
    for m in members:
        await m._beat_once()
        direct.append(m.msgs_last_beat)
    for m in members:
        cp = ControlPlaneClient(m.dht.transport, m.dht, m.peer_id)
        await cp.refresh(force=True)
        m.control_plane = cp
    # One warm round so every peer is registered, then the measured round.
    for m in members:
        await m._beat_once()
    batched = []
    for m in members:
        await m._beat_once()
        batched.append(m.msgs_last_beat)
    return {
        "n_volunteers": len(members),
        "permsg_msgs_per_interval_mean": round(sum(direct) / len(direct), 2),
        "batched_msgs_per_interval_mean": round(sum(batched) / len(batched), 2),
        "permsg_msgs_total": sum(direct),
        "batched_msgs_total": sum(batched),
        "reduction_x": round(sum(direct) / max(sum(batched), 1), 2),
    }


async def _throughput(op, n_ops, concurrency):
    """Run ``op(i)`` n_ops times across ``concurrency`` workers; ops/sec."""
    idx = {"i": 0}

    async def worker():
        done = 0
        while True:
            i = idx["i"]
            if i >= n_ops:
                return done
            idx["i"] = i + 1
            await op(i)
            done += 1

    t0 = time.monotonic()
    await asyncio.gather(*(worker() for _ in range(concurrency)))
    dt = time.monotonic() - t0
    return n_ops / dt, dt


async def bench_joins(nodes, rep, n_ops, concurrency):
    """Join = announce + first peers snapshot. Per-message: a DHT store
    fan-out plus an iterative lookup. Batched: one join exchange (the
    reply carries the snapshot)."""
    t, d = nodes[1]
    rep_addr = rep.transport.addr

    async def join_permsg(i):
        pid = f"jp-{i:05d}"
        await d.store(PEERS_KEY, {"addr": list(t.addr), "t": float(i)},
                      subkey=pid, ttl=30.0)
        await d.get(PEERS_KEY)

    async def join_batched(i):
        pid = f"jb-{i:05d}"
        await t.call(rep_addr, "cp.exchange", {
            "peer": pid, "record": {"addr": list(t.addr), "t": float(i)},
            "ttl": 30.0, "join": True, "report": _report_for(pid),
        }, timeout=10.0)

    permsg, dt_p = await _throughput(join_permsg, n_ops, concurrency)
    batched, dt_b = await _throughput(join_batched, n_ops, concurrency)
    return {
        "ops": n_ops, "concurrency": concurrency,
        "permsg_joins_per_sec": round(permsg, 1),
        "batched_joins_per_sec": round(batched, 1),
        "speedup_x": round(batched / permsg, 2),
    }


async def bench_heartbeats(nodes, rep, n_ops, concurrency):
    """Steady-state beat = announce refresh + metrics report. Per-message:
    DHT store fan-out + a standalone coord.report RPC. Batched: one
    exchange carrying both."""
    t, d = nodes[1]
    rep_addr = rep.transport.addr
    pids = [f"hb-{i:03d}" for i in range(concurrency)]

    async def beat_permsg(i):
        pid = pids[i % concurrency]
        await d.store(PEERS_KEY, {"addr": list(t.addr), "t": float(i)},
                      subkey=pid, ttl=30.0)
        await t.call(rep_addr, "coord.report", _report_for(pid), timeout=10.0)

    async def beat_batched(i):
        pid = pids[i % concurrency]
        await t.call(rep_addr, "cp.exchange", {
            "peer": pid, "record": {"addr": list(t.addr), "t": float(i)},
            "ttl": 30.0, "report": _report_for(pid),
        }, timeout=10.0)

    permsg, _ = await _throughput(beat_permsg, n_ops, concurrency)
    batched, _ = await _throughput(beat_batched, n_ops, concurrency)
    return {
        "ops": n_ops, "concurrency": concurrency,
        "permsg_heartbeats_per_sec": round(permsg, 1),
        "batched_heartbeats_per_sec": round(batched, 1),
        "speedup_x": round(batched / permsg, 2),
    }


async def run_bench(args):
    nodes = await _mesh(N_VOLUNTEERS + 1)
    boot_t, boot_d = nodes[0]
    # Long interval: the bench measures the SERVING paths, not tick noise.
    rep = ControlPlaneReplica(boot_t, boot_d, rid="bench-r0", interval=30.0)
    await rep.start()
    try:
        out = {"n_volunteers": N_VOLUNTEERS}
        out["msgs_per_interval"] = await bench_msgs_per_interval(nodes, rep)
        out["joins"] = await bench_joins(
            nodes, rep, args.join_ops, args.concurrency
        )
        out["heartbeats"] = await bench_heartbeats(
            nodes, rep, args.heartbeat_ops, args.concurrency
        )
        out["replica_counters"] = dict(rep.counters)
        return out
    finally:
        await rep.stop()
        await _teardown(nodes)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--join-ops", type=int, default=400)
    ap.add_argument("--heartbeat-ops", type=int, default=600)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=os.path.join(
        REPO, "experiments", "results", "controlplane_bench.json"
    ))
    args = ap.parse_args()
    if args.quick:
        args.join_ops, args.heartbeat_ops = 100, 150

    result = asyncio.run(run_bench(args))
    result["verdict"] = {
        # The acceptance bar: heartbeat batching cuts a volunteer's
        # control-plane message count >= 4x at N=16.
        "pass_batching_4x_msg_reduction": (
            result["msgs_per_interval"]["reduction_x"] >= 4.0
        ),
        # Batched throughput must BEAT the per-message paths outright —
        # the default-suite smoke fails loudly if this regresses.
        "pass_batched_joins_faster": result["joins"]["speedup_x"] > 1.0,
        "pass_batched_heartbeats_faster": (
            result["heartbeats"]["speedup_x"] > 1.0
        ),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(f"[done] artifact -> {args.out}")
    print(json.dumps(result, indent=2))
    sys.exit(0 if all(result["verdict"].values()) else 1)


if __name__ == "__main__":
    main()
