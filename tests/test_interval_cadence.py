"""Wall-clock averaging cadence (--average-interval-s) and samples-since-
merge contribution weighting — the heterogeneous-swarm alignment features.

Step-count cadence parks a fast volunteer at every rendezvous when peers
step at different speeds (the reference's config 4 is exactly such a swarm).
The interval cadence fires rounds at absolute wall-clock multiples of T and
weights each contribution by the steps actually taken since the last merge.
"""

import asyncio
import time

import numpy as np
import pytest

from distributedvolunteercomputing_tpu.models import get_model
from distributedvolunteercomputing_tpu.training.trainer import Trainer


def make_trainer(**kw):
    base = dict(batch_size=8, lr=1e-2, optimizer="adam", seed=0)
    base.update(kw)
    return Trainer(get_model("mnist_mlp"), **base)


class TestAvgDue:
    def test_step_cadence_unchanged(self):
        t = make_trainer(average_every=3)
        assert not t._avg_due(1)
        assert not t._avg_due(2)
        assert t._avg_due(3)
        assert t._avg_due(6)

    def test_interval_first_call_arms_only(self):
        t = make_trainer(average_interval_s=3600.0)
        assert not t._avg_due(1)  # arms the next hour boundary
        assert not t._avg_due(2)  # not due within the test's lifetime

    def test_interval_fires_once_per_boundary(self):
        t = make_trainer(average_interval_s=0.15)
        assert not t._avg_due(1)  # arm
        time.sleep(0.16)
        assert t._avg_due(2)  # crossed one boundary
        assert not t._avg_due(3)  # same window: not due again
        time.sleep(0.16)
        assert t._avg_due(4)

    def test_interval_boundaries_are_absolute(self):
        # Two trainers armed at different instants inside the same window
        # compute the SAME next boundary — the alignment property.
        t1 = make_trainer(average_interval_s=500.0)
        t2 = make_trainer(average_interval_s=500.0)
        t1._avg_due(1)
        time.sleep(0.05)
        t2._avg_due(1)
        assert t1._next_avg_t == t2._next_avg_t

    def test_slow_step_skipping_boundaries_yields_one_round(self):
        t = make_trainer(average_interval_s=0.05)
        t._avg_due(1)
        time.sleep(0.22)  # several boundaries pass
        assert t._avg_due(2)
        assert not t._avg_due(3)


class TestValidation:
    def test_grads_mode_rejected(self):
        with pytest.raises(ValueError, match="average_interval_s"):
            make_trainer(
                average_interval_s=5.0,
                average_what="grads",
                averager=lambda g, s: g,
            )

    def test_negative_rejected(self):
        with pytest.raises(ValueError, match="average_interval_s"):
            make_trainer(average_interval_s=-1.0)

    def test_volunteer_config_requires_params_mode(self):
        from distributedvolunteercomputing_tpu.swarm.volunteer import VolunteerConfig

        with pytest.raises(ValueError, match="average-interval-s"):
            VolunteerConfig(
                coordinator="127.0.0.1:1", averaging="sync",
                average_what="grads", average_interval_s=5.0,
            )
        with pytest.raises(ValueError, match="average-interval-s"):
            VolunteerConfig(
                coordinator="127.0.0.1:1", averaging="none",
                average_interval_s=5.0,
            )
        cfg = VolunteerConfig(
            coordinator="127.0.0.1:1", averaging="sync",
            average_what="params", average_interval_s=5.0,
        )
        assert cfg.average_interval_s == 5.0


class TestIntervalRounds:
    def test_rounds_fire_on_wall_clock_not_steps(self):
        calls = []

        def averager(tree, step):
            calls.append(step)
            return tree

        t = make_trainer(
            average_interval_s=0.1, averager=averager, average_what="params",
            average_every=1,  # would fire every step under step cadence
        )
        # Pin wall time per step so the test is load-independent: 40 steps
        # x 25ms = ~1s of wall time over 0.1s boundaries.
        t.on_step = lambda tr, s: time.sleep(0.025)
        t.run(steps=40, log_every=0)
        # Rounds track wall boundaries (~10), NOT the 40 the step cadence
        # would produce. Wide bounds: CI machines stall arbitrarily.
        assert 3 <= len(calls) < 30

    def test_huge_interval_never_fires(self):
        calls = []
        t = make_trainer(
            average_interval_s=3600.0,
            averager=lambda tree, step: calls.append(step) or tree,
            average_what="params", average_every=1,
        )
        t.run(steps=6, log_every=0)
        assert calls == []


class TestStepsSinceMergeOverlap:
    def test_overlap_merge_anchors_at_launch_step(self):
        """The overlap path must anchor steps_since_merge at the LAUNCH
        step (progress up to launch entered the average; the delta term
        keeps the rest locally) — not at the merge step. Drives the real
        _finish_overlap_round with fabricated completed futures, the same
        deterministic pattern as the outer-optimizer overlap test."""
        import concurrent.futures

        import jax
        t = make_trainer(
            averager=lambda p, s: p, overlap=True, average_every=5,
        )
        t._last_merge_step = 0

        def finish_with(launch_step, step_no):
            p0 = jax.tree_util.tree_map(
                np.asarray, t.bundle.avg_select(t.state.params)
            )
            fut = concurrent.futures.Future()
            fut.set_result((p0, 0.01))
            t._inflight = (launch_step, p0, fut)
            t._finish_overlap_round(step_no)

        # Round launched at step 5, merged at step 8 (3 steps in flight):
        # the NEXT contribution covers steps since LAUNCH (5), not merge.
        finish_with(5, 8)
        assert t._last_merge_step == 5
        t._note_window_progress(12)
        assert t.steps_since_merge == 7  # 12 - 5, not 12 - 8


class TestStepsSinceMerge:
    def test_weight_accumulates_over_failed_rounds(self):
        # Round at step 3 fails (None); the next round's steps_since_merge
        # must cover BOTH windows (6 steps), then reset after success.
        seen = []

        def flaky(tree, step):
            seen.append((step, trainer.steps_since_merge))
            return None if step == 3 else tree

        trainer = make_trainer(
            averager=flaky, average_what="params", average_every=3,
        )
        trainer.run(steps=9, log_every=0)
        assert seen[0] == (3, 3)  # first round: one window
        assert seen[1] == (6, 6)  # failed round's progress accumulated
        assert seen[2] == (9, 3)  # merged at 6: back to one window


class TestMethodKw:
    def test_unknown_keys_rejected_at_config_time(self):
        # A typo'd estimator kwarg must fail at startup, not raise inside
        # every round and get swallowed by the round-failure containment
        # (volunteer would train solo forever).
        from distributedvolunteercomputing_tpu.swarm.volunteer import VolunteerConfig

        with pytest.raises(ValueError, match="method-kw"):
            VolunteerConfig(
                coordinator="x:1", averaging="byzantine",
                method="trimmed_mean", method_kw={"n_byzantine": 1},
            )
        cfg = VolunteerConfig(
            coordinator="x:1", averaging="byzantine",
            method="krum", method_kw={"n_byzantine": 2},
        )
        assert cfg.method_kw == {"n_byzantine": 2}

    def test_unknown_method_name_rejected_at_config_time(self):
        # r4 advisor: the kwarg validation above silently no-op'd when the
        # METHOD name itself was a typo — robust.aggregate would then raise
        # KeyError inside every round's containment, the exact solo-forever
        # failure this validation exists to prevent.
        from distributedvolunteercomputing_tpu.swarm.volunteer import VolunteerConfig

        with pytest.raises(ValueError, match="unknown --method"):
            VolunteerConfig(
                coordinator="x:1", averaging="byzantine", method="trimed_mean",
            )
        # ...and regardless of averaging mode (fail fast beats dead config).
        with pytest.raises(ValueError, match="unknown --method"):
            VolunteerConfig(coordinator="x:1", averaging="gossip", method="nope")


class TestClockSync:
    """r4 VERDICT #9: --average-interval-s assumed NTP sync. ClockSync
    (swarm/clocksync.py) estimates per-peer offsets over the transport and
    corrects the boundary clock; these tests inject multi-second skew."""

    def _stack(self, peer_id, clock):
        async def make():
            from tests.test_averaging import _solo_stack
            from distributedvolunteercomputing_tpu.swarm.clocksync import ClockSync

            t, dht, mem = await _solo_stack(peer_id)
            return t, dht, mem, ClockSync(t, mem, clock=clock, samples_per_peer=2)

        return make()

    def test_two_nodes_meet_in_the_middle(self):
        import time as _t
        from tests.test_averaging import run
        from distributedvolunteercomputing_tpu.swarm.clocksync import ClockSync
        from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
        from distributedvolunteercomputing_tpu.swarm.membership import SwarmMembership
        from distributedvolunteercomputing_tpu.swarm.transport import Transport

        async def main():
            stacks = []
            boot = None
            skews = {"a": +6.0, "b": 0.0}
            for pid, skew in skews.items():
                t = Transport()
                dht = DHTNode(t)
                await dht.start(bootstrap=[boot] if boot else None)
                boot = boot or t.addr
                mem = SwarmMembership(dht, pid, ttl=10.0)
                await mem.join()
                cs = ClockSync(t, mem, clock=(lambda s=skew: _t.time() + s),
                               samples_per_peer=2)
                stacks.append((t, mem, cs))
            try:
                # A few simultaneous rounds: corrected clocks converge.
                for _ in range(4):
                    await asyncio.gather(*(cs.estimate() for _, _, cs in stacks))
                times = [cs.now() for _, _, cs in stacks]
                assert abs(times[0] - times[1]) < 0.5, times
                # ...and onto the midpoint, not one node's clock.
                mid = _t.time() + 3.0
                assert abs(times[0] - mid) < 1.5
            finally:
                for t, mem, _ in stacks:
                    try:
                        await mem.leave()
                    except Exception:
                        pass
                    await t.close()

        run(main())

    def test_skewed_minority_pinned_to_majority(self):
        import time as _t
        from tests.test_averaging import run
        from distributedvolunteercomputing_tpu.swarm.clocksync import ClockSync
        from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
        from distributedvolunteercomputing_tpu.swarm.membership import SwarmMembership
        from distributedvolunteercomputing_tpu.swarm.transport import Transport

        async def main():
            stacks = []
            boot = None
            for pid, skew in (("a", -7.0), ("b", 0.0), ("c", 0.0), ("d", 0.0)):
                t = Transport()
                dht = DHTNode(t)
                await dht.start(bootstrap=[boot] if boot else None)
                boot = boot or t.addr
                mem = SwarmMembership(dht, pid, ttl=10.0)
                await mem.join()
                cs = ClockSync(t, mem, clock=(lambda s=skew: _t.time() + s),
                               samples_per_peer=2)
                stacks.append((t, mem, cs))
            try:
                for _ in range(3):
                    await asyncio.gather(*(cs.estimate() for _, _, cs in stacks))
                times = [cs.now() for _, _, cs in stacks]
                true_now = _t.time()
                # Honest majority barely moves; the skewed node joins them.
                for ct in times:
                    assert abs(ct - true_now) < 1.0, times
            finally:
                for t, mem, _ in stacks:
                    try:
                        await mem.leave()
                    except Exception:
                        pass
                    await t.close()

        run(main())

    def test_trainer_boundary_uses_corrected_clock(self):
        from distributedvolunteercomputing_tpu.models import get_model
        from distributedvolunteercomputing_tpu.training.trainer import Trainer

        offset = {"v": 100.0}
        tr = Trainer(
            get_model("mnist_mlp"), batch_size=4, lr=1e-2,
            average_interval_s=10.0,
            wall_clock=lambda: 1000.0 + offset["v"],
            averager=lambda tree, step: None,
        )
        assert tr._avg_due(1) is False  # first call arms
        assert tr._next_avg_t == 1110.0  # armed on the CORRECTED clock
        offset["v"] = 111.0  # corrected clock crosses the boundary
        assert tr._avg_due(2) is True
