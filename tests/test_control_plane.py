"""Replicated control plane (ISSUE 9): replica election + key-range
sharding, batched heartbeat exchange, failover client, retiring tombstone,
and the batching-vs-per-message smoke.

Everything here runs in-process over real localhost transports (the swarm
test idiom): abrupt `transport.close()` + `dht.stop()` without leave() is
protocol-equivalent to kill -9.
"""

import asyncio
import time

import pytest

from distributedvolunteercomputing_tpu.swarm.control_plane import (
    MAX_REPLICAS,
    N_SHARDS,
    ControlPlaneClient,
    ControlPlaneReplica,
    active_replicas,
    owner_index,
    shard_of,
)
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
from distributedvolunteercomputing_tpu.swarm.membership import PEERS_KEY, SwarmMembership
from distributedvolunteercomputing_tpu.swarm.transport import Transport

pytestmark = pytest.mark.controlplane


def run(coro, timeout=180):
    return asyncio.run(asyncio.wait_for(coro, timeout=timeout))


async def _mesh(n, bootstrap=None, maintenance_interval=0.0):
    nodes = []
    boot = bootstrap
    for _ in range(n):
        t = Transport()
        d = DHTNode(t, maintenance_interval=maintenance_interval)
        await d.start(bootstrap=[boot] if boot else None)
        if boot is None:
            boot = t.addr
        nodes.append((t, d))
    return nodes


async def _teardown(nodes):
    for t, d in nodes:
        try:
            await d.stop()
        except Exception:
            pass
        try:
            await t.close()
        except Exception:
            pass


async def _kill(t, d):
    """kill -9 at the protocol level: no leave, no tombstone."""
    await d.stop()
    await t.close()


class TestElection:
    def test_active_set_is_sorted_capped_and_skips_retiring(self):
        recs = {
            f"r{i}": {"addr": ["h", 1000 + i], "t": 0.0} for i in range(8)
        }
        recs["r2"]["retiring"] = True
        recs["bad"] = {"t": 0.0}  # no addr: not a candidate
        active = active_replicas(recs)
        rids = [rid for rid, _ in active]
        assert len(rids) == MAX_REPLICAS
        assert "r2" not in rids and "bad" not in rids
        assert rids == sorted(rids)
        assert rids[0] == "r0"

    def test_key_ranges_are_contiguous_and_cover(self):
        for n_replicas in range(1, MAX_REPLICAS + 1):
            owners = [owner_index(s, n_replicas) for s in range(N_SHARDS)]
            # Every shard owned, owners form a non-decreasing sequence
            # (contiguous key ranges), every replica owns something.
            assert all(0 <= o < n_replicas for o in owners)
            assert owners == sorted(owners)
            assert set(owners) == set(range(n_replicas))

    def test_shard_of_stable_and_in_range(self):
        for pid in ("vol-a", "vol-b", "x" * 40):
            s = shard_of(pid)
            assert 0 <= s < N_SHARDS
            assert shard_of(pid) == s


class TestBatchedExchange:
    def test_batching_beats_per_message_rpcs_4x_at_n16(self):
        """THE batching smoke (fails loudly if batching stops beating the
        per-message path): at N=16, one volunteer's per-interval control
        traffic must shrink >= 4x — one coalesced cp.exchange vs the
        direct path's K-replica store fan-out + snapshot lookup."""

        async def scenario():
            nodes = await _mesh(17)
            boot_t, boot_d = nodes[0]
            rep = ControlPlaneReplica(boot_t, boot_d, rid="r0", interval=60.0)
            await rep.start()
            members = []
            try:
                for i, (t, d) in enumerate(nodes[1:]):
                    m = SwarmMembership(d, f"vol-{i:02d}", ttl=30.0)
                    m.keep_snapshot_fresh = True
                    await m.join()
                    members.append(m)
                # Per-message phase: a direct beat = K store RPCs + the
                # snapshot lookup.
                direct = []
                for m in members:
                    await m._beat_once()
                    direct.append(m.msgs_last_beat)
                # Batched phase: same memberships, control plane attached.
                for m in members:
                    cp = ControlPlaneClient(m.dht.transport, m.dht, m.peer_id)
                    await cp.refresh(force=True)
                    m.control_plane = cp
                batched = []
                for m in members:
                    await m._beat_once()
                    batched.append(m.msgs_last_beat)
                assert all(b > 0 for b in batched)
                d_sum, b_sum = sum(direct), sum(batched)
                assert d_sum >= 4 * b_sum, (
                    f"batching stopped beating per-message RPCs: "
                    f"direct {d_sum} msgs vs batched {b_sum} over "
                    f"{len(members)} volunteers"
                )
                assert all(m.batched_beats == 1 for m in members)
                # After one full beat round every peer has exchanged
                # through the replica, so the NEXT round's replies carry
                # the complete snapshot — alive_peers then needs no DHT
                # walk at all.
                for m in members:
                    await m._beat_once()
                snap = await members[0].alive_peers(max_age=5.0)
                assert len(snap) == 16
                assert rep.counters["exchanges"] == 32
            finally:
                await rep.stop()
                await _teardown(nodes)

        run(scenario())

    def test_exchange_report_reaches_status(self):
        """A report piggybacked on the batched beat must land in
        coord.status exactly like a legacy coord.report."""

        async def scenario():
            nodes = await _mesh(3)
            boot_t, boot_d = nodes[0]
            rep = ControlPlaneReplica(boot_t, boot_d, rid="r0", interval=60.0)
            await rep.start()
            try:
                t, d = nodes[1]
                m = SwarmMembership(
                    d, "vol-x", ttl=30.0,
                    report_source=lambda: {
                        "peer": "vol-x", "step": 7, "samples_per_sec": 123.0,
                    },
                )
                m.control_plane = ControlPlaneClient(t, d, "vol-x")
                await m.join()
                await m.control_plane.refresh(force=True)
                await m._beat_once()
                assert m.batched_beats == 1
                status, _ = await rep._rpc_status({}, b"")
                assert status["swarm_samples_per_sec"] == 123.0
                assert "vol-x" in status["alive"]
            finally:
                await rep.stop()
                await _teardown(nodes)

        run(scenario())


class TestFailover:
    def test_status_survives_replica_kill_within_one_heartbeat(self):
        """Acceptance bar: SIGKILL the replica serving a cohort's batched
        beats; surviving replica serves a COMPLETE coord.status (all peers
        alive, metrics merged from the replicated rollups) within one
        heartbeat interval, and the cohort's next beat fails over without
        losing cadence."""
        heartbeat_ttl = 15.0

        async def scenario():
            nodes = await _mesh(8)
            boot_t, boot_d = nodes[0]
            repA = ControlPlaneReplica(boot_t, boot_d, rid="a", interval=0.4)
            await repA.start()
            tB, dB = nodes[1]
            repB = ControlPlaneReplica(tB, dB, rid="b", interval=0.4)
            await repB.start()
            members = []
            try:
                for i, (t, d) in enumerate(nodes[2:]):
                    pid = f"vol-{i}"
                    m = SwarmMembership(
                        d, pid, ttl=heartbeat_ttl,
                        report_source=(
                            lambda pid=pid: {
                                "peer": pid, "step": 5, "samples_per_sec": 10.0,
                            }
                        ),
                    )
                    m.control_plane = ControlPlaneClient(t, d, pid)
                    await m.join()
                    await m.control_plane.refresh(force=True)
                    await m._beat_once()
                    assert m.batched_beats == 1
                    members.append(m)
                # Both replicas saw traffic (key-range routing splits the
                # cohort), and a tick flushed rollups to the DHT.
                await asyncio.sleep(0.9)
                assert repA.counters["exchanges"] + repB.counters["exchanges"] >= 6

                # kill -9 the first replica.
                t_kill = time.monotonic()
                await repA.stop()
                await _kill(boot_t, boot_d)

                # Every volunteer's next beat must stay batched (failover
                # to B on conn failure), not fall back to direct stores.
                for m in members:
                    await m._beat_once()
                    assert m.batched_beats == 2, m.stats()

                status, _ = await repB._rpc_status({}, b"")
                elapsed = time.monotonic() - t_kill
                assert elapsed <= heartbeat_ttl / 3.0, (
                    f"status took {elapsed:.1f}s, over one heartbeat interval"
                )
                assert status["n_alive"] == 6, sorted(status["alive"])
                assert status["swarm_samples_per_sec"] == pytest.approx(60.0)
                assert status["control_plane"]["rid"] == "b"
            finally:
                await repB.stop()
                await _teardown(nodes[2:] + [nodes[1]])

        run(scenario())

    def test_heartbeat_cadence_holds_through_dead_coordinator(self):
        """Satellite regression: with every known replica unreachable, each
        beat must (a) stay FAST — fail-fast dial, never the generic call
        timeout — (b) fall back to the direct DHT announce so the record
        stays alive, and (c) put the corpse on AIMD backoff so later beats
        stop dialing it entirely."""

        async def scenario():
            nodes = await _mesh(4)
            # A dead replica address: bind a port, then close it.
            probe = Transport()
            dead_addr = await probe.start()
            await probe.close()
            t, d = nodes[1]
            m = SwarmMembership(d, "vol-hb", ttl=2.4)
            cp = ControlPlaneClient(t, d, "vol-hb")
            cp.update_replicas({"corpse": {"addr": list(dead_addr), "t": 0.0}})
            m.control_plane = cp
            try:
                durations = []
                for _ in range(4):
                    t0 = time.monotonic()
                    await m._beat_once()
                    durations.append(time.monotonic() - t0)
                # Cadence holds: every beat completes well inside the
                # ttl/3 = 0.8s interval (fast-fail dial + direct store).
                assert max(durations) < 2.0, durations
                assert m.direct_beats == 4 and m.batched_beats == 0
                # The record stayed alive through the outage: another node
                # sees it.
                rec = await nodes[2][1].get(PEERS_KEY)
                assert rec.get("vol-hb") is not None
                # AIMD backoff engaged: after the first failures the
                # corpse is skipped, so failures stop accruing 1:1 with
                # beats.
                assert cp.counters["calls_failed"] >= 1
                assert cp.counters["calls_failed"] < len(durations)
                assert "corpse" in cp.stats()["backed_off"]
            finally:
                await _teardown(nodes)

        run(scenario())

    def test_backoff_is_aimd_bounded(self):
        async def scenario():
            nodes = await _mesh(2)
            t, d = nodes[0]
            cp = ControlPlaneClient(t, d, "x")
            try:
                delays = []
                for _ in range(8):
                    cp._note_fail("r")
                    delays.append(cp._backoff["r"][1])
                # Multiplicative increase, bounded at the cap.
                assert delays[0] == cp.BACKOFF_START
                assert delays[1] == 2 * delays[0]
                assert max(delays) == cp.BACKOFF_CAP
                # Additive decrease on recovery.
                cp._note_ok("r")
                assert cp._backoff["r"][1] == cp.BACKOFF_CAP - cp.BACKOFF_DECREASE
                assert cp._backoff["r"][0] == 0.0  # unblocked immediately
            finally:
                await _teardown(nodes)

        run(scenario())


class TestFencingRecovery:
    def test_reclaim_escalates_past_watermark_of_expired_rollup(self):
        """A fence watermark outlives the rollup record (600s vs 75s): a
        replica acquiring a shard after an ownership gap cannot learn the
        old generation from the record — its first claim gets fenced, and
        the reported watermark must FLOOR the re-claim so the shard
        recovers next tick instead of livelocking (claim 1, fenced by 5,
        drop, repeat) until the watermark expires."""

        async def scenario():
            from distributedvolunteercomputing_tpu.swarm.control_plane import (
                ROLLUP_KEY,
            )

            nodes = await _mesh(3)
            try:
                # A long-dead owner's watermark at gen 5; its rollup
                # record itself has expired.
                await nodes[1][1].store(
                    ROLLUP_KEY, {"gen": 5, "rid": "old"}, subkey="s3",
                    ttl=0.2, fence=5,
                )
                await asyncio.sleep(0.4)
                rep = ControlPlaneReplica(
                    nodes[0][0], nodes[0][1], rid="new", interval=60.0
                )
                # start() makes the initial claims: shard 3's gen-1 write
                # is fenced and dropped, but the watermark is recorded.
                await rep.start()
                assert 3 not in rep._shard_gens
                assert rep._gen_floor.get(3) == 5
                # The very next tick's recompute+write recovers the shard
                # ABOVE the watermark.
                await rep._refresh_views()
                await rep._recompute_ownership()
                await rep._write_rollups()
                assert rep._shard_gens.get(3) == 6
                rec = await nodes[2][1].get(ROLLUP_KEY)
                assert rec.get("s3", {}).get("gen") == 6
                await rep.stop()
            finally:
                await _teardown(nodes)

        run(scenario())


class TestRetiring:
    def test_retiring_tombstone_reresolves_immediately(self):
        """Satellite: a SIGTERM'd replica publishes a retiring tombstone;
        clients drop it from the active set at the very next exchange or
        refresh — no TTL wait, no suspicion accrual."""

        async def scenario():
            nodes = await _mesh(5)
            repA = ControlPlaneReplica(nodes[0][0], nodes[0][1], rid="a", interval=60.0)
            repB = ControlPlaneReplica(nodes[1][0], nodes[1][1], rid="b", interval=60.0)
            await repA.start()
            await repB.start()
            try:
                t, d = nodes[2]
                cp = ControlPlaneClient(t, d, "vol-r")
                await cp.refresh(force=True)
                assert [rid for rid, _ in cp.active()] == ["a", "b"]
                # Graceful SIGTERM path: tombstone + drain, socket STAYS
                # OPEN briefly (the point: re-resolve must not need a conn
                # failure).
                await repA.retire(grace=0.0)
                await cp.refresh(force=True)
                assert [rid for rid, _ in cp.active()] == ["b"]
                # Exchange routes straight to B, no failover/conn failure.
                ret = await cp.exchange({"addr": list(t.addr), "t": 1.0}, ttl=10.0)
                assert ret is not None and ret["rid"] == "b"
                assert cp.counters["failovers"] == 0
                assert cp.counters["calls_failed"] == 0
                # B's own ownership recompute absorbs the whole key range.
                await repB._refresh_views()
                await repB._recompute_ownership()
                assert sorted(repB._shard_gens) == list(range(N_SHARDS))
            finally:
                await repB.stop()
                await _teardown(nodes)

        run(scenario())

    @pytest.mark.slow
    def test_sigterm_retires_coordinator_subprocess(self):
        """run_coordinator_forever end-to-end: SIGTERM exits cleanly after
        publishing the retiring tombstone (the in-process half is covered
        above; this pins the signal wiring)."""
        import os
        import signal
        import subprocess
        import sys

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(
            [sys.executable, "coordinator.py", "--port", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        try:
            line = ""
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                line = proc.stdout.readline()
                if line.startswith("COORDINATOR_READY"):
                    break
            assert line.startswith("COORDINATOR_READY"), line
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=30)
            assert rc == 0, rc
        finally:
            if proc.poll() is None:
                proc.kill()


class TestRendezvousReads:
    def test_rendezvous_via_replica_with_dht_fallback(self):
        async def scenario():
            nodes = await _mesh(4)
            rep_t, rep_d = nodes[0]
            rep = ControlPlaneReplica(rep_t, rep_d, rid="r0", interval=60.0)
            await rep.start()
            try:
                t, d = nodes[1]
                await d.store("avg/test-round", {"addr": ["h", 1]}, subkey="p1", ttl=30)
                cp = ControlPlaneClient(t, d, "p1")
                await cp.refresh(force=True)
                rec = await cp.rendezvous_get("avg/test-round")
                assert rec == {"p1": {"addr": ["h", 1]}}
                assert rep.counters["rendezvous_served"] == 1
                # Second read inside the cache window: served without a
                # second DHT lookup.
                await cp.rendezvous_get("avg/test-round")
                assert rep.counters["rendezvous_served"] == 2
                assert rep.counters["rendezvous_lookups"] == 1
                # Replica dies: reader returns None; the matchmaker-level
                # wrapper falls back to the direct DHT walk.
                await rep.stop()
                await _kill(rep_t, rep_d)
                assert await cp.rendezvous_get("avg/test-round") is None
                from distributedvolunteercomputing_tpu.swarm.matchmaking import (
                    Matchmaker,
                )

                mm = Matchmaker(
                    t, d, "p1", rendezvous_get=cp.rendezvous_get
                )
                rec = await mm._read_rendezvous("avg/test-round")
                assert rec == {"p1": {"addr": ["h", 1]}}
            finally:
                try:
                    await rep.stop()
                except Exception:
                    pass
                await _teardown(nodes[1:])

        run(scenario())
