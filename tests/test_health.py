"""Training-health telemetry tests (swarm/health.py): the seeded
random-projection sketch estimator vs directly-computed parameter
dispersion, gradient-mass accounting balance across the deadline / abort /
fence matrix, per-peer contribution-quality attribution and flagging, the
--no-health-probe end-to-end plumbing (no sketch bytes on the heartbeat),
the coord.status["health"] schema walk, and the health-probe overhead
smoke (interleaved arms, like the PR-10 telemetry smoke).
"""

import asyncio
import statistics
import time

import numpy as np
import pytest

from distributedvolunteercomputing_tpu.swarm import health as H
from distributedvolunteercomputing_tpu.swarm import telemetry as T
from distributedvolunteercomputing_tpu.swarm.agg_stream import (
    StreamingAggregator,
    TilePool,
)
from distributedvolunteercomputing_tpu.swarm.averager import SyncAverager
from distributedvolunteercomputing_tpu.swarm.control_plane import ControlPlaneReplica
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
from distributedvolunteercomputing_tpu.swarm.membership import SwarmMembership
from distributedvolunteercomputing_tpu.swarm.transport import Transport

pytestmark = pytest.mark.health


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout=timeout))


def direct_rel_dispersion(bufs):
    """The offline (hierarchy_bench-style) relative dispersion: RMS
    deviation from the cross-peer mean over the RMS parameter norm —
    exactly what sketch_dispersion estimates from the projections."""
    stack = np.stack([np.asarray(b, np.float64).ravel() for b in bufs])
    dev = stack - stack.mean(axis=0)[None, :]
    rms = float(np.sqrt((dev * dev).sum(axis=1).mean()))
    norm = float(np.sqrt((stack * stack).sum(axis=1).mean()))
    return rms / norm if norm > 0 else 0.0


# -- sketch estimator (satellite: tolerance test at n in {4, 8}) -------------


class TestSketchEstimator:
    # JL with dim=64 distorts pairwise norms by ~1/sqrt(2*64) ~= 9% per
    # pair; the dispersion averages over n peers, so 25% relative is a
    # conservative documented tolerance (typical observed error: <6%).
    TOL = 0.25

    @pytest.mark.parametrize("n_peers", [4, 8])
    def test_dispersion_matches_direct(self, n_peers):
        rng = np.random.default_rng(n_peers)
        seed = H.sketch_seed("m")
        bufs = [
            (rng.standard_normal(20_000) + 0.3 * i).astype(np.float32)
            for i in range(n_peers)
        ]
        sk = [H.params_sketch(b, seed) for b in bufs]
        est = H.sketch_dispersion(sk)["rel"]
        direct = direct_rel_dispersion(bufs)
        assert abs(est - direct) <= self.TOL * direct, (
            f"sketch dispersion {est:.4f} vs direct {direct:.4f} "
            f"(> {self.TOL:.0%} off)"
        )

    def test_degenerate_all_equal_reads_zero(self):
        seed = H.sketch_seed("m")
        buf = np.random.default_rng(0).standard_normal(8_192).astype(np.float32)
        sk = [H.params_sketch(buf, seed) for _ in range(4)]
        d = H.sketch_dispersion(sk)
        assert d["rel"] < 1e-7 and d["rms"] < 1e-7

    def test_subsampled_big_model_still_agrees(self):
        """Models bigger than the sample budget project a seeded
        coordinate subsample — the dispersion estimate stays unbiased."""
        rng = np.random.default_rng(3)
        seed = H.sketch_seed("m")
        bufs = [
            (rng.standard_normal(3 * H.DEFAULT_SKETCH_SAMPLE) + 0.5 * i).astype(
                np.float32
            )
            for i in range(4)
        ]
        est = H.sketch_dispersion([H.params_sketch(b, seed) for b in bufs])["rel"]
        direct = direct_rel_dispersion(bufs)
        assert abs(est - direct) <= 0.3 * direct

    def test_deterministic_and_seed_scoped(self):
        buf = np.random.default_rng(1).standard_normal(10_000).astype(np.float32)
        a = H.params_sketch(buf, H.sketch_seed("m"))
        b = H.params_sketch(buf, H.sketch_seed("m"))
        c = H.params_sketch(buf, H.sketch_seed("other"))
        assert np.array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_dispersion_refuses_mixed_spaces(self):
        assert H.sketch_dispersion([np.zeros(8), np.zeros(16)]) is None
        assert H.sketch_dispersion([np.zeros(8)]) is None


# -- gradient-mass accounting (acceptance: the balance property) -------------


def _feed_streamed(agg, peer, w, buf, chunk_bytes, upto=None):
    data = np.ascontiguousarray(buf, np.float32).tobytes()
    sink = agg.make_sink(peer, w, len(data))
    assert sink is not None
    end = len(data) if upto is None else upto
    for off in range(0, end, chunk_bytes):
        sink(off, len(data), data[off : off + chunk_bytes])
    return sink


def _assert_balanced(rep):
    """The invariant: every armed slot in exactly one bucket, weights sum."""
    assert (
        rep["included_slots"] + rep["excluded_slots"] + rep["aborted_slots"]
        == rep["armed_slots"]
    )
    assert (
        rep["included_weight"] + rep["excluded_weight"] + rep["aborted_weight"]
        == pytest.approx(rep["armed_weight"], abs=1e-6)
    )
    by_outcome = {"included": 0, "excluded": 0, "aborted": 0}
    for rec in rep["per_peer"].values():
        by_outcome[rec["outcome"]] += 1
    assert by_outcome["included"] == rep["included_slots"]
    assert by_outcome["excluded"] == rep["excluded_slots"]
    assert by_outcome["aborted"] == rep["aborted_slots"]


class TestMassAccounting:
    """Property test across the deadline/failover/abort matrix (the PR 3/4
    scenarios): included + excluded + aborted always partitions the armed
    set and their weights sum to the armed weight."""

    N_ELEMS, CB = 230, 64 * 4

    def _agg(self, peers, method="mean"):
        return StreamingAggregator(
            self.N_ELEMS, peers, method, "f32", self.CB,
            kw_fn=lambda n: {"trim": 1} if method == "trimmed_mean" else {},
            pool=TilePool(),
        )

    @pytest.mark.parametrize("method", ["mean", "trimmed_mean"])
    def test_happy_path_full_mass(self, method):
        peers = [f"p{i}" for i in range(4)]
        rng = np.random.default_rng(0)
        bufs = rng.standard_normal((4, self.N_ELEMS)).astype(np.float32)

        async def main():
            agg = self._agg(peers, method)
            agg.add_dense(peers[0], 2.0, bufs[0])
            for i in range(1, 4):
                _feed_streamed(agg, peers[i], 1.0, bufs[i], self.CB).close(True)
            await agg.finalize(peers)
            return agg.mass_report()

        rep = run(main())
        _assert_balanced(rep)
        assert rep["mass_committed_frac"] == 1.0
        assert rep["armed_weight"] == pytest.approx(5.0)
        assert rep["excluded_slots"] == rep["aborted_slots"] == 0

    @pytest.mark.parametrize("method", ["mean", "trimmed_mean"])
    def test_deadline_drop_and_silent_peer(self, method):
        """One peer streams half and stalls past the freeze, one never
        speaks: both land in excluded; the partial peer's declared weight
        is the excluded mass, the silent one balances at weight 0."""
        peers = [f"p{i}" for i in range(5)]
        rng = np.random.default_rng(1)
        bufs = rng.standard_normal((5, self.N_ELEMS)).astype(np.float32)

        async def main():
            agg = self._agg(peers, method)
            for i in range(3):
                _feed_streamed(agg, peers[i], 1.0, bufs[i], self.CB).close(True)
            # p3: half-delivered at the deadline (no close), weight 2.5.
            _feed_streamed(agg, peers[3], 2.5, bufs[3], self.CB, upto=2 * self.CB)
            # p4: silent.
            await agg.finalize(peers[:3])
            return agg.mass_report()

        rep = run(main())
        _assert_balanced(rep)
        assert rep["included_slots"] == 3
        assert rep["excluded_slots"] == 2
        assert rep["excluded_weight"] == pytest.approx(2.5)
        assert rep["per_peer"]["p4"] == {"outcome": "excluded", "weight": 0.0}
        assert rep["mass_committed_frac"] == pytest.approx(3.0 / 5.5)

    def test_abort_after_committed_tiles_is_aborted_mass(self):
        """A streamed push that dies AFTER folding tiles (mean mode: the
        axpy is irreversible) taints the slot — its mass is ABORTED, not
        excluded, and the balance still holds."""
        peers = [f"p{i}" for i in range(4)]
        rng = np.random.default_rng(2)
        bufs = rng.standard_normal((4, self.N_ELEMS)).astype(np.float32)

        async def main():
            agg = self._agg(peers, "mean")
            for i in range(3):
                _feed_streamed(agg, peers[i], 1.0, bufs[i], self.CB).close(True)
            sink = _feed_streamed(agg, peers[3], 4.0, bufs[3], self.CB, upto=2 * self.CB)
            sink.close(False)  # connection died mid-payload
            await agg.finalize(peers[:3])
            return agg.mass_report()

        rep = run(main())
        _assert_balanced(rep)
        assert rep["per_peer"]["p3"]["outcome"] == "aborted"
        assert rep["aborted_weight"] == pytest.approx(4.0)
        assert rep["mass_committed_frac"] == pytest.approx(3.0 / 7.0)

    def test_clean_abort_before_any_tile(self):
        """An abort before the first full tile resets cleanly — still
        accounted as aborted mass for the round unless a retry lands."""
        peers = ["p0", "p1", "p2"]
        rng = np.random.default_rng(3)
        bufs = rng.standard_normal((3, self.N_ELEMS)).astype(np.float32)

        async def main():
            agg = self._agg(peers, "mean")
            for i in range(2):
                _feed_streamed(agg, peers[i], 1.0, bufs[i], self.CB).close(True)
            data = bufs[2].tobytes()
            sink = agg.make_sink("p2", 3.0, len(data))
            sink(0, len(data), data[: self.CB - 4])  # short chunk: poisons
            sink.close(False)
            await agg.finalize(peers[:2])
            return agg.mass_report()

        rep = run(main())
        _assert_balanced(rep)
        assert rep["per_peer"]["p2"]["outcome"] == "aborted"

    def test_fenced_round_still_balances(self):
        """Leader failover: the fenced (superseded) aggregator's report
        stays internally consistent — nothing double-counts."""
        peers = ["p0", "p1", "p2"]
        rng = np.random.default_rng(4)
        bufs = rng.standard_normal((3, self.N_ELEMS)).astype(np.float32)

        async def main():
            agg = self._agg(peers, "mean")
            _feed_streamed(agg, "p0", 1.0, bufs[0], self.CB).close(True)
            _feed_streamed(agg, "p1", 1.5, bufs[1], self.CB, upto=2 * self.CB)
            agg.fence()
            return agg.mass_report()

        rep = run(main())
        _assert_balanced(rep)
        assert rep["included_slots"] == 1
        assert rep["excluded_weight"] == pytest.approx(1.5)

    def test_mass_from_outcomes_dense_round(self):
        rep = H.mass_from_outcomes(
            ["a", "b", "c", "d"], {"a": 1.0, "b": 2.0}, aborted=["c"]
        )
        _assert_balanced(rep)
        assert rep["mass_committed_frac"] == pytest.approx(1.0)  # known mass all landed
        assert rep["per_peer"]["c"]["outcome"] == "aborted"
        assert rep["per_peer"]["d"]["outcome"] == "excluded"


# -- contribution quality ----------------------------------------------------


class TestContributionQuality:
    def test_byzantine_flagged_honest_clean(self):
        tele = T.Telemetry(peer_id="lead")
        m = tele.health
        for r in range(8):
            m.observe_round_quality(
                {"h0": 1.0 + 0.2 * r, "h1": 0.8, "h2": 1.3, "byz": 400.0},
                trace=f"t{r}",
            )
        assert m.flagged_peers() == ["byz"]
        assert m.quality_score("byz") < 0.5
        for p in ("h0", "h1", "h2"):
            assert m.quality_score(p) == 1.0
        evs = tele.recorder.dump(kinds=["peer_quality_flagged"])
        assert evs and evs[0]["peer"] == "byz"

    def test_degenerate_all_equal_flags_nobody(self):
        m = T.Telemetry(peer_id="l").health
        for r in range(6):
            m.observe_round_quality(
                {"a": 0.0, "b": 0.0, "c": 1e-12}, trace=f"t{r}"
            )
        assert m.flagged_peers() == []

    def test_flag_clears_when_evidence_decays(self):
        m = T.Telemetry(peer_id="l").health
        for r in range(5):
            m.observe_round_quality({"a": 1.0, "b": 1.0, "x": 900.0})
        assert m.flagged_peers() == ["x"]
        for r in range(12):
            m.observe_round_quality({"a": 1.0, "b": 1.0, "x": 1.1})
        assert m.flagged_peers() == []

    def test_streaming_window_attribution(self):
        """The window folds accumulate per-slot distance to the aggregate;
        quality_d2 ranks the scaled contributor far above the honest."""
        peers = [f"p{i}" for i in range(4)]
        n_elems, cb = 230, 64 * 4
        rng = np.random.default_rng(5)
        base = rng.standard_normal(n_elems).astype(np.float32)
        tele = T.Telemetry(peer_id="lead")

        async def main():
            agg = StreamingAggregator(
                n_elems, peers, "trimmed_mean", "f32", cb,
                kw_fn=lambda n: {"trim": 1}, pool=TilePool(),
                telemetry=tele,
            )
            for i in range(3):
                _feed_streamed(
                    agg, peers[i], 1.0, base + 0.01 * i, cb
                ).close(True)
            _feed_streamed(agg, peers[3], 1.0, base * 20.0, cb).close(True)
            await agg.finalize(peers)
            return agg.quality_d2()

        q = run(main())
        assert set(q) == set(peers)
        honest_max = max(q[p] for p in peers[:3])
        assert q["p3"] > 50.0 * max(honest_max, 1e-12)

    def test_live_round_flags_scaled_contributor(self):
        """In-process sync swarm, trimmed_mean: a peer contributing a
        scaled tree is flagged by the leader's monitor within a few
        committed rounds, with zero honest flags — the chaos campaign's
        assertion in miniature."""

        async def main():
            vols, boot = [], None
            for i in range(4):
                t = Transport()
                dht = DHTNode(t)
                await dht.start(bootstrap=[boot] if boot else None)
                if boot is None:
                    boot = t.addr
                mem = SwarmMembership(dht, f"vol{i}", ttl=10.0)
                await mem.join()
                tele = T.Telemetry(peer_id=f"vol{i}")
                avg = SyncAverager(
                    t, dht, mem, telemetry=tele, min_group=3,
                    join_timeout=6.0, gather_timeout=8.0,
                    method="trimmed_mean",
                )
                vols.append({"t": t, "dht": dht, "mem": mem, "avg": avg, "tele": tele})
            try:
                for r in range(5):
                    vals = [0.0, 1.0, 2.0, 24.0]  # vol3 scaled
                    await asyncio.gather(
                        *(
                            v["avg"].average(
                                {"w": np.full((8192,), vals[i], np.float32)},
                                round_no=r,
                            )
                            for i, v in enumerate(vols)
                        ),
                        return_exceptions=True,
                    )
            finally:
                for v in vols:
                    try:
                        await v["mem"].leave()
                    except Exception:
                        pass
                    try:
                        await v["dht"].stop()
                    except Exception:
                        pass
                    await v["t"].close()
            return vols

        vols = run(main())
        lead = vols[0]["tele"].health
        assert lead.flagged_peers() == ["vol3"]
        for p in ("vol0", "vol1", "vol2"):
            assert lead.quality_score(p) == 1.0
        # The flag also rode into the membership record fields.
        assert vols[0]["mem"].extra_info.get("health_flagged") == ["vol3"]
        # ... and the mass gauge saw full participation.
        s = lead.summary()
        assert s["mass"]["last"]["mass_committed_frac"] == 1.0
        assert s["sketch"] is not None and len(s["sketch"]["v"]) == H.DEFAULT_SKETCH_DIM

    def test_quality_attribution_on_non_streaming_wire(self):
        """A q8-wire sync round takes the DENSE leader branch (the
        streaming aggregator only arms on f32/bf16) — the quality votes
        must not depend on the wire codec."""

        async def main():
            vols, boot = [], None
            for i in range(4):
                t = Transport()
                dht = DHTNode(t)
                await dht.start(bootstrap=[boot] if boot else None)
                if boot is None:
                    boot = t.addr
                mem = SwarmMembership(dht, f"vol{i}", ttl=10.0)
                await mem.join()
                tele = T.Telemetry(peer_id=f"vol{i}")
                avg = SyncAverager(
                    t, dht, mem, telemetry=tele, min_group=3,
                    join_timeout=6.0, gather_timeout=8.0,
                    method="trimmed_mean", wire="q8",
                )
                vols.append({"t": t, "dht": dht, "mem": mem, "avg": avg, "tele": tele})
            try:
                for r in range(4):
                    vals = [0.0, 1.0, 2.0, 24.0]
                    await asyncio.gather(
                        *(
                            v["avg"].average(
                                {"w": np.full((4096,), vals[i], np.float32)},
                                round_no=r,
                            )
                            for i, v in enumerate(vols)
                        ),
                        return_exceptions=True,
                    )
            finally:
                for v in vols:
                    try:
                        await v["mem"].leave()
                    except Exception:
                        pass
                    try:
                        await v["dht"].stop()
                    except Exception:
                        pass
                    await v["t"].close()
            return vols

        vols = run(main())
        lead = vols[0]["tele"].health
        assert lead.flagged_peers() == ["vol3"]
        for p in ("vol0", "vol1", "vol2"):
            assert lead.quality_score(p) == 1.0


# -- disable plumbing (satellite: --no-health-probe end-to-end) --------------


class TestDisablePlumbing:
    def test_monitor_disabled_is_noop(self):
        tele = T.Telemetry(peer_id="p", health_enabled=False)
        m = tele.health
        m.note_sketch(np.ones(128, np.float32))
        m.observe_round_quality({"a": 1.0, "b": 1.0, "c": 99.0})
        m.note_round_mass(H.mass_from_outcomes(["a"], {"a": 1.0}))
        m.note_codec_error("bf16", 0.01)
        assert m.sketches_computed == 0
        assert m.flagged_peers() == []
        assert m.summary() is None
        assert m.scrape() is None
        assert tele.scrape()["health"] is None

    def test_no_telemetry_implies_no_health(self):
        tele = T.Telemetry(peer_id="p", enabled=False)
        assert not tele.health.enabled

    def test_volunteer_config_plumbs_health_probe(self):
        from distributedvolunteercomputing_tpu.swarm.volunteer import (
            Volunteer,
            VolunteerConfig,
        )

        v = Volunteer(VolunteerConfig(health_probe=False))
        assert v.telemetry.enabled and not v.telemetry.health.enabled
        report = v._build_report()
        assert "telemetry" in report and "health" not in report
        v_on = Volunteer(VolunteerConfig())
        assert v_on.telemetry.health.enabled

    def test_no_sketch_bytes_on_heartbeat_when_disabled(self):
        """End-to-end: a batched cp.exchange beat from a health-disabled
        volunteer carries NO health key (and an enabled one does)."""

        async def main():
            t = Transport()
            dht = DHTNode(t)
            await dht.start(bootstrap=None)
            rep = ControlPlaneReplica(t, dht, rid="cp0", interval=0.5)
            await rep.start()
            seen = {}
            try:
                for pid, health_on in (("voff", False), ("von", True)):
                    tele = T.Telemetry(peer_id=pid, health_enabled=health_on)
                    if health_on:
                        tele.health.note_sketch(
                            np.ones(256, np.float32), trace="tr"
                        )

                    def report_source(tele=tele, pid=pid):
                        # The volunteer's report shape: health only when
                        # the monitor yields a summary.
                        rep = {"peer": pid, "samples_per_sec": 1.0}
                        h = tele.health.summary()
                        if h is not None:
                            rep["health"] = h
                        return rep

                    vt = Transport()
                    vdht = DHTNode(vt)
                    await vdht.start(bootstrap=[t.addr])
                    from distributedvolunteercomputing_tpu.swarm.control_plane import (
                        ControlPlaneClient,
                    )

                    cp = ControlPlaneClient(vt, vdht, pid)
                    mem = SwarmMembership(
                        vdht, pid, ttl=10.0, control_plane=cp,
                        report_source=report_source, telemetry=tele,
                    )
                    await mem.join()
                    await mem._beat_once()
                    assert mem.last_beat_batched, "beat must ride cp.exchange"
                    seen[pid] = dict(rep.latest_metrics.get(pid) or {})
                    await mem.leave()
                    await vdht.stop()
                    await vt.close()
            finally:
                await rep.stop()
                await dht.stop()
                await t.close()
            return seen

        seen = run(main())
        assert "health" not in seen["voff"], "disabled probe leaked sketch bytes"
        assert "health" in seen["von"]
        assert seen["von"]["health"]["sketch"]["v"]


# -- coord.status["health"] schema (satellite: schema walk) ------------------


def _check_types(schema, obj, path=""):
    for key, typ in schema.items():
        assert key in obj, f"missing documented key {path}{key}"
        assert isinstance(obj[key], typ), (
            f"{path}{key}: expected {typ.__name__}, got {type(obj[key]).__name__}"
        )


class TestStatusHealthSchema:
    def test_status_health_schema_walk(self):
        async def main():
            t = Transport()
            dht = DHTNode(t)
            await dht.start(bootstrap=None)
            rep = ControlPlaneReplica(t, dht, rid="cp0", interval=0.5)
            await rep.start()
            try:
                for i, zone in enumerate(("dc-a", "dc-a", "dc-b")):
                    tele = T.Telemetry(peer_id=f"v{i}")
                    tele.health.zone_fn = lambda z=zone: z
                    tele.health.note_sketch(
                        np.full(512, float(i), np.float32), trace="tr1"
                    )
                    tele.health.observe_round_quality(
                        {"v0": 1.0, "v1": 1.1, "byz": 500.0}
                    )
                    tele.health.note_round_mass(
                        H.mass_from_outcomes(
                            ["v0", "v1", "byz"], {"v0": 1.0, "v1": 1.0}
                        )
                    )
                    tele.health.note_codec_error("bf16", 0.004)
                    await rep._rpc_report(
                        {
                            "peer": f"v{i}",
                            "samples_per_sec": 1.0,
                            "telemetry": tele.summary(),
                            "health": tele.health.summary(),
                        },
                        b"",
                    )
                status, _ = await rep._rpc_status({}, b"")
            finally:
                await rep.stop()
                await dht.stop()
                await t.close()
            return status

        status = run(main())
        roll = status["health"]
        assert roll is not None
        _check_types(H.STATUS_HEALTH_SCHEMA, roll)
        assert roll["schema_version"] == H.HEALTH_SCHEMA_VERSION
        assert roll["reporting"] == 3
        mixing = roll["mixing"]
        assert mixing["n_sketches"] == 3
        assert mixing["dispersion"]["n"] == 3
        # Two zones reported: per-zone and across-zone dispersion exist.
        assert set(mixing["per_zone"]) == {"dc-a", "dc-b"}
        assert mixing["across_zones"] is not None
        assert roll["mass"]["committed_frac_mean"] == pytest.approx(1.0)
        assert roll["codec"]["bf16"] == pytest.approx(0.004, rel=0.5)
        # The telemetry rollup counts health reporters (v2 schema key).
        t_roll = status["telemetry"]
        assert t_roll["health_reporting"] == 3

    def test_status_health_none_without_reports(self):
        async def main():
            t = Transport()
            dht = DHTNode(t)
            await dht.start(bootstrap=None)
            rep = ControlPlaneReplica(t, dht, rid="cp0", interval=0.5)
            await rep.start()
            try:
                status, _ = await rep._rpc_status({}, b"")
            finally:
                await rep.stop()
                await dht.stop()
                await t.close()
            return status

        assert run(main())["health"] is None

    def test_rollup_zone_dispersion_separates_converged_zones(self):
        """Zone-converged but globally-diverged sketches: per-zone
        dispersion ~0, across-zone dispersion high — the signal the
        hierarchy's cross_zone_every_k exists to converge."""
        seed = H.sketch_seed("m")
        a = H.params_sketch(np.full(4096, 1.0, np.float32), seed)
        b = H.params_sketch(np.full(4096, 9.0, np.float32), seed)
        reports = []
        for i, (zone, sk) in enumerate(
            (("za", a), ("za", a), ("zb", b), ("zb", b))
        ):
            tele = T.Telemetry(peer_id=f"v{i}")
            tele.health.zone_fn = lambda z=zone: z
            s = tele.health.summary()
            s["sketch"] = {
                "trace": "tr", "t": 0.0, "dim": H.DEFAULT_SKETCH_DIM,
                "seed": seed, "v": [float(x) for x in sk],
            }
            reports.append({"peer": f"v{i}", "health": s})
        roll = H.rollup_status(reports)
        mixing = roll["mixing"]
        assert mixing["per_zone"]["za"]["rel"] < 1e-9
        assert mixing["per_zone"]["zb"]["rel"] < 1e-9
        assert mixing["across_zones"]["rel"] > 0.5


# -- overhead smoke (satellite: health probe <5% of commit latency) ----------


class TestHealthOverheadSmoke:
    def test_health_probe_overhead_within_5pct(self):
        """Rounds with the health probe on must stay within 5% of
        probe-off commit latency (telemetry itself on in BOTH arms —
        this isolates the health layer's cost). Interleaved arms +
        medians + a small absolute grace, like the PR-10 smoke."""
        blocks, rounds_per_block, elems = 3, 3, 65_536

        async def spawn(health_on):
            vols, boot = [], None
            for i in range(3):
                t = Transport()
                dht = DHTNode(t)
                await dht.start(bootstrap=[boot] if boot else None)
                if boot is None:
                    boot = t.addr
                mem = SwarmMembership(dht, f"{'on' if health_on else 'off'}{i}", ttl=10.0)
                await mem.join()
                tele = T.Telemetry(
                    peer_id=mem.peer_id, health_enabled=health_on
                )
                avg = SyncAverager(
                    t, dht, mem, telemetry=tele, min_group=2,
                    join_timeout=6.0, gather_timeout=8.0,
                    method="trimmed_mean",
                )
                vols.append({"t": t, "dht": dht, "mem": mem, "avg": avg})
            return vols

        async def run_round(vols, r):
            res = await asyncio.gather(
                *(
                    v["avg"].average(
                        {"w": np.full((elems,), float(i), np.float32)}, round_no=r
                    )
                    for i, v in enumerate(vols)
                ),
                return_exceptions=True,
            )
            return all(x is not None and not isinstance(x, BaseException) for x in res)

        async def teardown(vols):
            for v in vols:
                try:
                    await v["mem"].leave()
                except Exception:
                    pass
                try:
                    await v["dht"].stop()
                except Exception:
                    pass
                await v["t"].close()

        async def main():
            arms = {False: await spawn(False)}
            try:
                arms[True] = await spawn(True)
            except BaseException:
                await teardown(arms[False])
                raise
            dts = {False: [], True: []}
            try:
                r = 0
                for on in (False, True):  # warmup both arms
                    await run_round(arms[on], r)
                    r += 1
                for _ in range(blocks):
                    for on in (False, True):
                        for _ in range(rounds_per_block):
                            r += 1
                            t0 = time.perf_counter()
                            if await run_round(arms[on], r):
                                dts[on].append(time.perf_counter() - t0)
            finally:
                await teardown(arms[False])
                await teardown(arms[True])
            return dts

        dts = run(main(), timeout=300)
        need = blocks * rounds_per_block // 2
        assert len(dts[True]) >= need and len(dts[False]) >= need
        med_on = statistics.median(dts[True])
        med_off = statistics.median(dts[False])
        assert med_on <= med_off * 1.05 + 0.030, (
            f"health probe overhead: enabled median {med_on:.4f}s vs "
            f"disabled {med_off:.4f}s — exceeds the 5% budget"
        )
