"""Pool lifecycle tests for the persistent multiplexed transport.

The properties the pooled rewrite must hold (ISSUE 3): a peer kill+restart
redials transparently (one retried call, not an error surfaced upward); a
stale pooled socket after an idle close retries exactly once; concurrent
in-flight RPCs demultiplex correctly on ONE connection; the connect budget
is split from the per-call budget; per-peer counters (bytes, RPCs,
connects, latency EWMA) account the traffic and feed the phi-accrual
detector's secondary signal.
"""

import asyncio
import random

import numpy as np
import pytest

from distributedvolunteercomputing_tpu.swarm.transport import (
    RPCError,
    StreamPayload,
    Transport,
)

pytestmark = pytest.mark.transport


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=90))


async def _echo_server(**kw):
    server = Transport(**kw)

    async def echo(args, payload):
        if args.get("sleep"):
            await asyncio.sleep(float(args["sleep"]))
        return {"got": args.get("x")}, bytes(payload)

    server.register("echo", echo)
    await server.start()
    return server


class TestPoolLifecycle:
    def test_concurrent_calls_share_one_connection_and_demux(self):
        """Many in-flight RPCs on one pooled connection, with handler-side
        delays scrambling the response ORDER: every call must get exactly
        its own response (rid demux), over exactly one dial."""

        async def main():
            server = await _echo_server()
            client = Transport()
            rng = random.Random(0)
            try:
                payloads = [bytes([i]) * (1 + i * 37) for i in range(24)]
                results = await asyncio.gather(
                    *(
                        client.call(
                            server.addr, "echo",
                            {"x": i, "sleep": rng.random() * 0.2},
                            payloads[i],
                        )
                        for i in range(24)
                    )
                )
                for i, (ret, pl) in enumerate(results):
                    assert ret == {"got": i}
                    assert pl == payloads[i]
                return client.connects, client.stats()
            finally:
                await client.close()
                await server.close()

        connects, stats = run(main())
        assert connects == 1, f"expected one dial for 24 concurrent RPCs, got {connects}"
        peer = next(iter(stats["peers"].values()))
        assert peer["rpcs"] == 24 and peer["connects"] == 1
        assert peer["latency_ewma_ms"] is not None and peer["latency_ewma_ms"] > 0
        assert peer["bytes_sent"] > sum(1 + i * 37 for i in range(24))

    def test_stale_pooled_socket_retries_exactly_once(self):
        """The server idle-closes its inbound connection; the client's next
        call must succeed via ONE transparent redial (connects goes 1 -> 2),
        invisible to the caller."""

        async def main():
            server = await _echo_server()
            client = Transport()
            try:
                ret, _ = await client.call(server.addr, "echo", {"x": 1})
                assert ret == {"got": 1} and client.connects == 1
                # Server-side idle close (e.g. peer restarted its process).
                for w in list(server._server_writers):
                    w.close()
                await asyncio.sleep(0.2)
                ret, _ = await client.call(server.addr, "echo", {"x": 2})
                assert ret == {"got": 2}
                assert client.connects == 2, "stale socket must cost exactly one redial"
                # And the redialed connection is pooled again.
                ret, _ = await client.call(server.addr, "echo", {"x": 3})
                assert ret == {"got": 3} and client.connects == 2
            finally:
                await client.close()
                await server.close()

        run(main())

    def test_peer_kill_and_restart_redials_transparently(self):
        """kill -9 + restart: the pooled connection points at a dead
        process; once a NEW server owns the same port, the next call must
        succeed without the caller seeing any error."""

        async def main():
            server = await _echo_server()
            addr = server.addr
            client = Transport()
            try:
                ret, _ = await client.call(addr, "echo", {"x": 1})
                assert ret == {"got": 1}
                await server.close()  # the "kill"
                server = await _echo_server(port=addr[1])  # the restart
                ret, _ = await client.call(addr, "echo", {"x": 2}, timeout=10)
                assert ret == {"got": 2}, "restarted peer must look like one retried call"
            finally:
                await client.close()
                await server.close()

        run(main())

    def test_unpooled_mode_dials_per_call(self):
        """pooled=False restores the v1 one-connection-per-call wire — the
        baseline arm of experiments/transport_bench.py."""

        async def main():
            server = await _echo_server()
            client = Transport(pooled=False)
            try:
                for i in range(5):
                    ret, _ = await client.call(server.addr, "echo", {"x": i})
                    assert ret == {"got": i}
                return client.connects
            finally:
                await client.close()
                await server.close()

        assert run(main()) == 5

    def test_connect_timeout_split_from_call_timeout(self):
        """The per-call budget starts AFTER the dial: a parked handler times
        out at ~the call timeout, and a refused dial surfaces as OSError
        without consuming the RPC budget."""

        async def main():
            server = await _echo_server()
            client = Transport()
            try:
                t0 = asyncio.get_running_loop().time()
                with pytest.raises(asyncio.TimeoutError):
                    await client.call(
                        server.addr, "echo", {"x": 1, "sleep": 30.0}, timeout=0.75
                    )
                dt = asyncio.get_running_loop().time() - t0
                assert dt < 5.0, f"call timeout took {dt:.1f}s"
                # The timed-out call must not poison the pooled connection.
                ret, _ = await client.call(server.addr, "echo", {"x": 2})
                assert ret == {"got": 2} and client.connects == 1
                with pytest.raises((OSError, asyncio.TimeoutError)):
                    await client.call(("127.0.0.1", 1), "echo", {}, timeout=5.0)
            finally:
                await client.close()
                await server.close()

        run(main())

    def test_timeout_queued_on_write_lock_spares_the_connection(self):
        """A call cancelled while still WAITING for the connection write
        lock (a bulk transfer holds it) never touched the stream: the
        pooled connection — and the bulk transfer mid-flight on it — must
        survive, and the transfer must not be re-sent."""

        async def main():
            server = await _echo_server()
            client = Transport()
            data = b"q" * (24 << 20)  # 24 MB: holds the write lock a while
            try:
                await client.call(server.addr, "echo", {"x": 0})  # warm the pool
                big = asyncio.create_task(
                    client.call(server.addr, "echo", {"x": 1}, data, timeout=60)
                )
                await asyncio.sleep(0.01)  # big's chunked write is in progress
                with pytest.raises(asyncio.TimeoutError):
                    # Queued behind the bulk write; times out before the
                    # lock frees. Must NOT poison the shared connection.
                    await client.call(server.addr, "echo", {"x": 2}, timeout=0.05)
                ret, pl = await big
                assert ret == {"got": 1} and pl == data
                assert client.connects == 1, "timeout while queued must not redial"
            finally:
                await client.close()
                await server.close()

        run(main())

    def test_auth_rides_the_pooled_connection(self):
        """HMAC auth end-to-end over one persistent connection: fresh rids
        keep the replay cache happy across many calls, and a chunked
        (multi-MB) payload authenticates via the trailer MAC."""

        async def main():
            server = await _echo_server(secret=b"s3kr1t")
            client = Transport(secret=b"s3kr1t")
            try:
                for i in range(6):
                    ret, _ = await client.call(server.addr, "echo", {"x": i})
                    assert ret == {"got": i}
                big = np.arange(800_000, dtype=np.float32).tobytes()  # 3 MB
                ret, pl = await client.call(server.addr, "echo", {"x": 99}, big)
                assert ret == {"got": 99} and pl == big
                assert client.connects == 1
            finally:
                await client.close()
                await server.close()

        run(main())


class TestStreamingPayloads:
    def test_stream_payload_roundtrip_and_retry(self):
        """A StreamPayload's chunks are produced lazily; its factory must
        re-iterate for the transparent retry after a stale pooled socket."""

        async def main():
            server = await _echo_server()
            client = Transport()
            data = np.arange(600_000, dtype=np.float32).tobytes()  # ~2.3 MB

            def factory():
                for i in range(0, len(data), 300_000):
                    yield data[i : i + 300_000]

            try:
                ret, pl = await client.call(
                    server.addr, "echo", {"x": 1}, StreamPayload(len(data), factory)
                )
                assert pl == data
                # Stale the socket, then stream again: the retry restarts
                # the factory from scratch.
                for w in list(server._server_writers):
                    w.close()
                await asyncio.sleep(0.2)
                ret, pl = await client.call(
                    server.addr, "echo", {"x": 2}, StreamPayload(len(data), factory)
                )
                assert pl == data and client.connects == 2
            finally:
                await client.close()
                await server.close()

        run(main())

    def test_chunk_sink_receives_verified_chunks(self):
        """chunk_sink streams the response payload out chunk-by-chunk (the
        decode-on-first-chunk hook); the returned payload is then empty."""

        async def main():
            server = await _echo_server()
            client = Transport()
            data = bytes(range(256)) * 16384  # 4 MB
            got = {}

            def sink(off, total, chunk):
                buf = got.setdefault("buf", bytearray(total))
                buf[off : off + len(chunk)] = chunk
                got["calls"] = got.get("calls", 0) + 1

            try:
                ret, pl = await client.call(
                    server.addr, "echo", {"x": 1}, data, chunk_sink=sink
                )
                assert pl == b""
                assert bytes(got["buf"]) == data
                assert got["calls"] >= 4, "a 4 MB payload must arrive in several chunks"
            finally:
                await client.close()
                await server.close()

        run(main())


class TestLatencySecondarySignal:
    def test_failure_detector_latency_suspicion(self):
        from distributedvolunteercomputing_tpu.swarm.failure_detector import (
            PhiAccrualDetector,
        )

        fd = PhiAccrualDetector()
        # Healthy baseline: ms-scale RPCs, even with CI-grade 10x jitter.
        for _ in range(20):
            fd.observe_latency("p", 0.004)
        fd.observe_latency("p", 0.040)
        assert not fd.latency_suspect("p"), "ms-scale jitter must not suspect"
        # Congested peer: seconds-scale EWMA far above its own baseline.
        fd.observe_latency("p", 6.0)
        assert fd.latency_suspect("p")
        assert fd.suspect("p"), "latency suspicion feeds suspect() even at phi 0"
        # forget() clears the latency history with the rest.
        fd.forget("p")
        assert not fd.latency_suspect("p")

    def test_membership_feeds_transport_latency(self):
        """alive_peers maps record addresses to peer ids and pushes the
        transport's per-peer latency EWMA into the detector."""

        async def main():
            from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
            from distributedvolunteercomputing_tpu.swarm.failure_detector import (
                PhiAccrualDetector,
            )
            from distributedvolunteercomputing_tpu.swarm.membership import (
                SwarmMembership,
            )

            t1 = Transport()
            dht1 = DHTNode(t1)
            await dht1.start()
            mem1 = SwarmMembership(dht1, "lat1", ttl=10.0)
            await mem1.join()
            t2 = Transport()
            dht2 = DHTNode(t2)
            await dht2.start(bootstrap=[t1.addr])
            fd = PhiAccrualDetector()
            mem2 = SwarmMembership(dht2, "lat2", ttl=10.0, failure_detector=fd)
            await mem2.join()
            try:
                # Bootstrap + join already produced RPCs to t1; observe.
                await mem2.alive_peers()
                return fd._lat.get("lat1")
            finally:
                for mem in (mem1, mem2):
                    try:
                        await mem.leave()
                    except Exception:
                        pass
                await t1.close()
                await t2.close()

        lat = run(main())
        assert lat is not None and lat[0] > 0, "transport latency must reach the detector"


class TestTransportBenchSmoke:
    def test_pooled_beats_per_call_smoke(self):
        """Fast n=2 smoke of experiments/transport_bench.py in the default
        lane: a regression that loses pooling's RPC-throughput win (or
        breaks the bench harness) fails loudly here. The full banked
        artifact is experiments/results/transport_bench.json."""
        from experiments.transport_bench import run_bench

        ratio = 0.0
        for attempt in range(2):  # one retry: a loaded CI core can skew one run
            result = run(
                run_bench(
                    seq_calls=120, payload_bytes=1024, concurrency=8,
                    conc_batches=6, large_mb=2, large_transfers=2,
                )
            )
            ratio = max(ratio, result["ratios"]["seq_small_rps"])
            if ratio >= 1.3:
                break
        # Full runs measure ~3.5x on this host (banked artifact); 1.3 leaves
        # generous CI slack while still catching "pooling silently off".
        assert ratio >= 1.3, f"pooled/per-call sequential RPC ratio {ratio:.2f} < 1.3"
        assert result["pooled"]["connects"] <= 3
        assert result["per_call"]["connects"] >= result["per_call"]["seq_calls"]
