"""steps_per_call (host-loop amortization): N train steps scanned inside one
compiled call must be bit-compatible with N single-step dispatches — the
scan runs the SAME traced body (training/steps.py train_step_body), so any
divergence is a bug, not tolerance.
"""

import jax
import numpy as np
import pytest

from distributedvolunteercomputing_tpu.models import get_model
from distributedvolunteercomputing_tpu.training.trainer import Trainer


def leaves(tree):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


def make_trainer(**kw):
    base = dict(batch_size=8, lr=1e-2, optimizer="adam", seed=0)
    base.update(kw)
    return Trainer(get_model("mnist_mlp"), **base)


class TestEquivalence:
    def test_scanned_matches_per_step_exactly(self):
        t1 = make_trainer()
        t8 = make_trainer(steps_per_call=8)
        t1.run(steps=16, log_every=0)
        t8.run(steps=16, log_every=0)
        assert int(t8.state.step) == 16
        for a, b in zip(leaves(t1.state.params), leaves(t8.state.params)):
            np.testing.assert_allclose(a, b, atol=1e-6)

    def test_chunk_not_dividing_steps(self):
        # 10 steps at steps_per_call=4: chunks of 4+4+2 — same endpoint.
        t1 = make_trainer(seed=5)
        t4 = make_trainer(seed=5, steps_per_call=4)
        t1.run(steps=10, log_every=0)
        t4.run(steps=10, log_every=0)
        assert int(t4.state.step) == 10
        for a, b in zip(leaves(t1.state.params), leaves(t4.state.params)):
            np.testing.assert_allclose(a, b, atol=1e-6)

    def test_chunks_end_at_averaging_boundary(self):
        # average_every=5 with steps_per_call=8: every round must still see
        # the exact post-step-5k params (chunks clip at the cadence).
        calls = []

        def averager(tree, step):
            calls.append(step)
            return tree

        t = make_trainer(
            steps_per_call=8, averager=averager, average_what="params",
            average_every=5,
        )
        t.run(steps=20, log_every=0)
        assert calls == [5, 10, 15, 20]

    def test_target_crossing_detected_inside_scan_prefix(self):
        # The mnist proxy crosses 0.3 within a few steps; with a 16-step
        # chunk the crossing happens INSIDE the scanned prefix and must be
        # attributed to the right step, not the chunk end.
        t_ref = make_trainer(seed=9)
        r_ref = t_ref.run(steps=32, target_loss=0.3, target_mode="record", log_every=0)
        t16 = make_trainer(seed=9, steps_per_call=16)
        r16 = t16.run(steps=32, target_loss=0.3, target_mode="record", log_every=0)
        assert r16["target_crossed_step"] == r_ref["target_crossed_step"]

    def test_target_stop_mode_breaks_after_prefix(self):
        t = make_trainer(seed=9, steps_per_call=16)
        r = t.run(steps=64, target_loss=0.3, target_mode="stop", log_every=0)
        # Stops at a chunk boundary at the latest — far short of 64.
        assert r["steps"] <= 32
        # final_loss must reflect the stopping chunk, not a stale metric
        # from the previous chunk (regression: summary said loss > target
        # after a mid-prefix stop).
        assert r["final_loss"] <= 0.3

    def test_chunk_cadences_respected(self):
        # A cadence declared via chunk_cadences (the volunteer's checkpoint
        # cadence) must end chunks exactly like eval/averaging boundaries.
        seen = []
        t = make_trainer(steps_per_call=5, chunk_cadences=(7,))
        t.on_step = lambda tr, s: seen.append(s)
        t.run(steps=21, log_every=0)
        # on_step fires at every chunk-final step; multiples of 7 must all
        # be present (7, 14, 21), whatever else the chunking produced.
        assert {7, 14, 21} <= set(seen)


class TestProfilingInterplay:
    def test_fast_path_disabled_while_profiling(self, tmp_path, monkeypatch):
        # The profiler hooks are per-step, so steps_per_call must silently
        # fall back to per-step dispatch when DVC_PROFILE_DIR is set — and
        # the trace must still be produced.
        import os

        monkeypatch.setenv("DVC_PROFILE_DIR", str(tmp_path / "trace"))
        monkeypatch.setenv("DVC_PROFILE_START", "2")
        monkeypatch.setenv("DVC_PROFILE_STEPS", "2")
        t = make_trainer(steps_per_call=4)
        t.run(steps=8, log_every=0)
        assert int(t.state.step) == 8
        assert os.path.isdir(tmp_path / "trace")  # trace was written


class TestValidation:
    def test_grads_mode_rejected(self):
        with pytest.raises(ValueError, match="steps_per_call"):
            make_trainer(
                steps_per_call=4, average_what="grads",
                averager=lambda g, s: g,
            )

    def test_zero_rejected(self):
        with pytest.raises(ValueError, match="steps_per_call"):
            make_trainer(steps_per_call=0)
