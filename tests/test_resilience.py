"""Adaptive resilience policy: deadline learning, failure backoff,
pre-exclusion, and runtime estimator escalation — all under explicit
inputs (no clocks, no swarms; the policy is pure bookkeeping)."""

import pytest

from distributedvolunteercomputing_tpu.swarm.resilience import ResiliencePolicy


class FakeDetector:
    def __init__(self, suspects=()):
        self.suspects = set(suspects)

    def suspect(self, peer):
        return peer in self.suspects


def complete_round(policy, duration_s, **kw):
    policy.record_round(duration_s=duration_s, ok=True, **kw)


class TestDeadline:
    def test_starts_at_ceiling(self):
        p = ResiliencePolicy(max_deadline_s=20.0)
        assert p.round_budget() == 20.0

    def test_initial_deadline_clamped(self):
        p = ResiliencePolicy(max_deadline_s=20.0, min_deadline_s=2.0,
                             initial_deadline_s=500.0)
        assert p.round_budget() == 20.0
        p = ResiliencePolicy(max_deadline_s=20.0, min_deadline_s=2.0,
                             initial_deadline_s=0.5)
        assert p.round_budget() == 2.0

    def test_learns_down_from_fast_rounds(self):
        """A healthy swarm's deadline converges toward observed round time
        + margin, far under the configured ceiling — the property that
        makes a stalled peer cheap."""
        p = ResiliencePolicy(max_deadline_s=20.0, min_deadline_s=1.0)
        for _ in range(20):
            complete_round(p, 0.5)
        assert 1.0 <= p.round_budget() < 4.0, p.round_budget()

    def test_failed_round_doubles_toward_ceiling(self):
        """AIMD recovery: a genuinely slow network must ratchet the budget
        back up instead of timing out forever at a learned-tight deadline."""
        p = ResiliencePolicy(max_deadline_s=20.0, min_deadline_s=1.0)
        for _ in range(20):
            complete_round(p, 0.5)
        tight = p.round_budget()
        p.record_round(duration_s=tight, ok=False)
        assert p.round_budget() == pytest.approx(min(tight * 2.0, 20.0))
        for _ in range(5):
            p.record_round(duration_s=1.0, ok=False)
        assert p.round_budget() == 20.0  # capped at the ceiling

    def test_degraded_round_is_not_an_observation(self):
        """A deadline-committed round took ~the deadline BY CONSTRUCTION;
        feeding it back would ratchet the estimate to the ceiling in
        exactly the persistent-straggler case the policy targets."""
        p = ResiliencePolicy(max_deadline_s=20.0, min_deadline_s=1.0)
        for _ in range(20):
            complete_round(p, 0.5)
        tight = p.round_budget()
        for _ in range(10):
            p.record_round(duration_s=tight, ok=True, degraded=True)
        assert p.round_budget() == pytest.approx(tight)
        assert p.rounds_degraded == 10

    def test_one_fast_outlier_does_not_slam_deadline(self):
        """Multiplicative decrease TOWARD the estimate: one unusually fast
        round must not cut the budget onto the next round's normal tail."""
        p = ResiliencePolicy(max_deadline_s=20.0, min_deadline_s=0.5)
        for _ in range(20):
            complete_round(p, 5.0)
        settled = p.round_budget()
        complete_round(p, 0.1)
        assert p.round_budget() > settled * 0.5


class TestBackoff:
    def test_exponential_growth_and_reset(self):
        p = ResiliencePolicy()
        assert p.backoff_s() == 0.0
        p.record_round(duration_s=1.0, ok=False)
        first = p.backoff_s()
        assert first > 0.0
        p.record_round(duration_s=1.0, ok=False)
        assert p.backoff_s() == pytest.approx(first * 2.0)
        for _ in range(20):
            p.record_round(duration_s=1.0, ok=False)
        assert p.backoff_s() <= 30.0  # capped
        complete_round(p, 1.0)  # one success clears the backoff
        assert p.backoff_s() == 0.0


class TestPreExclusion:
    def test_miss_streak_triggers_preexclusion(self):
        p = ResiliencePolicy(preexclude_misses=3)
        for _ in range(2):
            complete_round(p, 1.0, absent=["lag"])
        assert not p.should_preexclude("lag")
        complete_round(p, 1.0, absent=["lag"])
        assert p.should_preexclude("lag")

    def test_on_time_resets_streak(self):
        p = ResiliencePolicy(preexclude_misses=3)
        for _ in range(2):
            complete_round(p, 1.0, late=["flaky"])
        complete_round(p, 1.0, on_time=["flaky"])
        complete_round(p, 1.0, absent=["flaky"])
        assert not p.should_preexclude("flaky")

    def test_late_and_rejected_count_as_misses(self):
        p = ResiliencePolicy(preexclude_misses=3)
        complete_round(p, 1.0, late=["p"])
        complete_round(p, 1.0, rejected=["p"])
        complete_round(p, 1.0, absent=["p"])
        assert p.should_preexclude("p")

    def test_phi_suspicion_preexcludes(self):
        det = FakeDetector(suspects={"stalled"})
        p = ResiliencePolicy(failure_detector=det)
        assert p.should_preexclude("stalled")
        assert not p.should_preexclude("healthy")

    def test_late_arrival_outside_round_batch(self):
        p = ResiliencePolicy(preexclude_misses=3)
        for _ in range(3):
            p.record_late_arrival("slow")
        assert p.should_preexclude("slow")

    def test_late_after_absent_counts_one_miss(self):
        """A slow-but-alive peer is seen twice per round — absent in the
        commit-time batch, then late when its push finally lands. That is
        ONE missed round: the arrival reclassifies the absent event, it
        must not advance the streak (or the counters) a second time."""
        p = ResiliencePolicy(preexclude_misses=3)
        for _ in range(2):
            complete_round(p, 1.0, absent=["slow"])
            p.record_late_arrival("slow")
        # 2 slow rounds: still below the documented 3-round threshold
        # (double counting used to pre-exclude here).
        assert not p.should_preexclude("slow")
        assert p.peers["slow"].miss_streak == 2
        # The events were reclassified, not duplicated.
        assert p.peers["slow"].absent == pytest.approx(0.0)
        complete_round(p, 1.0, absent=["slow"])
        assert p.should_preexclude("slow")

    def test_late_before_flush_counts_one_miss(self):
        """Same slow round, opposite arrival order: the push lands between
        the commit and the round flush (record_late_arrival first, the
        absent batch after). Still one miss."""
        p = ResiliencePolicy(preexclude_misses=3)
        for _ in range(2):
            p.record_late_arrival("slow")
            complete_round(p, 1.0, absent=["slow"])
        assert not p.should_preexclude("slow")
        assert p.peers["slow"].miss_streak == 2
        complete_round(p, 1.0, absent=["slow"])
        assert p.should_preexclude("slow")

    def test_tight_gather_timeout_below_deadline_floor(self):
        """--resilience with a sub-2s --gather-timeout (tight LAN) must
        construct, the way the volunteer wires it: the default 2s deadline
        floor clamps to the ceiling instead of tripping the range check."""
        p = ResiliencePolicy(
            max_deadline_s=1.5, min_deadline_s=min(2.0, 1.5)
        )
        assert p.round_budget() == pytest.approx(1.5)


class TestEstimatorEscalation:
    def test_ladder_escalates_on_rejection_evidence(self):
        p = ResiliencePolicy(escalate_rejections=3.0)
        assert p.recommend_method("mean") == "mean"
        for _ in range(3):
            p.record_rejection("byz")
        assert p.recommend_method("mean") == "trimmed_mean"
        for _ in range(3):
            p.record_rejection("byz")
        assert p.recommend_method("mean") == "coordinate_median"

    def test_operator_chosen_method_is_the_floor(self):
        """Escalation only lifts an explicitly-cheap 'mean'; an operator's
        robust choice (krum, trimmed_mean, ...) is never overridden."""
        p = ResiliencePolicy(escalate_rejections=1.0)
        for _ in range(10):
            p.record_rejection("byz")
        assert p.recommend_method("krum") == "krum"
        assert p.recommend_method("trimmed_mean") == "trimmed_mean"

    def test_deescalates_only_after_evidence_decays(self):
        """No flapping: the ladder steps down only once the decayed
        rejection score is essentially gone."""
        p = ResiliencePolicy(escalate_rejections=3.0, decay=0.5)
        for _ in range(3):
            p.record_rejection("byz")
        assert p.recommend_method("mean") == "trimmed_mean"
        complete_round(p, 1.0)  # one clean round: evidence not gone yet
        assert p.recommend_method("mean") == "trimmed_mean"
        for _ in range(5):  # 1.5 * 0.5^k < 0.5 within a few clean rounds
            complete_round(p, 1.0)
        assert p.recommend_method("mean") == "mean"


class TestBookkeeping:
    def test_stats_shape(self):
        p = ResiliencePolicy()
        complete_round(p, 1.0, on_time=["a"], absent=["b"])
        s = p.stats()
        assert s["rounds_seen"] == 1
        assert s["method_level"] == "mean"
        assert s["peers"]["a"]["on_time"] == pytest.approx(1.0)
        assert s["peers"]["b"]["miss_streak"] == 1

    def test_validation(self):
        with pytest.raises(ValueError, match="deadline"):
            ResiliencePolicy(max_deadline_s=1.0, min_deadline_s=2.0)
        with pytest.raises(ValueError, match="decay"):
            ResiliencePolicy(decay=0.0)
