"""Kill-at-phase e2e: real volunteer PROCESSES through the actual CLI
entrypoints, the leader SIGKILLs itself at an instrumented round phase
(DVC_CHAOS_LEADER_DIE_PHASE), and the survivors must commit via failover
recovery and finish their runs.

Slow lane (subprocess jax startup is ~a minute per volunteer under sandbox
contention); the fast in-process twin of this matrix is
tests/test_failover.py::TestKillAtPhase.
"""

import json
import os
import re
import signal
import subprocess
import sys
import time

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.failover]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_MLP = ["--model-override", "d_hidden=16"]


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def start_coordinator():
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "coordinator.py")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=_env(),
    )
    deadline = time.time() + 30
    while time.time() < deadline:
        line = proc.stdout.readline()
        m = re.match(r"COORDINATOR_READY (\S+)", line or "")
        if m:
            return proc, m.group(1)
    proc.kill()
    raise RuntimeError("coordinator did not become ready")


def start_volunteer(coord_addr, peer_id, extra, env_extra=None, capture=True):
    env = _env()
    if env_extra:
        env.update(env_extra)
    out = subprocess.PIPE if capture else subprocess.DEVNULL
    err = subprocess.STDOUT if capture else subprocess.DEVNULL
    return subprocess.Popen(
        [
            sys.executable, os.path.join(REPO, "run_volunteer.py"),
            "--coordinator", coord_addr,
            "--peer-id", peer_id,
            "--batch-size", "16",
            "--lr", "0.01",
            *TINY_MLP,
            *extra,
        ],
        stdout=out, stderr=err, text=True, env=env,
    )


def wait_done(proc, timeout=300):
    out, _ = proc.communicate(timeout=timeout)
    for line in out.splitlines():
        if line.startswith("VOLUNTEER_DONE "):
            return json.loads(line[len("VOLUNTEER_DONE "):]), out
    raise AssertionError(f"no VOLUNTEER_DONE in output:\n{out[-3000:]}")


def wait_swarm_alive(coord_addr, n, timeout=180):
    """Poll coord.status until >= n peers are alive (deterministic
    readiness — a jax subprocess can take a minute to come up)."""
    import asyncio

    from distributedvolunteercomputing_tpu.swarm.transport import Transport

    host, _, port = coord_addr.rpartition(":")

    async def poll():
        t = Transport()
        try:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                try:
                    ret, _ = await t.call(
                        (host, int(port)), "coord.status", timeout=5.0
                    )
                    if int(ret.get("n_alive", 0)) >= n:
                        return True
                except Exception:
                    pass
                await asyncio.sleep(2.0)
            return False
        finally:
            await t.close()

    return asyncio.run(poll())


@pytest.mark.parametrize(
    "phase", ["pre_arm", "mid_stream", "post_partial_commit", "pre_fetch"]
)
def test_leader_sigkill_at_phase_survivors_recover(phase):
    """Peer 'a0' sorts first, so it leads every round it joins — and
    SIGKILLs itself at ``phase`` of its first led round. 'b1' and 'c2'
    must depose it, recover that round via the successor, and finish the
    run with healthy rounds afterwards (no EF on the f32 wire; the
    bit-level EF check across a recovered round is in-process:
    test_failover.py::test_ef_residual_bitwise_across_recovered_round)."""
    coord, addr = start_coordinator()
    common = [
        "--averaging", "sync", "--average-every", "5", "--steps", "900",
        "--max-group", "4",
        "--join-timeout", "20", "--gather-timeout", "15",
    ]
    vols = []
    try:
        # Survivors first: the doomed leader's first led round must contain
        # BOTH of them (a 2-member round would leave one survivor — below
        # min_group, correctly unrecoverable), so a0 starts only once b1/c2
        # are alive, and requires a 3-member group for its own rounds.
        # DVC_STEP_DELAY_MS stretches the survivors' runs so they are still
        # training when a0 (a jax subprocess can take a minute to come up)
        # joins, dies, and must be recovered from.
        slow = {"DVC_STEP_DELAY_MS": "50"}
        vols.append(start_volunteer(
            addr, "b1", [*common, "--min-group", "2"], env_extra=slow,
        ))
        vols.append(start_volunteer(
            addr, "c2", [*common, "--min-group", "2"], env_extra=slow,
        ))
        assert wait_swarm_alive(addr, 2), "survivors never came up"
        # The doomed leader's output goes to DEVNULL: nobody drains its
        # pipe after the SIGKILL, and a filled pipe would stall it BEFORE
        # the instrumented phase.
        vols.append(start_volunteer(
            addr, "a0", [*common, "--min-group", "3"],
            env_extra={"DVC_CHAOS_LEADER_DIE_PHASE": phase}, capture=False,
        ))
        rc = vols[2].wait(timeout=300)
        assert rc == -signal.SIGKILL, f"leader exited {rc}, expected SIGKILL"
        summaries = [wait_done(v)[0] for v in vols[:2]]
    finally:
        coord.kill()
        for v in vols:
            if v.poll() is None:
                v.kill()
    for s in summaries:
        assert s.get("rounds_ok", 0) >= 1, s
    recovered = [s.get("failover", {}).get("rounds_recovered", 0) for s in summaries]
    deposed = [s.get("failover", {}).get("leaders_deposed", 0) for s in summaries]
    assert any(r >= 1 for r in recovered), (recovered, summaries)
    assert all(d >= 1 for d in deposed), (deposed, summaries)
