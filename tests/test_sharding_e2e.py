"""Shard-holder kill-at-phase e2e: real volunteer PROCESSES through the
actual CLI entrypoints (--zone-shards), a shard-holding leader SIGKILLs
itself at an instrumented round phase (DVC_CHAOS_LEADER_DIE_PHASE) or
mid-re-shard (DVC_CHAOS_SHARD_DIE_PHASE=mid_resharding), and:

  - the survivors of its shard-scoped group commit the round via leader
    failover (the round commits THROUGH the loss), and
  - its zone-mate re-shards at generation+1 and recovers the dead
    holder's shard from its runner-up replica — without restarting the
    epoch (the mate's own run finishes normally, recovery gauges on its
    VOLUNTEER_DONE line).

Topology per cell: zone "dc" holds TWO sharded volunteers (the doomed
holder, advertising shard 0, and its mate on shard 1 — ids searched so
the 2-member HRW map splits 1/1); zones "zb"/"zc" hold one sharded
volunteer each (a singleton zone owns every shard and advertises its
primary, 0), so the cross-rotation shard-0 group is exactly {victim,
xb1, xc2} with the victim sorting first — it leads every round it joins.

Slow lane (subprocess jax startup is ~a minute per volunteer under
sandbox contention); the fast in-process twin of this matrix is
tests/test_sharding.py (TestShardedRounds + the mid_resharding manager
kill in TestReshardRecovery).
"""

import json
import os
import re
import signal
import subprocess
import sys
import time

import pytest

from distributedvolunteercomputing_tpu.swarm.sharding import ShardMap

pytestmark = [pytest.mark.slow, pytest.mark.sharding, pytest.mark.failover]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_MLP = ["--model-override", "d_hidden=16"]
NAMESPACE = "mnist_mlp/params"


def _dc_pair():
    """Deterministic id search: a zone-"dc" pair whose k=2 HRW map gives
    the a-prefixed member (the doomed leader — it must sort before the
    xb1/xc2 survivors) shard 0 and the mate shard 1."""
    for trial in range(4000):
        va, vm = f"a{trial:04d}", f"m{trial:04d}"
        m = ShardMap(
            members=(va, vm), k=2, gen=0, domain=f"dc|{NAMESPACE}"
        )
        if m.shards_of(va) == [0] and m.shards_of(vm) == [1]:
            return va, vm
    raise AssertionError("no balanced dc pair found")


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def start_coordinator():
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "coordinator.py")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=_env(),
    )
    deadline = time.time() + 30
    while time.time() < deadline:
        line = proc.stdout.readline()
        m = re.match(r"COORDINATOR_READY (\S+)", line or "")
        if m:
            return proc, m.group(1)
    proc.kill()
    raise RuntimeError("coordinator did not become ready")


def start_volunteer(coord_addr, peer_id, zone, extra, env_extra=None,
                    capture=True):
    env = _env()
    if env_extra:
        env.update(env_extra)
    out = subprocess.PIPE if capture else subprocess.DEVNULL
    err = subprocess.STDOUT if capture else subprocess.DEVNULL
    return subprocess.Popen(
        [
            sys.executable, os.path.join(REPO, "run_volunteer.py"),
            "--coordinator", coord_addr,
            "--peer-id", peer_id,
            "--zone", zone,
            "--zone-shards", "2",
            "--averaging", "sync", "--average-every", "5", "--steps", "900",
            "--group-size", "3", "--cross-zone-every-k", "1",
            "--max-group", "4",
            "--join-timeout", "20", "--gather-timeout", "15",
            "--batch-size", "16",
            "--lr", "0.01",
            *TINY_MLP,
            *extra,
        ],
        stdout=out, stderr=err, text=True, env=env,
    )


def wait_done(proc, timeout=300):
    out, _ = proc.communicate(timeout=timeout)
    for line in out.splitlines():
        if line.startswith("VOLUNTEER_DONE "):
            return json.loads(line[len("VOLUNTEER_DONE "):]), out
    raise AssertionError(f"no VOLUNTEER_DONE in output:\n{out[-3000:]}")


def wait_swarm_alive(coord_addr, n, timeout=180):
    import asyncio

    from distributedvolunteercomputing_tpu.swarm.transport import Transport

    host, _, port = coord_addr.rpartition(":")

    async def poll():
        t = Transport()
        try:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                try:
                    ret, _ = await t.call(
                        (host, int(port)), "coord.status", timeout=5.0
                    )
                    if int(ret.get("n_alive", 0)) >= n:
                        return True
                except Exception:
                    pass
                await asyncio.sleep(2.0)
            return False
        finally:
            await t.close()

    return asyncio.run(poll())


def _spawn_base(addr, mate_id, survivor_delay_ms=50):
    """The three long-lived volunteers every cell shares: the dc mate and
    the two singleton-zone shard-0 survivors, runs stretched so they are
    still training when the late-joining victim dies on them. Cells with
    a longer churn sequence before the kill (mid_resharding waits for a
    newcomer's full jax startup first) pass a bigger survivor delay, so
    the survivors' 900 steps still span the death."""
    slow = {"DVC_STEP_DELAY_MS": str(survivor_delay_ms)}
    # The mate's rounds all SKIP (it advertises shard 1 and is the only
    # s1 holder), so unlike the round-throttled survivors it would race
    # through its steps and exit before the late-starting victim even
    # dies — stretch it so its run spans the whole kill window.
    vols = [
        start_volunteer(addr, mate_id, "dc",
                        ["--min-group", "2"],
                        env_extra={"DVC_STEP_DELAY_MS": "200"}),
        start_volunteer(addr, "xb1", "zb",
                        ["--min-group", "2"], env_extra=slow),
        start_volunteer(addr, "xc2", "zc",
                        ["--min-group", "2"], env_extra=slow),
    ]
    assert wait_swarm_alive(addr, 3), "base swarm never came up"
    return vols


@pytest.mark.parametrize(
    "phase", ["pre_arm", "mid_stream", "post_partial_commit"]
)
def test_shard_holder_sigkill_at_leader_phase(phase):
    """The victim (dc's shard-0 holder, smallest id) leads its shard-0
    cross group and SIGKILLs itself at ``phase``. xb1/xc2 must depose it
    and commit through the loss; the dc mate must re-shard and recover
    shard 0 from its replica, finishing its run with nothing missing."""
    victim_id, mate_id = _dc_pair()
    coord, addr = start_coordinator()
    vols = []
    victim = None
    try:
        vols = _spawn_base(addr, mate_id)
        # The victim is throttled too: unthrottled it blasts its 900
        # steps in ~20s, cheap-skipping every round as a singleton
        # before the survivors' shard adverts even reach its membership
        # snapshot — and exits 0 instead of dying at the phase point.
        victim = start_volunteer(
            addr, victim_id, "dc", ["--min-group", "3"],
            env_extra={"DVC_CHAOS_LEADER_DIE_PHASE": phase,
                       "DVC_STEP_DELAY_MS": "100"}, capture=False,
        )
        rc = victim.wait(timeout=300)
        assert rc == -signal.SIGKILL, f"victim exited {rc}, expected SIGKILL"
        summaries = [wait_done(v)[0] for v in vols]
    finally:
        coord.kill()
        for v in vols + ([victim] if victim is not None else []):
            if v.poll() is None:
                v.kill()
    mate, b1, c2 = summaries
    # The round commits through the loss: survivors deposed the dead
    # leader and recovered its round.
    for s in (b1, c2):
        assert s.get("rounds_ok", 0) >= 1, s
    recovered = [s.get("failover", {}).get("rounds_recovered", 0)
                 for s in (b1, c2)]
    deposed = [s.get("failover", {}).get("leaders_deposed", 0)
               for s in (b1, c2)]
    assert any(r >= 1 for r in recovered), (recovered, summaries)
    assert all(d >= 1 for d in deposed), (deposed, summaries)
    # The shard comes back without an epoch restart: the mate saw the
    # churn (victim joined, then died), re-sharded past its initial map,
    # and finished holding everything it owns.
    assert mate.get("shard_reshardings", 0) >= 2, mate
    assert mate.get("shard_missing", -1) == 0, mate
    assert mate.get("shard_recoveries_failed", -1) == 0, mate
    assert mate.get("steps", 0) >= 900, mate  # full run, no restart


def test_shard_holder_sigkill_mid_resharding():
    """The fourth matrix column: the victim dies INSIDE a fenced
    re-shard (triggered by a newcomer joining its zone). The drop-after-
    phase protocol means its old copies were still intact at death, so
    the zone's survivors re-shard again and recover cleanly."""
    victim_id, mate_id = _dc_pair()
    coord, addr = start_coordinator()
    vols = []
    victim = newcomer = None
    try:
        vols = _spawn_base(addr, mate_id, survivor_delay_ms=150)
        victim = start_volunteer(
            addr, victim_id, "dc", ["--min-group", "2"],
            env_extra={"DVC_CHAOS_SHARD_DIE_PHASE": "mid_resharding",
                       "DVC_STEP_DELAY_MS": "100"},
            capture=False,
        )
        assert wait_swarm_alive(addr, 4), "victim never came up"
        # Zone churn: a newcomer joins dc — every dc holder re-shards to
        # adopt it, and the victim dies at that re-shard's phase point.
        # The newcomer runs SLOWER than the mate so it outlives it: if it
        # left first, the mate's final re-shard would hand it the
        # departed newcomer's shard with nobody left to pull from, and
        # the shard_missing==0 exit assertion would race the dissolve.
        newcomer = start_volunteer(
            addr, f"n{mate_id}", "dc", ["--min-group", "2"],
            env_extra={"DVC_STEP_DELAY_MS": "250"}, capture=False,
        )
        rc = victim.wait(timeout=300)
        assert rc == -signal.SIGKILL, f"victim exited {rc}, expected SIGKILL"
        summaries = [wait_done(v)[0] for v in vols]
    finally:
        coord.kill()
        for v in vols + [p for p in (victim, newcomer) if p is not None]:
            if v.poll() is None:
                v.kill()
    mate, b1, c2 = summaries
    # Survivors' rounds keep committing (the shard-0 group re-forms
    # without the dead holder at the next rotations).
    for s in (b1, c2):
        assert s.get("rounds_ok", 0) >= 1, s
    # The mate re-sharded at least three times (initial, victim/newcomer
    # churn, victim loss) and holds everything it owns — nothing was
    # stranded by the mid-re-shard death, and nobody restarted anything.
    assert mate.get("shard_reshardings", 0) >= 3, mate
    assert mate.get("shard_missing", -1) == 0, mate
    assert mate.get("shard_recoveries_failed", -1) == 0, mate
    assert mate.get("steps", 0) >= 900, mate
