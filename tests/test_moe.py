"""Mixture-of-Experts: routing math, gradient flow, expert parallelism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedvolunteercomputing_tpu.models import get_model
from distributedvolunteercomputing_tpu.models.moe import GPT2MoEConfig, moe_ffn, moe_init
from distributedvolunteercomputing_tpu.parallel import make_mesh
from distributedvolunteercomputing_tpu.parallel.sharding import make_param_shardings
from distributedvolunteercomputing_tpu.parallel.train_step import (
    make_sharded_train_step,
    put_batch,
    shard_train_state,
)
from distributedvolunteercomputing_tpu.training.optim import make_optimizer
from distributedvolunteercomputing_tpu.training.steps import TrainState, make_train_step

TINY = dict(vocab=128, max_len=16, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            n_experts=4, remat=False)


def test_single_expert_equals_dense_ffn():
    """E=1 with ample capacity must reduce exactly to the dense FFN (the
    router has one choice, softmax gate == 1, nothing overflows)."""
    cfg = GPT2MoEConfig(**{**TINY, "n_experts": 1, "capacity_factor": 2.0})
    p = moe_init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, cfg.d_model))
    y, aux = moe_ffn(p, x, cfg)
    dense = jax.nn.gelu(x @ p["moe_in"][0]) @ p["moe_out"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-6)  # E * 1 * 1


def test_capacity_overflow_drops_not_crashes():
    cfg = GPT2MoEConfig(**{**TINY, "capacity_factor": 0.1})  # brutal cap
    p = moe_init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, cfg.d_model))
    y, aux = moe_ffn(p, x, cfg)
    assert np.isfinite(np.asarray(y)).all()
    # with most tokens dropped the MoE output is mostly zeros
    zero_rows = np.mean(np.abs(np.asarray(y)).sum(-1) < 1e-6)
    assert zero_rows > 0.5


class TestTop2Routing:
    def test_top2_equals_convex_mixture_with_ample_capacity(self):
        """GShard top-2 with capacity for everyone: each token's output is
        the renormalized-gate convex mixture of its two experts' FFNs."""
        cfg = GPT2MoEConfig(
            **{**TINY, "n_experts": 4, "capacity_factor": 8.0, "router_top_k": 2}
        )
        p = moe_init(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, cfg.d_model))
        y, aux = moe_ffn(p, x, cfg)

        xs = np.asarray(x.reshape(-1, cfg.d_model))
        logits = xs.astype(np.float32) @ np.asarray(p["router"])
        gates = jax.nn.softmax(jnp.asarray(logits), axis=-1)
        tg, ti = jax.lax.top_k(gates, 2)
        tg = np.asarray(tg / jnp.sum(tg, -1, keepdims=True))
        ti = np.asarray(ti)
        ref = np.zeros_like(xs)
        for s in range(xs.shape[0]):
            for j in range(2):
                e_idx = ti[s, j]
                h = np.asarray(
                    jax.nn.gelu(jnp.asarray(xs[s] @ np.asarray(p["moe_in"][e_idx])))
                )
                ref[s] += tg[s, j] * (h @ np.asarray(p["moe_out"][e_idx]))
        np.testing.assert_allclose(
            np.asarray(y).reshape(-1, cfg.d_model), ref, rtol=2e-4, atol=2e-5
        )
        assert np.isfinite(float(aux))

    def test_top2_capacity_second_choice_yields(self):
        """Second choices queue AFTER all first choices: under a brutal cap
        the output matches an independent numpy reference that fills every
        expert's slots with first choices before any second choice."""
        import math

        cfg = GPT2MoEConfig(
            **{**TINY, "capacity_factor": 0.15, "router_top_k": 2}
        )
        p = moe_init(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, cfg.d_model))
        y, aux = moe_ffn(p, x, cfg)
        assert np.isfinite(np.asarray(y)).all()

        # Independent reference: sequential slot assignment, choice-major
        # (ALL first choices queue before ANY second choice).
        s, e = 32, cfg.n_experts
        cap = max(math.ceil(cfg.capacity_factor * cfg.router_top_k * s / e), 1)
        xs = np.asarray(x.reshape(s, -1))
        gates = np.asarray(
            jax.nn.softmax(
                jnp.asarray(xs.astype(np.float32) @ np.asarray(p["router"])), axis=-1
            )
        )
        ti = np.argsort(-gates, axis=-1)[:, :2]
        tg = np.take_along_axis(gates, ti, axis=-1)
        tg = tg / tg.sum(-1, keepdims=True)
        used = np.zeros(e, np.int64)
        ref = np.zeros_like(xs)
        for j in range(2):  # choice-major order is the invariant under test
            for tok in range(s):
                e_idx = ti[tok, j]
                if used[e_idx] < cap:
                    used[e_idx] += 1
                    h = np.asarray(
                        jax.nn.gelu(jnp.asarray(xs[tok] @ np.asarray(p["moe_in"][e_idx])))
                    )
                    ref[tok] += tg[tok, j] * (h @ np.asarray(p["moe_out"][e_idx]))
        np.testing.assert_allclose(
            np.asarray(y).reshape(s, -1), ref, rtol=2e-4, atol=2e-5
        )

    def test_router_top_k_validation(self):
        with pytest.raises(ValueError, match="router_top_k"):
            GPT2MoEConfig(**{**TINY, "router_top_k": 5})  # > n_experts=4
        with pytest.raises(ValueError, match="router_top_k"):
            GPT2MoEConfig(**{**TINY, "router_top_k": 0})

    def test_top2_trains_and_matches_ep_sharded(self, eight_devices):
        bundle = get_model("gpt2_moe", **{**TINY, "router_top_k": 2})
        tx = make_optimizer("adam", lr=1e-3)
        params = bundle.init(jax.random.PRNGKey(0))
        batch = bundle.make_batch(jax.random.PRNGKey(1), 8)

        ref_state = TrainState.create(params, tx, jax.random.PRNGKey(2))
        ref_step = make_train_step(bundle.loss_fn, tx, donate=False)
        ref_state, ref_m = ref_step(ref_state, batch)

        mesh = make_mesh(dp=2, ep=2, tp=2)
        state = TrainState.create(params, tx, jax.random.PRNGKey(2))
        state, _ = shard_train_state(state, mesh, tx)
        step = make_sharded_train_step(bundle.loss_fn, tx, mesh, donate=False)
        state, m = step(state, put_batch(batch, mesh))
        np.testing.assert_allclose(
            float(m["loss"]), float(ref_m["loss"]), rtol=2e-4
        )


def test_gpt2_moe_grads_reach_experts_and_router():
    bundle = get_model("gpt2_moe", **TINY)
    params = bundle.init(jax.random.PRNGKey(0))
    batch = bundle.make_batch(jax.random.PRNGKey(1), 4)
    (loss, metrics), grads = jax.value_and_grad(bundle.loss_fn, has_aux=True)(
        params, batch, jax.random.PRNGKey(2)
    )
    assert np.isfinite(float(loss))
    assert float(metrics["aux_loss"]) >= 0.99  # Switch aux lower bound is 1
    for leaf in ("router", "moe_in", "moe_out"):
        g = grads["blocks"]["moe"][leaf]
        assert float(jnp.sum(jnp.abs(g))) > 0, f"no gradient into {leaf}"


def test_gpt2_moe_trains():
    bundle = get_model("gpt2_moe", **TINY)
    tx = make_optimizer("adam", lr=3e-3)
    step = make_train_step(bundle.loss_fn, tx)
    batch = bundle.make_batch(jax.random.PRNGKey(1), 8)
    state = TrainState.create(bundle.init(jax.random.PRNGKey(0)), tx, jax.random.PRNGKey(3))
    losses = []
    for _ in range(25):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses[:3] + losses[-3:]


def test_ep_sharded_step_matches_single_device(eight_devices):
    from jax.sharding import PartitionSpec as P

    bundle = get_model("gpt2_moe", **TINY)
    tx = make_optimizer("adam", lr=1e-3)
    params = bundle.init(jax.random.PRNGKey(0))
    batch = bundle.make_batch(jax.random.PRNGKey(1), 8)

    ref_state = TrainState.create(params, tx, jax.random.PRNGKey(2))
    ref_step = make_train_step(bundle.loss_fn, tx, donate=False)
    ref_state, ref_metrics = ref_step(ref_state, batch)

    mesh = make_mesh(dp=2, ep=2, tp=2)
    shardings = make_param_shardings(mesh, params)
    # experts over ep, per-expert hidden over tp, layer axis replicated
    assert shardings["blocks"]["moe"]["moe_in"].spec == P(None, "ep", None, "tp")
    assert shardings["blocks"]["moe"]["moe_out"].spec == P(None, "ep", "tp", None)

    state = TrainState.create(params, tx, jax.random.PRNGKey(2))
    state, _ = shard_train_state(state, mesh, tx)
    step = make_sharded_train_step(bundle.loss_fn, tx, mesh, donate=False)
    with mesh:
        state, metrics = step(state, put_batch(batch, mesh))

    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=2e-4
    )
    got = jax.device_get(state.params["blocks"]["moe"]["moe_in"])
    np.testing.assert_allclose(
        got, np.asarray(ref_state.params["blocks"]["moe"]["moe_in"]),
        rtol=1e-3, atol=1e-5,
    )
