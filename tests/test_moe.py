"""Mixture-of-Experts: routing math, gradient flow, expert parallelism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedvolunteercomputing_tpu.models import get_model
from distributedvolunteercomputing_tpu.models.moe import GPT2MoEConfig, moe_ffn, moe_init
from distributedvolunteercomputing_tpu.parallel import make_mesh
from distributedvolunteercomputing_tpu.parallel.sharding import make_param_shardings
from distributedvolunteercomputing_tpu.parallel.train_step import (
    make_sharded_train_step,
    put_batch,
    shard_train_state,
)
from distributedvolunteercomputing_tpu.training.optim import make_optimizer
from distributedvolunteercomputing_tpu.training.steps import TrainState, make_train_step

TINY = dict(vocab=128, max_len=16, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            n_experts=4, remat=False)


def test_single_expert_equals_dense_ffn():
    """E=1 with ample capacity must reduce exactly to the dense FFN (the
    router has one choice, softmax gate == 1, nothing overflows)."""
    cfg = GPT2MoEConfig(**{**TINY, "n_experts": 1, "capacity_factor": 2.0})
    p = moe_init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, cfg.d_model))
    y, aux = moe_ffn(p, x, cfg)
    dense = jax.nn.gelu(x @ p["moe_in"][0]) @ p["moe_out"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-6)  # E * 1 * 1


def test_capacity_overflow_drops_not_crashes():
    cfg = GPT2MoEConfig(**{**TINY, "capacity_factor": 0.1})  # brutal cap
    p = moe_init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, cfg.d_model))
    y, aux = moe_ffn(p, x, cfg)
    assert np.isfinite(np.asarray(y)).all()
    # with most tokens dropped the MoE output is mostly zeros
    zero_rows = np.mean(np.abs(np.asarray(y)).sum(-1) < 1e-6)
    assert zero_rows > 0.5


def test_gpt2_moe_grads_reach_experts_and_router():
    bundle = get_model("gpt2_moe", **TINY)
    params = bundle.init(jax.random.PRNGKey(0))
    batch = bundle.make_batch(jax.random.PRNGKey(1), 4)
    (loss, metrics), grads = jax.value_and_grad(bundle.loss_fn, has_aux=True)(
        params, batch, jax.random.PRNGKey(2)
    )
    assert np.isfinite(float(loss))
    assert float(metrics["aux_loss"]) >= 0.99  # Switch aux lower bound is 1
    for leaf in ("router", "moe_in", "moe_out"):
        g = grads["blocks"]["moe"][leaf]
        assert float(jnp.sum(jnp.abs(g))) > 0, f"no gradient into {leaf}"


def test_gpt2_moe_trains():
    bundle = get_model("gpt2_moe", **TINY)
    tx = make_optimizer("adam", lr=3e-3)
    step = make_train_step(bundle.loss_fn, tx)
    batch = bundle.make_batch(jax.random.PRNGKey(1), 8)
    state = TrainState.create(bundle.init(jax.random.PRNGKey(0)), tx, jax.random.PRNGKey(3))
    losses = []
    for _ in range(25):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses[:3] + losses[-3:]


def test_ep_sharded_step_matches_single_device(eight_devices):
    from jax.sharding import PartitionSpec as P

    bundle = get_model("gpt2_moe", **TINY)
    tx = make_optimizer("adam", lr=1e-3)
    params = bundle.init(jax.random.PRNGKey(0))
    batch = bundle.make_batch(jax.random.PRNGKey(1), 8)

    ref_state = TrainState.create(params, tx, jax.random.PRNGKey(2))
    ref_step = make_train_step(bundle.loss_fn, tx, donate=False)
    ref_state, ref_metrics = ref_step(ref_state, batch)

    mesh = make_mesh(dp=2, ep=2, tp=2)
    shardings = make_param_shardings(mesh, params)
    # experts over ep, per-expert hidden over tp, layer axis replicated
    assert shardings["blocks"]["moe"]["moe_in"].spec == P(None, "ep", None, "tp")
    assert shardings["blocks"]["moe"]["moe_out"].spec == P(None, "ep", "tp", None)

    state = TrainState.create(params, tx, jax.random.PRNGKey(2))
    state, _ = shard_train_state(state, mesh, tx)
    step = make_sharded_train_step(bundle.loss_fn, tx, mesh, donate=False)
    with mesh:
        state, metrics = step(state, put_batch(batch, mesh))

    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=2e-4
    )
    got = jax.device_get(state.params["blocks"]["moe"]["moe_in"])
    np.testing.assert_allclose(
        got, np.asarray(ref_state.params["blocks"]["moe"]["moe_in"]),
        rtol=1e-3, atol=1e-5,
    )
