"""Tests for the minimum end-to-end slice: utils, MLP, train step, trainer.

Mirrors reference config 1: MNIST MLP, single volunteer, local SGD, no
averaging (BASELINE.json:7).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedvolunteercomputing_tpu.models import get_model, list_models
from distributedvolunteercomputing_tpu.training.steps import TrainState, make_train_step
from distributedvolunteercomputing_tpu.training.optim import make_optimizer
from distributedvolunteercomputing_tpu.training.trainer import Trainer
from distributedvolunteercomputing_tpu.utils.pytree import (
    flatten_to_buffer,
    unflatten_from_buffer,
    tree_size_bytes,
)


class TestPytreeSerde:
    def test_roundtrip(self, rng):
        tree = {
            "a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "b": {"c": jnp.ones((4,), jnp.bfloat16), "d": jnp.zeros((2, 2, 2), jnp.int32)},
        }
        buf, specs, treedef = flatten_to_buffer(tree)
        assert buf.dtype == np.float32
        assert buf.size == 6 + 4 + 8
        out = unflatten_from_buffer(buf, specs, treedef)
        for orig, rec in zip(jax.tree_util.tree_leaves(tree), jax.tree_util.tree_leaves(out)):
            assert np.asarray(orig).dtype == rec.dtype
            np.testing.assert_allclose(np.asarray(orig, np.float32), rec.astype(np.float32))

    def test_empty_tree(self):
        buf, specs, treedef = flatten_to_buffer({})
        assert buf.size == 0
        assert unflatten_from_buffer(buf, specs, treedef) == {}

    def test_size_mismatch_raises(self):
        tree = {"a": jnp.ones((3,))}
        buf, specs, treedef = flatten_to_buffer(tree)
        with pytest.raises(ValueError):
            unflatten_from_buffer(buf[:-1], specs, treedef)

    def test_tree_size_bytes(self):
        assert tree_size_bytes({"a": jnp.ones((4,), jnp.float32)}) == 16


class TestMLP:
    def test_registry_lists_all_configs(self):
        names = list_models()
        for expected in ("mnist_mlp", "cifar10_resnet18", "bert_mlm", "gpt2_small", "llama_lora"):
            assert expected in names

    def test_forward_shapes_and_loss(self, rng):
        bundle = get_model("mnist_mlp")
        params = bundle.init(rng)
        batch = bundle.make_batch(rng, 16)
        loss, metrics = bundle.loss_fn(params, batch, rng)
        assert loss.shape == ()
        assert np.isfinite(float(loss))
        assert 0.0 <= float(metrics["accuracy"]) <= 1.0

    def test_train_step_reduces_loss(self):
        # NB: the step donates its input state, so every TrainState.create gets
        # fresh key/param buffers — never reuse a donated array.
        bundle = get_model("mnist_mlp")
        tx = make_optimizer("adam", lr=1e-2)
        step = make_train_step(bundle.loss_fn, tx)
        batch = bundle.make_batch(jax.random.PRNGKey(7), 64)
        state = TrainState.create(bundle.init(jax.random.PRNGKey(8)), tx, jax.random.PRNGKey(9))
        _, m0 = step(state, batch)
        state = TrainState.create(bundle.init(jax.random.PRNGKey(8)), tx, jax.random.PRNGKey(9))
        for _ in range(30):
            state, m = step(state, batch)
        assert float(m["loss"]) < float(m0["loss"])
        assert int(state.step) == 30


class TestTrainerLocalSGD:
    def test_mnist_convergence_smoke(self):
        # Config 1: single volunteer, no averaging, bounded steps to target loss.
        t = Trainer(get_model("mnist_mlp"), batch_size=64, lr=1e-2, optimizer="adam", seed=0)
        summary = t.run(steps=200, target_loss=0.3, log_every=0)
        assert summary["final_loss"] <= 0.3, summary
        assert summary["steps"] < 200, "should hit target before budget"

    def test_target_loss_stops_early(self):
        t = Trainer(get_model("mnist_mlp"), batch_size=32, lr=1e-2, optimizer="adam", seed=1)
        summary = t.run(steps=500, target_loss=10.0, log_every=0)  # trivially satisfied
        assert summary["steps"] == 1
        assert summary["target_crossed_step"] == 1
        assert summary["target_crossed_s"] is not None

    def test_target_mode_record_trains_full_budget(self):
        """time-to-target-loss (BASELINE.json:2): record mode reports the
        first crossing but keeps training the full step budget, so one run
        yields BOTH the fixed-steps throughput row and the crossing time."""
        t = Trainer(get_model("mnist_mlp"), batch_size=32, lr=1e-2, optimizer="adam", seed=1)
        summary = t.run(steps=12, target_loss=10.0, target_mode="record", log_every=0)
        assert summary["steps"] == 12  # did NOT stop at the (trivial) target
        assert summary["target_crossed_step"] == 1
        assert summary["target_crossed_s"] >= 0.0
        # an unreachable target records a null crossing, not a crash
        t2 = Trainer(get_model("mnist_mlp"), batch_size=32, lr=1e-2, optimizer="adam", seed=1)
        s2 = t2.run(steps=3, target_loss=-1.0, target_mode="record", log_every=0)
        assert s2["target_crossed_step"] is None and s2["target_crossed_s"] is None
        import pytest

        with pytest.raises(ValueError, match="target_mode"):
            t2.run(steps=1, target_mode="bogus")

    def test_outer_optimizer_nesterov_math(self):
        """DiLoCo outer step, hand-checked over three rounds: with anchor a,
        round average v, g = a - v, m' = mu*m + g, a' = a - lr*(mu*m' + g).
        Round 1 seeds the anchor and passes the average through."""
        import numpy as np

        t = Trainer(
            get_model("mnist_mlp", d_hidden=4), batch_size=8,
            outer_optimizer="nesterov", outer_lr=0.5, outer_momentum=0.9,
        )
        lr, mu = 0.5, 0.9

        def tree(x):
            return {"w": np.full((3,), x, np.float32)}

        # round 1: seed anchor, pass through
        out1 = t._outer_transform(tree(10.0))
        np.testing.assert_allclose(out1["w"], 10.0)
        # round 2: v=7 -> g = 10-7 = 3; m = 3; a' = 10 - 0.5*(0.9*3 + 3) = 7.15
        out2 = t._outer_transform(tree(7.0))
        np.testing.assert_allclose(out2["w"], 7.15, rtol=1e-6)
        # round 3: v=7 -> g = 7.15-7 = 0.15; m = 0.9*3 + 0.15 = 2.85
        #          a' = 7.15 - 0.5*(0.9*2.85 + 0.15) = 7.15 - 1.3575 = 5.7925
        out3 = t._outer_transform(tree(7.0))
        np.testing.assert_allclose(out3["w"], 5.7925, rtol=1e-6)

    def test_outer_optimizer_identity_config_matches_plain_averaging(self):
        """lr=1, mu=0 reduces the outer step to plain adoption of the round
        average — the safety property that makes the default parameters a
        strict generalization."""
        import numpy as np

        t = Trainer(
            get_model("mnist_mlp", d_hidden=4), batch_size=8,
            outer_optimizer="nesterov", outer_lr=1.0, outer_momentum=0.0,
        )
        for v in (4.0, -2.0, 11.5):
            out = t._outer_transform({"w": np.full((5,), v, np.float32)})
            np.testing.assert_allclose(out["w"], v, rtol=1e-6)

    def test_outer_optimizer_reset_on_adoption(self):
        """A state-sync adoption invalidates the momentum stream: the next
        round must re-seed the anchor instead of differencing against a
        pre-adoption one."""
        import numpy as np

        t = Trainer(
            get_model("mnist_mlp", d_hidden=4), batch_size=8,
            outer_optimizer="nesterov", outer_lr=0.5, outer_momentum=0.9,
        )
        t._outer_transform({"w": np.full((3,), 10.0, np.float32)})
        assert t._outer_anchor is not None
        t.adopt_params(t.state.params, step=50)
        assert t._outer_anchor is None and t._outer_m is None
        # next round re-seeds: passes the average through unchanged
        out = t._outer_transform({"w": np.full((3,), 3.0, np.float32)})
        np.testing.assert_allclose(out["w"], 3.0)

    def test_outer_optimizer_overlap_path(self):
        """The overlap merge must apply the outer step to the ROUND result
        and ride the local-progress delta on top — and a staleness-dropped
        round must not touch the momentum stream. Drives the real
        _finish_overlap_round with fabricated completed futures, so the
        ordering (ok/staleness checks BEFORE the outer transform) is pinned
        deterministically."""
        import concurrent.futures

        import numpy as np

        t = Trainer(
            get_model("mnist_mlp", d_hidden=4), batch_size=8,
            averager=lambda p, s: p, overlap=True,
            outer_optimizer="nesterov", outer_lr=0.5, outer_momentum=0.9,
        )

        def payload_like(value):
            return jax.tree_util.tree_map(
                lambda x: np.full_like(np.asarray(x), value),
                t.bundle.avg_select(t.state.params),
            )

        def finish_with(averaged, launch_step, step_no):
            p0 = jax.tree_util.tree_map(
                np.asarray, t.bundle.avg_select(t.state.params)
            )
            fut = concurrent.futures.Future()
            fut.set_result((averaged, 0.01))
            t._inflight = (launch_step, p0, fut)
            t._finish_overlap_round(step_no)

        # round 1: seeds the anchor; no local steps taken since snapshot, so
        # params land exactly on the averaged tree
        finish_with(payload_like(10.0), 1, 1)
        for leaf in jax.tree_util.tree_leaves(t.state.params):
            np.testing.assert_allclose(np.asarray(leaf), 10.0)
        # round 2: v=7 -> Nesterov a' = 10 - 0.5*(0.9*3 + 3) = 7.15
        finish_with(payload_like(7.0), 2, 2)
        for leaf in jax.tree_util.tree_leaves(t.state.params):
            np.testing.assert_allclose(np.asarray(leaf), 7.15, rtol=1e-6)
        anchor_before = jax.tree_util.tree_leaves(t._outer_anchor)[0].copy()
        m_before = jax.tree_util.tree_leaves(t._outer_m)[0].copy()
        # stale round: dropped BEFORE the outer transform — anchor, momentum
        # and params all untouched
        t.max_staleness = 1
        finish_with(payload_like(0.0), 10, 20)
        np.testing.assert_array_equal(
            jax.tree_util.tree_leaves(t._outer_anchor)[0], anchor_before
        )
        np.testing.assert_array_equal(
            jax.tree_util.tree_leaves(t._outer_m)[0], m_before
        )
        for leaf in jax.tree_util.tree_leaves(t.state.params):
            np.testing.assert_allclose(np.asarray(leaf), 7.15, rtol=1e-6)

    def test_outer_optimizer_state_survives_checkpoint_resume(self, tmp_path):
        """The momentum stream persists across preemption (sidecar .npz
        beside the orbax snapshot): a resumed trainer continues the Nesterov
        sequence exactly where the saved one would have."""
        import numpy as np

        from distributedvolunteercomputing_tpu.training import checkpoint

        def make():
            return Trainer(
                get_model("mnist_mlp", d_hidden=4), batch_size=8,
                outer_optimizer="nesterov", outer_lr=0.5, outer_momentum=0.9,
            )

        def payload_like(t, value):
            return jax.tree_util.tree_map(
                lambda x: np.full_like(np.asarray(x), value),
                t.bundle.avg_select(t.state.params),
            )

        a = make()
        a._outer_transform(payload_like(a, 10.0))
        a._outer_transform(payload_like(a, 7.0))  # anchor now 7.15, m = 3
        checkpoint.save(a, str(tmp_path))
        b = make()
        assert checkpoint.maybe_restore(b, str(tmp_path))
        for la, lb in zip(
            jax.tree_util.tree_leaves(a._outer_anchor),
            jax.tree_util.tree_leaves(b._outer_anchor),
        ):
            np.testing.assert_array_equal(la, lb)
        # both continue identically: round 3 lands on the hand-checked 5.7925
        out_a = a._outer_transform(payload_like(a, 7.0))
        out_b = b._outer_transform(payload_like(b, 7.0))
        for la, lb in zip(
            jax.tree_util.tree_leaves(out_a), jax.tree_util.tree_leaves(out_b)
        ):
            np.testing.assert_allclose(la, lb, rtol=1e-7)
            np.testing.assert_allclose(np.asarray(lb), 5.7925, rtol=1e-6)
        # a mismatched schema re-seeds instead of loading garbage
        c = Trainer(
            get_model("mnist_mlp", d_hidden=8), batch_size=8,
            outer_optimizer="nesterov",
        )
        # restore params will fail template match before outer state matters;
        # drive the sidecar path directly with the wrong-schema trainer
        import os

        snap = os.path.join(str(tmp_path), f"step_{int(a.state.step)}")
        checkpoint._maybe_restore_outer_state(c, snap)
        assert c._outer_anchor is None  # re-seeded, not mis-loaded

    def test_outer_optimizer_rejects_grads_mode(self):
        import pytest

        with pytest.raises(ValueError, match="params"):
            Trainer(
                get_model("mnist_mlp", d_hidden=4), batch_size=8,
                averager=lambda p, s: p, average_what="grads",
                outer_optimizer="nesterov",
            )

    def test_checkpoint_gc_keeps_last_n(self, tmp_path, monkeypatch):
        """Periodic saves must not grow the directory without bound: after
        each save, all but the newest KEEP_LAST snapshots are removed, and
        restore still loads the newest."""
        import os

        from distributedvolunteercomputing_tpu.training import checkpoint

        monkeypatch.setattr(checkpoint, "KEEP_LAST", 3)
        t = Trainer(get_model("mnist_mlp", d_hidden=8), batch_size=8, lr=1e-2)
        batch_iter = iter(t.data_iter())
        for _ in range(5):
            t.state, _ = t._step_fn(t.state, next(batch_iter))
            checkpoint.save(t, str(tmp_path))
        dirs = sorted(os.listdir(tmp_path))
        assert dirs == ["step_3", "step_4", "step_5"], dirs
        t2 = Trainer(get_model("mnist_mlp", d_hidden=8), batch_size=8, lr=1e-2)
        assert checkpoint.maybe_restore(t2, str(tmp_path))
        assert int(t2.state.step) == 5
        # Stale HIGHER-step entries (reused dir / lagging second writer)
        # must never make GC eat the snapshot just written.
        os.makedirs(tmp_path / "step_1000")
        t.state, _ = t._step_fn(t.state, next(batch_iter))
        checkpoint.save(t, str(tmp_path))  # step 6
        assert "step_6" in os.listdir(tmp_path)
        assert "step_1000" in os.listdir(tmp_path)

    def test_eval_hook_records_held_out_loss(self, tmp_path):
        """eval_every: periodic held-out loss without updating params —
        recorded as 'eval' metrics events, params untouched by eval."""
        import json

        mpath = str(tmp_path / "m.jsonl")
        t = Trainer(
            get_model("mnist_mlp"), batch_size=32, lr=1e-2, optimizer="adam",
            seed=0, metrics_path=mpath, eval_every=5, eval_batches=2,
        )
        before_eval = t.evaluate()  # public API works standalone
        assert np.isfinite(before_eval)
        summary = t.run(steps=10, log_every=0)
        events = [
            json.loads(l) for l in open(mpath)
            if '"eval"' in l and "eval_loss" in l
        ]
        assert len(events) == 2  # steps 5 and 10
        losses = [e["eval_loss"] for e in events]
        assert all(np.isfinite(v) for v in losses)
        # training reduces held-out loss on the synthetic blobs task
        assert losses[-1] < before_eval
        # eval stream is held-out: a fresh trainer's eval batches differ from
        # its training batches (different fold of the seed)
        t2 = Trainer(get_model("mnist_mlp"), batch_size=4, seed=3)
        train_batch = next(iter(t2.data_iter()))
        import jax as _jax

        rng, k = _jax.random.split(t2._eval_rng)
        eval_batch = t2.bundle.make_batch(k, 4)
        assert not np.array_equal(np.asarray(train_batch["x"]), np.asarray(eval_batch["x"]))

    def test_init_seed_pins_shared_base_across_volunteer_seeds(self):
        # Config-5 semantics (BASELINE.json:11): every volunteer finetunes ONE
        # shared base, so different per-volunteer --seed values must still
        # produce IDENTICAL initial params (the frozen LoRA base is never
        # averaged), while the data streams differ.
        tiny = dict(vocab=64, max_len=16, d_model=32, n_heads=2, n_kv_heads=2,
                    n_layers=2, d_ff=64, lora_rank=2, remat=False)
        t0 = Trainer(get_model("llama_lora", **tiny), batch_size=4, seed=0)
        t1 = Trainer(get_model("llama_lora", **tiny), batch_size=4, seed=1)
        for a, b in zip(
            jax.tree_util.tree_leaves(t0.state.params),
            jax.tree_util.tree_leaves(t1.state.params),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        b0 = next(iter(t0.data_iter()))
        b1 = next(iter(t1.data_iter()))
        assert not np.array_equal(np.asarray(b0["tokens"]), np.asarray(b1["tokens"]))
        # a distinct init_seed changes the init (it's a real knob, not dead)
        t2 = Trainer(get_model("llama_lora", **tiny), batch_size=4, seed=0, init_seed=7)
        leaves0 = jax.tree_util.tree_leaves(t0.state.params)
        leaves2 = jax.tree_util.tree_leaves(t2.state.params)
        assert any(
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(leaves0, leaves2)
        )

    def test_overlap_round_runs_concurrently_and_merges_delta(self):
        """Overlapped averaging: the device keeps stepping while the WAN
        round is in flight, and the result is merged Moshpit-style as
        new = averaged + (current - snapshot)."""
        import threading

        def make_trainer(averager):
            return Trainer(
                get_model("mnist_mlp"), batch_size=8, seed=0,
                average_every=9, averager=averager, overlap=True,
            )

        def run_with(offset):
            release = threading.Event()
            seen = {}

            def averager(payload, step):
                seen["launch_step"] = step
                # True only if the train loop reached the LAST step while this
                # round was still in flight — i.e. compute really overlapped.
                seen["released_by_training"] = release.wait(timeout=60)
                return jax.tree_util.tree_map(
                    lambda x: np.asarray(x, np.float32) + offset, payload
                )

            t = make_trainer(averager)
            t.on_step = lambda tr, s: release.set() if s >= 10 else None
            t.run(steps=10, log_every=0)
            assert seen["launch_step"] == 9
            assert seen["released_by_training"], "train loop blocked on the round"
            return jax.tree_util.tree_map(np.asarray, t.state.params)

        # offset 0: averaged == snapshot -> merge must be a no-op vs local
        # trajectory; offset 1: every leaf exactly +1 vs the offset-0 run
        # (merge is the last action: the round drains after the final step).
        p_identity = run_with(0.0)
        p_shifted = run_with(1.0)
        for a, b in zip(
            jax.tree_util.tree_leaves(p_identity), jax.tree_util.tree_leaves(p_shifted)
        ):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a) + 1.0, rtol=1e-6)

    def test_averager_callback_applied(self):
        calls = []

        def fake_averager(params, step):
            calls.append(step)
            # returns zeros — trainer must adopt them
            return jax.tree_util.tree_map(lambda x: np.zeros_like(np.asarray(x)), params)

        t = Trainer(
            get_model("mnist_mlp"),
            batch_size=8,
            average_every=5,
            averager=fake_averager,
        )
        t.run(steps=10, log_every=0)
        assert calls == [5, 10]
        # params adopted from averager at step 10... then no further steps ran
        leaf = jax.tree_util.tree_leaves(t.state.params)[0]
        assert float(jnp.abs(leaf).sum()) == 0.0


def test_trainer_param_dtype_bf16():
    """--param-dtype bfloat16: params AND optimizer moments run in bf16
    (the bench's DVC_BENCH_PARAM_DTYPE arm as a first-class option);
    training stays finite and integer leaves keep their dtypes."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributedvolunteercomputing_tpu.models import get_model
    from distributedvolunteercomputing_tpu.training.trainer import Trainer

    t = Trainer(
        get_model("mnist_mlp"), batch_size=16, lr=1e-2, optimizer="adam",
        param_dtype="bfloat16",
    )
    s = t.run(steps=5, log_every=0)
    assert np.isfinite(s["final_loss"])
    leaves = jax.tree_util.tree_leaves(t.state.params)
    assert all(
        l.dtype == jnp.bfloat16
        for l in leaves if jnp.issubdtype(l.dtype, jnp.floating)
    )
    # ...and the optimizer moments followed (the "halves param/optimizer
    # HBM" claim): every floating leaf of the opt state is bf16 too.
    opt_leaves = [
        l for l in jax.tree_util.tree_leaves(t.state.opt_state)
        if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)
    ]
    assert opt_leaves and all(l.dtype == jnp.bfloat16 for l in opt_leaves)
    # config-time validation of the dtype name
    import pytest

    from distributedvolunteercomputing_tpu.swarm.volunteer import VolunteerConfig

    with pytest.raises(ValueError, match="param-dtype"):
        VolunteerConfig(coordinator="x:1", param_dtype="float17")
    assert VolunteerConfig(coordinator="x:1", param_dtype="bfloat16").param_dtype


def test_param_dtype_reapplied_on_restore(tmp_path):
    """A snapshot taken at f32 restored into a --param-dtype bfloat16
    trainer must come back CAST: restoring the old dtype verbatim would
    flip the averaging schema hash away from same-config peers and strand
    the volunteer solo (round-5 review finding)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributedvolunteercomputing_tpu.models import get_model
    from distributedvolunteercomputing_tpu.training import checkpoint
    from distributedvolunteercomputing_tpu.training.trainer import Trainer

    t1 = Trainer(get_model("mnist_mlp"), batch_size=8, lr=1e-2)
    t1.run(steps=2, log_every=0)
    checkpoint.save(t1, str(tmp_path))

    t2 = Trainer(
        get_model("mnist_mlp"), batch_size=8, lr=1e-2, param_dtype="bfloat16"
    )
    assert checkpoint.maybe_restore(t2, str(tmp_path))
    assert int(t2.state.step) == 2
    leaves = jax.tree_util.tree_leaves(t2.state.params)
    assert all(
        l.dtype == jnp.bfloat16
        for l in leaves if jnp.issubdtype(l.dtype, jnp.floating)
    )
    s = t2.run(steps=2, log_every=0)
    assert np.isfinite(s["final_loss"])
