"""Test harness: force an 8-device virtual CPU mesh BEFORE jax initializes.

Multi-chip hardware is not available in the sandbox; all sharding tests run
on xla_force_host_platform_device_count=8 CPU devices (SURVEY.md §4
"multi-node-without-a-cluster"). Swarm tests additionally spawn real
localhost processes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # for subprocesses spawned by swarm tests

# The sandbox's sitecustomize imports jax at interpreter startup (to register
# the axon TPU plugin), so jax.config has already snapshotted JAX_PLATFORMS —
# override via config, not just env (utils/jaxenv.py is the single home for
# this workaround).
from distributedvolunteercomputing_tpu.utils.jaxenv import pin_platform  # noqa: E402

pin_platform("cpu", min_host_devices=8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: payload-scale / long-running tests (opt-in: -m slow or DVC_RUN_SLOW=1)"
    )
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection tests (drop/delay/corrupt/partition, fault "
        "schedules, deadline-bounded degradation) — in the default lane, and "
        "selectable on their own with -m chaos",
    )
    config.addinivalue_line(
        "markers",
        "transport: wire/pool/framing tests (connection pooling, rid demux, "
        "chunked payload streaming, per-peer counters, RPC-throughput "
        "smoke) — in the default lane, and selectable on their own with "
        "-m transport",
    )
    config.addinivalue_line(
        "markers",
        "aggregation: streaming leader-aggregation tests (tile pipeline, "
        "request sinks, streaming<->dense equivalence, bench smoke) — in "
        "the default lane, and selectable on their own with -m aggregation",
    )
    config.addinivalue_line(
        "markers",
        "failover: leader-failover tests (epoch fencing, successor "
        "election, recovery rounds, kill-at-phase matrix, leader-kill "
        "chaos smoke) — in the default lane, and selectable on their own "
        "with -m failover",
    )
    config.addinivalue_line(
        "markers",
        "mesh_codec: on-mesh data-path tests (bf16 codec, device tile "
        "folds, mean folder, sharded/pallas equivalence, degraded-slice "
        "fallback, codec bench smoke) — in the default lane, and "
        "selectable on their own with -m mesh_codec",
    )
    config.addinivalue_line(
        "markers",
        "mesh_collective: fused ring reduce-scatter/all-gather tests "
        "(interpret-mode kernel equivalence vs host/staged folds, eager "
        "xla ingest, NaN propagation, mid-round degrade, aggregator "
        "parity, fused bench smoke) — in the default lane, and selectable "
        "on their own with -m mesh_collective",
    )
    config.addinivalue_line(
        "markers",
        "multigroup: rotating multi-group schedule tests (grid partition, "
        "Moshpit mixing bound, group-scoped rounds, group-local failover, "
        "per-group stats rollups, scale-bench smoke) — in the default "
        "lane, and selectable on their own with -m multigroup",
    )
    config.addinivalue_line(
        "markers",
        "controlplane: replicated control-plane tests (replica election + "
        "key-range shard handoff, fenced stale-write rejection, batched "
        "heartbeat exchange, failover client + AIMD backoff, retiring "
        "tombstone, coordinator-kill chaos smoke, batching-vs-per-message "
        "bench smoke) — in the default lane, and selectable on their own "
        "with -m controlplane",
    )
    config.addinivalue_line(
        "markers",
        "hierarchy: hierarchical (zone-aware) scheduling tests (two-level "
        "grid, per-level mixing bound, zone-local failover, bandwidth-"
        "weighted leader election, per-pair link model, per-zone rollups, "
        "cross-zone-bytes bench smoke) — in the default lane, and "
        "selectable on their own with -m hierarchy",
    )
    config.addinivalue_line(
        "markers",
        "telemetry: telemetry-plane tests (metrics registry + scrape, "
        "cross-volunteer round tracing / frame-meta trace propagation, "
        "flight recorder, stats() snapshot semantics, coord.status "
        "telemetry schema, structured JSONL logging, overhead smoke) — in "
        "the default lane, and selectable on their own with -m telemetry",
    )
    config.addinivalue_line(
        "markers",
        "health: training-health telemetry tests (seeded random-projection "
        "sketch estimator vs direct parameter dispersion, gradient-mass "
        "accounting balance across the deadline/abort/fence matrix, "
        "per-peer contribution-quality attribution + flagging, "
        "--no-health-probe end-to-end plumbing, coord.status health "
        "schema, health-probe overhead smoke) — in the default lane, and "
        "selectable on their own with -m health",
    )
    config.addinivalue_line(
        "markers",
        "tailopt: tail-optimal aggregation tests (per-tile arrival "
        "scoreboard, hedged range re-requests + (peer, tile, fence) "
        "idempotency property test, recovered-mass accounting, summand "
        "redundancy XOR decode, AIMD hedge budget, heavy-tailed link "
        "jitter, hedged-vs-drop bench smoke failing loudly below the "
        "lost-mass bar) — in the default lane, and selectable on their "
        "own with -m tailopt",
    )
    config.addinivalue_line(
        "markers",
        "controller: closed-loop adaptive-controller tests (decision "
        "hysteresis property tests — noisy in-band series produce zero "
        "transitions, a step change exactly one per knob — epoch-fence "
        "application, per-level deadline divergence, regime-folded hedge "
        "budget, dense-wire selection + schema re-key, cadence learning, "
        "coord.status controller schema walk, --no-adapt end-to-end "
        "plumbing, controller overhead smoke) — in the default lane, and "
        "selectable on their own with -m controller",
    )
    config.addinivalue_line(
        "markers",
        "watchdog: swarm-watchdog tests (online baselines + anomaly "
        "detectors with hysteresis/cooldown, SLO burn-rate windows, "
        "alert lifecycle + flight severity, incremental flight cursor, "
        "Prometheus exposition + /metrics endpoint, coord.status "
        "slo/alerts schema walk, --no-watchdog end-to-end plumbing, "
        "watchdog overhead smoke) — in the default lane, and selectable "
        "on their own with -m watchdog",
    )
    config.addinivalue_line(
        "markers",
        "sharding: zone-sharded training tests (HRW shard map stability "
        "under churn, generation fencing both ends, fenced re-shard + "
        "hedged shard recovery, kill-at-phase matrix on shard holders, "
        "per-shard mass-balance property test, shard-scoped matchmaking, "
        "control-plane snapshot deltas, OOM-sized model across a sharded "
        "zone, bytes-vs-K bench smoke) — in the default lane, and "
        "selectable on their own with -m sharding",
    )


def pytest_collection_modifyitems(config, items):
    """Slow (payload-scale) tests are OPT-IN: on the sandbox's single CPU
    core they are timing-sensitive under concurrent load, and the default
    sweep runs with -x where one contention flake aborts everything. Run
    them explicitly with `-m slow` or DVC_RUN_SLOW=1."""
    if os.environ.get("DVC_RUN_SLOW") or "slow" in (config.option.markexpr or ""):
        return
    skip = pytest.mark.skip(reason="slow: opt-in via -m slow or DVC_RUN_SLOW=1")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def rng():
    import jax

    return jax.random.PRNGKey(0)


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs


@pytest.fixture
def np_rng():
    return np.random.default_rng(0)
