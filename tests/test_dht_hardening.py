"""DHT hardening (VERDICT r2 #8): ping-before-evict, owned-record republish,
periodic refresh — proven by a 16-node rolling-restart churn scenario.

The round-1/2 table used blind LRS-drop and never republished, which is fine
at n=4 but silently loses live records at 16+ under churn: a record's
original k-closest replica set can be entirely restarted away while the
owner still considers the record live.
"""

import asyncio

from distributedvolunteercomputing_tpu.swarm.dht import DHTNode, RoutingTable, K
from distributedvolunteercomputing_tpu.swarm.transport import Transport


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout=timeout))


class TestPingBeforeEvict:
    def test_full_bucket_returns_candidate_not_blind_drop(self):
        table = RoutingTable(own_id=0)
        # ids 2^150 + j all land in bucket 150 relative to own_id 0
        ids = [(1 << 150) + j for j in range(K + 1)]
        for j in range(K):
            assert table.add(ids[j], ("127.0.0.1", 1000 + j)) is None
        cand = table.add(ids[K], ("127.0.0.1", 2000))
        assert cand == (ids[0], ("127.0.0.1", 1000)), "LRS must be the candidate"
        # newcomer NOT inserted until the caller decides
        assert ids[K] not in [nid for nid, _ in table.buckets[150]]
        # touching an existing contact moves it to MRU and returns None
        assert table.add(ids[1], ("127.0.0.1", 1001)) is None
        assert table.buckets[150][-1][0] == ids[1]

    def test_dead_lrs_is_replaced_live_lrs_survives(self):
        async def scenario():
            t_self = Transport()
            node = DHTNode(t_self, maintenance_interval=0)  # no background noise
            await node.start()
            t_live = Transport()
            live_peer = DHTNode(t_live, maintenance_interval=0)
            await live_peer.start()
            try:
                bucket_i = 150
                base = node.node_id ^ (1 << bucket_i)
                # Fill one bucket: LRS is a DEAD addr (closed port), rest dead too.
                for j in range(K):
                    node.table.add(base + j, ("127.0.0.1", 9))  # nothing listens
                newcomer = base + K
                node._add_contact(newcomer, ("127.0.0.1", 7777))
                await asyncio.sleep(0)  # let the probe task start
                for _ in range(100):
                    if not node._pinging:
                        break
                    await asyncio.sleep(0.1)
                in_bucket = [nid for nid, _ in node.table.buckets[bucket_i]]
                assert newcomer in in_bucket, "dead LRS must be evicted for the newcomer"
                assert base not in in_bucket

                # Now the LRS is a LIVE node: it must survive, newcomer2 dropped.
                node.table.remove(in_bucket[0])
                live_id = base + 50
                bucket = node.table.buckets[bucket_i]
                bucket.insert(0, (live_id, t_live.addr))  # live contact as LRS
                newcomer2 = base + K + 1
                node._add_contact(newcomer2, ("127.0.0.1", 7778))
                for _ in range(100):
                    if not node._pinging:
                        break
                    await asyncio.sleep(0.1)
                in_bucket = [nid for nid, _ in node.table.buckets[bucket_i]]
                assert live_id in in_bucket, "live LRS must survive the probe"
                assert newcomer2 not in in_bucket
                assert in_bucket[-1] == live_id, "probed-alive LRS moves to MRU"
            finally:
                await node.stop()
                await live_peer.stop()
                await t_self.close()
                await t_live.close()

        run(scenario())


def test_sixteen_node_rolling_restart_keeps_live_records():
    """Half the swarm (incl. most of a record's original replica set) is
    restarted with FRESH identities; the owner's republish + bucket refresh
    must make the record reachable from the new nodes."""

    async def scenario():
        nodes = []
        boot = None
        try:
            for i in range(16):
                t = Transport()
                d = DHTNode(t, maintenance_interval=0.4)
                await d.start(bootstrap=[boot] if boot else None)
                if boot is None:
                    boot = t.addr
                nodes.append([t, d])
            # Node 0 owns a long-lived record (e.g. a coordinator rendezvous).
            await nodes[0][1].store("svc/rendezvous", {"v": 42}, subkey="owner", ttl=90)
            # Rolling restart: nodes 8..15 die and are replaced by NEW nodes
            # (new ports => new DHT ids), bootstrapped via a survivor.
            for i in range(8, 16):
                t, d = nodes[i]
                await d.stop()
                await t.close()
                t2 = Transport()
                d2 = DHTNode(t2, maintenance_interval=0.4)
                await d2.start(bootstrap=[nodes[1][0].addr])
                nodes[i] = [t2, d2]
                await asyncio.sleep(0.1)
            # A couple of maintenance cycles: republish to the new closest
            # set, refresh buckets past the dead contacts.
            await asyncio.sleep(1.5)
            for i in (8, 11, 15):
                rec = await nodes[i][1].get("svc/rendezvous")
                assert rec.get("owner") == {"v": 42}, (
                    f"record lost after rolling restart (node {i} sees {rec})"
                )
        finally:
            for t, d in nodes:
                await d.stop()
                await t.close()

    run(scenario())
