"""DHT hardening (VERDICT r2 #8): ping-before-evict, owned-record republish,
periodic refresh — proven by a 16-node rolling-restart churn scenario.

The round-1/2 table used blind LRS-drop and never republished, which is fine
at n=4 but silently loses live records at 16+ under churn: a record's
original k-closest replica set can be entirely restarted away while the
owner still considers the record live.

Control-plane extensions (ISSUE 9): FENCED stores (per-(key,subkey)
generation watermarks; stale writers refused — the replicated control
plane's shard-handoff fencing), BATCHED multi-subkey stores (one RPC frame
per storage replica for a whole membership shard), and key-range ownership
transfer across replica join/leave/kill.
"""

import asyncio

import pytest

from distributedvolunteercomputing_tpu.swarm.dht import (
    DHTNode,
    K,
    RoutingTable,
    StaleWriteFenced,
)
from distributedvolunteercomputing_tpu.swarm.transport import Transport


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout=timeout))


class TestPingBeforeEvict:
    def test_full_bucket_returns_candidate_not_blind_drop(self):
        table = RoutingTable(own_id=0)
        # ids 2^150 + j all land in bucket 150 relative to own_id 0
        ids = [(1 << 150) + j for j in range(K + 1)]
        for j in range(K):
            assert table.add(ids[j], ("127.0.0.1", 1000 + j)) is None
        cand = table.add(ids[K], ("127.0.0.1", 2000))
        assert cand == (ids[0], ("127.0.0.1", 1000)), "LRS must be the candidate"
        # newcomer NOT inserted until the caller decides
        assert ids[K] not in [nid for nid, _ in table.buckets[150]]
        # touching an existing contact moves it to MRU and returns None
        assert table.add(ids[1], ("127.0.0.1", 1001)) is None
        assert table.buckets[150][-1][0] == ids[1]

    def test_dead_lrs_is_replaced_live_lrs_survives(self):
        async def scenario():
            t_self = Transport()
            node = DHTNode(t_self, maintenance_interval=0)  # no background noise
            await node.start()
            t_live = Transport()
            live_peer = DHTNode(t_live, maintenance_interval=0)
            await live_peer.start()
            try:
                bucket_i = 150
                base = node.node_id ^ (1 << bucket_i)
                # Fill one bucket: LRS is a DEAD addr (closed port), rest dead too.
                for j in range(K):
                    node.table.add(base + j, ("127.0.0.1", 9))  # nothing listens
                newcomer = base + K
                node._add_contact(newcomer, ("127.0.0.1", 7777))
                await asyncio.sleep(0)  # let the probe task start
                for _ in range(100):
                    if not node._pinging:
                        break
                    await asyncio.sleep(0.1)
                in_bucket = [nid for nid, _ in node.table.buckets[bucket_i]]
                assert newcomer in in_bucket, "dead LRS must be evicted for the newcomer"
                assert base not in in_bucket

                # Now the LRS is a LIVE node: it must survive, newcomer2 dropped.
                node.table.remove(in_bucket[0])
                live_id = base + 50
                bucket = node.table.buckets[bucket_i]
                bucket.insert(0, (live_id, t_live.addr))  # live contact as LRS
                newcomer2 = base + K + 1
                node._add_contact(newcomer2, ("127.0.0.1", 7778))
                for _ in range(100):
                    if not node._pinging:
                        break
                    await asyncio.sleep(0.1)
                in_bucket = [nid for nid, _ in node.table.buckets[bucket_i]]
                assert live_id in in_bucket, "live LRS must survive the probe"
                assert newcomer2 not in in_bucket
                assert in_bucket[-1] == live_id, "probed-alive LRS moves to MRU"
            finally:
                await node.stop()
                await live_peer.stop()
                await t_self.close()
                await t_live.close()

        run(scenario())


async def _mesh(n, maintenance_interval=0.0):
    """n DHT nodes, all bootstrapped via the first."""
    nodes = []
    boot = None
    for _ in range(n):
        t = Transport()
        d = DHTNode(t, maintenance_interval=maintenance_interval)
        await d.start(bootstrap=[boot] if boot else None)
        if boot is None:
            boot = t.addr
        nodes.append((t, d))
    return nodes


async def _teardown_mesh(nodes):
    for t, d in nodes:
        try:
            await d.stop()
        except Exception:
            pass
        try:
            await t.close()
        except Exception:
            pass


@pytest.mark.controlplane
class TestFencedStores:
    """Generation-watermarked stores: the stale-replica-write rejection the
    control plane's key-range handoff rides on (the PR-4 fencing idea
    applied to DHT records)."""

    def test_stale_write_rejected_across_replicas(self):
        async def scenario():
            nodes = await _mesh(4)
            try:
                a, b = nodes[1][1], nodes[2][1]
                # Replica A owns the record at gen 1.
                await a.store("cp/rollup", {"rid": "A"}, subkey="s3", ttl=30, fence=1)
                # Handoff: B claims the key range at gen 2.
                await b.store("cp/rollup", {"rid": "B"}, subkey="s3", ttl=30, fence=2)
                # A's late write (still at gen 1) must be REFUSED loudly...
                with pytest.raises(StaleWriteFenced) as ei:
                    await a.store("cp/rollup", {"rid": "A2"}, subkey="s3", ttl=30, fence=1)
                assert ei.value.gen >= 2
                # ...and every reader still sees B's record.
                for _, d in nodes:
                    rec = await d.get("cp/rollup")
                    assert rec.get("s3") == {"rid": "B"}, rec
                # B (current gen) keeps writing fine; a re-claim at gen 3
                # then fences B out in turn.
                await b.store("cp/rollup", {"rid": "B2"}, subkey="s3", ttl=30, fence=2)
                await a.store("cp/rollup", {"rid": "A3"}, subkey="s3", ttl=30, fence=3)
                with pytest.raises(StaleWriteFenced):
                    await b.store("cp/rollup", {"rid": "B3"}, subkey="s3", ttl=30, fence=2)
            finally:
                await _teardown_mesh(nodes)

        run(scenario())

    def test_equal_generation_tie_resolves_to_smallest_owner(self):
        """Two replicas whose split views claim the SAME generation must
        resolve deterministically (smallest writer id wins — the election
        idiom), not flip-flop the record as silent co-writers."""

        async def scenario():
            nodes = await _mesh(3)
            try:
                a, b = nodes[0][1], nodes[1][1]
                await b.store("cp/rollup", {"rid": "r-b"}, subkey="s2",
                              ttl=30, fence=2, fence_owner="r-b")
                # Smaller id at the same generation takes the slot...
                await a.store("cp/rollup", {"rid": "r-a"}, subkey="s2",
                              ttl=30, fence=2, fence_owner="r-a")
                # ...and the larger id is now fenced at that generation.
                with pytest.raises(StaleWriteFenced):
                    await b.store("cp/rollup", {"rid": "r-b2"}, subkey="s2",
                                  ttl=30, fence=2, fence_owner="r-b")
                rec = await nodes[2][1].get("cp/rollup")
                assert rec.get("s2") == {"rid": "r-a"}
                # A HIGHER generation still beats the tiebreak outright.
                await b.store("cp/rollup", {"rid": "r-b3"}, subkey="s2",
                              ttl=30, fence=3, fence_owner="r-b")
            finally:
                await _teardown_mesh(nodes)

        run(scenario())

    def test_fence_watermark_outlives_record_ttl(self):
        async def scenario():
            nodes = await _mesh(3)
            try:
                a, b = nodes[0][1], nodes[1][1]
                await b.store("cp/rollup", {"rid": "B"}, subkey="s0", ttl=0.2, fence=5)
                await asyncio.sleep(0.4)  # record expires; watermark must not
                for _, d in nodes:
                    assert (await d.get("cp/rollup")).get("s0") is None, (
                        "premise: the record itself must have expired"
                    )
                with pytest.raises(StaleWriteFenced):
                    await a.store("cp/rollup", {"rid": "A"}, subkey="s0", ttl=30, fence=4)
            finally:
                await _teardown_mesh(nodes)

        run(scenario())

    def test_deposed_owner_stops_republishing(self):
        """A fenced-out owned record must drop out of the republish loop:
        republishing it IS the stale write the fence exists to reject."""

        async def scenario():
            nodes = await _mesh(3, maintenance_interval=0.3)
            try:
                a, b = nodes[0][1], nodes[1][1]
                await a.store("cp/rollup", {"rid": "A"}, subkey="s1", ttl=30, fence=1)
                assert ("cp/rollup", "s1") in a._owned
                await b.store("cp/rollup", {"rid": "B"}, subkey="s1", ttl=30, fence=2)
                # A's next republish hits the watermark and drops ownership.
                for _ in range(30):
                    if ("cp/rollup", "s1") not in a._owned:
                        break
                    await asyncio.sleep(0.1)
                assert ("cp/rollup", "s1") not in a._owned
                rec = await nodes[2][1].get("cp/rollup")
                assert rec.get("s1") == {"rid": "B"}
            finally:
                await _teardown_mesh(nodes)

        run(scenario())


@pytest.mark.controlplane
class TestBatchedStores:
    def test_store_many_one_frame_per_storage_replica(self):
        """A whole cohort of subkeys must cross as ONE dht.store RPC per
        storage replica (the heartbeat-coalescing primitive), and read
        back identically to individual stores."""

        async def scenario():
            nodes = await _mesh(5)
            try:
                t0, d0 = nodes[0]
                values = {f"peer-{i}": {"addr": ["h", i], "t": float(i)} for i in range(12)}
                rpcs_before = t0.rpcs_sent
                await d0.store_many("peers-batch", values, ttl=30.0)
                batched_rpcs = t0.rpcs_sent - rpcs_before
                # One lookup walk (<= a few finds) + one store per replica;
                # definitely NOT 12 * replicas.
                assert batched_rpcs <= 4 + 12, batched_rpcs
                for _, d in nodes:
                    rec = await d.get("peers-batch")
                    assert rec == values, (len(rec), len(values))
                # Per-subkey TTLs: a short-lived entry expires alone.
                await d0.store_many(
                    "peers-batch2", {"a": 1, "b": 2}, ttl=30.0, ttls={"b": 0.2}
                )
                await asyncio.sleep(0.4)
                rec = await nodes[2][1].get("peers-batch2")
                assert rec == {"a": 1}
            finally:
                await _teardown_mesh(nodes)

        run(scenario())


@pytest.mark.controlplane
class TestShardOwnershipTransfer:
    """Key-range ownership across replica churn: join/leave/kill move
    shard ownership with a GENERATION bump, and the deposed owner is
    fenced out (extends the DHT hardening suite per ISSUE 9)."""

    def test_ownership_transfer_on_join_leave_kill(self):
        from distributedvolunteercomputing_tpu.swarm.control_plane import (
            N_SHARDS,
            ControlPlaneReplica,
        )

        async def scenario():
            nodes = await _mesh(2)
            reps = []
            try:
                # One replica owns everything.
                r1 = ControlPlaneReplica(nodes[0][1].transport, nodes[0][1], rid="r1")
                await r1.start()
                reps.append(r1)
                assert sorted(r1._shard_gens) == list(range(N_SHARDS))
                gens_before = dict(r1._shard_gens)

                # JOIN: a second replica takes over half the key range at a
                # bumped generation; r1 releases those shards on its next
                # ownership recompute.
                t2 = Transport()
                d2 = DHTNode(t2, maintenance_interval=0)
                await d2.start(bootstrap=[nodes[0][1].transport.addr])
                r2 = ControlPlaneReplica(t2, d2, rid="r2")
                await r2.start()
                reps.append(r2)
                await r1._refresh_views()
                await r1._recompute_ownership()
                owned1, owned2 = set(r1._shard_gens), set(r2._shard_gens)
                assert owned1 and owned2
                assert owned1.isdisjoint(owned2)
                assert owned1 | owned2 == set(range(N_SHARDS))
                # The acquiring replica claimed gen+1 over what r1 wrote.
                await r1._write_rollups()
                await r2._write_rollups()
                for s in owned2:
                    assert r2._shard_gens[s] > gens_before[s]

                # A deposed write from r1 for one of r2's shards is fenced.
                s = min(owned2)
                r1._shard_gens[s] = gens_before[s]  # simulate a stale view
                await r1._write_rollups()
                assert s not in r1._shard_gens, "fenced write must drop ownership"
                assert r1.counters["rollups_fenced"] >= 1

                # KILL r2 abruptly (no retire): once its record expires,
                # r1 re-acquires the whole range at a higher generation.
                r2_gens = dict(r2._shard_gens)
                await r2.stop()
                await d2.stop()
                await t2.close()
                # Expire r2's replica record from every storage node's
                # view (TTL'd soft state; force-expire for test speed).
                for _, d in nodes:
                    rec = d.storage.get("cp/replicas", {})
                    if "r2" in rec:
                        v, _exp = rec["r2"]
                        rec["r2"] = (v, 0.0)
                await r1._refresh_views()
                await r1._recompute_ownership()
                assert sorted(r1._shard_gens) == list(range(N_SHARDS))
                for s, g in r2_gens.items():
                    assert r1._shard_gens[s] > g
            finally:
                for r in reps:
                    try:
                        await r.stop()
                    except Exception:
                        pass
                await _teardown_mesh(nodes)

        run(scenario())


def test_sixteen_node_rolling_restart_keeps_live_records():
    """Half the swarm (incl. most of a record's original replica set) is
    restarted with FRESH identities; the owner's republish + bucket refresh
    must make the record reachable from the new nodes."""

    async def scenario():
        nodes = []
        boot = None
        try:
            for i in range(16):
                t = Transport()
                d = DHTNode(t, maintenance_interval=0.4)
                await d.start(bootstrap=[boot] if boot else None)
                if boot is None:
                    boot = t.addr
                nodes.append([t, d])
            # Node 0 owns a long-lived record (e.g. a coordinator rendezvous).
            await nodes[0][1].store("svc/rendezvous", {"v": 42}, subkey="owner", ttl=90)
            # Rolling restart: nodes 8..15 die and are replaced by NEW nodes
            # (new ports => new DHT ids), bootstrapped via a survivor.
            for i in range(8, 16):
                t, d = nodes[i]
                await d.stop()
                await t.close()
                t2 = Transport()
                d2 = DHTNode(t2, maintenance_interval=0.4)
                await d2.start(bootstrap=[nodes[1][0].addr])
                nodes[i] = [t2, d2]
                await asyncio.sleep(0.1)
            # A couple of maintenance cycles: republish to the new closest
            # set, refresh buckets past the dead contacts.
            await asyncio.sleep(1.5)
            for i in (8, 11, 15):
                rec = await nodes[i][1].get("svc/rendezvous")
                assert rec.get("owner") == {"v": 42}, (
                    f"record lost after rolling restart (node {i} sees {rec})"
                )
        finally:
            for t, d in nodes:
                await d.stop()
                await t.close()

    run(scenario())
