"""Transport / DHT / membership tests — in-process, real localhost sockets.

The "multi-node-without-a-cluster" strategy (SURVEY.md §4): every node is a
real asyncio TCP server on 127.0.0.1, so the wire protocol, timeouts, and
churn behavior are exercised for real; only process isolation is elided
(covered separately by the entrypoint e2e test).
"""

import asyncio

import numpy as np
import pytest

from distributedvolunteercomputing_tpu.swarm.coordinator import Coordinator
from distributedvolunteercomputing_tpu.swarm.dht import DHTNode
from distributedvolunteercomputing_tpu.swarm.membership import SwarmMembership
from distributedvolunteercomputing_tpu.swarm.transport import RPCError, Transport


def run(coro):
    return asyncio.run(coro)


@pytest.mark.transport
class TestTransport:
    def test_echo_roundtrip(self):
        async def main():
            server = Transport()

            async def echo(args, payload):
                return {"got": args["x"]}, payload[::-1]

            server.register("echo", echo)
            addr = await server.start()
            client = Transport()
            ret, payload = await client.call(addr, "echo", {"x": 42}, b"abc")
            await server.close()
            return ret, payload

        ret, payload = run(main())
        assert ret == {"got": 42}
        assert payload == b"cba"

    def test_survives_garbage_frames(self):
        """Frame-parser fuzz: raw TCP garbage — bad magic, truncated
        headers, oversize lengths, invalid JSON meta, non-dict JSON meta —
        must each produce a clean drop (no task crash), and the server must
        keep serving legitimate RPCs afterwards."""
        import json as _json
        import zlib

        from distributedvolunteercomputing_tpu.swarm.transport import (
            _HEADER, MAGIC, VERSION,
        )

        def frame(meta_b: bytes, payload: bytes = b"", magic=MAGIC, version=VERSION):
            crc = zlib.crc32(payload) & 0xFFFFFFFF
            return (
                _HEADER.pack(magic, version, 1, len(meta_b), len(payload), crc)
                + meta_b + payload
            )

        garbage = [
            b"\x00" * 64,                                  # not a frame at all
            frame(b"{}", magic=b"XX"),                     # bad magic
            frame(b"{}", version=99),                      # bad version
            frame(b"not json at all"),                     # invalid JSON meta
            frame(_json.dumps([1, 2, 3]).encode()),        # JSON, not an object
            frame(_json.dumps("str").encode()),            # JSON scalar meta
            _HEADER.pack(MAGIC, VERSION, 1, 10, 0, 0),     # truncated: no meta
            _HEADER.pack(MAGIC, VERSION, 1, 0, 1 << 62, 0),  # absurd payload len
            frame(b"[" * 100_000 + b"1" + b"]" * 100_000),  # parser stack bomb
        ]

        async def main():
            server = Transport()

            async def echo(args, payload):
                return {"ok": True}, payload

            server.register("echo", echo)
            addr = await server.start()
            for g in garbage:
                reader, writer = await asyncio.open_connection(*addr)
                writer.write(g)
                try:
                    await writer.drain()
                    # EOF makes a server blocked on readexactly for bytes
                    # that will never come fail fast (IncompleteReadError)
                    # instead of stalling this test for the full timeout.
                    writer.write_eof()
                    # Server replies with an error frame or just drops us;
                    # either way the connection ends without wedging.
                    await asyncio.wait_for(reader.read(1 << 16), timeout=5)
                except (ConnectionResetError, BrokenPipeError, asyncio.TimeoutError):
                    pass
                finally:
                    writer.close()
            # The real client still works after every garbage volley.
            client = Transport()
            ret, payload = await client.call(addr, "echo", {"x": 1}, b"ok")
            await server.close()
            return ret, payload

        ret, payload = run(main())
        assert ret == {"ok": True}
        assert payload == b"ok"

    def test_large_binary_payload(self):
        async def main():
            server = Transport()

            async def double(args, payload):
                arr = np.frombuffer(payload, np.float32) * 2
                return {}, arr.tobytes()

            server.register("double", double)
            addr = await server.start()
            client = Transport()
            data = np.arange(300_000, dtype=np.float32)
            _, resp = await client.call(addr, "double", payload=data.tobytes())
            await server.close()
            return data, np.frombuffer(resp, np.float32)

        data, resp = run(main())
        np.testing.assert_allclose(resp, data * 2)

    def test_auth_roundtrip_and_rejection(self):
        """Shared-secret HMAC frame auth: matching secrets work end-to-end;
        a client with the wrong secret (or none) is rejected — the whole
        swarm tier crosses this transport, so this one gate is what keeps
        identity spoofing out of the Byzantine first-write-wins rule."""

        async def main():
            server = Transport(secret=b"s3kr1t")

            async def echo(args, payload):
                return {"got": args["x"]}, payload

            server.register("echo", echo)
            addr = await server.start()

            ok_client = Transport(secret=b"s3kr1t")
            ret, payload = await ok_client.call(addr, "echo", {"x": 1}, b"hi")
            assert ret == {"got": 1} and payload == b"hi"

            outcomes = {}
            for name, client in (
                ("wrong", Transport(secret=b"wrong")),
                ("none", Transport()),
            ):
                try:
                    # The server drops unauthenticated frames; from the
                    # client side that surfaces as an error or a dead
                    # connection — never a successful call.
                    await client.call(addr, "echo", {"x": 2}, b"x", timeout=5.0)
                    outcomes[name] = "accepted"
                except (
                    RPCError, OSError, asyncio.IncompleteReadError,
                    asyncio.TimeoutError, TimeoutError,
                ):
                    outcomes[name] = "rejected"
            await server.close()
            return outcomes

        assert run(main()) == {"wrong": "rejected", "none": "rejected"}

    def test_auth_client_rejects_unauthenticated_server(self):
        """Auth is mutual: a secret-holding client refuses responses from a
        server that can't sign them (e.g. a man-in-the-middle without the
        secret)."""

        async def main():
            server = Transport()  # no secret: cannot sign responses

            async def echo(args, payload):
                return {}, payload

            server.register("echo", echo)
            addr = await server.start()
            client = Transport(secret=b"s3kr1t")
            try:
                await client.call(addr, "echo", {}, b"x", timeout=5.0)
                outcome = "accepted"
            except (RPCError, OSError, asyncio.TimeoutError, TimeoutError):
                outcome = "rejected"
            await server.close()
            return outcome

        assert run(main()) == "rejected"

    def test_auth_timestamp_window(self):
        """Frames outside the auth window are rejected (bounds replay)."""

        async def main():
            server = Transport(secret=b"k", auth_window=0.0)  # everything stale

            async def echo(args, payload):
                return {}, payload

            server.register("echo", echo)
            addr = await server.start()
            client = Transport(secret=b"k")
            try:
                await client.call(addr, "echo", {}, b"", timeout=5.0)
                outcome = "accepted"
            except (RPCError, OSError, asyncio.TimeoutError, TimeoutError):
                outcome = "rejected"
            await server.close()
            return outcome

        assert run(main()) == "rejected"

    def test_auth_rejects_replayed_request_frame(self):
        """A captured request frame (e.g. a membership heartbeat) re-sent
        within the auth window must be refused: every legitimate request
        carries a fresh rid inside the MAC'd meta, so the server treats an
        already-accepted MAC as a replay."""
        import json as _json
        import time as _time
        import zlib as _zlib

        from distributedvolunteercomputing_tpu.swarm.transport import (
            _HEADER, MAGIC, TYPE_ERR, TYPE_REQ, TYPE_RESP, VERSION,
        )

        async def main():
            server = Transport(secret=b"s3kr1t")
            calls = []

            async def ping(args, payload):
                calls.append(args)
                return {"ok": True}, b""

            server.register("ping", ping)
            addr = await server.start()
            # A second node in the same swarm (same secret): the captured
            # frame must be unusable there too (cross-node replay).
            other = Transport(secret=b"s3kr1t")

            async def ping2(args, payload):
                calls.append(("other", args))
                return {"ok": True}, b""

            other.register("ping", ping2)
            other_addr = await other.start()
            # Craft ONE authenticated request frame (what an eavesdropper
            # inside the window holds), then send the identical bytes twice
            # on two fresh connections.
            signer = Transport(secret=b"s3kr1t")
            meta = {
                "rid": "feedfacefeedface", "method": "ping", "args": {"n": 1},
                "dst": [addr[0], addr[1]], "ts": round(_time.time(), 3),
            }
            meta["auth"] = signer._mac(TYPE_REQ, meta, b"")
            meta_b = _json.dumps(meta).encode()
            frame = _HEADER.pack(
                MAGIC, VERSION, TYPE_REQ, len(meta_b), 0,
                _zlib.crc32(b"") & 0xFFFFFFFF,
            ) + meta_b

            async def send_raw(to):
                reader, writer = await asyncio.open_connection(*to)
                try:
                    writer.write(frame)
                    await writer.drain()
                    return await signer._read_frame(reader)
                finally:
                    writer.close()

            ftype1, meta1, _ = await send_raw(addr)
            ftype2, meta2, _ = await send_raw(addr)
            ftype3, meta3, _ = await send_raw(other_addr)
            await server.close()
            await other.close()
            assert ftype1 == TYPE_RESP and meta1["ret"] == {"ok": True}
            # same-node replay: rejected by the seen-MAC cache
            assert ftype2 == TYPE_ERR and "replay" in meta2.get("error", "")
            # cross-node replay: rejected by the MAC'd dst binding
            assert ftype3 == TYPE_ERR and "different node" in meta3.get("error", "")
            assert len(calls) == 1  # the handler ran exactly once, on one node

        run(main())

    def test_dst_alias_matching(self):
        """The MAC'd destination must match this node: port exactly, host
        by legitimate alias (advertised, bound, loopback). Distinct nodes'
        alias sets can't collide — same machine implies distinct ports."""
        t = Transport(host="0.0.0.0", advertise_host="10.1.2.3")
        t._port = 7000
        assert t._dst_is_me(["10.1.2.3", 7000])   # advertised
        assert t._dst_is_me(["0.0.0.0", 7000])    # bound
        assert t._dst_is_me(["127.0.0.1", 7000])  # loopback dial
        assert t._dst_is_me(["localhost", 7000])
        assert not t._dst_is_me(["10.9.9.9", 7000])   # another machine
        assert not t._dst_is_me(["10.1.2.3", 7001])   # another node, same host
        assert not t._dst_is_me(None)                 # frame without dst
        assert not t._dst_is_me(["10.1.2.3"])         # malformed

    def test_unknown_method_raises(self):
        async def main():
            server = Transport()
            addr = await server.start()
            client = Transport()
            try:
                with pytest.raises(RPCError, match="no such method"):
                    await client.call(addr, "nope")
            finally:
                await server.close()

        run(main())

    def test_handler_exception_propagates(self):
        async def main():
            server = Transport()

            async def boom(args, payload):
                raise ValueError("kaboom")

            server.register("boom", boom)
            addr = await server.start()
            client = Transport()
            try:
                with pytest.raises(RPCError, match="kaboom"):
                    await client.call(addr, "boom")
            finally:
                await server.close()

        run(main())

    def test_dead_peer_times_out(self):
        async def main():
            client = Transport()
            with pytest.raises((OSError, asyncio.TimeoutError)):
                await client.call(("127.0.0.1", 1), "ping", timeout=2.0)

        run(main())


async def _spawn_swarm(n, bootstrap_first=True):
    nodes = []
    for i in range(n):
        node = DHTNode(Transport())
        boot = [nodes[0].transport.addr] if (nodes and bootstrap_first) else []
        await node.start(bootstrap=boot)
        nodes.append(node)
    return nodes


async def _teardown(nodes):
    for n in nodes:
        await n.transport.close()


class TestDHT:
    def test_store_get_across_nodes(self):
        async def main():
            nodes = await _spawn_swarm(5)
            try:
                await nodes[1].store("model_version", {"step": 120}, ttl=30)
                seen = await nodes[4].get_value("model_version")
                return seen
            finally:
                await _teardown(nodes)

        assert run(main()) == {"step": 120}

    def test_subkey_merge_from_different_writers(self):
        async def main():
            nodes = await _spawn_swarm(4)
            try:
                for i, node in enumerate(nodes):
                    await node.store("peers", {"rank": i}, subkey=f"peer{i}", ttl=30)
                views = [await n.get("peers") for n in nodes]
                return views
            finally:
                await _teardown(nodes)

        views = run(main())
        for view in views:
            assert set(view) == {"peer0", "peer1", "peer2", "peer3"}
            assert view["peer2"] == {"rank": 2}

    def test_expiry(self):
        async def main():
            nodes = await _spawn_swarm(3)
            try:
                await nodes[0].store("ephemeral", "x", ttl=0.5)
                now = await nodes[2].get_value("ephemeral")
                await asyncio.sleep(0.8)
                later = await nodes[2].get_value("ephemeral", default="GONE")
                return now, later
            finally:
                await _teardown(nodes)

        now, later = run(main())
        assert now == "x"
        assert later == "GONE"

    def test_survives_node_death(self):
        async def main():
            nodes = await _spawn_swarm(6)
            try:
                await nodes[1].store("k", "v", ttl=30)
                # kill half the swarm, including the bootstrap node
                for victim in nodes[:3]:
                    await victim.transport.close()
                return await nodes[4].get_value("k", default="LOST")
            finally:
                await _teardown(nodes[3:])

        # replication factor K=8 > swarm size, so every node holds a replica
        assert run(main()) == "v"


class TestMembership:
    def test_join_heartbeat_leave(self):
        async def main():
            nodes = await _spawn_swarm(3)
            try:
                members = [
                    SwarmMembership(node, f"vol{i}", ttl=2.0) for i, node in enumerate(nodes)
                ]
                for m in members:
                    await m.join()
                alive = await members[0].alive_peers()
                await members[2].leave()
                after_leave = await members[0].alive_peers()
                return alive, after_leave
            finally:
                await _teardown(nodes)

        alive, after_leave = run(main())
        assert set(alive) == {"vol0", "vol1", "vol2"}
        assert set(after_leave) == {"vol0", "vol1"}

    def test_crashed_peer_expires(self):
        async def main():
            nodes = await _spawn_swarm(3)
            try:
                members = [
                    SwarmMembership(node, f"vol{i}", ttl=1.2) for i, node in enumerate(nodes)
                ]
                for m in members:
                    await m.join()
                # simulate kill -9: no leave(), just stop heartbeats + socket
                members[1]._heartbeat_task.cancel()
                await nodes[1].transport.close()
                await asyncio.sleep(1.6)
                alive = await members[0].alive_peers()
                return alive
            finally:
                await _teardown([nodes[0], nodes[2]])

        alive = run(main())
        assert "vol1" not in alive
        assert {"vol0", "vol2"} <= set(alive)


class TestCoordinator:
    def test_status_aggregates(self):
        async def main():
            coord = Coordinator()
            caddr = await coord.start()
            try:
                nodes = []
                for i in range(3):
                    node = DHTNode(Transport())
                    await node.start(bootstrap=[caddr])
                    nodes.append(node)
                    m = SwarmMembership(node, f"vol{i}", ttl=10.0)
                    await m.join()
                    await node.transport.call(
                        caddr,
                        "coord.report",
                        {"peer": f"vol{i}", "step": 10 * i, "samples_per_sec": 100.0},
                    )
                status, _ = await coord._rpc_status({}, b"")
                await _teardown(nodes)
                return status
            finally:
                await coord.close()

        status = run(main())
        assert status["n_alive"] == 3
        assert status["swarm_samples_per_sec"] == pytest.approx(300.0)
