"""Robust aggregation ops vs. hand-computed numpy references (SURVEY.md §4)."""

import numpy as np
import pytest

from distributedvolunteercomputing_tpu.ops import robust


@pytest.fixture
def stack(np_rng):
    return np_rng.normal(size=(6, 40)).astype(np.float32)


def test_mean_weighted(stack):
    w = np.array([1, 1, 2, 0, 0, 0], np.float64)
    expect = (stack[0] + stack[1] + 2 * stack[2]) / 4
    np.testing.assert_allclose(robust.mean(stack, w), expect, rtol=1e-6)


def test_median_resists_one_attacker(stack):
    poisoned = stack.copy()
    poisoned[0] = 1e9  # malicious volunteer
    out = robust.coordinate_median(poisoned)
    assert np.abs(out).max() < 100.0


def test_trimmed_mean_drops_extremes(stack):
    poisoned = stack.copy()
    poisoned[0] = 1e9
    poisoned[1] = -1e9
    out = robust.trimmed_mean(poisoned, trim=1)
    clean = np.sort(poisoned, axis=0)[1:-1].mean(axis=0)
    np.testing.assert_allclose(out, clean, rtol=1e-5)
    assert np.abs(out).max() < 100.0


def test_trimmed_mean_rejects_overtrim(stack):
    with pytest.raises(ValueError):
        robust.trimmed_mean(stack, trim=3)


def test_trim_zero_is_mean(stack):
    # sorting reorders the summation; equality is up to f32 rounding
    np.testing.assert_allclose(
        robust.trimmed_mean(stack, trim=0), stack.mean(0), rtol=1e-5, atol=1e-6
    )


def test_krum_picks_clean_point(np_rng):
    honest = np_rng.normal(size=(5, 20)).astype(np.float32) * 0.1
    attacker = np.full((1, 20), 50.0, np.float32)
    stack = np.concatenate([honest, attacker])
    out = robust.krum(stack, n_byzantine=1)
    assert np.abs(out).max() < 1.0


def test_krum_degrades_to_median_for_tiny_groups(np_rng):
    stack = np_rng.normal(size=(3, 10)).astype(np.float32)
    np.testing.assert_allclose(
        robust.krum(stack, n_byzantine=1), robust.coordinate_median(stack)
    )


def test_geometric_median_bounded_under_attack(np_rng):
    honest = np_rng.normal(size=(6, 30)).astype(np.float32)
    poisoned = np.concatenate([honest, np.full((2, 30), 1e6, np.float32)])
    out = robust.geometric_median(poisoned)
    assert np.abs(out).max() < 10.0


def test_aggregate_dispatch_and_errors(stack):
    np.testing.assert_allclose(robust.aggregate(stack, "mean"), stack.mean(0), rtol=1e-6)
    with pytest.raises(KeyError):
        robust.aggregate(stack, "nope")
    with pytest.raises(ValueError):
        robust.aggregate(stack[0], "mean")


def test_bulyan_bounded_under_attack(np_rng):
    """n=7, f=1 (meets n >= 4f+3): one arbitrary attacker can move the
    Bulyan aggregate only within the honest points' spread; with no
    attacker the aggregate stays close to the honest mean."""
    honest = np_rng.normal(size=(6, 32)).astype(np.float32)
    poisoned = np.concatenate([honest, np.full((1, 32), 1e9, np.float32)])
    out = robust.bulyan(poisoned, n_byzantine=1)
    assert np.abs(out).max() < 100.0
    all_honest = np.concatenate([honest, honest[:1]])  # n=7, nobody malicious
    clean = robust.bulyan(all_honest, n_byzantine=1)
    # the aggregate lies inside the honest points' per-coordinate envelope
    # (it averages a median-centred subset of them)
    assert (clean >= honest.min(axis=0) - 1e-6).all()
    assert (clean <= honest.max(axis=0) + 1e-6).all()


def test_bulyan_degrades_below_guarantee(np_rng):
    """n < 4f+3: falls back to the geometric median rather than running
    with a vacuous guarantee."""
    small = np_rng.normal(size=(4, 16)).astype(np.float32)
    np.testing.assert_allclose(
        robust.bulyan(small, n_byzantine=1),
        robust.geometric_median(small),
        rtol=1e-6,
    )


def test_bulyan_dispatch(np_rng):
    big = np_rng.normal(size=(8, 16)).astype(np.float32)
    out = robust.aggregate(big, "bulyan", n_byzantine=1)
    assert out.shape == (16,)
    assert out.dtype == np.float32


def test_bulyan_selection_excludes_attacker_and_is_order_independent(np_rng):
    """Regression for the degenerate selection edge: with the single-pass
    Multi-Krum scoring the Byzantine row is excluded by VALUE (its
    neighbour distances are huge), and permuting peer rows cannot change
    the aggregate — the old iterative re-scoring hit zero-neighbour ties
    in its late iterations and picked by row index."""
    honest = np_rng.normal(size=(6, 32)).astype(np.float32)
    poisoned = np.concatenate([np.full((1, 32), 1e9, np.float32), honest])
    out = robust.bulyan(poisoned, n_byzantine=1)
    perm = np_rng.permutation(len(poisoned))
    out_perm = robust.bulyan(poisoned[perm], n_byzantine=1)
    np.testing.assert_allclose(out, out_perm, rtol=1e-6)
    assert np.abs(out).max() < 100.0


def test_centered_clip_honest_only_matches_mean(np_rng):
    # With no outliers and self-tuned tau, iterations converge toward the
    # mean (honest deviations mostly pass the clip).
    honest = np_rng.normal(size=(8, 50)).astype(np.float32)
    out = robust.centered_clip(honest, iters=12)
    dist_mean = np.linalg.norm(out - honest.mean(0))
    dist_median = np.linalg.norm(out - np.median(honest, 0))
    assert dist_mean < np.linalg.norm(honest.mean(0) - np.median(honest, 0))
    assert np.isfinite(out).all() and (dist_mean < 2.0 or dist_median < 2.0)


def test_centered_clip_bounded_under_unbounded_attack(np_rng):
    honest = np_rng.normal(size=(6, 30)).astype(np.float32)
    poisoned = np.concatenate([honest, np.full((2, 30), 1e9, np.float32)])
    out = robust.centered_clip(poisoned)
    assert np.abs(out).max() < 10.0


def test_centered_clip_l2_bound_beats_coordinate_trim_evasion(np_rng):
    """The case coordinate-wise estimators are weakest at: an attacker
    spreads a large L2 vector over MANY small coordinates, so no single
    coordinate looks extreme. CenteredClip bounds the L2 pull per
    iteration, so the aggregate stays near the honest mean."""
    d = 400
    honest = np_rng.normal(size=(6, d)).astype(np.float32) * 0.1
    # Each attacker coordinate is only ~1.5x an honest std, but the vector's
    # L2 norm is ~30x an honest row's.
    attack = np.full((1, d), 0.15, np.float32)
    poisoned = np.concatenate([honest, attack])
    out = robust.centered_clip(poisoned, iters=8)
    shift = np.linalg.norm(out - honest.mean(0))
    honest_radius = np.median(
        np.linalg.norm(honest - honest.mean(0), axis=1)
    )
    assert shift < honest_radius, (shift, honest_radius)


def test_centered_clip_dispatch_and_validation(np_rng):
    stack = np_rng.normal(size=(4, 10)).astype(np.float32)
    np.testing.assert_allclose(
        robust.aggregate(stack, "centered_clip"), robust.centered_clip(stack)
    )
    with pytest.raises(ValueError):
        robust.centered_clip(stack, iters=0)
    with pytest.raises(ValueError):
        robust.centered_clip(stack, clip_tau=-1.0)


def test_centered_clip_survives_nonfinite_rows(np_rng):
    # inf * 0 == NaN: without dropping non-finite rows first, a single
    # inf-filled byzantine row turned the whole aggregate NaN (found by
    # review, verified by execution) — while the coordinate-wise
    # estimators survived the same input.
    honest = np_rng.normal(size=(5, 20)).astype(np.float32)
    for bad in (np.inf, -np.inf, np.nan):
        poisoned = np.concatenate([honest, np.full((1, 20), bad, np.float32)])
        out = robust.centered_clip(poisoned)
        assert np.isfinite(out).all()
        assert np.abs(out - honest.mean(0)).max() < 3.0
    # Degenerate all-non-finite stack: defined, finite output.
    allbad = np.full((3, 20), np.nan, np.float32)
    assert np.isfinite(robust.centered_clip(allbad)).all()
